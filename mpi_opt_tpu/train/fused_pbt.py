"""Fully-fused on-device PBT: whole sweeps as one XLA program.

This is the performance thesis of the framework (BASELINE.json
north_star: PBT exploit/explore "become lax.top_k/psum over a device
mesh instead of MPI_Allgather"). The generic driver path (host PBT +
TPU backend) round-trips tiny score arrays once per generation; this
module removes even that: a ``lax.scan`` over generations where each
iteration trains the population (itself a scan of vmapped steps),
evaluates it, runs exploit/explore, and gathers winner states — all
inside a single jit. The host launches one computation and gets back
the final population + per-generation score curves.

Works unchanged on a sharded population: launch with a mesh-sharded
PopState (parallel/mesh.py) and XLA partitions the whole loop,
inserting the all_gathers for the ranking/gather steps over ICI.

Why fused beats the reference's architecture (and our own host loop):
- zero host↔device sync per generation (the reference pays an
  MPI_Allgather + Python decision per rank per generation);
- XLA overlaps the next generation's first steps with the previous
  exploit gather where dependencies allow;
- hyperparameters are data, so G generations of mutated schedules cost
  one compile.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from mpi_opt_tpu.obs import memory, trace
from mpi_opt_tpu.ops.pbt import PBTConfig, pbt_exploit_explore, pbt_exploit_explore_mo
from mpi_opt_tpu.train.common import (
    eval_population_objectives,
    finite_winner,
    journal_boundary,
    journal_require_prefix,
    launch_boundary,
    make_fused_journal,
    momentum_dtype_str,
    oom_funnel,
    segment_flops_hint,
)
from mpi_opt_tpu.utils import profiling, resources
from mpi_opt_tpu.train.population import OptHParams, PopState, PopulationTrainer

# the shared fault-tolerant wave executor (train/engine.py): wave
# scheduling, host-pool staging, OOM backoff, drain/heartbeat — this
# module supplies only PBT's boundary op (truncation exploit/explore).
# The private aliases preserve this module's historical seams: tests
# intercept ``fused_pbt._run_wave`` for crash/OOM drills.
from mpi_opt_tpu.train.engine import (
    WaveRunner,
    boundary_span,
    resolve_wave_size,
    _wave_train_program,  # noqa: F401  (re-exported test seam)
)
from mpi_opt_tpu.train.engine import balanced_split as _balanced_split
from mpi_opt_tpu.train.engine import engine_rollover as _engine_rollover  # noqa: F401
from mpi_opt_tpu.train.engine import run_wave as _run_wave
from mpi_opt_tpu.train.engine import wave_layout as _wave_layout
from mpi_opt_tpu.train.engine import writable as _writable


@functools.partial(
    jax.jit,
    static_argnames=(
        "trainer", "hparams_fn", "discrete_mask", "generations",
        "steps_per_gen", "cfg", "objectives",
    ),
    donate_argnames=("state", "unit"),
)
def run_fused_pbt(
    trainer: PopulationTrainer,
    state: PopState,
    unit: jax.Array,  # float32[P, d] initial hparams (unit cube)
    hparams_fn: Callable,  # unit matrix -> OptHParams (static, hashable)
    train_x: jax.Array = None,
    train_y: jax.Array = None,
    val_x: jax.Array = None,
    val_y: jax.Array = None,
    key: jax.Array = None,
    discrete_mask: tuple = (),
    generations: int = 10,
    steps_per_gen: int = 100,
    cfg: PBTConfig = PBTConfig(),
    objectives=None,  # static ObjectiveSpec: multi-objective exploit (ISSUE 17)
):
    """Returns (state, unit, key', best_curve[G], mean_curve[G],
    member_fail[G], final_scores[P], pre_scores[G, P], pre_units[G, P, d]).

    ``member_fail`` counts the PRE-exploit members whose eval came back
    non-finite each generation — the divergence the exploit step then
    masks by replacing losers with winners. Tallied in-scan (one int32
    per generation) so reporting it costs no extra fetch.

    ``pre_scores``/``pre_units`` are each generation's PRE-exploit
    member scores and the unit rows those members actually trained
    with — the per-member facts the fused ledger journals (one record
    per member per generation; ledger/fused.py). They ride the scan's
    stacked outputs, so collecting them costs no extra program.

    ``key'`` is the scan-carried RNG key after ``generations`` steps of
    the chain — feeding it into a following call continues the EXACT
    trajectory one longer call would have taken, which is what makes
    ``gen_chunk`` launch-splitting bit-identical to a single launch.

    ``objectives`` (a static, hashable ``ObjectiveSpec``) switches the
    generation boundary to multi-objective selection: each generation
    evaluates the full objective matrix on device
    (``eval_population_objectives``), the exploit ranks by Pareto
    score inside the same compiled scan (``pbt_exploit_explore_mo`` —
    no host round-trip is added to the hot path), and the scan's
    scalar outputs carry the spec-scalarized primary objective so
    every scalar consumer (curves, journaling, snapshots) works
    unchanged. The return grows two trailing outputs:
    ``pre_mo[G, P, m]`` (raw pre-exploit objective matrices — the
    ledger's ``scores`` vectors) and ``final_mo[P, m]`` (the final
    post-exploit population's objectives, for the winner pick /
    front summary). Scalar calls return the original 9-tuple.
    """
    if generations < 1:  # static arg: raises at trace time, not opaquely later
        raise ValueError(f"generations must be >= 1, got {generations}")
    disc = jnp.asarray(discrete_mask, dtype=bool)
    norm_bounds = (
        objectives.norm_bounds()
        if objectives is not None and objectives.has_bounds
        else None
    )

    def one_generation(carry, g):
        st, u, k = carry
        k, k_train, k_pbt = jax.random.split(k, 3)
        hp = hparams_fn(u)
        st, _ = trainer.train_segment(st, hp, train_x, train_y, k_train, steps_per_gen)
        if objectives is not None:
            mo = eval_population_objectives(
                trainer, st, val_x, val_y, objectives.names
            )
            scores = objectives.scalarize(mo)
            new_u, src_idx, _, _eff = pbt_exploit_explore_mo(
                k_pbt,
                u,
                objectives.normalize(mo),
                disc,
                cfg,
                norm_bounds=norm_bounds,
            )
            st = trainer.gather_members(st, src_idx)
            # a non-finite value in ANY objective is the member failure
            n_fail = jnp.sum(~jnp.all(jnp.isfinite(mo), axis=-1)).astype(jnp.int32)
            return (st, new_u, k), (
                scores.max(), scores.mean(), n_fail, scores[src_idx],
                scores, u, mo, mo[src_idx],
            )
        scores = trainer.eval_population(st, val_x, val_y)
        new_u, src_idx, _ = pbt_exploit_explore(k_pbt, u, scores, disc, cfg)
        st = trainer.gather_members(st, src_idx)
        # the post-exploit population's scores are exactly the gathered
        # pre-exploit scores (weights are copied verbatim, eval is
        # deterministic) — so no final re-eval is ever needed
        n_fail = jnp.sum(~jnp.isfinite(scores)).astype(jnp.int32)
        return (st, new_u, k), (
            scores.max(), scores.mean(), n_fail, scores[src_idx], scores, u,
        )

    if objectives is not None:
        (state, unit, key), (
            best, mean, fails, gen_scores, pre_scores, pre_units, pre_mo, gen_mo
        ) = jax.lax.scan(one_generation, (state, unit, key), jnp.arange(generations))
        return (
            state, unit, key, best, mean, fails, gen_scores[-1],
            pre_scores, pre_units, pre_mo, gen_mo[-1],
        )

    (state, unit, key), (best, mean, fails, gen_scores, pre_scores, pre_units) = (
        jax.lax.scan(one_generation, (state, unit, key), jnp.arange(generations))
    )
    return state, unit, key, best, mean, fails, gen_scores[-1], pre_scores, pre_units


@functools.partial(
    jax.jit,
    static_argnames=("trainer", "discrete_mask", "cfg"),
    donate_argnames=("state", "unit"),
)
def finish_generation(
    trainer: PopulationTrainer,
    state: PopState,
    unit: jax.Array,
    key: jax.Array,  # the generation's PBT key
    val_x: jax.Array,
    val_y: jax.Array,
    discrete_mask: tuple = (),
    cfg: PBTConfig = PBTConfig(),
):
    """The generation-boundary program for step-chunked sweeps: eval the
    population, run exploit/explore, gather winner states — the tail of
    ``run_fused_pbt.one_generation`` without the training scan (which
    ran as separate ``train_segment`` launches). Returns
    (state, unit, best, mean, n_fail, post_exploit_scores, pre_scores,
    pre_unit) — the pre-exploit scores AND the unit matrix the
    generation trained with ride along for the fused ledger's
    per-member records, mirroring ``run_fused_pbt``'s stacked outputs
    (``unit`` is donated, so the caller must take the pre-exploit view
    from the OUTPUT, not its dead input reference)."""
    disc = jnp.asarray(discrete_mask, dtype=bool)
    scores = trainer.eval_population(state, val_x, val_y)
    new_u, src_idx, _ = pbt_exploit_explore(key, unit, scores, disc, cfg)
    state = trainer.gather_members(state, src_idx)
    n_fail = jnp.sum(~jnp.isfinite(scores)).astype(jnp.int32)
    return (
        state, new_u, scores.max(), scores.mean(), n_fail, scores[src_idx],
        scores, unit,
    )


@functools.partial(jax.jit, static_argnames=("discrete_mask", "cfg"))
def _wave_exploit(
    key: jax.Array,
    unit: jax.Array,  # float32[P, d] — the FULL population's hparams
    scores: jax.Array,  # float32[P] — all waves' pre-exploit scores
    discrete_mask: tuple = (),
    cfg: PBTConfig = PBTConfig(),
):
    """Generation-boundary decision for the wave-scheduled path: exactly
    the tail of ``run_fused_pbt.one_generation`` minus the eval (already
    done per wave) and minus the device gather — the winner-weight copy
    is realized LAZILY by the next generation's stage-in indexing the
    host pool with ``src_idx`` (train/staging.py), so exploit over a
    host-staged population still operates on full-population scores.
    Returns (new_unit, src_idx, best, mean, n_fail, post_scores)."""
    disc = jnp.asarray(discrete_mask, dtype=bool)
    new_u, src_idx, _ = pbt_exploit_explore(key, unit, scores, disc, cfg)
    n_fail = jnp.sum(~jnp.isfinite(scores)).astype(jnp.int32)
    return new_u, src_idx, scores.max(), scores.mean(), n_fail, scores[src_idx]


def _fused_pbt_waves(  # sweeplint: barrier(wave host loop: stages pools, gathers scores, exploits at generation boundaries)
    workload,
    trainer,
    space,
    train_x,
    train_y,
    val_x,
    val_y,
    population: int,
    generations: int,
    steps_per_gen: int,
    seed: int,
    cfg: PBTConfig,
    mesh,
    member_chunk: int,
    wave_size: int,
    checkpoint_dir,
    snapshot_every: int,
    snapshot_last: bool,
    ledger=None,
    warm_obs=None,
    oom_backoff: int = 0,
):
    """Wave-scheduled fused PBT: ``population > residency``.

    ``oom_backoff`` (ISSUE 13): on a device OOM during a generation's
    wave launches, halve the wave cap and RE-RUN the generation from
    its first wave, up to ``oom_backoff`` times — everything the re-run
    needs (pool_front, unit, perm, the generation's carried key) is
    still in host memory, reads of pool_front are non-destructive, and
    wave mode is bit-identical at ANY wave size, so backoff preserves
    result identity (tested). The settled-on cap is recorded in every
    snapshot's meta (``wave_size_run``) and adopted on resume — once a
    post-backoff snapshot lands, later resumes skip straight to the
    settled cap (a crash in the backoff-to-snapshot window re-learns
    the halving with a fresh budget; it converges, just not for free).

    Each generation trains ``ceil(P/W)`` resident waves of ~``W``
    members in sequence through the SAME compiled per-wave program
    (balanced split: at most two distinct wave sizes, so at most two
    compiles), staging cold members' params+momentum on host between
    waves, while exploit/explore at the generation boundary operates
    over the FULL population: scores are gathered across waves,
    truncation selection and perturbation run on all P members at once
    (``_wave_exploit``), and winners' weights reach the next
    generation's waves through the stage-in permutation.

    Semantics: bit-identical to resident mode for ANY wave size on the
    CPU backend (tested) — batch RNG is shared population-wide, member
    RNG windows the full split (``train_segment_window``), init keys
    slice the same ``split(k_init, P)``, and the exploit op sees the
    same (key, unit, scores) triple. On accelerators where different
    compiled shapes change float rounding this weakens to
    documented-equivalent, the ``step_chunk`` standard.

    Overlap: stage-out of wave k (device→host through this container's
    ~15 MB/s tunnel) runs on ``StagingEngine``'s background thread
    while the main thread dispatches wave k+1's stage-in + compute; the
    only hard barrier is ``drain()`` at the generation boundary, where
    the full score vector is needed. Device residency: at most two
    waves (one computing, one being fetched).

    Snapshots: generation-boundary on the ``snapshot_every`` cadence
    (post-exploit pool + perm + unit + key), plus BETWEEN-WAVES
    snapshots flushed by the graceful-shutdown drain at any wave
    boundary (front+back pools, partial scores, pre-generation key) —
    a preempted sweep resumes mid-generation without re-training
    completed waves.
    """
    import time

    import numpy as np

    from mpi_opt_tpu.parallel.mesh import fetch_global, place_pop
    from mpi_opt_tpu.train.common import HParamsFn
    from mpi_opt_tpu.train.staging import population_pool, write_rows
    from mpi_opt_tpu.utils.checkpoint import SweepCheckpointer

    # the REQUESTED cap is the sweep's config identity (stable across
    # resumes under the same flag); the EXECUTION cap (WaveRunner) may
    # shrink via OOM backoff, recorded per snapshot in meta (wave_size_run)
    req_wave_size = wave_size
    wave_lens, _, _ = _wave_layout(population, wave_size)
    disc = tuple(bool(b) for b in space.discrete_mask())
    hparams_fn = HParamsFn(space, workload)
    key = jax.random.key(seed)
    k_init, k_unit, k_run = jax.random.split(key, 3)
    # the SAME per-member init keys the resident program derives inside
    # init_population — gen-0 waves slice windows of this split
    member_keys = jax.random.split(k_init, population)

    best_list: list = []
    mean_list: list = []
    fail_list: list = []
    gen_walls: list = []
    start_gen = 0
    start_wave = 0
    scores_host = np.full((population,), np.nan, np.float32)
    post_scores = None
    pool_front = pool_back = None
    perm = None
    unit = None
    k_gen = None

    snap = None
    restored = None
    if checkpoint_dir is not None:
        import dataclasses

        snap = SweepCheckpointer(
            checkpoint_dir,
            {
                "workload": getattr(workload, "name", type(workload).__name__),
                "population": population,
                "generations": generations,
                "steps_per_gen": steps_per_gen,
                "seed": seed,
                "member_chunk": member_chunk,
                "cfg": dataclasses.asdict(cfg),
                "momentum_dtype": momentum_dtype_str(),
                # the wave split is part of the sweep's identity: the
                # snapshot payload is pool+perm shaped by it, and a
                # resident run must not silently resume a wave snapshot.
                # The REQUESTED cap, deliberately: an OOM backoff's
                # smaller execution cap lives in meta (wave_size_run),
                # so a resume under the same flag matches here and
                # adopts the settled cap below
                "wave_size": req_wave_size,
                "wave_lens": list(wave_lens),
            },
        )
        restored = snap.restore_wave_sweep()
        if restored is not None:
            sweep, meta = restored
            best_list = [float(v) for v in meta["best"]]
            mean_list = [float(v) for v in meta["mean"]]
            fail_list = [int(v) for v in meta["member_fail"]]
            gen_walls = [float(v) for v in meta["gen_walls"]]
            start_gen = int(meta["gen"])
            start_wave = int(meta["waves_done"])
            # adopt a prior attempt's OOM-settled cap: waves_done counts
            # waves of the split the snapshot was taken under, and
            # resuming at the requested size would re-OOM a generation
            # just to re-learn the answer
            run_ws = int(meta.get("wave_size_run", wave_size))
            if run_ws != wave_size:
                wave_size = run_ws
            pool_front = _writable(sweep["front"])
            perm = np.asarray(sweep["perm"])
            unit = jnp.asarray(sweep["unit"])
            restored_key = jax.random.wrap_key_data(jnp.asarray(sweep["key_data"]))
            if start_wave:
                # mid-generation: the saved key is the PRE-generation
                # carried key (k_train/k_pbt re-derive from it)
                k_gen = restored_key
                pool_back = _writable(sweep["back"])
                scores_host = np.array(sweep["scores"], np.float32)
            else:
                k_run = restored_key
                post_scores = np.asarray(sweep["scores"])
    journal = make_fused_journal(ledger, space)
    journal_require_prefix(journal, start_gen)
    if restored is None:
        unit = space.sample_unit(k_unit, population)
        if warm_obs:
            from mpi_opt_tpu.ledger.warmstart import best_observation

            bo = best_observation(warm_obs)
            if bo is not None:
                # same sampler-family seeding as the resident path
                unit = np.array(unit)
                unit[0] = np.asarray(bo.unit, dtype=unit.dtype)
                unit = jnp.asarray(unit)
        perm = np.arange(population)
        # the cold population's host residence; gen 0 fills it by
        # stage-out (members init on device per wave)
        pool_front = population_pool(trainer, train_x[:2], population)
    if pool_back is None:
        pool_back = population_pool(trainer, train_x[:2], population)
    if mesh is not None:
        unit = place_pop(unit, mesh)

    snapshot_every = max(1, snapshot_every)
    # the shared wave executor (train/engine.py) owns the StagingEngine,
    # the execution cap, and the OOM-backoff retry loop; the generation
    # loop below supplies only PBT's shapes (dispatch/payload/labels)
    # and boundary op
    runner = WaveRunner(population, wave_size, oom_backoff=oom_backoff)
    # per-generation FLOPs for the trace layer's achieved-TF/s (None
    # when tracing is off — the probe is never paid untraced)
    flops_gen = segment_flops_hint(workload, population, steps_per_gen)

    def _writer(off):
        def on_host(host):  # sweeplint: barrier(stage-out landing: writes fetched wave scores into the host pool)
            write_rows(pool_back, off, host["state"])
            w = len(host["scores"])
            scores_host[off : off + w] = np.asarray(host["scores"], np.float32)

        return on_host

    try:
        for g in range(start_gen, generations):
            t_gen = time.perf_counter()
            resumed_mid = g == start_gen and start_wave > 0
            gen_partial0 = 0.0
            if resumed_mid:
                # the interrupted generation's pre-crash elapsed time,
                # so its launch wall stays the launch's real cost
                gen_partial0 = float(restored[1].get("wall_partial", 0.0))
            else:
                k_gen = k_run
                scores_host[:] = np.nan
            # the carried-key chain matches run_fused_pbt.one_generation
            # exactly: next carry, train key, exploit key
            k_run, k_train, k_pbt = jax.random.split(k_gen, 3)

            def _dispatch(w, off, wl_, eng, g=g, k_train=k_train):
                # ``_run_wave`` resolved at call time (module global) so
                # the chaos drills' monkeypatch seam keeps working
                return _run_wave(
                    trainer,
                    pool_front,
                    perm[off : off + wl_],
                    off,
                    unit,
                    hparams_fn,
                    train_x,
                    train_y,
                    val_x,
                    val_y,
                    k_train,
                    steps_per_gen,
                    population,
                    mesh,
                    eng,
                    init_keys=member_keys[off : off + wl_] if g == 0 else None,
                    sample_x=train_x[:2],
                )

            def _payload(st, sc):
                return {
                    "state": {
                        "params": st.params,
                        "momentum": st.momentum,
                        "step": st.step,
                    },
                    "scores": sc,
                }

            def _stage_label(w, nw, g=g):
                return f"pbt gen {g + 1}/{generations} wave {w + 1}/{nw}"

            def _boundary_kwargs(w, nw, g=g):
                return {"launch": g * nw + w + 1, "of": generations * nw}

            def _midgen_snapshot(w, nw, g=g):
                def save_midgen():  # sweeplint: barrier(between-waves drain snapshot: fetches partial state for the checkpoint)
                    runner.engine.drain()  # pools must hold every completed wave
                    # COPY the pools: orbax's save is async, and the live
                    # buffers are mutated in place by later waves' stage-out
                    # writers — handing them over uncopied can tear the
                    # snapshot (same contract as the resident path's
                    # host-fetch-before-save)
                    snap.save(
                        g * nw + w + 1,
                        sweep={
                            "front": jax.tree.map(np.array, pool_front),
                            "back": jax.tree.map(np.array, pool_back),
                            "perm": np.asarray(perm),
                            "unit": fetch_global(unit),
                            "key_data": np.asarray(jax.random.key_data(k_gen)),
                            "scores": scores_host.copy(),
                        },
                        meta_extra={
                            "gen": g,
                            "waves_done": w + 1,
                            # a mid-generation snapshot completes no
                            # boundary: only g generations are journaled
                            "boundaries_done": g,
                            # the OOM-settled execution cap: waves_done
                            # counts waves of THIS split, and a resume
                            # must adopt it rather than re-OOM
                            "wave_size_run": runner.wave_size,
                            "best": best_list,
                            "mean": mean_list,
                            "member_fail": fail_list,
                            "gen_walls": gen_walls,
                            "wall_partial": time.perf_counter() - t_gen + gen_partial0,
                        },
                    )

                return save_midgen

            wave_scores = runner.run_interval(
                n=population,
                run_wave_fn=_dispatch,
                payload_fn=_payload,
                writer_fn=_writer,
                scores_host=scores_host,
                stage_label=_stage_label,
                boundary_kwargs=_boundary_kwargs,
                midpoint_snapshot=None if snap is None else _midgen_snapshot,
                span_attrs=lambda nw, g=g: {"launch": g + 1, "gens": 1, "waves": nw},
                flops=flops_gen,
                start_wave=start_wave if resumed_mid else 0,
                notify_fields=(("gen", g + 1),),
            )
            # the settled layout this generation actually ran under (an
            # absorbed OOM halved it): boundary numbering + snapshot meta
            n_waves = runner.n_waves
            # journal this generation's members (pre-exploit scores +
            # the units they trained with) BEFORE the boundary snapshot;
            # a resumed generation verifies instead of re-writing
            journal_boundary(
                journal,
                g,
                np.arange(population),
                fetch_global(unit),
                scores_host,
                step=(g + 1) * steps_per_gen,
            )
            scores_dev = jnp.concatenate([jnp.asarray(s) for s in wave_scores])
            with boundary_span("exploit", gen=g + 1):
                new_unit, src_idx, best, mean, n_fail, post = _wave_exploit(
                    k_pbt, unit, scores_dev, discrete_mask=disc, cfg=cfg
                )
                # the host conversions below ARE the exploit's completion
                # barrier — inside the span so its duration is real
                best_list.append(float(best))
                mean_list.append(float(mean))
                fail_list.append(int(n_fail))
                unit = new_unit
                perm = np.asarray(src_idx)
                post_scores = np.asarray(post)
            pool_front, pool_back = pool_back, pool_front
            gen_walls.append(time.perf_counter() - t_gen + gen_partial0)
            is_last = g + 1 == generations
            due = (g + 1) % snapshot_every == 0

            def save_boundary(g=g):  # sweeplint: barrier(generation-boundary snapshot: fetches pool + perm for the checkpoint)
                # COPY the pool: the async orbax write may still be in
                # flight when this buffer (pool_back after the swap) is
                # mutated in place by a LATER generation's stage-out
                # writers — an uncopied save can mix generations' rows
                # into one silently corrupt snapshot
                snap.save(
                    (g + 1) * n_waves,
                    sweep={
                        "front": jax.tree.map(np.array, pool_front),
                        "perm": np.asarray(perm),
                        "unit": fetch_global(unit),
                        "key_data": np.asarray(jax.random.key_data(k_run)),
                        "scores": post_scores,
                    },
                    meta_extra={
                        "gen": g + 1,
                        "waves_done": 0,
                        "boundaries_done": g + 1,
                        # the OOM-settled execution cap (adopted on resume)
                        "wave_size_run": runner.wave_size,
                        "best": best_list,
                        "mean": mean_list,
                        "member_fail": fail_list,
                        "gen_walls": gen_walls,
                    },
                )

            saved = False
            if snap is not None and ((due and not is_last) or (is_last and snapshot_last)):
                save_boundary()
                saved = True
            launch_boundary(
                f"pbt gen {g + 1}/{generations} wave {n_waves}/{n_waves}",
                final=is_last,
                snapshot=None if (snap is None or saved) else save_boundary,
                launch=(g + 1) * n_waves,
                of=generations * n_waves,
            )
    finally:
        runner.close()
        if snap is not None:
            snap.close()

    best_i, diverged = finite_winner(post_scores)
    np_unit = fetch_global(unit)
    # post-exploit population state, materialized on HOST (that is where
    # a beyond-residency population lives): winners' rows via the perm
    state = PopState(
        params=jax.tree.map(lambda l: l[perm], pool_front["params"]),
        momentum=jax.tree.map(lambda l: l[perm], pool_front["momentum"]),
        step=pool_front["step"][perm],
    )
    return {
        "best_score": float("nan") if diverged else float(post_scores[best_i]),
        "best_params": None if diverged else space.materialize_row(np_unit[best_i]),
        "diverged": diverged,
        "best_curve": np.asarray(best_list, dtype=np.float32),
        "mean_curve": np.asarray(mean_list, dtype=np.float32),
        "member_failures": [int(v) for v in fail_list],
        "state": state,
        "unit": np_unit,
        "launch_gens": [1] * generations,
        "launch_walls": [float(v) for v in gen_walls],
        # wave-scheduling observability (acceptance: staging must be
        # visible, not inferred) from the shared runner: the settled
        # EXECUTION split (after an OOM backoff it differs from the
        # requested cap, which is the point), halvings absorbed, bytes
        # moved, and how much transfer time the double buffer hid
        # behind compute
        **runner.result_extras(),
        "journal": None
        if journal is None
        else {"written": journal.written, "verified": journal.verified},
    }


def _run_stepped_generation(
    trainer,
    state,
    unit,
    hparams_fn,
    train_x,
    train_y,
    val_x,
    val_y,
    key,
    disc,
    steps: int,
    step_chunk: int,
    cfg: PBTConfig,
):
    """One PBT generation as ceil(steps/step_chunk) train launches plus
    one boundary launch — the sub-generation analogue of gen_chunk, for
    populations whose single-generation program exceeds a platform's
    execution window (PERF_NOTES.md: pop=512 x 100 steps ~fills this
    container's 60 s kill limit). Deterministic given (seed, step_chunk)
    but NOT bit-identical to the unchunked scan: sub-segment RNG keys
    are derived by folding the generation's train key, where the fused
    scan threads one key through all ``steps``. Return shapes match one
    ``run_fused_pbt(generations=1)`` launch.
    """
    from mpi_opt_tpu.health import heartbeat

    key, k_train, k_pbt = jax.random.split(key, 3)
    hp = hparams_fn(unit)
    sub_lens = _balanced_split(steps, step_chunk)
    for i, s in enumerate(sub_lens):
        state, _ = trainer.train_segment(
            state, hp, train_x, train_y, jax.random.fold_in(k_train, i), s
        )
        # sub-launch liveness (ROADMAP follow-up): each train sub-segment
        # beats, so launch.py's --stall-timeout can be sized to one
        # step_chunk instead of a whole generation's train_segment scan
        heartbeat.beat(stage=f"pbt train sub-launch {i + 1}/{len(sub_lens)}")
    with boundary_span("exploit"):
        state, unit, best, mean, n_fail, gen_scores, pre_scores, pre_unit = (
            finish_generation(
                trainer, state, unit, k_pbt, val_x, val_y, discrete_mask=disc, cfg=cfg
            )
        )
    return (
        state, unit, key, best[None], mean[None], n_fail[None], gen_scores,
        pre_scores[None], pre_unit[None],
    )


def fused_pbt(  # sweeplint: barrier(resident host loop: launch boundaries, exploit, journal, snapshot)
    workload,
    population: int,
    generations: int,
    steps_per_gen: int,
    seed: int = 0,
    cfg: PBTConfig = PBTConfig(),
    mesh=None,
    member_chunk: int = 0,
    gen_chunk: int = 0,
    step_chunk: int = 0,
    wave_size=0,
    checkpoint_dir: str = None,
    snapshot_every: int = 1,
    snapshot_last: bool = True,
    ledger=None,
    warm_obs=None,
    oom_backoff: int = 2,
    objectives=None,
):
    """Convenience wrapper: run a whole PBT sweep for a vision-style
    workload; optionally sharded over a ``('pop','data')`` mesh.

    ``objectives`` (an ``ObjectiveSpec``, ISSUE 17) runs the sweep
    multi-objective: the exploit selects by Pareto rank + crowding
    inside the compiled generation scan, records journal raw objective
    vectors beside their scalarized score, and the result carries the
    final population's Pareto front + hypervolume with a
    constraint-aware winner (typed ``selection``: feasible /
    least_violation / diverged). Resident + ``gen_chunk`` only — wave
    scheduling and ``step_chunk`` refuse (their boundary programs are
    scalar), and the objective names must come from the workload's
    ``objective_metrics()``.

    ``oom_backoff`` (wave mode; ISSUE 13): budget of automatic
    wave-size halvings on a device OOM — each absorbed OOM re-runs its
    generation at half the wave, bit-identically (0 disables; resident
    mode and an exhausted budget raise typed ``DeviceOOM``, which the
    CLI maps to the classified exit 74). With a MEASURED device budget
    (obs/memory.py) an explicit cap above the residency estimate is
    also pre-clamped before the first launch (``wave_resized``), so the
    common case never OOMs at all.

    ``ledger`` (an open ``SweepLedger`` whose fused header the CLI has
    already committed) journals one record per member per generation —
    pre-exploit score + the unit the member trained with — BEFORE that
    generation's snapshot saves; on resume, already-journaled
    generations are verified instead of re-written (ledger/fused.py).
    ``warm_obs`` (prior-ledger ``Observation``s, cross-mode) seeds the
    initial population's row 0 with the prior best point — the
    sampler-family warm-start semantic, matching driver random/ASHA.

    Returns a result dict with the best member's hparams and curves.
    (For FLOPs/MFU accounting of a sweep, call
    ``utils.flops.population_sweep_flops`` OUTSIDE any timed window —
    it lowers tiny probe programs, which must not count against a
    measurement; see bench.py.)

    ``gen_chunk`` splits the sweep into ceil(G/gen_chunk) launches
    (0 = whole sweep in one launch), sized near-equally so at most TWO
    distinct launch lengths exist — i.e. at most two compiled programs,
    exactly one when gen_chunk divides G. The population and the
    scan-carried RNG key thread through launches on-device, so a
    chunked sweep is BIT-IDENTICAL to a single launch (tested) and the
    steady-state cost is ~ms of dispatch per chunk. This exists because
    some environments bound single-program execution time (this
    container's tunneled TPU kills programs running longer than ~60s —
    measured 2026-07-30: pop=128 x 4 gens x 100 steps survives, 8 gens
    does not), and because big-G scans compile slower for no runtime
    benefit: generations are identical program text.

    ``checkpoint_dir`` makes the sweep crash-recoverable (SURVEY.md §5
    failure model; this container's TPU worker demonstrably dies
    mid-sweep): after every ``snapshot_every`` completed launches the
    carried (state, unit, key) is fetched to host and orbax-saved with
    the sweep config + curves. A fresh call with the same arguments and
    directory resumes at the last snapshot and — because the RNG key is
    part of the snapshot — finishes with the IDENTICAL result the
    uninterrupted sweep would have produced (tested). A checkpoint
    whose recorded config mismatches the call's raises ValueError.
    Host-fetching before the async save (rather than saving device
    buffers) is deliberate: the next launch donates the state buffers,
    which would invalidate them under orbax's background write.

    ``step_chunk`` splits each GENERATION's training into
    ceil(steps_per_gen/step_chunk) launches plus a boundary launch
    (eval + exploit) — the sub-generation analogue of ``gen_chunk``,
    needed when even ONE generation's program exceeds a platform's
    execution window (PERF_NOTES.md "single-chip population envelope":
    pop=512 x 100 steps ~fills this container's 60 s kill). Snapshots
    stay generation-granular. Unlike gen_chunk it is deterministic but
    NOT bit-identical to the unchunked sweep (sub-segment RNG keys are
    folded, not threaded), so it is recorded in the checkpoint config
    and a resume under a different step_chunk is refused. Mutually
    exclusive with gen_chunk > 1.

    ``snapshot_last=False`` skips the unconditional final-launch save.
    The final snapshot is what makes a completed sweep re-runnable
    without recompute (tested), but a caller that consumes the returned
    result immediately gets nothing from it — and on this container a
    pop=64 ResNet snapshot's host fetch costs ~6 minutes through the
    tunnel (PERF_NOTES.md), so benches turn it off.
    """
    import numpy as np

    from mpi_opt_tpu.parallel.mesh import fetch_global, shard_popstate
    from mpi_opt_tpu.train.common import workload_arrays

    if generations < 1:  # before any data/device work
        raise ValueError(f"generations must be >= 1, got {generations}")
    if step_chunk > 0 and gen_chunk > 1:
        raise ValueError(
            "step_chunk splits within generations; combining it with "
            f"gen_chunk={gen_chunk} (grouping whole generations) is ambiguous"
        )
    if objectives is not None:
        if step_chunk > 0:
            raise ValueError(
                "step_chunk is not supported with multi-objective sweeps "
                "(the sub-segment boundary program is scalar); use gen_chunk"
            )
        if wave_size:
            raise ValueError(
                "wave scheduling is not supported with multi-objective "
                "sweeps yet; run resident (wave_size=0) or shard the "
                "population over a mesh"
            )
        supported = tuple(workload.objective_metrics())
        missing = [n for n in objectives.names if n not in supported]
        if missing:
            raise ValueError(
                f"workload {getattr(workload, 'name', '?')!r} cannot "
                f"evaluate objectives {missing}; supported: {supported}"
            )
    trainer, space, train_x, train_y, val_x, val_y = workload_arrays(
        workload, member_chunk, mesh
    )
    # wave scheduling (population > residency): resolve the cap through
    # the shared engine door (``auto`` estimation, explicit pre-clamp,
    # multi-process refusal — train/engine.py), then hand off to the
    # host-staged driver. A cap at or above the population means
    # everything fits — resident mode, the bit-identical baseline.
    if wave_size:
        wave_size = resolve_wave_size(
            trainer,
            train_x[:2],
            population,
            wave_size=wave_size,
            mesh=mesh,
            oom_backoff=oom_backoff,
        )
        if 0 < wave_size < population:
            if step_chunk > 0 or gen_chunk > 1:
                raise ValueError(
                    "wave_size schedules whole generations as resident "
                    "waves; combining it with gen_chunk/step_chunk launch "
                    "splitting is ambiguous"
                )
            return _fused_pbt_waves(
                workload,
                trainer,
                space,
                train_x,
                train_y,
                val_x,
                val_y,
                population,
                generations,
                steps_per_gen,
                seed,
                cfg,
                mesh,
                member_chunk,
                wave_size,
                checkpoint_dir,
                snapshot_every,
                snapshot_last,
                ledger,
                warm_obs,
                oom_backoff=oom_backoff,
            )
    key = jax.random.key(seed)
    k_init, k_unit, k_run = jax.random.split(key, 3)

    disc = tuple(bool(b) for b in space.discrete_mask())
    if step_chunk > 0:
        gen_chunk = 1  # every launch is (part of) exactly one generation
    g_chunk = generations if gen_chunk <= 0 else min(gen_chunk, generations)
    # balanced split (e.g. G=3, chunk=2 -> [2, 1]; G=7, chunk=3 ->
    # [3, 2, 2]): a non-dividing chunk costs one extra compile, never more
    launch_lens = _balanced_split(generations, g_chunk)
    n_launches = len(launch_lens)

    # restore BEFORE initializing: a resumed sweep must not pay (or
    # transiently hold the memory of) a full-population init it discards
    snap = None
    restored = None
    start_launch = 0
    best_parts, mean_parts = [], []
    fail_parts: list = []  # per-gen diverged-member counts per launch
    fails_complete = True  # False when resuming a pre-tally snapshot
    launch_walls: list = []  # seconds per completed launch (excl. snapshot saves)
    walls_complete = True  # False when resuming a pre-duration-recording snapshot
    scores = None
    # final [P, m] raw objective matrix (MO only); None until a launch of
    # THIS process completes — a resume that starts past the last launch
    # leaves it None and the Pareto summary falls back to the ledger
    np_final_mo = None
    if checkpoint_dir is not None:
        import dataclasses

        from mpi_opt_tpu.utils.checkpoint import SweepCheckpointer

        ck_config = {
            "workload": getattr(workload, "name", type(workload).__name__),
            "population": population,
            "generations": generations,
            "steps_per_gen": steps_per_gen,
            "seed": seed,
            "launch_lens": launch_lens,
            "member_chunk": member_chunk,
            # PBT knobs change exploit/explore behavior: resuming under
            # a different cfg would not be the continuation we promise
            "cfg": dataclasses.asdict(cfg),
            # step_chunk changes the RNG derivation (folded sub-segment
            # keys), i.e. the trajectory itself — not just the launch
            # split the way gen_chunk does
            "step_chunk": step_chunk,
            # the momentum STORAGE dtype is part of the carried state's
            # structure: resuming a bf16-momentum snapshot into an f32
            # trainer would crash in the scan carry (or silently change
            # numerics) instead of refusing cleanly here
            "momentum_dtype": momentum_dtype_str(),
            # resident mode is wave_size=0; a wave-scheduled snapshot
            # (different payload: host pools + perm) must be refused
            # here, not crash in PopState reconstruction
            "wave_size": 0,
        }
        if objectives is not None:
            # objective identity is part of the trajectory (selection
            # pressure differs per spec); scalar sweeps never write the
            # key, so every pre-existing snapshot still resumes
            ck_config["objectives"] = objectives.spec()
        snap = SweepCheckpointer(checkpoint_dir, ck_config)
        restored = snap.restore_population_sweep()
        if restored is not None:
            state, unit, k_run, scores, meta = restored
            best_parts = [np.asarray(v, dtype=np.float32) for v in meta["best"]]
            mean_parts = [np.asarray(v, dtype=np.float32) for v in meta["mean"]]
            start_launch = int(meta["launches_done"])
            # per-launch durations (not cumulative timestamps): they stay
            # meaningful across a crash/resume, where the sweep's wall
            # clock is discontinuous but each launch's cost is real. A
            # snapshot from before durations were recorded has none for
            # its completed launches; mark the set incomplete rather
            # than inventing values (the result then reports
            # launch_walls=None and consumers fall back to whole-sweep
            # prorating)
            if "launch_walls" in meta:
                launch_walls = [float(w) for w in meta["launch_walls"]]
            else:
                walls_complete = False
            # same pre-upgrade rule as launch_walls: a snapshot written
            # before member-failure tallies existed cannot supply the
            # completed launches' counts — report None, never invent
            if "member_fail" in meta:
                fail_parts = [np.asarray(v, dtype=np.int32) for v in meta["member_fail"]]
            else:
                fails_complete = False
    journal = make_fused_journal(ledger, space)
    # resume gate: every generation the snapshot records complete must
    # already be journaled (journal-before-snapshot ordering); the
    # re-trained generations past the snapshot verify against their
    # records instead of re-writing
    journal_require_prefix(journal, sum(launch_lens[:start_launch]))
    if restored is None:
        unit = space.sample_unit(k_unit, population)
        if warm_obs:
            from mpi_opt_tpu.ledger.warmstart import best_observation

            bo = best_observation(warm_obs)
            if bo is not None:
                # sampler-family warm start: one population row starts
                # at the prior sweep's best point; PBT's exploit/explore
                # spreads it if it earns its keep
                unit = np.array(unit)
                unit[0] = np.asarray(bo.unit, dtype=unit.dtype)
                unit = jax.numpy.asarray(unit)
        state = trainer.init_population(k_init, train_x[:2], population)
    if mesh is not None:
        from mpi_opt_tpu.parallel.mesh import place_pop

        # datasets were already replicated over the mesh by workload_arrays
        state = shard_popstate(state, mesh)
        unit = place_pop(unit, mesh)

    # hparams_fn must be hashable-static; space comes from the per-
    # workload cache above so its identity is stable across calls
    from mpi_opt_tpu.train.common import HParamsFn

    hparams_fn = HParamsFn(space, workload)

    snapshot_every = max(1, snapshot_every)
    import time

    # per-generation FLOPs for the trace layer's achieved-TF/s spans
    # (None when tracing is off — the probe is never paid untraced)
    flops_gen = segment_flops_hint(workload, population, steps_per_gen)
    try:
        for i in range(start_launch, n_launches):
            profiling.launch_tick()
            t_launch = time.perf_counter()
            # the launch's train span covers dispatch AND the curve
            # fetches (the launch completion barrier), so dur_s is the
            # launch's real wall and flops/dur_s is achieved TF/s.
            # Resident mode has no wave to halve: the funnel's DeviceOOM
            # propagates to the CLI's classified exit (74) instead of an
            # unclassified traceback launch.py would burn retries on
            with oom_funnel(), trace.span(
                "train", launch=i + 1, gens=launch_lens[i]
            ) as _sp:
                if objectives is not None:
                    # mark MO launches in the trace (registered span
                    # attr); selection still runs inside this same
                    # program — no extra host-sync span appears
                    _sp["objectives"] = ",".join(objectives.names)
                # chaos seam (inject_oom): one guarded launch ordinal; a
                # synthetic RESOURCE_EXHAUSTED here classifies exactly
                # like a real warmup OOM (the staging.py docstring's
                # pop=1024 death shape) — typed via the funnel above
                resources.launch_fault("launch")
                if step_chunk > 0:
                    # one generation as k sub-segment launches + a boundary
                    # launch; the carried key advances exactly once per gen
                    state, unit, k_run, best, mean, fails, final_scores, pre_s, pre_u = _run_stepped_generation(
                        trainer,
                        state,
                        unit,
                        hparams_fn,
                        train_x,
                        train_y,
                        val_x,
                        val_y,
                        k_run,
                        disc,
                        steps_per_gen,
                        step_chunk,
                        cfg,
                    )
                elif objectives is not None:
                    # the MO program journals the raw objective matrix per
                    # generation besides the scalarized curve; selection
                    # already happened on-device via pareto_score
                    state, unit, k_run, best, mean, fails, final_scores, pre_s, pre_u, pre_mo, final_mo = run_fused_pbt(
                        trainer,
                        state,
                        unit,
                        hparams_fn,
                        train_x=train_x,
                        train_y=train_y,
                        val_x=val_x,
                        val_y=val_y,
                        key=k_run,
                        discrete_mask=disc,
                        generations=launch_lens[i],
                        steps_per_gen=steps_per_gen,
                        cfg=cfg,
                        objectives=objectives,
                    )
                else:
                    # k_run is the scan-carried key returned by the previous
                    # launch: the chain continues exactly as one longer scan
                    # would
                    state, unit, k_run, best, mean, fails, final_scores, pre_s, pre_u = run_fused_pbt(
                        trainer,
                        state,
                        unit,
                        hparams_fn,
                        train_x=train_x,
                        train_y=train_y,
                        val_x=val_x,
                        val_y=val_y,
                        key=k_run,
                        discrete_mask=disc,
                        generations=launch_lens[i],
                        steps_per_gen=steps_per_gen,
                        cfg=cfg,
                    )
                # curves to host eagerly: they are tiny, and a later crash
                # must not lose completed launches' history (fetch_global:
                # under multi-process SPMD these are global arrays)
                best_parts.append(fetch_global(best))
                mean_parts.append(fetch_global(mean))
                fail_parts.append(fetch_global(fails))
                scores = fetch_global(final_scores)
                if objectives is not None:
                    np_final_mo = fetch_global(final_mo)
                # flops only after the fetch barrier completed: a launch
                # that raised mid-span emits its partial duration
                # WITHOUT the attr (no inflated TF/s from partial work)
                if flops_gen:
                    _sp["flops"] = flops_gen * launch_lens[i]
                # post-barrier device-memory watermark (obs/memory.py):
                # resident population + activations just peaked
                memory.note(_sp)
            # the fetches above are the launch's completion barrier
            # (block_until_ready is unreliable under the axon plugin —
            # PERF_NOTES.md), so the duration is measured AFTER them and
            # BEFORE any snapshot save
            launch_walls.append(time.perf_counter() - t_launch)
            if journal is not None:
                # journal this launch's generations BEFORE its snapshot
                # (the boundary ordering contract); re-trained
                # generations of a resume verify instead of re-writing
                np_pre_s = fetch_global(pre_s)
                np_pre_u = fetch_global(pre_u)
                np_pre_mo = (
                    fetch_global(pre_mo) if objectives is not None else None
                )
                gens_before = sum(launch_lens[:i])
                for j in range(launch_lens[i]):
                    g = gens_before + j
                    journal_boundary(
                        journal,
                        g,
                        np.arange(population),
                        np_pre_u[j],
                        np_pre_s[j],
                        step=(g + 1) * steps_per_gen,
                        scores_mo=None if np_pre_mo is None else np_pre_mo[j],
                    )
            is_last = i + 1 == n_launches
            due = (i + 1) % snapshot_every == 0

            def save_now(i=i):
                meta_extra = {
                    "launches_done": i + 1,
                    # the ledger cross-check unit (fsck, resume gate):
                    # generations complete at this snapshot
                    "boundaries_done": sum(launch_lens[: i + 1]),
                    "best": [v.tolist() for v in best_parts],
                    "mean": [v.tolist() for v in mean_parts],
                }
                if fails_complete:
                    # an incomplete set must stay absent (see launch_walls)
                    meta_extra["member_fail"] = [v.tolist() for v in fail_parts]
                if walls_complete:
                    # an incomplete set must stay absent: writing the
                    # post-resume tail alone would misalign the NEXT
                    # resume's restore
                    meta_extra["launch_walls"] = [float(w) for w in launch_walls]
                snap.save_population_sweep(
                    i + 1, state, unit, k_run, scores, meta_extra=meta_extra
                )

            # save when a mid-sweep save comes due, or at the final
            # launch when the caller wants the completed-sweep snapshot
            saved = False
            if snap is not None and ((due and not is_last) or (is_last and snapshot_last)):
                save_now()
                saved = True
            # heartbeat + graceful-shutdown drain: a preemption flushes
            # an off-cadence snapshot (if checkpointing and the cadence
            # save didn't just run) so --resume loses no launches
            launch_boundary(
                f"pbt launch {i + 1}/{n_launches}",
                final=is_last,
                snapshot=None if (snap is None or saved) else save_now,
                launch=i + 1,
                of=n_launches,
            )
    finally:
        if snap is not None:
            snap.close()
    best = np.concatenate(best_parts)
    mean = np.concatenate(mean_parts)
    # a diverged member (NaN, or +/-inf from an exploded loss) must not
    # hijack the winner via argmax's first-NaN behavior — shared rule:
    # train.common.finite_winner; an all-diverged population reports
    # best_params=None with diverged=True
    best_i, diverged = finite_winner(scores)
    np_unit = fetch_global(unit)
    pareto = None
    if objectives is not None and np_final_mo is not None:
        from mpi_opt_tpu.objectives import (
            hypervolume,
            pareto_front_mask,
            select_best,
        )

        # constraint-aware winner override: "best" under objectives is
        # the best FEASIBLE member (typed degradation to the
        # least-violating one when none is feasible — never a crash)
        sel = select_best(np_final_mo, objectives)
        if sel["index"] is None:
            best_i, diverged = 0, True
        else:
            best_i, diverged = int(sel["index"]), False
        norm = objectives.normalize(np_final_mo)
        mask = pareto_front_mask(norm)
        front_members = [int(i) for i in np.flatnonzero(mask)]
        pareto = {
            "front_size": len(front_members),
            "front_members": front_members,
            "front_scores": [
                [float(v) for v in np_final_mo[i]] for i in front_members
            ],
            "hypervolume": float(hypervolume(norm[mask])) if front_members else 0.0,
            "selection": sel["kind"],
            "violation": sel["violation"],
        }
    return {
        # diverged normalizes to NaN (not a raw +/-inf row) so library
        # callers can detect it uniformly across fused SHA/PBT/TPE
        "best_score": float("nan") if diverged else float(scores[best_i]),
        "best_params": None if diverged else space.materialize_row(np_unit[best_i]),
        "diverged": diverged,
        "best_curve": np.asarray(best),
        "mean_curve": np.asarray(mean),
        # per-generation diverged-member tallies (ROADMAP open item):
        # how many members each exploit step silently replaced for
        # non-finite scores. None when a pre-upgrade snapshot left the
        # completed launches' counts unknown
        "member_failures": (
            [int(v) for v in np.concatenate(fail_parts)] if fails_complete else None
        ),
        "state": state,
        "unit": np_unit,
        # measured per-launch durations + generation split, for
        # launch-granular wall-to-target (utils.metrics); on a resumed
        # sweep, pre-crash launches' durations come from the snapshot.
        # None when a pre-upgrade snapshot left earlier durations
        # unknown — callers fall back to wall_to_target
        "launch_gens": launch_lens,
        "launch_walls": [float(w) for w in launch_walls] if walls_complete else None,
        # ledger observability: how many member records this run
        # appended vs re-verified on resume (None = no ledger active)
        "journal": None
        if journal is None
        else {"written": journal.written, "verified": journal.verified},
        # multi-objective extras (ISSUE 17): the final population's
        # non-dominated front + hypervolume and how the winner was
        # selected (feasible / least_violation / diverged). None on
        # scalar sweeps, and on a resume that restarted past the final
        # launch (the final objective matrix lives in the ledger then —
        # ``report`` recomputes the front from journaled vectors)
        "objectives": None if objectives is None else list(objectives.names),
        "pareto": pareto,
    }
