"""Fully-fused on-device PBT: whole sweeps as one XLA program.

This is the performance thesis of the framework (BASELINE.json
north_star: PBT exploit/explore "become lax.top_k/psum over a device
mesh instead of MPI_Allgather"). The generic driver path (host PBT +
TPU backend) round-trips tiny score arrays once per generation; this
module removes even that: a ``lax.scan`` over generations where each
iteration trains the population (itself a scan of vmapped steps),
evaluates it, runs exploit/explore, and gathers winner states — all
inside a single jit. The host launches one computation and gets back
the final population + per-generation score curves.

Works unchanged on a sharded population: launch with a mesh-sharded
PopState (parallel/mesh.py) and XLA partitions the whole loop,
inserting the all_gathers for the ranking/gather steps over ICI.

Why fused beats the reference's architecture (and our own host loop):
- zero host↔device sync per generation (the reference pays an
  MPI_Allgather + Python decision per rank per generation);
- XLA overlaps the next generation's first steps with the previous
  exploit gather where dependencies allow;
- hyperparameters are data, so G generations of mutated schedules cost
  one compile.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from mpi_opt_tpu.ops.pbt import PBTConfig, pbt_exploit_explore
from mpi_opt_tpu.train.common import finite_winner, launch_boundary, momentum_dtype_str
from mpi_opt_tpu.train.population import OptHParams, PopState, PopulationTrainer


@functools.partial(
    jax.jit,
    static_argnames=("trainer", "hparams_fn", "discrete_mask", "generations", "steps_per_gen", "cfg"),
    donate_argnames=("state", "unit"),
)
def run_fused_pbt(
    trainer: PopulationTrainer,
    state: PopState,
    unit: jax.Array,  # float32[P, d] initial hparams (unit cube)
    hparams_fn: Callable,  # unit matrix -> OptHParams (static, hashable)
    train_x: jax.Array = None,
    train_y: jax.Array = None,
    val_x: jax.Array = None,
    val_y: jax.Array = None,
    key: jax.Array = None,
    discrete_mask: tuple = (),
    generations: int = 10,
    steps_per_gen: int = 100,
    cfg: PBTConfig = PBTConfig(),
):
    """Returns (state, unit, key', best_curve[G], mean_curve[G],
    member_fail[G], final_scores[P]).

    ``member_fail`` counts the PRE-exploit members whose eval came back
    non-finite each generation — the divergence the exploit step then
    masks by replacing losers with winners. Tallied in-scan (one int32
    per generation) so reporting it costs no extra fetch.

    ``key'`` is the scan-carried RNG key after ``generations`` steps of
    the chain — feeding it into a following call continues the EXACT
    trajectory one longer call would have taken, which is what makes
    ``gen_chunk`` launch-splitting bit-identical to a single launch.
    """
    if generations < 1:  # static arg: raises at trace time, not opaquely later
        raise ValueError(f"generations must be >= 1, got {generations}")
    disc = jnp.asarray(discrete_mask, dtype=bool)

    def one_generation(carry, g):
        st, u, k = carry
        k, k_train, k_pbt = jax.random.split(k, 3)
        hp = hparams_fn(u)
        st, _ = trainer.train_segment(st, hp, train_x, train_y, k_train, steps_per_gen)
        scores = trainer.eval_population(st, val_x, val_y)
        new_u, src_idx, _ = pbt_exploit_explore(k_pbt, u, scores, disc, cfg)
        st = trainer.gather_members(st, src_idx)
        # the post-exploit population's scores are exactly the gathered
        # pre-exploit scores (weights are copied verbatim, eval is
        # deterministic) — so no final re-eval is ever needed
        n_fail = jnp.sum(~jnp.isfinite(scores)).astype(jnp.int32)
        return (st, new_u, k), (scores.max(), scores.mean(), n_fail, scores[src_idx])

    (state, unit, key), (best, mean, fails, gen_scores) = jax.lax.scan(
        one_generation, (state, unit, key), jnp.arange(generations)
    )
    return state, unit, key, best, mean, fails, gen_scores[-1]


def _balanced_split(total: int, chunk: int) -> list[int]:
    """Split ``total`` into ceil(total/chunk) near-equal parts (lengths
    differ by at most 1, so at most two distinct compiled program
    lengths exist). Shared by gen_chunk (generations per launch) and
    step_chunk (steps per sub-launch); total=0 yields [0] — one empty
    part, matching the unchunked path's empty-scan behavior."""
    if total <= 0:
        return [0]
    n_parts = -(-total // chunk)
    base, rem = divmod(total, n_parts)
    return [base + 1] * rem + [base] * (n_parts - rem)


@functools.partial(
    jax.jit,
    static_argnames=("trainer", "discrete_mask", "cfg"),
    donate_argnames=("state", "unit"),
)
def finish_generation(
    trainer: PopulationTrainer,
    state: PopState,
    unit: jax.Array,
    key: jax.Array,  # the generation's PBT key
    val_x: jax.Array,
    val_y: jax.Array,
    discrete_mask: tuple = (),
    cfg: PBTConfig = PBTConfig(),
):
    """The generation-boundary program for step-chunked sweeps: eval the
    population, run exploit/explore, gather winner states — the tail of
    ``run_fused_pbt.one_generation`` without the training scan (which
    ran as separate ``train_segment`` launches). Returns
    (state, unit, best, mean, n_fail, post_exploit_scores)."""
    disc = jnp.asarray(discrete_mask, dtype=bool)
    scores = trainer.eval_population(state, val_x, val_y)
    new_u, src_idx, _ = pbt_exploit_explore(key, unit, scores, disc, cfg)
    state = trainer.gather_members(state, src_idx)
    n_fail = jnp.sum(~jnp.isfinite(scores)).astype(jnp.int32)
    return state, new_u, scores.max(), scores.mean(), n_fail, scores[src_idx]


def _run_stepped_generation(
    trainer,
    state,
    unit,
    hparams_fn,
    train_x,
    train_y,
    val_x,
    val_y,
    key,
    disc,
    steps: int,
    step_chunk: int,
    cfg: PBTConfig,
):
    """One PBT generation as ceil(steps/step_chunk) train launches plus
    one boundary launch — the sub-generation analogue of gen_chunk, for
    populations whose single-generation program exceeds a platform's
    execution window (PERF_NOTES.md: pop=512 x 100 steps ~fills this
    container's 60 s kill limit). Deterministic given (seed, step_chunk)
    but NOT bit-identical to the unchunked scan: sub-segment RNG keys
    are derived by folding the generation's train key, where the fused
    scan threads one key through all ``steps``. Return shapes match one
    ``run_fused_pbt(generations=1)`` launch.
    """
    key, k_train, k_pbt = jax.random.split(key, 3)
    hp = hparams_fn(unit)
    sub_lens = _balanced_split(steps, step_chunk)
    for i, s in enumerate(sub_lens):
        state, _ = trainer.train_segment(
            state, hp, train_x, train_y, jax.random.fold_in(k_train, i), s
        )
    state, unit, best, mean, n_fail, gen_scores = finish_generation(
        trainer, state, unit, k_pbt, val_x, val_y, discrete_mask=disc, cfg=cfg
    )
    return state, unit, key, best[None], mean[None], n_fail[None], gen_scores


def fused_pbt(
    workload,
    population: int,
    generations: int,
    steps_per_gen: int,
    seed: int = 0,
    cfg: PBTConfig = PBTConfig(),
    mesh=None,
    member_chunk: int = 0,
    gen_chunk: int = 0,
    step_chunk: int = 0,
    checkpoint_dir: str = None,
    snapshot_every: int = 1,
    snapshot_last: bool = True,
):
    """Convenience wrapper: run a whole PBT sweep for a vision-style
    workload; optionally sharded over a ``('pop','data')`` mesh.

    Returns a result dict with the best member's hparams and curves.
    (For FLOPs/MFU accounting of a sweep, call
    ``utils.flops.population_sweep_flops`` OUTSIDE any timed window —
    it lowers tiny probe programs, which must not count against a
    measurement; see bench.py.)

    ``gen_chunk`` splits the sweep into ceil(G/gen_chunk) launches
    (0 = whole sweep in one launch), sized near-equally so at most TWO
    distinct launch lengths exist — i.e. at most two compiled programs,
    exactly one when gen_chunk divides G. The population and the
    scan-carried RNG key thread through launches on-device, so a
    chunked sweep is BIT-IDENTICAL to a single launch (tested) and the
    steady-state cost is ~ms of dispatch per chunk. This exists because
    some environments bound single-program execution time (this
    container's tunneled TPU kills programs running longer than ~60s —
    measured 2026-07-30: pop=128 x 4 gens x 100 steps survives, 8 gens
    does not), and because big-G scans compile slower for no runtime
    benefit: generations are identical program text.

    ``checkpoint_dir`` makes the sweep crash-recoverable (SURVEY.md §5
    failure model; this container's TPU worker demonstrably dies
    mid-sweep): after every ``snapshot_every`` completed launches the
    carried (state, unit, key) is fetched to host and orbax-saved with
    the sweep config + curves. A fresh call with the same arguments and
    directory resumes at the last snapshot and — because the RNG key is
    part of the snapshot — finishes with the IDENTICAL result the
    uninterrupted sweep would have produced (tested). A checkpoint
    whose recorded config mismatches the call's raises ValueError.
    Host-fetching before the async save (rather than saving device
    buffers) is deliberate: the next launch donates the state buffers,
    which would invalidate them under orbax's background write.

    ``step_chunk`` splits each GENERATION's training into
    ceil(steps_per_gen/step_chunk) launches plus a boundary launch
    (eval + exploit) — the sub-generation analogue of ``gen_chunk``,
    needed when even ONE generation's program exceeds a platform's
    execution window (PERF_NOTES.md "single-chip population envelope":
    pop=512 x 100 steps ~fills this container's 60 s kill). Snapshots
    stay generation-granular. Unlike gen_chunk it is deterministic but
    NOT bit-identical to the unchunked sweep (sub-segment RNG keys are
    folded, not threaded), so it is recorded in the checkpoint config
    and a resume under a different step_chunk is refused. Mutually
    exclusive with gen_chunk > 1.

    ``snapshot_last=False`` skips the unconditional final-launch save.
    The final snapshot is what makes a completed sweep re-runnable
    without recompute (tested), but a caller that consumes the returned
    result immediately gets nothing from it — and on this container a
    pop=64 ResNet snapshot's host fetch costs ~6 minutes through the
    tunnel (PERF_NOTES.md), so benches turn it off.
    """
    import numpy as np

    from mpi_opt_tpu.parallel.mesh import fetch_global, shard_popstate
    from mpi_opt_tpu.train.common import workload_arrays

    if generations < 1:  # before any data/device work
        raise ValueError(f"generations must be >= 1, got {generations}")
    if step_chunk > 0 and gen_chunk > 1:
        raise ValueError(
            "step_chunk splits within generations; combining it with "
            f"gen_chunk={gen_chunk} (grouping whole generations) is ambiguous"
        )
    trainer, space, train_x, train_y, val_x, val_y = workload_arrays(
        workload, member_chunk, mesh
    )
    key = jax.random.key(seed)
    k_init, k_unit, k_run = jax.random.split(key, 3)

    disc = tuple(bool(b) for b in space.discrete_mask())
    if step_chunk > 0:
        gen_chunk = 1  # every launch is (part of) exactly one generation
    g_chunk = generations if gen_chunk <= 0 else min(gen_chunk, generations)
    # balanced split (e.g. G=3, chunk=2 -> [2, 1]; G=7, chunk=3 ->
    # [3, 2, 2]): a non-dividing chunk costs one extra compile, never more
    launch_lens = _balanced_split(generations, g_chunk)
    n_launches = len(launch_lens)

    # restore BEFORE initializing: a resumed sweep must not pay (or
    # transiently hold the memory of) a full-population init it discards
    snap = None
    restored = None
    start_launch = 0
    best_parts, mean_parts = [], []
    fail_parts: list = []  # per-gen diverged-member counts per launch
    fails_complete = True  # False when resuming a pre-tally snapshot
    launch_walls: list = []  # seconds per completed launch (excl. snapshot saves)
    walls_complete = True  # False when resuming a pre-duration-recording snapshot
    scores = None
    if checkpoint_dir is not None:
        import dataclasses

        from mpi_opt_tpu.utils.checkpoint import SweepCheckpointer

        snap = SweepCheckpointer(
            checkpoint_dir,
            {
                "workload": getattr(workload, "name", type(workload).__name__),
                "population": population,
                "generations": generations,
                "steps_per_gen": steps_per_gen,
                "seed": seed,
                "launch_lens": launch_lens,
                "member_chunk": member_chunk,
                # PBT knobs change exploit/explore behavior: resuming under
                # a different cfg would not be the continuation we promise
                "cfg": dataclasses.asdict(cfg),
                # step_chunk changes the RNG derivation (folded sub-segment
                # keys), i.e. the trajectory itself — not just the launch
                # split the way gen_chunk does
                "step_chunk": step_chunk,
                # the momentum STORAGE dtype is part of the carried state's
                # structure: resuming a bf16-momentum snapshot into an f32
                # trainer would crash in the scan carry (or silently change
                # numerics) instead of refusing cleanly here
                "momentum_dtype": momentum_dtype_str(),
            },
        )
        restored = snap.restore_population_sweep()
        if restored is not None:
            state, unit, k_run, scores, meta = restored
            best_parts = [np.asarray(v, dtype=np.float32) for v in meta["best"]]
            mean_parts = [np.asarray(v, dtype=np.float32) for v in meta["mean"]]
            start_launch = int(meta["launches_done"])
            # per-launch durations (not cumulative timestamps): they stay
            # meaningful across a crash/resume, where the sweep's wall
            # clock is discontinuous but each launch's cost is real. A
            # snapshot from before durations were recorded has none for
            # its completed launches; mark the set incomplete rather
            # than inventing values (the result then reports
            # launch_walls=None and consumers fall back to whole-sweep
            # prorating)
            if "launch_walls" in meta:
                launch_walls = [float(w) for w in meta["launch_walls"]]
            else:
                walls_complete = False
            # same pre-upgrade rule as launch_walls: a snapshot written
            # before member-failure tallies existed cannot supply the
            # completed launches' counts — report None, never invent
            if "member_fail" in meta:
                fail_parts = [np.asarray(v, dtype=np.int32) for v in meta["member_fail"]]
            else:
                fails_complete = False
    if restored is None:
        unit = space.sample_unit(k_unit, population)
        state = trainer.init_population(k_init, train_x[:2], population)
    if mesh is not None:
        from mpi_opt_tpu.parallel.mesh import place_pop

        # datasets were already replicated over the mesh by workload_arrays
        state = shard_popstate(state, mesh)
        unit = place_pop(unit, mesh)

    # hparams_fn must be hashable-static; space comes from the per-
    # workload cache above so its identity is stable across calls
    from mpi_opt_tpu.train.common import HParamsFn

    hparams_fn = HParamsFn(space, workload)

    snapshot_every = max(1, snapshot_every)
    import time

    try:
        for i in range(start_launch, n_launches):
            t_launch = time.perf_counter()
            if step_chunk > 0:
                # one generation as k sub-segment launches + a boundary
                # launch; the carried key advances exactly once per gen
                state, unit, k_run, best, mean, fails, final_scores = _run_stepped_generation(
                    trainer,
                    state,
                    unit,
                    hparams_fn,
                    train_x,
                    train_y,
                    val_x,
                    val_y,
                    k_run,
                    disc,
                    steps_per_gen,
                    step_chunk,
                    cfg,
                )
            else:
                # k_run is the scan-carried key returned by the previous
                # launch: the chain continues exactly as one longer scan
                # would
                state, unit, k_run, best, mean, fails, final_scores = run_fused_pbt(
                    trainer,
                    state,
                    unit,
                    hparams_fn,
                    train_x=train_x,
                    train_y=train_y,
                    val_x=val_x,
                    val_y=val_y,
                    key=k_run,
                    discrete_mask=disc,
                    generations=launch_lens[i],
                    steps_per_gen=steps_per_gen,
                    cfg=cfg,
                )
            # curves to host eagerly: they are tiny, and a later crash
            # must not lose completed launches' history (fetch_global:
            # under multi-process SPMD these are global arrays)
            best_parts.append(fetch_global(best))
            mean_parts.append(fetch_global(mean))
            fail_parts.append(fetch_global(fails))
            scores = fetch_global(final_scores)
            # the fetches above are the launch's completion barrier
            # (block_until_ready is unreliable under the axon plugin —
            # PERF_NOTES.md), so the duration is measured AFTER them and
            # BEFORE any snapshot save
            launch_walls.append(time.perf_counter() - t_launch)
            is_last = i + 1 == n_launches
            due = (i + 1) % snapshot_every == 0

            def save_now(i=i):
                meta_extra = {
                    "launches_done": i + 1,
                    "best": [v.tolist() for v in best_parts],
                    "mean": [v.tolist() for v in mean_parts],
                }
                if fails_complete:
                    # an incomplete set must stay absent (see launch_walls)
                    meta_extra["member_fail"] = [v.tolist() for v in fail_parts]
                if walls_complete:
                    # an incomplete set must stay absent: writing the
                    # post-resume tail alone would misalign the NEXT
                    # resume's restore
                    meta_extra["launch_walls"] = [float(w) for w in launch_walls]
                snap.save_population_sweep(
                    i + 1, state, unit, k_run, scores, meta_extra=meta_extra
                )

            # save when a mid-sweep save comes due, or at the final
            # launch when the caller wants the completed-sweep snapshot
            saved = False
            if snap is not None and ((due and not is_last) or (is_last and snapshot_last)):
                save_now()
                saved = True
            # heartbeat + graceful-shutdown drain: a preemption flushes
            # an off-cadence snapshot (if checkpointing and the cadence
            # save didn't just run) so --resume loses no launches
            launch_boundary(
                f"pbt launch {i + 1}/{n_launches}",
                final=is_last,
                snapshot=None if (snap is None or saved) else save_now,
                launch=i + 1,
                of=n_launches,
            )
    finally:
        if snap is not None:
            snap.close()
    best = np.concatenate(best_parts)
    mean = np.concatenate(mean_parts)
    # a diverged member (NaN, or +/-inf from an exploded loss) must not
    # hijack the winner via argmax's first-NaN behavior — shared rule:
    # train.common.finite_winner; an all-diverged population reports
    # best_params=None with diverged=True
    best_i, diverged = finite_winner(scores)
    np_unit = fetch_global(unit)
    return {
        # diverged normalizes to NaN (not a raw +/-inf row) so library
        # callers can detect it uniformly across fused SHA/PBT/TPE
        "best_score": float("nan") if diverged else float(scores[best_i]),
        "best_params": None if diverged else space.materialize_row(np_unit[best_i]),
        "diverged": diverged,
        "best_curve": np.asarray(best),
        "mean_curve": np.asarray(mean),
        # per-generation diverged-member tallies (ROADMAP open item):
        # how many members each exploit step silently replaced for
        # non-finite scores. None when a pre-upgrade snapshot left the
        # completed launches' counts unknown
        "member_failures": (
            [int(v) for v in np.concatenate(fail_parts)] if fails_complete else None
        ),
        "state": state,
        "unit": np_unit,
        # measured per-launch durations + generation split, for
        # launch-granular wall-to-target (utils.metrics); on a resumed
        # sweep, pre-crash launches' durations come from the snapshot.
        # None when a pre-upgrade snapshot left earlier durations
        # unknown — callers fall back to wall_to_target
        "launch_gens": launch_lens,
        "launch_walls": [float(w) for w in launch_walls] if walls_complete else None,
    }
