"""One fault-tolerant fused engine: the wave/stage/drain/OOM skeleton.

Every fused driver (PBT, SHA, TPE, BOHB — ``train/fused_*.py``) used to
hand-copy the same robustness machinery: wave scheduling through host
pools when the population exceeds device residency, double-buffered
async stage-out, the generation/rung/batch retry loop that halves the
wave cap on a device OOM (``--oom-backoff``), per-wave heartbeats,
between-waves graceful-drain service points, and the drain barrier at
every algorithm boundary. This module is that skeleton written ONCE,
parameterized by the algorithm's boundary op — PBT truncation-exploit,
SHA/BOHB rung cut, TPE/BOHB batch re-suggest — so a robustness contract
(bit-identical backoff re-runs, boundary-granular journaling, verified
snapshot resume, sub-launch liveness) lands for all four algorithms the
day it is written instead of four diverging times.

The division of labor:

- ``WaveRunner.run_interval`` owns ONE algorithm interval (a PBT
  generation, an SHA rung, a TPE batch) executed as resident waves:
  the wave loop, per-wave heartbeat + stage-out, between-waves
  ``launch_boundary`` drain points, the interval-ending drain barrier,
  and the DeviceOOM wave-halving retry. The caller supplies closures
  for everything algorithm-shaped: how to dispatch a wave, what to
  stage out, where scores land, how labels/snapshots are built.
- ``run_wave`` stages in + trains + evals one wave — the one function
  the chaos drills intercept (``resources.launch_fault("wave")`` is its
  first line, so OOM/crash injection covers every algorithm for free).
- ``resolve_wave_size`` is the single sizing door: ``auto`` estimation,
  the uniform pre-clamp of explicit caps against the measured residency
  estimate, and the multi-process refusal — identical behavior for
  every ``--wave-size``-capable algorithm.
- ``boundary_span`` wraps an algorithm's boundary op in a traced span
  that ALSO heartbeats from inside it, so ``launch.py`` stall events
  can say "stalled during boundary:rung_cut" instead of naming the
  last train phase.

Bit-identity contract (the PERF_NOTES round-6 moral): every transform
feeding an RNG decision stays inside jit. ``_wave_train_program``
applies the unit→hparams mapping IN-program for the drivers whose
resident path does (PBT, TPE); ``_wave_train_hp_program`` accepts
pre-mapped hparams for SHA, whose resident rung loop maps them eagerly
— each wave path reproduces ITS resident twin bit-for-bit on the CPU
backend for any wave size (tested).
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp

from mpi_opt_tpu.obs import memory, trace
from mpi_opt_tpu.train.common import launch_boundary, oom_funnel
from mpi_opt_tpu.train.population import PopState
from mpi_opt_tpu.utils import profiling, resources


def balanced_split(total: int, chunk: int) -> list[int]:
    """Split ``total`` into ceil(total/chunk) near-equal parts (lengths
    differ by at most 1, so at most two distinct compiled program
    lengths exist). Shared by wave scheduling and the PBT gen_chunk /
    step_chunk launch splitting; total=0 yields [0] — one empty part,
    matching the unchunked path's empty-scan behavior."""
    if total <= 0:
        return [0]
    n_parts = -(-total // chunk)
    base, rem = divmod(total, n_parts)
    return [base + 1] * rem + [base] * (n_parts - rem)


def wave_layout(population: int, wave_size: int):
    """(wave_lens, offs, n_waves) for a wave cap — recomputed in place
    when the OOM backoff halves the cap mid-run."""
    wave_lens = balanced_split(population, wave_size)
    offs = [0]
    for w in wave_lens[:-1]:
        offs.append(offs[-1] + w)
    return wave_lens, offs, len(wave_lens)


def engine_rollover(old):
    """Fresh StagingEngine carrying the old one's cumulative accounting
    (results and trace attrs report RUN totals): after a device OOM the
    old engine may hold a latched transfer error — ``device_get`` of a
    never-materialized wave fails on the worker thread — which would
    refuse every later ``stage_out`` on sight."""
    from mpi_opt_tpu.train.staging import StagingEngine

    old.close()
    new = StagingEngine()
    new.staged_bytes = old.staged_bytes
    new.transfers = old.transfers
    new.transfer_s = old.transfer_s
    new.wait_s = old.wait_s
    return new


def writable(tree):
    """Orbax restores may hand back read-only numpy arrays; the pools
    are written in place per wave, so copy only the leaves that need it."""
    import numpy as np

    return jax.tree.map(
        lambda l: l if isinstance(l, np.ndarray) and l.flags.writeable else np.array(l),
        tree,
    )


@contextlib.contextmanager
def boundary_span(op: str, **attrs):
    """Trace an algorithm's boundary op (exploit / rung_cut / suggest)
    AND heartbeat from inside it: the beat records the span's phase
    (``boundary:<op>``, obs/trace.py), so a rank that stalls inside the
    boundary — a wedged cross-host gather during the cut, a hung
    acquisition — is attributed to THAT op by launch.py's stall report
    instead of to whatever train phase beat last."""
    from mpi_opt_tpu.health import heartbeat

    with trace.span("boundary", op=op, **attrs) as sp:
        heartbeat.beat(stage=f"boundary {op}")
        yield sp


def resolve_wave_size(trainer, sample_x, population: int, *, wave_size, mesh=None, oom_backoff: int = 0) -> int:
    """Resolve a requested wave cap (``'auto'`` or int) for a
    ``population``-member fused sweep — the ONE sizing door every
    wave-capable driver goes through, so ``auto`` estimation, the
    pre-clamp of explicit caps, and the multi-process cap agreement
    cannot drift between algorithms.

    Returns the resolved integer cap; 0 (or a cap >= population) means
    resident mode, the bit-identical baseline. With ``oom_backoff``
    enabled and a MEASURED device budget (obs/memory.py), an explicit
    cap above the residency estimate is pre-clamped (``wave_resized``
    event) so the common case never pays an OOM to learn the answer.

    Under multi-process SPMD (an active ``parallel/coord.py`` plane),
    each rank sizes against ITS host's budget and then the settled cap
    is min-agreed through the control plane — all ranks must run the
    same wave schedule or their collectives diverge, and the most
    memory-constrained host is the binding one.
    """
    if not wave_size:
        return 0
    from mpi_opt_tpu.train.staging import estimate_wave_size

    was_auto = wave_size == "auto"
    if was_auto:
        wave_size = estimate_wave_size(trainer, sample_x, population, mesh)
        if wave_size < population:
            # the pre-launch headroom clamp engaged: auto sized the
            # wave from the measured budget (or its fallbacks)
            # BEFORE the first OOM — record it as an event, not a
            # silent number (ISSUE 13)
            resources.notify(
                "wave_resized",
                requested="auto",
                wave_size=int(wave_size),
                population=population,
            )
    wave_size = int(wave_size)
    if wave_size < 0:
        raise ValueError(f"wave_size must be >= 0, got {wave_size}")
    if oom_backoff and not was_auto and 0 < wave_size < population:
        from mpi_opt_tpu.obs import memory as obs_memory

        # EXPLICIT cap vs MEASURED headroom (auto already sized from
        # the estimate — re-deriving it here would compare the estimate
        # against itself for a wasted eval_shape pass; and never clamp
        # against the 8 GiB default — shrinking a hand-picked cap on a
        # guess would surprise, the measured bytes_limit is evidence):
        # shrink before the first OOM instead of paying one
        if obs_memory.measured_budget() is not None:
            est = estimate_wave_size(trainer, sample_x, population, mesh)
            if est < wave_size:
                resources.notify(
                    "wave_resized",
                    requested=wave_size,
                    wave_size=est,
                    population=population,
                )
                wave_size = est
    from mpi_opt_tpu.parallel import coord

    plane = coord.active_plane()
    if plane is not None and 0 < wave_size:
        # every rank proposes its locally-settled cap (a cap at or
        # above the population still constrains a peer that sized
        # smaller, so it votes its true value, clamped to resident);
        # min-agreement picks the most constrained host's answer.
        # Without a plane a multi-process run still proceeds — SPMD
        # ranks derive identical caps from identical code on
        # homogeneous hosts — but heterogeneous budgets and OOM
        # absorption need the agreement (the backoff handler refuses
        # to halve unilaterally).
        agreed = plane.agree_cap("wave_cap", min(wave_size, population))
        if agreed and agreed != wave_size:
            resources.notify(
                "wave_resized",
                requested=wave_size,
                wave_size=agreed,
                population=population,
                agreed=True,
            )
            wave_size = agreed
    return wave_size


@functools.partial(
    jax.jit,
    static_argnames=("trainer", "hparams_fn", "steps", "n_total"),
    donate_argnames=("state",),
)
def _wave_train_program(
    trainer, state, unit_slice, hparams_fn, train_x, train_y, key, steps, n_total, offset
):
    """One wave's training launch, with the unit->hparams mapping
    applied IN-program. Applying it eagerly instead looks harmless but
    is not: eager op-by-op kernels and fused XLA codegen disagree by
    ~1e-7 relative on the log-uniform transforms, and the augmentation's
    DISCRETE decisions (rounded shift offsets, bernoulli flips) amplify
    an ulp of hparam difference into entirely different batches —
    measured as 1e-2 param divergence within 4 steps. In-program hp is
    what makes wave mode reproduce the resident scan bit-for-bit for
    the drivers (PBT, TPE) whose resident program maps in-scan."""
    hp = hparams_fn(unit_slice)
    return type(trainer)._train_segment_window(
        trainer, state, hp, train_x, train_y, key, steps, n_total, offset
    )


@functools.partial(
    jax.jit,
    static_argnames=("trainer", "steps", "n_total"),
    donate_argnames=("state",),
)
def _wave_train_hp_program(
    trainer, state, hp_slice, train_x, train_y, key, steps, n_total, offset
):
    """The eager-hparams twin of ``_wave_train_program``, for SHA: the
    resident rung loop maps unit->hparams EAGERLY before its
    ``train_segment`` call, so the wave path must hand this program the
    SAME eagerly-mapped values (sliced to the wave's rows — slicing is
    exact) to be bit-identical to it. Mapping in-program here would
    reproduce a program the resident SHA never ran."""
    return type(trainer)._train_segment_window(
        trainer, state, hp_slice, train_x, train_y, key, steps, n_total, offset
    )


def run_wave(
    trainer,
    pool,
    rows,
    offset: int,
    unit,
    hparams_fn,
    train_x,
    train_y,
    val_x,
    val_y,
    k_train,
    steps: int,
    population: int,
    mesh,
    engine,
    init_keys=None,
    sample_x=None,
    hp=None,
):
    """Stage in + train + eval ONE wave: members [offset, offset+W) of
    the interval's cohort. ``rows`` is the host-pool row index array and
    already carries the previous boundary's gather map (PBT's exploit
    sources, SHA's rung survivors), so staging in IS the winner gather.
    A cohort's first interval passes ``init_keys`` instead (members
    don't exist yet — initializing on device skips a pointless host
    round trip; the keys are the same ``split(k_init, P)`` window the
    resident program would use, so the weights are bit-identical).

    ``hp`` switches to the eager-hparams program (SHA parity, see
    ``_wave_train_hp_program``); the default maps ``unit`` rows
    in-program (PBT/TPE parity). Module-level so crash-injection tests
    can intercept it — the adapters re-export it as ``_run_wave``."""
    from mpi_opt_tpu.train.staging import stage_in, tree_bytes

    # chaos seam (inject_oom): one guarded launch ordinal per wave —
    # raises a synthetic RESOURCE_EXHAUSTED at the drilled wave, which
    # the interval's oom_funnel classifies exactly like a real one.
    # Living HERE means every algorithm's waves inherit the drill seam.
    resources.launch_fault("wave")
    w = len(rows)
    if init_keys is not None:
        st = trainer.init_members(init_keys, sample_x)
        if mesh is not None:
            from mpi_opt_tpu.parallel.mesh import shard_popstate

            st = shard_popstate(st, mesh)
    else:
        with trace.span("stage_in", members=w) as sp:
            dev = stage_in(pool, rows, mesh)
            n_bytes = tree_bytes(dev)
            sp["bytes"] = n_bytes
            memory.note(sp)
        engine.note_bytes(n_bytes)
        st = PopState(params=dev["params"], momentum=dev["momentum"], step=dev["step"])
    if hp is not None:
        hp_slice = jax.tree.map(lambda v: v[offset : offset + w], hp)
        st, _ = _wave_train_hp_program(
            trainer,
            st,
            hp_slice,
            train_x,
            train_y,
            k_train,
            steps,
            population,
            jnp.int32(offset),
        )
    else:
        st, _ = _wave_train_program(
            trainer,
            st,
            unit[offset : offset + w],
            hparams_fn,
            train_x,
            train_y,
            k_train,
            steps,
            population,
            jnp.int32(offset),
        )
    scores = trainer.eval_population(st, val_x, val_y)
    return st, scores


class WaveRunner:
    """The shared wave-scheduling executor: owns the StagingEngine
    lifecycle, the current (possibly OOM-halved) wave cap, and the
    backoff budget, and runs each algorithm interval — a PBT
    generation, an SHA rung, a TPE batch — through the one wave loop.

    ``wave_size`` here is the EXECUTION cap: it starts at the resolved
    request (or a snapshot's adopted ``wave_size_run``) and halves on
    absorbed OOMs; the REQUESTED cap stays the sweep's config identity
    in each driver's checkpoint config. After ``run_interval`` returns,
    ``wave_size`` / ``wave_lens`` / ``offs`` / ``n_waves`` reflect the
    settled layout the interval actually ran under — callers read them
    for snapshot meta (``wave_size_run``), step numbering, and result
    reporting.
    """

    def __init__(self, population: int, wave_size: int, *, oom_backoff: int = 0):
        from mpi_opt_tpu.train.staging import StagingEngine

        self.population = int(population)
        self.wave_size = int(wave_size)
        self.oom_budget = max(0, int(oom_backoff))
        self.oom_backoffs = 0
        self.waves_run = 0  # cumulative across intervals AND retries
        self.engine = StagingEngine()
        self.wave_lens, self.offs, self.n_waves = wave_layout(
            self.population, self.wave_size
        )

    def adopt(self, wave_size_run) -> None:
        """Adopt a snapshot's OOM-settled execution cap (meta
        ``wave_size_run``): waves_done in that snapshot counts waves of
        the settled split, and resuming at the requested size would
        re-OOM an interval just to re-learn the answer."""
        self.wave_size = int(wave_size_run)
        self.wave_lens, self.offs, self.n_waves = wave_layout(
            self.population, self.wave_size
        )

    def close(self) -> None:
        self.engine.close()

    def run_interval(  # sweeplint: barrier(wave interval loop: stages pools, gathers wave scores, drains at the algorithm boundary)
        self,
        *,
        n: int,
        run_wave_fn,
        payload_fn,
        writer_fn,
        scores_host,
        stage_label,
        boundary_kwargs=None,
        midpoint_snapshot=None,
        span_attrs=None,
        flops=None,
        start_wave: int = 0,
        notify_fields=(),
    ):
        """Run ONE algorithm interval (``n`` cohort members) as resident
        waves; returns the per-wave device score arrays in wave order.

        The caller parameterizes the algorithm-shaped parts:

        - ``run_wave_fn(w, off, wl, engine) -> (state, scores)``
          dispatches wave ``w`` (usually a closure over the adapter
          module's patchable ``_run_wave`` seam);
        - ``payload_fn(state, scores) -> tree`` is what the background
          thread stages out (PBT/SHA fetch the trained states into the
          back pool; TPE discards states and fetches scores only);
        - ``writer_fn(off) -> callback`` lands a fetched payload into
          host memory — it MUST fill ``scores_host[off:off+w]``, the
          NaN-initialized accumulator mid-interval resume and the OOM
          re-run both reset and re-read;
        - ``stage_label(w, n_waves)`` / ``boundary_kwargs(w, n_waves)``
          / ``midpoint_snapshot(w, n_waves)`` shape the per-wave
          heartbeat, the between-waves ``launch_boundary`` progress
          fields, and the optional graceful-drain snapshot closure;
        - ``span_attrs(n_waves)`` shapes the interval's train span.

        ``start_wave`` (mid-interval snapshot resume) skips completed
        waves, reconstituting their scores from ``scores_host`` — f32
        round-trips host storage exactly, so the reconstructed device
        arrays equal the originals.

        On a classified DeviceOOM with budget remaining, the interval
        re-runs from wave 0 under a halved cap (``oom_backoff``): pool
        reads are non-destructive, the caller's interval keys are
        already derived, and wave scheduling is bit-identical at ANY
        wave size, so the re-run reproduces the interval exactly — the
        engine is rolled over (a latched transfer error would refuse
        every later stage-out) and an ``oom_backoff`` event is
        notified with the caller's ``notify_fields`` identifying the
        interval. Budget exhausted (or cap already 1) re-raises for the
        CLI's classified exit.
        """
        import numpy as np

        from mpi_opt_tpu.health import heartbeat
        from mpi_opt_tpu.parallel import coord

        while True:  # one iteration per OOM-backoff attempt
            wave_lens, offs, n_waves = wave_layout(n, self.wave_size)
            self.wave_lens, self.offs, self.n_waves = wave_lens, offs, n_waves
            wave_scores: list = [None] * n_waves
            w0 = start_wave
            for w in range(w0):
                off, wl = offs[w], wave_lens[w]
                # completed waves' scores round-trip exactly (f32)
                wave_scores[w] = jnp.asarray(scores_host[off : off + wl])

            def _train_interval(
                w0=w0, wave_scores=wave_scores, wave_lens=wave_lens,
                offs=offs, n_waves=n_waves,
            ):
                for w in range(w0, n_waves):
                    off, wl = offs[w], wave_lens[w]
                    st, sc = run_wave_fn(w, off, wl, self.engine)
                    wave_scores[w] = sc
                    self.waves_run += 1
                    # per-wave liveness: beat as soon as the wave's
                    # programs are dispatched, so a stall timeout sized
                    # to one wave also covers the interval's LAST wave
                    # (whose next boundary beat waits on the full drain
                    # + boundary op)
                    heartbeat.beat(stage=f"{stage_label(w, n_waves)} dispatched")
                    # async stage-out: the background fetch blocks on
                    # THIS wave's compute while the loop dispatches the
                    # next wave
                    self.engine.stage_out(payload_fn(st, sc), writer_fn(off))
                    if w + 1 < n_waves:
                        # between-waves service point: heartbeat +
                        # graceful drain, with a mid-interval snapshot
                        # when the algorithm supports one (completed
                        # waves are never re-trained on resume)
                        launch_boundary(
                            stage_label(w, n_waves),
                            final=False,
                            snapshot=(
                                None
                                if midpoint_snapshot is None
                                else midpoint_snapshot(w, n_waves)
                            ),
                            **(
                                {}
                                if boundary_kwargs is None
                                else boundary_kwargs(w, n_waves)
                            ),
                        )
                # interval boundary: the ONLY hard transfer barrier —
                # the boundary op needs the full score vector and a
                # settled pool
                self.engine.drain()

            # the interval's train span covers every wave dispatch AND
            # the drain barrier, so its duration is the interval's real
            # compute+transfer wall; nested stage_in/stage_out/
            # stage_wait/save spans subtract from its self time.
            # ``flops`` makes the trace CLI report achieved TF/s per
            # interval. The oom_funnel classifies an XLA
            # RESOURCE_EXHAUSTED escaping any wave into typed DeviceOOM
            # for the backoff below.
            profiling.launch_tick()
            try:
                with oom_funnel(self.wave_size):
                    with trace.span(
                        "train",
                        **({"waves": n_waves} if span_attrs is None else span_attrs(n_waves)),
                    ) as sp:
                        _train_interval()
                        # flops only AFTER the drain barrier completed:
                        # an interval interrupted between waves emits
                        # its real partial duration WITHOUT the attr, so
                        # the trace CLI never divides full-interval
                        # FLOPs by partial wall
                        if flops:
                            sp["flops"] = flops
                        # post-drain device-memory watermark: the
                        # interval's peak residency (two waves +
                        # activations) just happened
                        memory.note(sp)
                local_oom = None
            except resources.DeviceOOM as e:
                if self.oom_budget <= 0 or self.wave_size <= 1:
                    # no wave left to halve (or backoff disabled):
                    # the classified answer propagates — CLI exit 74.
                    # Under a coord plane the peers waiting at this
                    # interval's agreement barrier wedge out on their
                    # timeout and exit too — the supervisor's
                    # coordinated restart is the recovery either way
                    raise
                if coord.active_plane() is None and jax.process_count() > 1:
                    # halving unilaterally would put this rank on a
                    # different wave schedule than its peers; without
                    # the control plane the only coordinated recovery
                    # is a job-level restart
                    raise
                local_oom = e

            # OOM agreement (multi-process SPMD): one barrier per
            # interval attempt on EVERY rank — a clean rank votes cap 0
            # ("no local constraint"), an OOMed rank votes its halved
            # cap; min-agreement means the whole cohort absorbs the
            # most constrained rank's halving together, so budgets and
            # wave schedules stay lockstep. Without a plane the local
            # proposal stands (single-process: local IS global).
            proposed = 0 if local_oom is None else max(1, self.wave_size // 2)
            plane = coord.active_plane()
            agreed = plane.agree_cap("oom", proposed) if plane is not None else proposed
            if not agreed:
                return wave_scores
            self.oom_budget -= 1
            self.oom_backoffs += 1
            # settle what completed; a transfer that died WITH
            # the OOM latched its error in the engine — roll it
            # over (accounting carried) so re-run stage-outs
            # aren't refused on sight
            try:
                self.engine.drain()
            # sweeplint: disable=drain-swallow -- settling in-flight transfers before the backoff re-run: the error here is the same already-classified OOM this handler is absorbing, and the engine is rolled over fresh below
            except BaseException:
                pass
            self.engine = engine_rollover(self.engine)
            self.wave_size = agreed
            # re-run THIS interval from wave 0 under the new split:
            # pool reads are non-destructive, the interval's keys
            # are already derived, and rewritten pool rows carry
            # identical values — bit-identity is preserved
            scores_host[:] = np.nan
            start_wave = 0
            resources.notify(
                "oom_backoff",
                **dict(notify_fields),
                wave_size=self.wave_size,
                remaining=self.oom_budget,
                error=(
                    str(local_oom)[:300]
                    if local_oom is not None
                    else "agreed backoff: device OOM on a peer rank"
                ),
            )
            continue

    def result_extras(self) -> dict:
        """The wave-observability result fields every wave-scheduled
        driver reports (acceptance: staging must be visible, not
        inferred): the settled execution split, absorbed OOM halvings,
        bytes moved, and how much of the transfer time the double
        buffer hid behind compute."""
        return {
            "wave_size": self.wave_size,
            "wave_lens": list(self.wave_lens),
            "n_waves": self.n_waves,
            # n_waves/wave_lens are the LAST interval's settled layout
            # (SHA's rungs shrink); waves_run counts every wave actually
            # dispatched, backoff re-runs included
            "waves_run": self.waves_run,
            "oom_backoffs": self.oom_backoffs,
            "staged_bytes": int(self.engine.staged_bytes),
            "stage_transfer_s": float(self.engine.transfer_s),
            "stage_wait_s": float(self.engine.wait_s),
            "stage_overlap_s": float(self.engine.overlap_s),
        }
