"""Population training: the vmapped train-step machinery."""

from mpi_opt_tpu.train.population import OptHParams, PopulationTrainer, PopState

__all__ = ["OptHParams", "PopulationTrainer", "PopState"]

# fused sweep drivers (import lazily where cycles matter):
#   mpi_opt_tpu.train.fused_pbt.fused_pbt — whole PBT sweep in one jit
#   mpi_opt_tpu.train.fused_asha.fused_sha — per-rung device programs
