"""Population training: the vmapped train-step machinery."""

from mpi_opt_tpu.train.population import OptHParams, PopulationTrainer, PopState

__all__ = ["OptHParams", "PopulationTrainer", "PopState"]
