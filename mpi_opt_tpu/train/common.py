"""Shared plumbing for the fused (whole-sweep-on-device) drivers."""

from __future__ import annotations

import jax.numpy as jnp


def workload_arrays(workload, member_chunk: int = 0, mesh=None):
    """(trainer, space, train_x, train_y, val_x, val_y) for a population
    workload, cached on the workload instance.

    The trainer/space are static jit args (identity-hashed), so
    rebuilding them per call would make every fused invocation a
    guaranteed retrace; the device arrays ride along so the dataset is
    uploaded once per search. ``mesh`` is part of the cache key: a
    meshed trainer constrains its batches over the 'data' axis, which
    changes the compiled program.
    """
    cache = getattr(workload, "_fused_cache", None)
    if cache is None or cache[0] != (member_chunk, mesh):
        d = workload.data()
        workload._fused_cache = (
            (member_chunk, mesh),
            workload.make_trainer(member_chunk=member_chunk, mesh=mesh),
            workload.default_space(),
            jnp.asarray(d["train_x"]),
            jnp.asarray(d["train_y"]),
            jnp.asarray(d["val_x"]),
            jnp.asarray(d["val_y"]),
        )
    return workload._fused_cache[1:]
