"""Shared plumbing for the fused (whole-sweep-on-device) drivers."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mpi_opt_tpu.obs import trace


def workload_arrays(workload, member_chunk: int = 0, mesh=None):
    """(trainer, space, train_x, train_y, val_x, val_y) for a population
    workload, cached on the workload instance.

    The trainer/space are static jit args (identity-hashed), so
    rebuilding them per call would make every fused invocation a
    guaranteed retrace; the device arrays ride along so the dataset is
    uploaded once per search. ``mesh`` is part of the cache key: a
    meshed trainer constrains its batches over the 'data' axis, which
    changes the compiled program — and with a mesh the datasets come
    back replicated across it (every shard samples the same shared
    minibatch; the trainer's in-program constraint then splits each
    batch over 'data'). This is the single placement point for fused
    sweep data — don't re-place at call sites.
    """
    from mpi_opt_tpu.workloads.base import resolve_momentum_dtype

    # the momentum-dtype knob changes the trainer make_trainer builds;
    # it must be part of the cache key or flipping it mid-process
    # silently reuses the stale-dtype trainer. Resolved ONCE and passed
    # down, so the key and the built trainer cannot disagree
    mdt = resolve_momentum_dtype()
    key = (member_chunk, mesh, mdt)
    cache = getattr(workload, "_fused_cache", None)
    if cache is None or cache[0] != key:
        # setup span: dataset load + upload + trainer build — the cold
        # pre-first-launch time the trace CLI must attribute (it is part
        # of time-to-first-trial, and invisible without a span)
        with trace.span("setup", workload=getattr(workload, "name", None)) as sp:
            # device kind keys the roofline's platform-cap calibration
            trace.note_device(sp)
            d = workload.data()
            arrays = (
                jnp.asarray(d["train_x"]),
                jnp.asarray(d["train_y"]),
                jnp.asarray(d["val_x"]),
                jnp.asarray(d["val_y"]),
            )
            if mesh is not None:
                from mpi_opt_tpu.parallel.mesh import replicate

                rep = replicate(mesh)
                arrays = tuple(jax.device_put(a, rep) for a in arrays)
            workload._fused_cache = (
                key,
                workload.make_trainer(
                    member_chunk=member_chunk, mesh=mesh, momentum_dtype=mdt
                ),
                workload.default_space(),
                *arrays,
            )
    return workload._fused_cache[1:]


def finite_winner(scores, ok=None):
    """(best_i, diverged) for a host score vector: the argmax over
    finite (and ``ok``-masked) entries, with argmax's first-NaN behavior
    gated out — the numpy-level twin of ``algorithms.base.best_finite``,
    shared by the fused SHA/PBT/TPE winner picks so the divergence rule
    lives in ONE place. An all-diverged set returns (0, True): callers
    report best_params=None and a non-finite best_score."""
    import numpy as np

    scores = np.asarray(scores)
    mask = np.isfinite(scores) if ok is None else (np.asarray(ok) & np.isfinite(scores))
    diverged = not bool(mask.any())
    best_i = 0 if diverged else int(np.where(mask, scores, -np.inf).argmax())
    return best_i, diverged


def momentum_dtype_str() -> str:
    """Checkpoint-config form of the momentum storage dtype ('float32'
    default). Part of every fused sweep's config-mismatch check: the
    dtype is carried-state STRUCTURE — resuming a bf16-momentum snapshot
    into an f32 trainer would crash in the scan carry (or silently
    change numerics) instead of refusing cleanly."""
    from mpi_opt_tpu.workloads.base import resolve_momentum_dtype

    return resolve_momentum_dtype() or "float32"


def oom_funnel(wave_size=None):
    """The fused drivers' device-OOM classification boundary (ISSUE 13):
    wrap a launch dispatch so an XLA ``RESOURCE_EXHAUSTED`` escaping it
    re-raises as ``utils.resources.DeviceOOM`` — the ONE type the CLI's
    classified exit (``EX_IOERR``) and the wave scheduler's
    ``--oom-backoff`` handler catch. All four fused drivers classify
    through this door (run_fused wraps the whole dispatch; the shared
    wave engine — train/engine.py, all algorithms — additionally
    guards each wave so backoff can catch per generation/rung/batch);
    everything else propagates raw. ``wave_size`` rides on the typed
    error so diagnostics can say what to halve."""
    from mpi_opt_tpu.utils.resources import oom_funnel as _funnel

    return _funnel(wave_size)


def launch_boundary(stage: str, *, final: bool, snapshot=None, **progress) -> None:
    """The fused host loops' per-launch service point (one call at the
    end of every launch/rung/generation): write the rank heartbeat, then
    honor a pending graceful-shutdown request — flush the boundary
    snapshot via ``snapshot()`` (pass None when the cadence save already
    ran, or the sweep doesn't checkpoint) and raise ``SweepInterrupted``
    so the CLI exits EX_TEMPFAIL and the launch supervisor restarts with
    ``--resume`` for free. ``final=True`` (the sweep's last boundary)
    suppresses the drain: completing normally strictly dominates
    preempting a finished sweep.

    This is also the cooperative-slice point for the resident sweep
    service (service/scheduler.py): an installed slice hook
    (``shutdown.set_slice_hook``) gets its per-boundary look FIRST and
    may set the very drain flag checked next — so a time-sliced tenant
    parks through the identical flush-snapshot-and-raise path a
    platform SIGTERM takes, and its ledger/snapshot state cannot
    differ from a preempted run's.

    Under multi-process SPMD the same hook slot carries the coord
    plane's drain agreement (``parallel/coord.py``): the tick votes
    this rank's shutdown flag into the boundary's barrier, and the
    drain below additionally requires the AGREED verdict
    (``coord.drain_allowed``) — a SIGTERM that landed on one rank
    after this boundary's vote closed must wait for the next
    boundary's vote, or half the world drains while the other half
    issues the next collective alone. The ``resources.boundary_fault``
    seam fires first: the ``rank_kill`` chaos injector counts 1-based
    boundary ordinals here.
    """
    from mpi_opt_tpu.health import heartbeat, shutdown
    from mpi_opt_tpu.parallel import coord
    from mpi_opt_tpu.utils import resources

    if coord.active_plane() is not None:
        # multi-process: label the beat (and a drain's ``at``) as a
        # boundary phase — a rank frozen HERE is waiting in the
        # agreement barrier, the exact last-beat shape launch.py's
        # collective-wedge classifier keys on; and identical labels
        # across ranks let drills assert "all ranks drained at the
        # same boundary" from the summaries alone
        stage = f"boundary:{stage}"
    resources.boundary_fault(stage)
    heartbeat.beat(stage=stage, **progress)
    if not final:
        shutdown.poll_slice(stage)
    if final or not shutdown.requested():
        return
    if not coord.drain_allowed():
        return
    if snapshot is not None:
        snapshot()
    raise shutdown.SweepInterrupted(shutdown.active_signal(), at=stage)


def journal_boundary(
    journal, b_local: int, members, units, scores, step: int, scores_mo=None
) -> None:
    """The fused drivers' shared ledger service point, paired with
    ``launch_boundary``: called once per natural boundary (PBT
    generation, SHA/BOHB rung, TPE batch) with the boundary's member
    identities, unit rows, and scores — BEFORE that boundary's snapshot
    is saved, so the journal never lags the snapshot (the fused twin of
    the driver path's fsync-before-report invariant). No-op without a
    journal; on a re-computed boundary (resume) it verifies against the
    journal instead of re-writing (ledger/fused.py).

    ``scores_mo`` (optional ``[n, m]`` raw objective matrix) is the
    multi-objective sweeps' vector payload: ``scores`` stays the
    authoritative scalarized score (what resume/fsck/warm-start
    verify), the vectors ride beside it as each record's ``scores``
    field."""
    if journal is None:
        return
    # one journal span per boundary (not per member record: a pop-1024
    # generation journals 1024 fsync'd lines — span volume must stay
    # proportional to boundaries, not members)
    with trace.span("journal", boundary=int(b_local), n=len(members)):
        journal.record_boundary(
            b_local, members, units, scores, step, scores_mo=scores_mo
        )


def journal_require_prefix(journal, n_boundaries: int) -> None:
    """Resume-time consistency gate: every boundary the restored
    snapshot records as complete must already be fully journaled
    (``FusedJournal.require_prefix``); no-op without a journal."""
    if journal is not None:
        journal.require_prefix(n_boundaries)


def make_fused_journal(ledger, space, **offsets):
    """``ledger/fused.make_journal`` re-export at the drivers' layer:
    the four fused drivers build their journal views through one door
    so offsets/construction cannot drift between them."""
    from mpi_opt_tpu.ledger.fused import make_journal

    return make_journal(ledger, space, **offsets)


#: objective metric names the population eval path can produce; the
#: ObjectiveSpec names of a fused multi-objective sweep must come from
#: this set (validated in the CLI before anything compiles)
POPULATION_METRICS = ("accuracy", "params", "latency")


def eval_population_objectives(trainer, state, val_x, val_y, names):
    """Multi-metric population eval: raw ``float32[P, m]``, one column
    per objective name (ISSUE 17).

    Jit-safe with ``names`` static (it arrives from the frozen
    ObjectiveSpec that is itself a static jit arg), so inside
    ``run_fused_pbt`` this compiles into the generation scan; called
    eagerly from the SHA rung loop it dispatches the same jitted
    programs with no extra host sync — columns stay on device until
    the driver's one per-boundary fetch.
    """
    cols = []
    for name in names:
        if name == "accuracy":
            cols.append(trainer.eval_population(state, val_x, val_y))
        elif name == "params":
            cols.append(trainer.member_effective_params(state))
        elif name == "latency":
            cols.append(trainer.member_latency_proxy(state))
        else:
            raise ValueError(
                f"unknown population objective {name!r}; "
                f"supported: {POPULATION_METRICS}"
            )
    return jnp.stack(cols, axis=-1)


def segment_flops_hint(workload, population: int, steps: int):
    """Per-boundary FLOPs (one train segment of ``population`` members
    for ``steps`` steps + one eval pass) for the trace layer's achieved-
    TF/s attribution — the number that turns the 33-of-157 TF/s kernel
    gap (PERF_NOTES) into something the system REPORTS per launch.

    Only computed when tracing is enabled (the probe lowers tiny
    one-member programs through XLA's cost analysis —
    utils.flops.population_sweep_flops — which an untraced sweep must
    not pay), cached per (population, steps) on the workload instance,
    and probe compiles are span-suppressed so they don't pollute the
    very attribution they serve. None when tracing is off or the
    backend offers no cost analysis; callers then omit the ``flops``
    span attr and the trace CLI reports TF/s as unavailable.
    """
    if not trace.enabled():
        return None
    cache = getattr(workload, "_flops_hint_cache", None)
    if cache is None:
        cache = workload._flops_hint_cache = {}
    key = (int(population), int(steps))
    if key not in cache:
        from mpi_opt_tpu.utils.flops import population_sweep_flops

        # the probe's own wall is attributed as setup (it is real
        # pre-train time of a traced sweep); the tiny programs it
        # lowers are span-SUPPRESSED so their compiles don't count as
        # the sweep's compile phase
        with trace.span("setup", op="flops_probe", members=int(population)):
            with trace.suppressed():
                cache[key] = population_sweep_flops(
                    workload, int(population), 1, int(steps), n_evals=1
                )
    return cache[key]


class HParamsFn:
    """Hashable (space, workload)-bound unit->OptHParams mapping, usable
    as a static jit argument (identity-hashed: space/workload come from
    per-workload caches, so identity is stable across calls and a fresh
    pair correctly forces a retrace)."""

    def __init__(self, space, workload):
        self.space = space
        self.workload = workload

    def __call__(self, unit):
        return self.workload.make_hparams(self.space.from_unit(unit))

    def __hash__(self):
        return hash((id(self.space), id(self.workload)))

    def __eq__(self, other):
        return isinstance(other, HParamsFn) and (
            self.space is other.space and self.workload is other.workload
        )
