"""Fused generational TPE: suggest → train → report without the host.

The driver path (algorithms/tpe.py + the TPU backend) already runs the
vectorized acquisition kernel on-device, but observations round-trip
through the host trial ledger between batches. Here the ring buffer of
observations IS device state: each generation is one XLA program that
draws a batch of suggestions from the buffer (ops.tpe.tpe_suggest, with
diversified batched top-k), initializes that many FRESH members, trains
them for the trial budget, evaluates, and writes (units, scores) back
into the buffer in place. The host sees one tiny per-generation fetch
(the generation's scores, for the progress curve) — the config-4
"surrogate-model sweep" with the surrogate fully resident on-chip.

Unlike PBT/SHA there is no population carried between generations —
every trial trains from scratch (TPE semantics) — so the recovery
snapshot is just the buffer + RNG key, making crash recovery
(``checkpoint_dir``) nearly free at generation granularity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_opt_tpu.obs import memory, trace
from mpi_opt_tpu.ops.tpe import TPEConfig, tpe_suggest
from mpi_opt_tpu.train.common import (
    finite_winner,
    journal_boundary,
    journal_require_prefix,
    launch_boundary,
    make_fused_journal,
    momentum_dtype_str,
    segment_flops_hint,
    workload_arrays,
)
from mpi_opt_tpu.train.engine import (
    WaveRunner,
    boundary_span,
    resolve_wave_size,
)
from mpi_opt_tpu.train.engine import run_wave as _run_wave  # chaos-drill seam
from mpi_opt_tpu.utils import profiling


@functools.partial(
    jax.jit,
    static_argnames=("trainer", "hparams_fn", "n_suggest", "budget", "cfg"),
    donate_argnames=("obs_unit", "obs_scores", "valid"),
)
def tpe_generation(
    trainer,
    obs_unit,  # float32[M, d] ring buffer (donated, updated in place)
    obs_scores,  # float32[M]
    valid,  # bool[M]
    hparams_fn,
    train_x,
    train_y,
    val_x,
    val_y,
    key,
    write_pos,  # int32[] — first buffer row this generation writes
    n_suggest: int,
    budget: int,
    cfg: TPEConfig,
):
    """One fused generation. Returns (obs_unit, obs_scores, valid,
    key', gen_scores[n_suggest], gen_units[n_suggest, d])."""
    from mpi_opt_tpu.parallel.mesh import constrain_pop

    key, k_sug, k_init, k_train = jax.random.split(key, 4)
    sugg, _ = tpe_suggest(k_sug, obs_unit, obs_scores, valid, n_suggest, cfg)
    # the generation's cohort is born inside this program: constrain it
    # over 'pop' so training shards instead of inheriting the (replicated)
    # buffer layout. trainer.mesh is static, so this traces to a no-op
    # without a mesh.
    state = constrain_pop(
        trainer.init_population(k_init, train_x[:2], n_suggest), trainer.mesh
    )
    hp = hparams_fn(sugg)
    state, _ = trainer.train_segment(state, hp, train_x, train_y, k_train, budget)
    scores = trainer.eval_population(state, val_x, val_y)
    obs_unit = jax.lax.dynamic_update_slice(obs_unit, sugg, (write_pos, 0))
    obs_scores = jax.lax.dynamic_update_slice(obs_scores, scores, (write_pos,))
    valid = jax.lax.dynamic_update_slice(
        valid, jnp.ones((n_suggest,), bool), (write_pos,)
    )
    return obs_unit, obs_scores, valid, key, scores, sugg


@functools.partial(jax.jit, static_argnames=("n_suggest", "cfg"))
def _tpe_suggest_program(obs_unit, obs_scores, valid, key, n_suggest: int, cfg):
    """Wave mode's suggest boundary op: the SAME key split + acquisition
    call ``tpe_generation`` opens with, as its own program. The buffers
    are NOT donated — the ring is updated only after the batch's waves
    have all landed (``_tpe_ring_update``), and an OOM-backoff re-run
    must be able to replay the batch from these exact suggestions.
    Separate-jit boundary ops preserve CPU bit-identity with the fused
    program (the engine's ``_wave_exploit`` precedent), so wave-mode
    suggestions equal resident-mode ones bit for bit."""
    key, k_sug, k_init, k_train = jax.random.split(key, 4)
    sugg, _ = tpe_suggest(k_sug, obs_unit, obs_scores, valid, n_suggest, cfg)
    return key, k_init, k_train, sugg


@functools.partial(
    jax.jit,
    static_argnames=("n_suggest",),
    donate_argnames=("obs_unit", "obs_scores", "valid"),
)
def _tpe_ring_update(obs_unit, obs_scores, valid, sugg, scores, write_pos, n_suggest: int):
    """The tail of ``tpe_generation`` — writing a completed batch's
    (units, scores) into the observation ring — split out so wave mode
    runs it once per batch, after the wave scores are gathered. f32
    scores round-trip host staging exactly, so the buffer after this
    equals the resident program's in-place update bit for bit."""
    obs_unit = jax.lax.dynamic_update_slice(obs_unit, sugg, (write_pos, 0))
    obs_scores = jax.lax.dynamic_update_slice(obs_scores, scores, (write_pos,))
    valid = jax.lax.dynamic_update_slice(
        valid, jnp.ones((n_suggest,), bool), (write_pos,)
    )
    return obs_unit, obs_scores, valid


def fused_tpe(  # sweeplint: barrier(batch host loop: fetches obs ring for snapshot/journal at batch boundaries)
    workload,
    n_trials: int,
    batch: int = 32,
    budget: int = 100,
    seed: int = 0,
    cfg: TPEConfig = TPEConfig(),
    member_chunk: int = 0,
    mesh=None,
    wave_size=0,
    oom_backoff: int = 2,
    checkpoint_dir: str = None,
    ledger=None,
    warm_obs=None,
):
    """Run an n_trials TPE sweep as ceil(n_trials/batch) fused
    generations (the last one sized to the remainder).

    ``wave_size`` (int or ``'auto'``) runs each generation's cohort as
    resident waves through the shared engine (train/engine.py) when the
    batch exceeds device residency: the suggest step runs as its own
    boundary program, each wave initializes its members from the SAME
    ``split(k_init, batch)`` key window the resident program would use,
    and only scores stage back out (TPE carries no state between
    generations) — bit-identical to resident mode at any wave size.
    ``oom_backoff`` halves the wave cap and replays the generation from
    its already-drawn suggestions on a classified device OOM.

    ``ledger`` journals one record per suggestion per generation batch
    (unit params + score at the trial budget) before the generation's
    snapshot saves; resume verifies already-journaled batches
    (ledger/fused.py). ``warm_obs`` (prior-ledger observations,
    cross-mode) PRE-FILLS the on-device observation ring: the buffer
    grows by the finite-scored prior count and the acquisition kernel
    sees the priors from its first suggestion — the fused equivalent of
    driver TPE's surrogate warm start. Warm rows are facts, not trials:
    they are barred from the best pick and the curve, and ``n_warm`` is
    part of the checkpoint identity (the buffer shape depends on it).

    Returns best score/params, the per-generation cumulative-best curve,
    and the full observation history. ``checkpoint_dir`` makes the sweep
    crash-recoverable at generation granularity; the RNG key snapshots
    with the buffer, so a resumed sweep finishes with the IDENTICAL
    result of an uninterrupted one (tested).

    ``mesh``: optional ``('pop','data')`` mesh. The observation buffer
    (tiny) replicates; each generation's cohort trains sharded over
    'pop' (constraint applied inside ``tpe_generation``) with the batch
    data-parallel over 'data' — the suggest step reads the replicated
    buffer identically on every device, so no collective is needed
    beyond what the partitioner inserts for training.
    """
    if n_trials < 1:
        raise ValueError(f"n_trials must be >= 1, got {n_trials}")
    trainer, space, train_x, train_y, val_x, val_y = workload_arrays(
        workload, member_chunk, mesh
    )
    d = len(space.discrete_mask())
    sizes = [batch] * (n_trials // batch)
    if n_trials % batch:
        sizes.append(n_trials % batch)
    # the residency question is about the LARGEST generation cohort;
    # the engine re-lays out smaller (remainder) generations per batch
    wave_size = resolve_wave_size(
        trainer,
        train_x[:2],
        max(sizes),
        wave_size=wave_size,
        mesh=mesh,
        oom_backoff=oom_backoff,
    )
    waves = 0 < wave_size < max(sizes)
    # finite-scored priors only: a diverged prior point carries no
    # evidence the model should build on (same rule as driver ingest)
    warm = [o for o in (warm_obs or []) if np.isfinite(float(o.score))]
    n_warm = len(warm)
    M = n_trials + n_warm  # buffer fits the sweep plus the priors

    def place_buffers(obs_unit, obs_scores, valid):
        """The obs buffer replicates over the mesh (single placement
        point for both the fresh-init and checkpoint-restore paths)."""
        if mesh is None:
            return obs_unit, obs_scores, valid
        from mpi_opt_tpu.parallel.mesh import replicate

        rep = replicate(mesh)
        return tuple(jax.device_put(a, rep) for a in (obs_unit, obs_scores, valid))

    key = jax.random.key(seed)
    unit0 = np.zeros((M, d), np.float32)
    scores0 = np.zeros((M,), np.float32)
    valid0 = np.zeros((M,), bool)
    if n_warm:
        unit0[:n_warm] = np.stack([np.asarray(o.unit, np.float32) for o in warm])
        scores0[:n_warm] = np.array([float(o.score) for o in warm], np.float32)
        valid0[:n_warm] = True
    obs_unit, obs_scores, valid = place_buffers(
        jnp.asarray(unit0), jnp.asarray(scores0), jnp.asarray(valid0)
    )
    from mpi_opt_tpu.train.common import HParamsFn

    hparams_fn = HParamsFn(space, workload)

    snap = None
    restored = None
    start_gen = 0
    run_wave_size = wave_size  # execution cap; adopted from snapshot meta
    done = n_warm  # write position: live trials append after the priors
    best_curve = []
    member_fail: list = []  # per-gen diverged-suggestion counts
    fails_complete = True
    if checkpoint_dir is not None:
        import dataclasses

        from mpi_opt_tpu.utils.checkpoint import SweepCheckpointer

        snap = SweepCheckpointer(
            checkpoint_dir,
            {
                "workload": getattr(workload, "name", type(workload).__name__),
                "n_trials": n_trials,
                "batch": batch,
                "budget": budget,
                "seed": seed,
                "member_chunk": member_chunk,
                # acquisition knobs change suggest behavior: a resumed
                # sweep must continue under the SAME cfg
                "cfg": dataclasses.asdict(cfg),
                # carried-state structure (see fused_pbt)
                "momentum_dtype": momentum_dtype_str(),
                # the warm prefix is buffer STRUCTURE (its rows shift
                # every live write position): resuming under a
                # different prior set must refuse, not corrupt
                "n_warm": n_warm,
                # wave mode's REQUESTED cap is config identity (the
                # OOM-settled execution cap travels in per-snapshot
                # meta); resident configs deliberately DON'T write the
                # key, so pre-wave snapshots keep resuming via the
                # checkpointer's setdefault back-compat
                **({"wave_size": wave_size} if waves else {}),
            },
        )
        restored = snap.restore()
        if restored is not None:
            sweep, meta = restored
            obs_unit, obs_scores, valid = place_buffers(
                jnp.asarray(sweep["obs_unit"]),
                jnp.asarray(sweep["obs_scores"]),
                jnp.asarray(sweep["valid"]),
            )
            key = jax.random.wrap_key_data(jnp.asarray(sweep["key_data"]))
            start_gen = int(meta["gens_done"])
            done = n_warm + sum(sizes[:start_gen])
            best_curve = [float(v) for v in meta["best_curve"]]
            # pre-upgrade snapshots have no per-gen failure tallies for
            # the completed generations: report None, never invent
            if "member_fail" in meta:
                member_fail = [int(v) for v in meta["member_fail"]]
            else:
                fails_complete = False
            if waves:
                run_wave_size = int(meta.get("wave_size_run", wave_size))

    from mpi_opt_tpu.parallel.mesh import fetch_global

    # uncheckpointed sweeps defer the per-generation running-best fetch
    # (one tunnel round trip each) to a single batched barrier at the
    # end — the same deferral train/fused_asha.py's fused_sha applies
    # to its rung ledger; checkpointed sweeps keep it eager (each
    # snapshot records the curve so far). fused_pbt deliberately does
    # NOT defer: its per-launch fetch doubles as the launch-duration
    # barrier that launch-granular wall-to-target accounting needs.
    journal = make_fused_journal(ledger, space)
    journal_require_prefix(journal, start_gen)
    # a fused journal forces the eager path (its per-batch records must
    # be fsync-durable before the batch's snapshot — deferral breaks
    # the ordering contract), same as a checkpoint does; wave mode's
    # scores land on the host per batch anyway, so its curve is eager
    defer = snap is None and journal is None and not waves
    runner = None
    if waves:
        runner = WaveRunner(max(sizes), run_wave_size, oom_backoff=oom_backoff)
    # warm prior rows are facts, not trials of THIS sweep: bar them
    # from the running-best curve and the final winner pick
    live = jnp.arange(M) >= n_warm
    curve_dev: list = []
    fail_dev: list = []
    try:
        for g in range(start_gen, len(sizes)):
            n_g = sizes[g]
            if waves:
                # engine path: suggest as its own boundary program, the
                # cohort as resident waves (scores-only stage-out — TPE
                # carries no state between generations), the ring update
                # once the batch's scores have all landed. The runner
                # owns launch_tick, the train span, the per-wave
                # heartbeats, the drain barrier, and the OOM-backoff
                # replay (the replay re-trains from the SAME suggestions
                # and init keys, so it is bit-identical).
                with boundary_span("suggest", generation=g + 1, n=n_g):
                    key, k_init, k_train, sugg = _tpe_suggest_program(
                        obs_unit, obs_scores, valid, key, n_g, cfg
                    )
                member_keys = jax.random.split(k_init, n_g)
                scores_host = np.full((n_g,), np.nan, np.float32)

                def _dispatch(
                    w, off, wl_, eng,
                    k_train=k_train, sugg=sugg, member_keys=member_keys, n_g=n_g,
                ):
                    return _run_wave(
                        trainer,
                        None,
                        np.arange(off, off + wl_),
                        off,
                        sugg,
                        hparams_fn,
                        train_x,
                        train_y,
                        val_x,
                        val_y,
                        k_train,
                        budget,
                        n_g,
                        mesh,
                        eng,
                        init_keys=member_keys[off : off + wl_],
                        sample_x=train_x[:2],
                    )

                def _payload(st, sc):
                    return {"scores": sc}

                def _writer(off, scores_host=scores_host):
                    def _write(host_tree):  # sweeplint: barrier(stage-out landing: writes fetched wave scores into the batch accumulator)
                        s = host_tree["scores"]
                        scores_host[off : off + len(s)] = s

                    return _write

                f = segment_flops_hint(workload, n_g, budget)
                runner.run_interval(
                    n=n_g,
                    run_wave_fn=_dispatch,
                    payload_fn=_payload,
                    writer_fn=_writer,
                    scores_host=scores_host,
                    stage_label=lambda w, nw, g=g: (
                        f"tpe generation {g + 1}/{len(sizes)} wave {w + 1}/{nw}"
                    ),
                    boundary_kwargs=lambda w, nw, g=g: {
                        "generation": g + 1,
                        "of": len(sizes),
                    },
                    span_attrs=lambda nw, g=g, n_g=n_g: {
                        "launch": g + 1,
                        "members": n_g,
                        "steps": budget,
                        "waves": nw,
                    },
                    flops=f,
                    notify_fields=(("generation", g + 1),),
                )
                # f32 round-trips host staging exactly: this equals the
                # device scores tpe_generation would have produced
                scores = jnp.asarray(scores_host.copy())
                with boundary_span("observe", generation=g + 1):
                    obs_unit, obs_scores, valid = _tpe_ring_update(
                        obs_unit, obs_scores, valid, sugg, scores,
                        jnp.int32(done), n_g,
                    )
                done += n_g
                running_dev = jnp.max(
                    jnp.where(
                        valid & jnp.isfinite(obs_scores) & live, obs_scores, -jnp.inf
                    )
                )
                fail_dev_g = jnp.sum(~jnp.isfinite(scores)).astype(jnp.int32)
                best_curve.append(float(fetch_global(running_dev)))
                member_fail.append(int(fetch_global(fail_dev_g)))
            else:
                profiling.launch_tick()
                # eager mode's curve fetch is the batch's completion barrier
                # (real duration -> flops attr for achieved TF/s); deferred
                # mode dispatches async, so the span carries no flops. The
                # hint probes OUTSIDE the span (one-time cost must not
                # inflate the first batch's duration), attaches only after
                # the barrier (a crashed batch must not report full-batch
                # FLOPs over a partial duration).
                f = None if defer else segment_flops_hint(workload, sizes[g], budget)
                with trace.span(
                    "train", launch=g + 1, members=sizes[g], steps=budget
                ) as sp:
                    obs_unit, obs_scores, valid, key, scores, sugg = tpe_generation(
                        trainer,
                        obs_unit,
                        obs_scores,
                        valid,
                        hparams_fn,
                        train_x,
                        train_y,
                        val_x,
                        val_y,
                        key,
                        jnp.int32(done),
                        n_suggest=sizes[g],
                        budget=budget,
                        cfg=cfg,
                    )
                    done += sizes[g]
                    # valid alone is not enough: one valid-but-NaN observation
                    # would propagate through jnp.max into every later curve
                    # point — gate on finiteness too (same rule as best_i below)
                    running_dev = jnp.max(
                        jnp.where(
                            valid & jnp.isfinite(obs_scores) & live, obs_scores, -jnp.inf
                        )
                    )
                    # this generation's diverged-suggestion count (ROADMAP open
                    # item): the obs ring masks non-finite scores from the model,
                    # but operators need the tally the masking hides
                    fail_dev_g = jnp.sum(~jnp.isfinite(scores)).astype(jnp.int32)
                    if defer:
                        curve_dev.append(running_dev)
                        fail_dev.append(fail_dev_g)
                    else:
                        # fetch_global: under multi-process SPMD the buffer is a
                        # process-spanning (replicated) global array
                        best_curve.append(float(fetch_global(running_dev)))
                        member_fail.append(int(fetch_global(fail_dev_g)))
                        if f:
                            sp["flops"] = f
                        # post-barrier device-memory watermark: batch cohort
                        # + obs ring resident
                        memory.note(sp)
            if journal is not None:
                # one record per suggestion of this batch (members are
                # the sweep's global trial indices), journaled BEFORE
                # the generation snapshot below
                first = sum(sizes[:g])
                journal_boundary(
                    journal,
                    g,
                    np.arange(first, first + sizes[g]),
                    fetch_global(sugg),
                    fetch_global(scores),
                    step=budget,
                )
            if snap is not None:
                # fetch_global for the payload too — np.asarray on the
                # process-spanning buffers raises, killing the sweep at
                # its first snapshot exactly where the mesh needs it
                snap.save(
                    g + 1,
                    sweep={
                        "obs_unit": fetch_global(obs_unit),
                        "obs_scores": fetch_global(obs_scores),
                        "valid": fetch_global(valid),
                        "key_data": np.asarray(jax.random.key_data(key)),
                    },
                    meta_extra={
                        "gens_done": g + 1,
                        "boundaries_done": g + 1,
                        "best_curve": best_curve,
                        **({"member_fail": member_fail} if fails_complete else {}),
                        # the OOM-settled execution cap: a resume adopts
                        # it instead of re-paying the halvings
                        **({"wave_size_run": runner.wave_size} if waves else {}),
                    },
                )
            # heartbeat + graceful-shutdown drain: checkpointed sweeps
            # snapshot every generation, so a preemption here resumes
            # at exactly the next generation
            launch_boundary(
                f"tpe generation {g + 1}/{len(sizes)}",
                final=g + 1 == len(sizes),
                generation=g + 1,
                of=len(sizes),
            )
    finally:
        if runner is not None:
            runner.close()
        if snap is not None:
            snap.close()

    if curve_dev or fail_dev:
        from mpi_opt_tpu.parallel.mesh import fetch_global_batched

        fetched = fetch_global_batched(curve_dev + fail_dev)
        best_curve.extend(float(v) for v in fetched[: len(curve_dev)])
        member_fail.extend(int(v) for v in fetched[len(curve_dev):])
    # warm prior rows are sliced off the returned history: callers get
    # exactly this sweep's n_trials observations, warm-started or not
    np_unit = np.asarray(fetch_global(obs_unit))[n_warm:]
    raw_scores = np.asarray(fetch_global(obs_scores))[n_warm:]
    np_scores = np.asarray(raw_scores)
    np_valid = np.asarray(fetch_global(valid))[n_warm:]
    # invalid rows AND non-finite scores are barred from the winner
    # pick: a valid-but-NaN observation must not win argmax (NaN sorts
    # first). Shared rule: train.common.finite_winner; an all-diverged
    # sweep reports best_params=None / best_score NaN with
    # diverged=True, matching fused SHA/PBT
    best_i, diverged = finite_winner(np_scores, ok=np_valid)
    return {
        "best_score": float("nan") if diverged else float(np_scores[best_i]),
        "best_params": None if diverged else space.materialize_row(np_unit[best_i]),
        "diverged": diverged,
        "best_curve": np.asarray(best_curve, dtype=np.float32),
        # per-generation diverged-suggestion tallies; None when a
        # pre-upgrade snapshot left completed generations' counts unknown
        "member_failures": member_fail if fails_complete else None,
        "obs_unit": np_unit,
        "obs_scores": raw_scores,
        "n_trials": n_trials,
        "n_warm": n_warm,
        "journal": None
        if journal is None
        else {"written": journal.written, "verified": journal.verified},
        **({} if runner is None else runner.result_extras()),
    }
