"""The vmapped population trainer — the framework's hot loop.

Reference call stack being replaced (SURVEY.md §3; reference unreadable,
contract from BASELINE.json): Coordinator → MPI send → N MPIWorker ranks
each train one trial → MPI gather of scores. Here the N workers ARE one
XLA program: ``jax.jit(jax.vmap(member_step))`` over a leading population
axis, scanned over steps so the whole multi-step training segment is a
single device computation — hyperparameters are *data* (one row per
member), so one compilation serves every trial the search will ever
propose.

Design notes (TPU):
- member step = loss + grad + SGD/momentum update fused in one vmapped
  function; XLA sees [P, ...] batched matmuls/convs that tile the MXU.
- the minibatch is shared across members (one gather from the on-device
  dataset per step); per-member *augmentation* decorrelates members,
  with member-folded RNG. Augmentation = per-sample horizontal flip +
  per-member-per-step circular shift (jnp.roll) — branchless, fusable.
- hyperparameters (lr, momentum, weight decay, aug strengths) enter as
  an ``OptHParams`` of [P]-vectors; inside the vmap each member sees
  scalars. PBT can therefore mutate them between segments without
  recompiling anything.
- optimizer state (momentum) lives beside params in ``PopState``; PBT
  exploit is a single ``jax.tree.map(lambda x: x[src_idx], state)`` —
  the weight copy the reference does with MPI point-to-point transfers
  becomes one on-device gather.
- datasets stay device-resident across the entire search (one host →
  device transfer per search, vs per-trial pickling over MPI).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class OptHParams:
    """Per-member hyperparameters; every field is a [P] vector."""

    lr: jax.Array
    momentum: jax.Array
    weight_decay: jax.Array
    flip_prob: jax.Array  # per-sample horizontal flip probability
    shift: jax.Array  # max augmentation shift in pixels (continuous)

    @staticmethod
    def defaults(n: int, lr: float = 0.1) -> "OptHParams":
        f = lambda v: jnp.full((n,), v, dtype=jnp.float32)
        return OptHParams(f(lr), f(0.9), f(1e-4), f(0.5), f(3.0))


@flax.struct.dataclass
class PopState:
    """Population training state: leading axis = member."""

    params: Any
    momentum: Any
    step: jax.Array  # int32[P]


def _augment(key: jax.Array, x: jax.Array, flip_prob: jax.Array, shift: jax.Array):
    """Per-member augmentation of a shared [B, H, W, C] batch.

    Branchless: flip via a per-sample mask, translation via a circular
    roll with a traced per-member offset (wrap-around stands in for
    pad-and-crop; equally effective as regularization, far cheaper to
    compile than dynamic_slice per sample).
    """
    k_flip, k_dy, k_dx = jax.random.split(key, 3)
    b = x.shape[0]
    do_flip = jax.random.bernoulli(k_flip, flip_prob, (b, 1, 1, 1))
    x = jnp.where(do_flip, x[:, :, ::-1, :], x)
    max_s = jnp.maximum(shift, 0.0)
    dy = jnp.round(jax.random.uniform(k_dy, (), minval=-max_s, maxval=max_s)).astype(jnp.int32)
    dx = jnp.round(jax.random.uniform(k_dx, (), minval=-max_s, maxval=max_s)).astype(jnp.int32)
    return jnp.roll(x, (dy, dx), axis=(1, 2))


class PopulationTrainer:
    """Builds the jitted population train/eval programs for one model.

    Args:
        apply_fn: ``apply(params, x) -> logits`` (flax ``Module.apply``
            partial'd over everything but params and inputs).
        init_fn: ``init(rng, sample_x) -> params``.
        batch_size: per-step minibatch size (shared across members).
        augment: whether image augmentation applies (False for tabular).
        member_chunk: if >0, process members in chunks of this size via
            ``lax.map`` (activation-memory relief for big populations;
            params/momentum still resident for all members).
        donate: donate the input state to ``train_segment`` so XLA can
            reuse its buffers for the output instead of holding old and
            new population state simultaneously — the difference between
            1x and 2x resident params+momentum, which is what caps the
            single-chip ResNet population. Callers must not touch a
            state after passing it in (``make_trainer`` turns this on;
            keep it off when comparing states across calls).
        mesh: optional ``('pop','data')`` Mesh. When set, every train/
            eval batch carries a sharding constraint over the ``data``
            axis, so within-member compute is data-parallel: each data
            shard computes grads on its slice of the shared batch and
            the SPMD partitioner inserts the gradient all-reduce over
            ``data`` — the MPI allreduce of a data-parallel rank block,
            as a layout annotation (tested by HLO inspection in
            tests/test_parallel.py). Without the constraint the batch
            is replicated and the axis does nothing.
    """

    def __init__(
        self,
        apply_fn: Callable,
        init_fn: Callable,
        batch_size: int = 256,
        augment: bool = True,
        member_chunk: int = 0,
        donate: bool = False,
        mesh=None,
        momentum_dtype=None,
    ):
        self.apply_fn = apply_fn
        self.init_fn = init_fn
        self.batch_size = batch_size
        self.augment = augment
        self.member_chunk = member_chunk
        self.donate = donate
        self.mesh = mesh
        # storage dtype for the momentum buffers (None = match params,
        # i.e. f32). The update math always runs in f32; a narrower
        # STORAGE dtype only changes the bytes the bandwidth-bound
        # optimizer fusions move (probes/probe_bf16_momentum.py measures
        # whether that's a win on this platform)
        self.momentum_dtype = momentum_dtype
        if mesh is not None and batch_size % mesh.shape["data"]:
            raise ValueError(
                f"batch_size {batch_size} not divisible by the mesh 'data' "
                f"axis ({mesh.shape['data']})"
            )
        self.train_segment = functools.partial(
            jax.jit(
                type(self)._train_segment,
                static_argnames=("self", "steps"),
                donate_argnames=("state",) if donate else (),
            ),
            self,
        )
        self.train_segment_masked = functools.partial(
            jax.jit(
                type(self)._train_segment_masked,
                static_argnames=("self", "steps"),
                donate_argnames=("state",) if donate else (),
            ),
            self,
        )

    # -- init -------------------------------------------------------------

    @functools.partial(jax.jit, static_argnames=("self", "n"))
    def init_population(self, key: jax.Array, sample_x: jax.Array, n: int) -> PopState:
        return self.init_members(jax.random.split(key, n), sample_x)

    @functools.partial(jax.jit, static_argnames=("self",))
    def init_members(self, keys: jax.Array, sample_x: jax.Array) -> PopState:
        """Init one member per key (leading axis = member).

        The wave-sliced form of ``init_population``: member m of a
        P-member population inits from ``split(key, P)[m]`` whether it
        lands on device as part of the full resident cohort or as a
        host-staged wave (``train/staging.py``) — so wave-mode initial
        weights are bit-identical to resident mode's.
        """
        n = keys.shape[0]
        params = jax.vmap(lambda k: self.init_fn(k, sample_x))(keys)
        dt = self.momentum_dtype
        momentum = jax.tree.map(lambda p: jnp.zeros(p.shape, dt or p.dtype), params)
        return PopState(params=params, momentum=momentum, step=jnp.zeros((n,), jnp.int32))

    # -- member-level pieces (scalar hparams; vmapped below) -------------

    def _member_loss(self, params, hp: OptHParams, key, bx, by):
        if self.augment and bx.ndim == 4:
            bx = _augment(key, bx, hp.flip_prob, hp.shift)
        logits = self.apply_fn(params, bx)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, by[:, None], axis=1))

    def _member_update(self, params, momentum, step, hp: OptHParams, key, bx, by):
        loss, grads = jax.value_and_grad(self._member_loss)(params, hp, key, bx, by)
        # SGD + momentum + coupled L2 weight decay (wd*p folded into the
        # gradient, so the effective decay is lr-scaled), hparams as
        # traced scalars. Math in f32 regardless of the momentum STORAGE
        # dtype (the astype is a no-op at the default f32 storage).
        m32 = jax.tree.map(
            lambda m, g, p: hp.momentum * m.astype(jnp.float32) + g + hp.weight_decay * p,
            momentum, grads, params,
        )
        params = jax.tree.map(lambda p, m: p - hp.lr * m, params, m32)
        dt = self.momentum_dtype
        momentum = m32 if dt is None else jax.tree.map(lambda m: m.astype(dt), m32)
        return params, momentum, step + 1, loss

    def _constrain_data(self, bx, by):
        """Shard a batch over the mesh 'data' axis (no-op without a mesh)."""
        if self.mesh is None:
            return bx, by
        from jax.sharding import NamedSharding, PartitionSpec

        sh = lambda a: jax.lax.with_sharding_constraint(
            a, NamedSharding(self.mesh, PartitionSpec("data"))
        )
        return sh(bx), sh(by)

    # -- population programs ---------------------------------------------

    def _pop_update(self, state: PopState, hp: OptHParams, keys, bx, by):
        """One step for the whole population on a shared batch."""
        fn = lambda p, m, s, hp_m, k: self._member_update(p, m, s, hp_m, k, bx, by)
        if self.member_chunk > 0:
            p, m, s, loss = jax.lax.map(
                lambda args: fn(*args),
                (state.params, state.momentum, state.step, hp, keys),
                batch_size=self.member_chunk,
            )
        else:
            p, m, s, loss = jax.vmap(fn)(state.params, state.momentum, state.step, hp, keys)
        return PopState(params=p, momentum=m, step=s), loss

    def _train_segment(
        self,
        state: PopState,
        hp: OptHParams,
        train_x: jax.Array,
        train_y: jax.Array,
        key: jax.Array,
        steps: int,
    ) -> tuple[PopState, jax.Array]:
        """Run ``steps`` shared-batch steps; returns (state, mean losses [steps]).

        Jitted as ``self.train_segment`` in __init__ (donation is
        per-instance, so the jit wrapper cannot be a class decorator).
        """
        n = state.step.shape[0]
        n_data = train_x.shape[0]

        def one_step(carry, t):
            st, k = carry
            k, k_batch, k_aug = jax.random.split(k, 3)
            idx = jax.random.randint(k_batch, (self.batch_size,), 0, n_data)
            bx = jnp.take(train_x, idx, axis=0)
            by = jnp.take(train_y, idx, axis=0)
            bx, by = self._constrain_data(bx, by)
            member_keys = jax.random.split(k_aug, n)
            st, loss = self._pop_update(st, hp, member_keys, bx, by)
            return (st, k), jnp.mean(loss)

        (state, _), losses = jax.lax.scan(one_step, (state, key), jnp.arange(steps))
        return state, losses

    def _train_segment_window(
        self,
        state: PopState,
        hp: OptHParams,
        train_x: jax.Array,
        train_y: jax.Array,
        key: jax.Array,
        steps: int,
        n_total: int,  # static: full population size
        offset: jax.Array,  # int32: this wave's first member index
    ) -> tuple[PopState, jax.Array]:
        """``_train_segment`` for a WAVE of a larger population: the
        state holds members [offset, offset+W) of an ``n_total``-member
        population (host-staged wave scheduling, train/staging.py).

        Bit-identity contract with the resident program: the batch key
        chain threads exactly as in ``_train_segment`` (the minibatch is
        shared population-wide, so every wave of a generation must draw
        the SAME batch sequence — they do, by receiving the same
        ``key``), and per-member augmentation keys are the wave's WINDOW
        of the full population's per-step split — member m sees
        ``split(k_aug, n_total)[m]`` whether it trains resident or in a
        wave. ``offset`` is traced (dynamic_slice on the key data), so
        all same-sized waves share one compiled program.
        """
        n = state.step.shape[0]
        n_data = train_x.shape[0]

        def one_step(carry, t):
            st, k = carry
            k, k_batch, k_aug = jax.random.split(k, 3)
            idx = jax.random.randint(k_batch, (self.batch_size,), 0, n_data)
            bx = jnp.take(train_x, idx, axis=0)
            by = jnp.take(train_y, idx, axis=0)
            bx, by = self._constrain_data(bx, by)
            all_keys = jax.random.split(k_aug, n_total)
            member_keys = jax.random.wrap_key_data(
                jax.lax.dynamic_slice_in_dim(
                    jax.random.key_data(all_keys), offset, n, axis=0
                )
            )
            st, loss = self._pop_update(st, hp, member_keys, bx, by)
            return (st, k), jnp.mean(loss)

        (state, _), losses = jax.lax.scan(one_step, (state, key), jnp.arange(steps))
        return state, losses

    def _train_segment_masked(
        self,
        state: PopState,
        hp: OptHParams,
        train_x: jax.Array,
        train_y: jax.Array,
        key: jax.Array,
        steps: int,
        rem: jax.Array,  # int32[P]: per-member steps remaining
    ) -> tuple[PopState, jax.Array]:
        """``_train_segment`` with per-member step budgets: member m's
        update applies only while the scan index is < ``rem[m]``, so one
        program trains a MIXED-budget cohort (an ASHA batch spanning
        rungs) to each member's own budget. ``steps`` should be
        ``max(rem)``. Members past their budget still compute a step
        (SPMD lockstep — there is no early exit inside one program) but
        the update is discarded, trading those FLOPs for what they buy:
        ONE launch and ONE score fetch per driver batch instead of one
        per rung group, which is what the 20-90 ms/RTT tunnel actually
        charges for (VERDICT r3 item 2). RNG advances in lockstep too,
        so a member's trajectory depends on its cohort's step schedule —
        deterministic given the batch plan, not bit-identical to the
        grouped path.
        """
        n = state.step.shape[0]
        n_data = train_x.shape[0]

        def one_step(carry, t):
            st, k = carry
            k, k_batch, k_aug = jax.random.split(k, 3)
            idx = jax.random.randint(k_batch, (self.batch_size,), 0, n_data)
            bx = jnp.take(train_x, idx, axis=0)
            by = jnp.take(train_y, idx, axis=0)
            bx, by = self._constrain_data(bx, by)
            member_keys = jax.random.split(k_aug, n)
            new_st, loss = self._pop_update(st, hp, member_keys, bx, by)
            active = t < rem  # bool[P]

            def pick(a, b):
                m = active.reshape((-1,) + (1,) * (a.ndim - 1))
                return jnp.where(m, a, b)

            st = jax.tree.map(pick, new_st, st)
            return (st, k), jnp.mean(jnp.where(active, loss, 0.0))

        (state, _), losses = jax.lax.scan(one_step, (state, key), jnp.arange(steps))
        return state, losses

    @functools.partial(jax.jit, static_argnames=("self", "eval_chunk"))
    def eval_population(
        self, state: PopState, val_x: jax.Array, val_y: jax.Array, eval_chunk: int = 1024
    ) -> jax.Array:
        """Validation accuracy per member: float32[P].

        Scans the val set in fixed chunks so activation memory stays
        O(P * eval_chunk) regardless of val-set size; with
        ``member_chunk`` set, members are additionally lax.map'ed in
        chunks, bounding activations at O(member_chunk * eval_chunk) —
        ResNet-scale populations OOM the forward pass without this. The
        tail chunk is masked, not dropped.
        """
        n_val = val_x.shape[0]
        n_chunks = -(-n_val // eval_chunk)
        pad = n_chunks * eval_chunk - n_val
        vx = jnp.pad(val_x, [(0, pad)] + [(0, 0)] * (val_x.ndim - 1))
        vy = jnp.pad(val_y, (0, pad), constant_values=-1)
        vx = vx.reshape((n_chunks, eval_chunk) + val_x.shape[1:])
        vy = vy.reshape((n_chunks, eval_chunk))

        def member_correct(params, cx, cy):
            logits = self.apply_fn(params, cx)
            pred = jnp.argmax(logits, axis=-1)
            return jnp.sum((pred == cy) & (cy >= 0))

        def chunk_step(acc, chunk):
            cx, cy = chunk
            cx, cy = self._constrain_data(cx, cy)
            if self.member_chunk > 0:
                corr = jax.lax.map(
                    lambda p: member_correct(p, cx, cy),
                    state.params,
                    batch_size=self.member_chunk,
                )
            else:
                corr = jax.vmap(member_correct, in_axes=(0, None, None))(state.params, cx, cy)
            acc = acc + corr
            return acc, None

        correct, _ = jax.lax.scan(chunk_step, jnp.zeros((state.step.shape[0],), jnp.int32), (vx, vy))
        return correct.astype(jnp.float32) / n_val

    # -- multi-objective member metrics (ISSUE 17) ------------------------

    @functools.partial(jax.jit, static_argnames=("self", "threshold"))
    def member_effective_params(
        self, state: PopState, threshold: float = 1e-3
    ) -> jax.Array:
        """Effective parameter count per member: float32[P].

        Counts weights with ``|w| > threshold`` — the model-size
        objective of the multi-objective eval path. Unlike the dense
        parameter count (identical across members — static shapes),
        this varies with each member's weight-decay trajectory, so
        "accuracy vs params" is a real trade-off the search can move
        along. Members with any non-finite weight poison to NaN, which
        is what marks a diverged member infeasible in every objective
        consumer (journal status, Pareto ok-mask, warm-start guard).
        """
        n = state.step.shape[0]
        count = jnp.zeros((n,), jnp.float32)
        bad = jnp.zeros((n,), bool)
        for leaf in jax.tree.leaves(state.params):
            axes = tuple(range(1, leaf.ndim))
            count = count + jnp.sum(
                (jnp.abs(leaf) > threshold).astype(jnp.float32), axis=axes
            )
            bad = bad | ~jnp.all(jnp.isfinite(leaf), axis=axes)
        return jnp.where(bad, jnp.nan, count)

    @functools.partial(jax.jit, static_argnames=("self",))
    def member_latency_proxy(self, state: PopState) -> jax.Array:
        """Step-time latency proxy per member: float32[P], pseudo-ms.

        ``2 * MACs / 1e6`` over the weights a structured-sparse kernel
        could not skip (coarser prunability threshold than the params
        metric, 1e-2) — a deterministic, device-computable stand-in
        for inference step time that needs no wall-clock measurement
        (which would not be per-member attributable inside one fused
        program anyway).
        """
        return 2e-6 * self.member_effective_params(state, threshold=1e-2)

    # -- population surgery (exploit / slot management) ------------------

    @staticmethod
    @jax.jit
    def gather_members(state: PopState, src_idx: jax.Array) -> PopState:
        """Exploit/copy: member i continues from member src_idx[i].

        The MPI weight transfer of the reference, as one device gather.
        """
        return jax.tree.map(lambda x: x[src_idx], state)

    @staticmethod
    @jax.jit
    def select_members(fresh_mask: jax.Array, fresh: PopState, existing: PopState) -> PopState:
        """Per-member choice between a fresh init and existing state."""
        def pick(a, b):
            m = fresh_mask.reshape((-1,) + (1,) * (a.ndim - 1))
            return jnp.where(m, a, b)
        return jax.tree.map(pick, fresh, existing)
