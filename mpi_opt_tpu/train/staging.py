"""Host-staged member waves: population > device residency.

The single-chip population envelope is RESIDENCY-bound, not speed-bound
(PERF_NOTES "single-chip population envelope": pop=1024 SmallCNN is
4.5 GB of params+momentum and dies RESOURCE_EXHAUSTED at warmup while
member throughput stays flat to pop=512). The reference's MPI worker
pool never hits this wall — members live in host processes and visit
the accelerator one trial at a time. This module is the fused-path
answer: keep a resident WAVE of W members on device, stream the cold
population through host memory, and hide the host<->device transfer
cost behind wave compute.

Three pieces:

- ``StagingEngine``: a single background worker thread that fetches
  trained wave state device->host (``jax.device_get`` blocks until the
  wave's compute completes, so the fetch doubles as that wave's
  completion barrier) and writes it into the host pool. The main thread
  meanwhile dispatches the NEXT wave's stage-in + compute — on this
  container's ~15-16 MB/s tunnel (PERF_NOTES round-5 addendum) a
  serial fetch per wave would dominate the sweep, so stage-out of wave
  k overlapping compute of wave k+1 is the difference between the
  feature existing and not. ``drain()`` is the generation boundary's
  completion barrier; its block time is the UN-hidden remainder of the
  transfer cost, which is why the engine accounts both.

- Host pool helpers: the cold population lives as one numpy pytree with
  a leading [P] member axis (``population_pool``, built from abstract
  member shapes); waves slice rows out (``stage_in``) and the engine
  writes trained rows back (``write_rows``). Two pools ping-pong per
  boundary (read the previous generation's/rung's states while writing
  this one's), which is what lets the NEXT boundary's stage-in apply
  the algorithm's survivor/winner index map lazily — PBT's exploit
  gather and SHA's rung-cut gather both become an indexed read, not an
  extra full-population copy. The per-algorithm wave loops live in
  train/engine.py (the shared fused engine); this module stays the
  transport + pool layer.

- ``estimate_wave_size``: the ``--wave-size auto`` residency estimate —
  per-member params+momentum bytes from ``jax.eval_shape`` (no compute,
  no allocation) against the device's reported memory budget, with
  double-buffer + activation headroom.

Memory contract: device holds at most TWO waves (the one computing and
the one being fetched); host holds two full population pools plus one
wave-sized staging slice.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Optional

import jax
import numpy as np


def tree_bytes(tree: Any) -> int:
    """Total leaf bytes of an array pytree (host or device)."""
    return sum(
        int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(tree)
    )


def population_pool(trainer, sample_x, population: int) -> dict:
    """Zeroed host pool for a full population's carried state, from
    ABSTRACT member shapes (``jax.eval_shape`` over the trainer's init:
    no device allocation — the whole point is that the full population
    never exists on device). Layout matches ``PopState`` fields."""
    params_sd = jax.eval_shape(trainer.init_fn, jax.random.key(0), sample_x)
    mk = lambda sd, dt: np.zeros((population,) + tuple(sd.shape), np.dtype(dt))
    dt = trainer.momentum_dtype
    return {
        "params": jax.tree.map(lambda sd: mk(sd, sd.dtype), params_sd),
        "momentum": jax.tree.map(lambda sd: mk(sd, dt or sd.dtype), params_sd),
        "step": np.zeros((population,), np.int32),
    }


def stage_in(pool: Any, rows: np.ndarray, mesh=None) -> Any:
    """Device copy of ``pool``'s ``rows`` (host gather + device_put).

    ``rows`` is an index array, so the previous generation's exploit
    source map composes for free: passing ``perm[lo:hi]`` stages in the
    WINNERS' states — the MPI weight transfer of the reference, as a
    host-side indexed read. With a mesh the wave lands sharded over
    'pop' (replicated, with the standard warning, when the wave size
    does not divide the axis). device_put is async — dispatching the
    wave's compute right after overlaps the upload with whatever the
    device is still finishing.

    Under multi-process SPMD every process holds the FULL host pool
    (identical by construction: the stage-in permutation is derived
    from in-jit RNG decisions every rank computes identically — the
    PERF_NOTES round-6 moral) and this function stages ITS devices'
    shard of the wave: a process-spanning mesh routes through
    ``shard_popstate_global``, whose per-shard callback reads only the
    rows this process's devices own.
    """
    sliced = jax.tree.map(lambda l: l[rows], pool)
    if mesh is None:
        return jax.device_put(sliced)
    from mpi_opt_tpu.parallel.mesh import (
        shard_popstate,
        shard_popstate_global,
        spans_processes,
    )

    if spans_processes(mesh):
        return shard_popstate_global(sliced, mesh)
    return shard_popstate(sliced, mesh)


def _fetch_tree(tree: Any) -> Any:  # sweeplint: barrier(the staging worker's fetch IS the wave's completion barrier — it blocks on the transfer thread, never the main loop)
    """Host copy of a wave's trained state, on the staging worker.

    The common case (host-local mesh or no mesh) is one batched
    ``jax.device_get``. Under a process-spanning mesh the leaves are
    NOT fully addressable and the fetch routes through
    ``fetch_global_batched`` — a collective (``process_allgather``), so
    it relies on the engine's strict-FIFO worker and the SPMD ranks'
    identical stage_out order: every process's staging thread issues
    the same collectives in the same sequence, the same discipline the
    deferred ledger flush already depends on.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if any(isinstance(l, jax.Array) and not l.is_fully_addressable for l in leaves):
        from mpi_opt_tpu.parallel.mesh import fetch_global_batched

        return jax.tree.unflatten(treedef, fetch_global_batched(leaves))
    return jax.device_get(tree)


def write_rows(pool: Any, lo: int, host_tree: Any) -> None:
    """Write a fetched wave (host arrays) into pool rows [lo, lo+W)."""

    def _assign(dst, src):
        dst[lo : lo + src.shape[0]] = src

    jax.tree.map(_assign, pool, host_tree)


class StagingEngine:
    """One background transfer thread + overlap accounting.

    ``stage_out(tree, on_host)`` enqueues: the worker fetches ``tree``
    to host (blocking THERE, not on the main thread) and calls
    ``on_host(host_tree)`` — jobs run strictly FIFO so pool writes are
    ordered. ``drain()`` blocks until every enqueued job has completed
    and re-raises the first worker error.

    Accounting (surfaced as ``staged_bytes`` / ``stage_overlap_s`` in
    sweep results and the metrics summary):
    - ``staged_bytes``: bytes moved, both directions (``note_bytes``
      adds the main thread's stage-in puts).
    - ``transfer_s``: worker busy seconds (fetch + pool write).
    - ``wait_s``: main-thread seconds blocked in ``drain()`` — the
      transfer cost that compute did NOT hide.
    - ``overlap_s`` = max(0, transfer_s - wait_s): the hidden part. A
      healthy wave schedule has overlap_s ~ transfer_s and wait_s ~ the
      final wave's fetch only.

    The cumulative ``overlap_s``/``wait_s`` values also ride on every
    ``stage_out``/``stage_wait`` span as attrs (ISSUE 11), so a traced
    run carries its overlap evidence in the stream itself — including
    a wave run killed mid-generation, whose summary counters never
    reach a result dict.
    """

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._errors: list[BaseException] = []
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._pending = 0
        self.staged_bytes = 0
        self.transfers = 0
        self.transfer_s = 0.0
        self.wait_s = 0.0
        self._thread = threading.Thread(
            target=self._loop, name="mpi-opt-staging", daemon=True
        )
        self._thread.start()
        self._closed = False

    # -- worker ----------------------------------------------------------

    def _loop(self):  # sweeplint: barrier(the transfer thread IS the barrier: its whole job is host<->device copies)
        from mpi_opt_tpu.health import heartbeat
        from mpi_opt_tpu.obs import memory, trace

        while True:
            job = self._q.get()
            if job is None:
                return
            tree, on_host = job
            t0 = time.perf_counter()
            try:
                # the stage_out span runs on THIS thread (obs/trace.py is
                # thread-safe): because device_get doubles as the wave's
                # completion barrier, its duration carries compute-wait +
                # transfer — overlap analysis reads it against the main
                # thread's train/stage_wait spans by timestamp
                with trace.span("stage_out") as sp:
                    # device_get blocks until the arrays' producing programs
                    # finish — this IS the wave's completion barrier, paid
                    # on this thread while the main thread dispatches ahead
                    host = _fetch_tree(tree)
                    on_host(host)
                    n_bytes = tree_bytes(host)
                    sp["bytes"] = n_bytes
                    # post-fetch watermark: both waves (computing +
                    # fetched) were resident just before this point — the
                    # reading the wave-size estimate needs validated
                    memory.note(sp)
                    with self._lock:
                        self.staged_bytes += n_bytes
                        self.transfers += 1
                        n = self.transfers
                        # the engine's CUMULATIVE overlap accounting on
                        # every transfer span (ISSUE 11): a wave run
                        # killed mid-generation still carries partial
                        # overlap evidence in its trace — the summary
                        # counters alone die with the process. This
                        # job's own elapsed rides in because transfer_s
                        # is only folded in by the finally below.
                        done_s = self.transfer_s + (time.perf_counter() - t0)
                        sp["wait_s"] = round(self.wait_s, 6)
                        sp["overlap_s"] = round(max(0.0, done_s - self.wait_s), 6)
                    # per-transfer liveness: the main thread parks in
                    # drain() at generation boundaries, so without beats
                    # from HERE a hung host<->device stage (dead tunnel,
                    # wedged runtime) freezes the wave silently until the
                    # whole-generation timeout — with them, launch.py's
                    # --stall-timeout can be sized to one wave's transfer.
                    # Beaten INSIDE the span so the beat's phase field
                    # reads "stage_out" — what a stall report shows.
                    # (heartbeat.beat is thread-safe; no-op when the CLI
                    # configured no heartbeat file)
                    heartbeat.beat(stage="staging transfer", transfers=n)
            # sweeplint: disable=drain-swallow -- transfer-thread containment: the error is stored and re-raised to the main thread by drain()
            except BaseException as e:  # surfaced by drain()
                with self._lock:
                    self._errors.append(e)
            finally:
                with self._lock:
                    self.transfer_s += time.perf_counter() - t0
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.notify_all()

    # -- main-thread API -------------------------------------------------

    def stage_out(self, tree: Any, on_host: Callable[[Any], None]) -> None:
        if self._closed:
            raise RuntimeError("StagingEngine is closed")
        with self._lock:
            if self._errors:  # fail fast instead of queueing onto a wreck
                raise self._errors[0]
            self._pending += 1
        self._q.put((tree, on_host))

    def note_bytes(self, n: int) -> None:
        """Account main-thread transfer bytes (stage-in device_puts)."""
        with self._lock:
            self.staged_bytes += int(n)

    def drain(self) -> None:
        """Completion barrier: block until all enqueued transfers are
        done; re-raise the first worker error. Block time is accounted
        as un-hidden transfer cost (``wait_s``) and traced as a
        ``stage_wait`` span — the staging cost compute did NOT hide,
        now a number the trace CLI reports instead of a summed counter."""
        from mpi_opt_tpu.obs import trace

        t0 = time.perf_counter()
        with trace.span("stage_wait") as sp:
            with self._idle:
                while self._pending:
                    self._idle.wait(timeout=0.5)
                self.wait_s += time.perf_counter() - t0
                # at a drain every enqueued transfer has completed, so
                # these are the engine's EXACT cumulative numbers — the
                # per-generation overlap evidence the trace layer
                # promotes into attribution (obs/bubbles.py)
                sp["wait_s"] = round(self.wait_s, 6)
                sp["overlap_s"] = round(self.overlap_s, 6)
                if self._errors:
                    raise self._errors[0]

    @property
    def overlap_s(self) -> float:
        return max(0.0, self.transfer_s - self.wait_s)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout=60)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _per_member_bytes(trainer, sample_x) -> int:
    """The static per-member envelope: params at their own dtypes plus
    momentum at the trainer's storage dtype, from ``jax.eval_shape``
    over the trainer's init (abstract — no compute, no allocation).
    ONE home for the byte math ``estimate_wave_size`` sizes with and
    ``envelope_report`` validates against measurement."""
    params_sd = jax.eval_shape(trainer.init_fn, jax.random.key(0), sample_x)
    p_bytes = tree_bytes(params_sd)
    m_dt = trainer.momentum_dtype
    if m_dt is None:
        return 2 * p_bytes
    itemsize = np.dtype(m_dt).itemsize
    return p_bytes + sum(
        int(np.prod(l.shape)) * itemsize for l in jax.tree.leaves(params_sd)
    )


def measured_train_peak(metrics_path: str) -> Optional[int]:
    """The max ``mem_peak_bytes`` watermark over the device-occupying
    spans (train / stage_in / stage_out) of a prior traced run's JSONL
    metrics stream (ISSUE 10 instrumented them; ISSUE 13 closes the
    loop by reading them back). None when the stream has no usable
    watermark — untraced run, missing file, or pre-watermark records.
    Torn/foreign lines are skipped, not fatal: a metrics stream is
    append-only and may end mid-line after a kill."""
    import json

    peak = None
    try:
        with open(metrics_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or rec.get("event") != "span":
                    continue
                if rec.get("span") not in ("train", "stage_in", "stage_out"):
                    continue
                v = rec.get("mem_peak_bytes")
                if isinstance(v, (int, float)):
                    peak = max(peak or 0, int(v))
    except OSError:
        return None
    return peak


def envelope_report(trainer, sample_x, population: int, metrics_path: str) -> dict:
    """Validate the static per-member envelope math against a MEASURED
    watermark (the carried ROADMAP item: "validate the 4.5 GB pop=1024
    envelope math against measured mem_peak_bytes watermarks").

    ``metrics_path`` is a prior traced run of the SAME (workload,
    population) — its train-span ``mem_peak_bytes`` is what the
    population actually cost the device (allocator counters on TPU;
    live-array accounting on CPU, which also sees datasets — the
    ``measured_over_static`` ratio is therefore a CEILING of the true
    state overhead there, honest but conservative). Returns::

        {"per_member_bytes", "static_pop_bytes", "measured_peak_bytes",
         "measured_over_static"}

    with None measurement fields when the stream carries no watermark.
    The static math is validated (not replaced): a ratio far above the
    activation-headroom assumption baked into ``estimate_wave_size``'s
    35% offer means the envelope UNDERestimates and auto waves would
    OOM — feed the measurement back via that function's
    ``measured_peak`` argument."""
    per_member = _per_member_bytes(trainer, sample_x)
    static_pop = per_member * int(population)
    peak = measured_train_peak(metrics_path)
    return {
        "per_member_bytes": int(per_member),
        "static_pop_bytes": int(static_pop),
        "measured_peak_bytes": None if peak is None else int(peak),
        "measured_over_static": (
            None if peak is None or static_pop <= 0 else round(peak / static_pop, 4)
        ),
    }


def estimate_wave_size(
    trainer,
    sample_x,
    population: int,
    mesh=None,
    budget_bytes: Optional[int] = None,
    measured_peak: Optional[tuple] = None,
) -> int:
    """Residency estimate for ``--wave-size auto``: the largest wave the
    device budget fits with double-buffer + activation headroom.

    Per-member bytes come from ``jax.eval_shape`` over the trainer's
    init (abstract — no compute, no allocation): params at their own
    dtypes plus momentum at the trainer's storage dtype. Budget
    resolution order (ISSUE 10): ``budget_bytes`` argument, else the
    ``MPI_OPT_TPU_DEVICE_BYTES`` env var (the operator's EXPLICIT
    override — it must beat a measurement, or there is no way to size
    waves for a device other than the one present), else the device's
    MEASURED capacity (``obs.memory.measured_budget()``: the
    ``memory_stats`` ``bytes_limit`` — absent on CPU), else a
    conservative 8 GiB default.
    Only ~35% of it is offered to ONE wave's params+momentum: the wave
    loop keeps up to two waves resident (compute + in-flight fetch) and
    training needs activation/update headroom on top (the measured
    envelope: 4.5 GB of state tipped a 16 GB chip — PERF_NOTES).

    ``measured_peak`` (ISSUE 13, closing the ROADMAP envelope-math
    item): ``(peak_bytes, resident_members)`` from a prior traced run —
    typically ``measured_train_peak(stream)`` with the members that run
    held resident. The measured all-in per-member cost (state +
    activations + double buffer, everything the allocator actually saw)
    sizes a second wave estimate WITHOUT the 35% static headroom guess
    (the measurement already includes what the guess models, modulo a
    15% safety margin), and the SMALLER of the two estimates wins —
    measurement tightens the static math, never loosens it past what
    the envelope would allow.

    With a mesh the wave shards over the 'pop' axis, so the budget
    scales by that axis and the result is rounded DOWN to a multiple of
    it (replicated waves would defeat the mesh silently). Returns a
    value in [1, population]; ``population`` means everything fits —
    callers run resident mode.

    Under multi-process SPMD this is a PER-HOST estimate (the budget
    sources — env override, ``memory_stats`` — describe the local
    devices); ``resolve_wave_size`` min-agrees the settled cap across
    ranks through the coord plane, so heterogeneous hosts converge on
    the most constrained one's answer rather than each guessing.
    """
    per_member = _per_member_bytes(trainer, sample_x)
    if budget_bytes is None:
        env = os.environ.get("MPI_OPT_TPU_DEVICE_BYTES")
        if env:
            budget_bytes = int(env)
    if budget_bytes is None:
        from mpi_opt_tpu.obs import memory as obs_memory

        budget_bytes = obs_memory.measured_budget()
    if budget_bytes is None:
        budget_bytes = 8 << 30
    n_pop = int(mesh.shape["pop"]) if mesh is not None else 1
    w = int(budget_bytes * 0.35 * n_pop // max(1, per_member))
    if measured_peak:
        peak_bytes, members = measured_peak
        if peak_bytes and members:
            # all-in measured cost per member: no 0.35 headroom guess
            # (the watermark already holds activations + buffers), just
            # a 15% safety margin against run-to-run spread
            measured_member = max(1, int(peak_bytes) // max(1, int(members)))
            w_measured = int(budget_bytes * 0.85 * n_pop // measured_member)
            w = min(w, max(1, w_measured))
    if mesh is not None and w > n_pop:
        w -= w % n_pop
    return max(1, min(population, w))
