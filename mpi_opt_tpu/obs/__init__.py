"""Observability: span tracing + phase-time attribution (ISSUE 8).

The paper's metric of record is wall-clock-to-target, but until this
layer the system could only measure totals — the ~2x kernel gap and the
140-210 s warmup (PERF_NOTES.md) were known from hand-run probes, not
from anything the system emits. ``obs`` closes that: library code wraps
its hot phases in ``trace.span("phase", ...)`` context managers that
emit rank-tagged, ``ts``-correlatable duration records into the
existing JSONL metrics stream, and ``mpi_opt_tpu trace FILE|DIR``
renders a phase-attribution table (wall %, p50/p95, achieved TF/s,
time-to-first-trial) over one or many streams.

Modules:
- ``trace``   — the tracer: ``span``/``traced``/``configure``; costs
  nothing when no sink is configured (the ``null_logger`` contract).
- ``events``  — the registry of every legal event/span/attr name; a
  tier-1 test walks the codebase and fails on an unregistered name.
- ``report``  — the ``trace`` subcommand (merge by ``ts``, attribute).
- ``diff``    — ``trace --diff``: two attributions become per-phase
  deltas with a noise-model significance verdict, and ``--gate``
  turns them into an exit code (the perf-regression gate, ISSUE 10).
- ``memory``  — device-memory watermark telemetry: ``memory_stats()``
  where the backend provides it, live-array accounting fallback;
  feeds span attrs, bench records, and ``estimate_wave_size`` auto.
- ``bubbles`` — intra-phase attribution (ISSUE 11): device-idle gaps
  between busy spans attributed by cause, the staging engine's
  overlap accounting promoted to per-run trace evidence, and the
  roofline verdict (compute-/transfer-/bubble-bound against a
  platform cap) the gate budgets via ``idle_frac``/``min_overlap``/
  ``min_mxu_frac``.
- ``timeline`` — ``trace --timeline OUT.json``: the merged span
  streams as Chrome trace-event JSON (Perfetto-loadable), per-rank
  process rows, per-thread tracks, and a synthetic device-idle track.
"""

from mpi_opt_tpu.obs import trace  # noqa: F401
