"""Span tracing: where a sweep's seconds go, from the system itself.

``span("phase", **attrs)`` wraps a region of host code; on exit it
emits ONE duration record into the configured metrics sink::

    {"event": "span", "span": "train", "dur_s": 1.23, "self_s": 1.01,
     "t": ..., "ts": <end, epoch>, "rank": 0, "tid": 0, ...attrs}

Design rules:

- **Null mode costs nothing.** With no sink configured (``configure``
  never called — every library/test entry point), a span does zero JSON
  work: it only pushes/pops a thread-local frame, which the heartbeat's
  ``phase`` field (health/heartbeat.py) needs even untraced. This is
  the ``null_logger`` contract extended to tracing.
- **Thread-safe.** Each thread owns its own span stack (StagingEngine's
  background transfer thread traces its fetches concurrently with the
  main loop); records carry a small ``tid`` so a consumer can rebuild
  per-thread nesting. The sink itself (MetricsLogger) serializes
  writes under its own lock.
- **Self time is computed at exit, not reconstructed.** Every span
  accumulates its direct children's durations in its stack frame;
  ``self_s = dur_s - children``. Attribution (obs/report.py) sums
  ``self_s``, so nested spans never double-count wall.
- **Tracing must never kill the run being traced**: sink failures warn
  once and go quiet (the heartbeat rule).
- **Correlatable**: ``ts`` is absolute epoch (MetricsLogger stamps it),
  so multi-rank launch.py streams and multi-tenant service streams
  merge by timestamp after the fact. ``rank``/``tenant`` tags are set
  at ``configure`` time.

Compile visibility rides jax's own monitoring events: a registered
duration listener turns every XLA backend compile into a ``compile``
span (``cache="cold"``) and every persistent-compilation-cache load
into one with ``cache="persistent"`` — an in-process jit-cache hit
emits nothing, which is itself the signal (a launch span with no
compile span inside it hit the jit cache). The listener charges the
duration to the enclosing span's child accumulator so self times stay
exclusive.

When a ``jax.profiler`` trace is active (utils/profiling.py), each
span additionally enters a ``jax.profiler.TraceAnnotation`` of the same
name, so XLA timelines carry sweep semantics ("train", "stage_in")
instead of bare op names.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from mpi_opt_tpu.utils import profiling

# -- process-global sink + tags ------------------------------------------

_SINK = None  # the MetricsLogger spans emit through (None = disabled)
_TAGS: dict = {}  # rank/tenant labels stamped into every record
# warn-once latch, deliberately unlocked: the race window is two
# threads both observing False and both warning — a duplicate warning,
# never a lost error; a lock on the emission failure path buys nothing
# sweeplint: disable=guarded-by -- idempotent warn-once latch: worst race outcome is a duplicate warning
_WARNED = False
_LOCAL = threading.local()  # .stack: list[[name, child_dur]]; .tid; .off
_TID_LOCK = threading.Lock()
_NEXT_TID = [0]
# best-effort cross-thread "most recently entered, still active" span
# name: the heartbeat's fallback when the BEATING thread holds no span
# (boundary beats happen between spans). Plain assignment — GIL-atomic,
# approximate under races, which is fine for a diagnostic label; a lock
# here would put a contention point inside EVERY span enter/exit.
# sweeplint: disable=guarded-by -- GIL-atomic store of a best-effort diagnostic label; approximate-under-races is the documented contract
_LAST_PHASE: Optional[str] = None


def _stack() -> list:
    st = getattr(_LOCAL, "stack", None)
    if st is None:
        st = _LOCAL.stack = []
        with _TID_LOCK:
            _LOCAL.tid = _NEXT_TID[0]
            _NEXT_TID[0] += 1
    return st


def configure(metrics, rank: int = 0, tenant: Optional[str] = None):
    """Install ``metrics`` (a MetricsLogger) as the span sink; returns
    the PRIOR (sink, tags) state for ``deconfigure`` — the service
    scheduler traces through its own stream while each tenant slice
    re-configures to the tenant's, so configuration must nest."""
    global _SINK, _TAGS
    prior = (_SINK, _TAGS)
    _SINK = metrics
    tags = {"rank": int(rank)}
    if tenant:
        tags["tenant"] = str(tenant)
    _TAGS = tags
    _install_compile_listener()
    return prior


def deconfigure(prior=None) -> None:
    """Drop (or restore) the span sink. ``prior`` is ``configure``'s
    return value; None restores the disabled state."""
    global _SINK, _TAGS
    if prior is None:
        _SINK, _TAGS = None, {}
    else:
        _SINK, _TAGS = prior


def save():
    """The current (sink, tags) state, shaped like ``configure``'s
    return value: capture at the top of an in-process CLI run and
    ``deconfigure(saved)`` in its finally, so a tenant slice that exits
    through ANY path (usage error included) restores the server's own
    sink instead of clobbering it."""
    return (_SINK, _TAGS)


def enabled() -> bool:
    return _SINK is not None


def note_device(sp: dict) -> None:
    """Attach the local device kind to an active span's attr dict (the
    setup spans carry it so obs/bubbles.py can default the roofline's
    platform cap from its calibration table without the operator
    passing --peak-tflops). No-op untraced, never raises — a telemetry
    attr must not kill the run."""
    if _SINK is None:
        return
    try:
        import jax

        sp["device"] = str(jax.local_devices()[0].device_kind)
    except Exception:
        pass


def current_phase() -> Optional[str]:
    """The calling thread's innermost active span name, else the most
    recently entered still-active span on any thread (best effort),
    else None. Feeds the heartbeat's ``phase`` field so a stall report
    can say "stalled during stage_in" instead of a bare kill."""
    st = getattr(_LOCAL, "stack", None)
    if st:
        return st[-1][0]
    return _LAST_PHASE


@contextlib.contextmanager
def suppressed():
    """Silence span emission on THIS thread for the body (the flops
    probe lowers tiny programs whose compile spans would pollute the
    sweep's own attribution)."""
    prev = getattr(_LOCAL, "suppress", False)
    _LOCAL.suppress = True
    try:
        yield
    finally:
        _LOCAL.suppress = prev


def _emit(name: str, dur_s: float, self_s: float, attrs: dict) -> None:
    global _WARNED
    sink = _SINK
    if sink is None or getattr(_LOCAL, "suppress", False):
        return
    try:
        sink.log(
            "span",
            span=name,
            dur_s=round(dur_s, 6),
            self_s=round(self_s, 6),
            tid=getattr(_LOCAL, "tid", 0),
            **_TAGS,
            **attrs,
        )
    except Exception as e:
        if not _WARNED:
            _WARNED = True
            import warnings

            warnings.warn(
                f"span emission failed ({type(e).__name__}: {e}); tracing "
                "records may be incomplete for this process",
                stacklevel=3,
            )


@contextlib.contextmanager
def span(name: str, **attrs):
    """Trace one phase of host work; yields a mutable dict for attrs
    only known at exit (``sp["bytes"] = n``). Exceptions propagate
    untouched — the span still emits, so a crashed phase is visible in
    the attribution rather than vanishing from it."""
    st = _stack()
    # an ``op`` attr joins the phase name (``boundary:rung_cut``): the
    # phase feeds heartbeat records and stall attribution, where "which
    # boundary op" is the question — the emitted span keeps the bare
    # name so per-kind aggregation is unchanged
    phase = f"{name}:{attrs['op']}" if "op" in attrs else name
    frame = [phase, 0.0]
    st.append(frame)
    global _LAST_PHASE
    _LAST_PHASE = phase
    ann = None
    if profiling.active():  # TraceAnnotation only under a live profiler
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
    t0 = time.perf_counter()
    try:
        yield attrs
    finally:
        dur = time.perf_counter() - t0
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        st.pop()
        _LAST_PHASE = st[-1][0] if st else None
        if st:
            st[-1][1] += dur  # credit the parent's child accumulator
        _emit(name, dur, max(0.0, dur - frame[1]), attrs)


def traced(name: Optional[str] = None, **attrs):
    """Decorator form of ``span``: ``@traced("save")`` (defaults to the
    function's own name)."""

    def deco(fn):
        import functools

        label = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(label, **attrs):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# -- compile visibility (jax.monitoring) ---------------------------------

# event key -> how the compile was satisfied. A cold compile records
# the backend_compile duration; a persistent-cache hit records only the
# retrieval time; an in-process jit-cache hit records neither.
_COMPILE_EVENTS = {
    "/jax/core/compile/backend_compile_duration": "cold",
    "/jax/compilation_cache/cache_retrieval_time_sec": "persistent",
}
_LISTENER_INSTALLED = False


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    kind = _COMPILE_EVENTS.get(event)
    if kind is None or _SINK is None or getattr(_LOCAL, "suppress", False):
        return
    # leaf span synthesized from jax's own measurement: charge it to the
    # enclosing span's children so that span's self time stays exclusive
    st = getattr(_LOCAL, "stack", None)
    during = None
    if st:
        st[-1][1] += float(duration)
        during = st[-1][0]
    _emit("compile", float(duration), float(duration), {"cache": kind, "during": during})


def _install_compile_listener() -> None:
    """Register the jax.monitoring duration listener ONCE per process.
    jax offers no single-listener removal, so the callback stays
    registered and goes inert (``_SINK is None`` check) when tracing is
    deconfigured."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    _LISTENER_INSTALLED = True
    try:
        import jax

        jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
    except Exception:  # pragma: no cover - jax-less environments
        pass
