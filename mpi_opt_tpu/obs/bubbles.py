"""Bubble attribution, staging-overlap promotion, and the roofline verdict.

PR 8 measured where *busy* time goes (per-phase self seconds); this
module measures where time HIDES — the device-idle gaps between
consecutive device-occupying spans, what was happening during each gap
(compile, staging wait, journal fsync, checkpoint I/O, setup,
unattributed), how much of the wave-staging transfer cost the double
buffer actually hid (promoted from StagingEngine's summary counters to
per-run trace evidence), and a roofline verdict per train launch:
compute-bound / transfer-bound / bubble-bound against a platform-cap
config. These are the numbers ROADMAP's top item (close the ~2x kernel
gap, scale waves to pop=1024) is graded with — PERF_NOTES could only
produce them from one-off probe runs.

Method notes:

- **Busy vs idle is per (tenant, rank).** Device-occupying spans
  (``BUSY_SPANS``: train, stage_in, stage_out, boundary) from ALL of a
  rank's threads merge into one interval union — the staging worker's
  ``stage_out`` overlapping the main thread's ``train`` is one
  continuous busy region, which is exactly the overlap working. Gaps
  are the complement within the rank's own [first-begin, last-end]
  window, so they are >= 0 by construction and cross-rank clock skew
  can never manufacture negative idle (ranks are never compared
  against each other's clocks).
- **Gap attribution is by overlap with host-side spans.** Each
  cause's merged intervals intersect each gap; ``unattributed`` is the
  gap time no span of any kind covers (host Python between phases —
  the dispatch loop itself). Distinct causes may overlap the same gap
  seconds (journal during an async save), so per-cause seconds are
  each honest but may sum past the gap total; ``unattributed`` uses
  the union of ALL non-busy spans and never goes negative.
- **Staging overlap prefers the engine's own cumulative counters.**
  stage_out/stage_wait spans carry ``overlap_s``/``wait_s`` attrs
  (train/staging.py emits the engine-lifetime values at every span,
  so a wave run killed mid-generation still carries partial overlap
  evidence); the newest tagged span IS the engine's accounting.
  Legacy streams without the attrs fall back to span-duration sums.
- **The roofline verdict** classifies where the next second of speedup
  lives: ``bubble-bound`` when the device idles more than
  ``IDLE_BOUND_FRAC`` of the wall, ``transfer-bound`` when un-hidden
  staging wait exceeds ``TRANSFER_BOUND_FRAC``, else ``compute-bound``
  — with ``mxu_frac`` (achieved TF/s over the platform cap) saying how
  far the kernel itself sits from the roof. The cap comes from
  ``--peak-tflops``, else ``CALIBRATED_PEAK_TFLOPS`` keyed by the
  device kind the setup span recorded (``trace.note_device``).
"""

from __future__ import annotations

from typing import Optional

#: spans during which the device is occupied (compute or an active
#: host<->device transfer); everything between their merged intervals
#: is a bubble
BUSY_SPANS = frozenset({"train", "stage_in", "stage_out", "boundary"})

#: non-busy span -> bubble cause bucket (anything else is "other")
CAUSE_OF_SPAN = {
    "compile": "compile",
    "stage_wait": "staging_wait",
    "journal": "journal",
    "save": "checkpoint",
    "save_wait": "checkpoint",
    "restore": "checkpoint",
    "digest": "checkpoint",
    "setup": "setup",
    "slice_setup": "setup",
}

#: run-level verdict thresholds (see module docstring). A quarter of
#: the wall is the point where the named cost dominates any plausible
#: kernel win — below it the kernel gap is the bigger lever.
IDLE_BOUND_FRAC = 0.25
TRANSFER_BOUND_FRAC = 0.25

#: measured platform matmul caps by device kind (TF/s) — the
#: ``measure_platform_cap`` numbers PERF_NOTES records, so a trace from
#: a known device gets a roofline without re-running the probe. Add a
#: line per measured device; unknown kinds need --peak-tflops.
CALIBRATED_PEAK_TFLOPS = {
    # PERF_NOTES round 3: 4096^3 bf16 fori_loop probe on the tunneled
    # chip this repo's BENCH history was measured on
    "TPU v5 lite": 157.0,
}


# -- interval arithmetic ---------------------------------------------------


def _merge(intervals: list) -> list:
    """Sorted union of (begin, end) intervals (empty/inverted dropped)."""
    ivs = sorted((b, e) for b, e in intervals if e > b)
    out: list = []
    for b, e in ivs:
        if out and b <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((b, e))
    return out

def _complement(merged: list, lo: float, hi: float) -> list:
    """Gaps of a MERGED interval union within [lo, hi] (each >= 0)."""
    gaps = []
    cur = lo
    for b, e in merged:
        if b > cur:
            gaps.append((cur, min(b, hi)))
        cur = max(cur, e)
        if cur >= hi:
            break
    if cur < hi:
        gaps.append((cur, hi))
    return [(b, e) for b, e in gaps if e > b]

def _overlap_len(merged: list, gap: tuple) -> float:
    """Seconds a MERGED union overlaps one (begin, end) gap."""
    lo, hi = gap
    return sum(max(0.0, min(e, hi) - max(b, lo)) for b, e in merged)


def _span_interval(rec: dict) -> tuple:
    ts = float(rec["ts"])
    return (ts - float(rec["dur_s"]), ts)


def _group_key(rec: dict) -> tuple:
    return (rec.get("tenant"), int(rec.get("rank") or 0))


def _group_label(key: tuple) -> str:
    tenant, rank = key
    return f"{tenant}:rank{rank}" if tenant else f"rank{rank}"


# -- bubble analysis -------------------------------------------------------


def analyze(spans: list, include_gaps: bool = False) -> Optional[dict]:
    """Device-idle gaps per (tenant, rank), attributed by cause.

    Returns the attribution's ``bubbles`` section (None when no spans):
    run totals (``wall_s``/``busy_s``/``idle_s``/``idle_frac``, gap
    count, largest gap, per-cause idle seconds) plus a ``per_rank``
    breakdown. ``wall_s`` is the SUM of per-rank windows (each rank
    judged on its own clock), so ``busy_s + idle_s == wall_s`` exactly
    — the invariant the tier-1 TIMELINE_DRILL asserts.
    ``include_gaps=True`` adds each rank's raw gap list (the timeline
    export's idle track); the attribution JSON omits it."""
    if not spans:
        return None
    groups: dict = {}
    for r in spans:
        groups.setdefault(_group_key(r), []).append(r)
    per_rank = {}
    tot_wall = tot_busy = tot_idle = tot_largest = 0.0
    tot_gaps = 0
    by_cause_tot: dict = {}
    for key in sorted(groups, key=lambda k: (k[0] or "", k[1])):
        group = groups[key]
        ivs = [_span_interval(r) for r in group]
        lo = min(b for b, _e in ivs)
        hi = max(e for _b, e in ivs)
        busy = _merge(
            [_span_interval(r) for r in group if r["span"] in BUSY_SPANS]
        )
        gaps = _complement(busy, lo, hi)
        cause_ivs: dict = {}
        all_nonbusy = []
        for r in group:
            if r["span"] in BUSY_SPANS:
                continue
            iv = _span_interval(r)
            all_nonbusy.append(iv)
            cause = CAUSE_OF_SPAN.get(r["span"], "other")
            cause_ivs.setdefault(cause, []).append(iv)
        cause_merged = {c: _merge(v) for c, v in cause_ivs.items()}
        nonbusy_merged = _merge(all_nonbusy)
        by_cause: dict = {}
        unattributed = 0.0
        gap_list = []
        for gap in gaps:
            g_len = gap[1] - gap[0]
            g_causes = {}
            for cause, merged in cause_merged.items():
                sec = _overlap_len(merged, gap)
                if sec > 0:
                    g_causes[cause] = sec
                    by_cause[cause] = by_cause.get(cause, 0.0) + sec
            covered = _overlap_len(nonbusy_merged, gap)
            un = max(0.0, g_len - covered)
            unattributed += un
            if include_gaps:
                dominant = (
                    max(g_causes, key=g_causes.get) if g_causes else "unattributed"
                )
                gap_list.append(
                    {
                        "begin_s": round(gap[0], 6),
                        "end_s": round(gap[1], 6),
                        "dur_s": round(g_len, 6),
                        "cause": dominant,
                    }
                )
        if unattributed > 0:
            by_cause["unattributed"] = unattributed
        wall = hi - lo
        idle = sum(e - b for b, e in gaps)
        busy_s = wall - idle
        entry = {
            "rank": key[1],
            "tenant": key[0],
            "wall_s": round(wall, 4),
            "busy_s": round(busy_s, 4),
            "idle_s": round(idle, 4),
            "idle_frac": round(idle / wall, 4) if wall > 0 else None,
            "gaps": len(gaps),
            "largest_gap_s": round(max((e - b for b, e in gaps), default=0.0), 4),
            "by_cause": {c: round(v, 4) for c, v in sorted(by_cause.items())},
        }
        if include_gaps:
            entry["gap_list"] = gap_list
        per_rank[_group_label(key)] = entry
        tot_wall += wall
        tot_busy += busy_s
        tot_idle += idle
        tot_gaps += len(gaps)
        tot_largest = max(tot_largest, entry["largest_gap_s"])
        for c, v in by_cause.items():
            by_cause_tot[c] = by_cause_tot.get(c, 0.0) + v
    return {
        "wall_s": round(tot_wall, 4),
        "busy_s": round(tot_busy, 4),
        "idle_s": round(tot_idle, 4),
        "idle_frac": round(tot_idle / tot_wall, 4) if tot_wall > 0 else None,
        "gaps": tot_gaps,
        "largest_gap_s": tot_largest,
        "by_cause": {c: round(v, 4) for c, v in sorted(by_cause_tot.items())},
        "per_rank": per_rank,
    }


# -- staging overlap -------------------------------------------------------


def staging_summary(spans: list) -> Optional[dict]:
    """The run's staging-overlap accounting, promoted from StagingEngine
    counters to trace evidence (None when the run staged nothing).

    Each (tenant, rank) group runs its OWN StagingEngine, so the
    cumulative counters are read per group and summed — collapsing a
    multi-rank merge onto one rank's newest span would divide one
    engine's overlap by every engine's transfer and under-report
    overlap by roughly the rank count. Per group:
    ``overlap_s``/``wait_s`` come from the newest stage span carrying
    the engine's cumulative attrs — exact, and present even for a run
    killed mid-generation; ``transfer_s`` is the sum of ``stage_out``
    durations (the worker's measured busy time); legacy streams without
    the attrs fall back to span-duration arithmetic. ``overlap_frac``
    is total overlap over total transfer — probe_wave's "overlap
    efficiency", now a per-run number instead of a probe printout."""
    groups: dict = {}
    for r in spans:
        if r["span"] in ("stage_out", "stage_wait", "stage_in"):
            groups.setdefault(_group_key(r), []).append(r)
    if not groups:
        return None
    transfer_s = wait_s = overlap_s = 0.0
    staged_bytes = n_outs = n_drains = 0
    for group in groups.values():
        outs = [r for r in group if r["span"] == "stage_out"]
        waits = [r for r in group if r["span"] == "stage_wait"]
        g_transfer = sum(float(r["dur_s"]) for r in outs)
        tagged = [
            r
            for r in outs + waits
            if isinstance(r.get("overlap_s"), (int, float))
            and isinstance(r.get("wait_s"), (int, float))
        ]
        if tagged:
            last = max(tagged, key=lambda r: float(r["ts"]))
            g_overlap, g_wait = float(last["overlap_s"]), float(last["wait_s"])
        else:
            g_wait = sum(float(r["dur_s"]) for r in waits)
            g_overlap = max(0.0, g_transfer - g_wait)
        transfer_s += g_transfer
        wait_s += g_wait
        overlap_s += g_overlap
        staged_bytes += sum(
            int(r["bytes"])
            for r in group
            if r["span"] != "stage_wait" and isinstance(r.get("bytes"), (int, float))
        )
        n_outs += len(outs)
        n_drains += len(waits)
    return {
        "transfer_s": round(transfer_s, 4),
        "wait_s": round(wait_s, 4),
        "overlap_s": round(overlap_s, 4),
        "overlap_frac": round(overlap_s / transfer_s, 4) if transfer_s > 0 else None,
        "staged_bytes": staged_bytes,
        "stage_outs": n_outs,
        "drains": n_drains,
    }


# -- the roofline verdict --------------------------------------------------


def resolve_peak(spans: list, peak_tflops=None) -> tuple:
    """(platform cap in TF/s, provenance) — explicit ``--peak-tflops``
    first, else the calibration table keyed by the device kind a setup
    span recorded, else (None, None)."""
    if peak_tflops:
        return float(peak_tflops), "cli"
    for r in spans:
        kind = r.get("device")
        if isinstance(kind, str) and kind in CALIBRATED_PEAK_TFLOPS:
            return CALIBRATED_PEAK_TFLOPS[kind], f"calibration:{kind}"
    return None, None


def roofline(
    spans: list,
    bubbles: Optional[dict],
    staging: Optional[dict],
    peak_tflops=None,
    peak_source=None,
) -> Optional[dict]:
    """The roofline section: per train launch, achieved TF/s against the
    platform cap (``mxu_frac``) and a bound verdict; run level, the
    single verdict the diff gate budgets (``idle_frac``/``min_overlap``
    /``min_mxu_frac`` keys). None when the run has no train spans."""
    train = sorted(
        (r for r in spans if r["span"] == "train"), key=lambda r: float(r["ts"])
    )
    if not train:
        return None
    # per-group stage_wait unions: a launch's un-hidden transfer wait is
    # the stage_wait time INSIDE its window, judged on its own rank
    waits_by_group: dict = {}
    for r in spans:
        if r["span"] == "stage_wait":
            waits_by_group.setdefault(_group_key(r), []).append(_span_interval(r))
    waits_by_group = {k: _merge(v) for k, v in waits_by_group.items()}
    per_launch = []
    for r in train:
        dur = float(r["dur_s"])
        window = _span_interval(r)
        stall = _overlap_len(waits_by_group.get(_group_key(r), []), window)
        stall_frac = stall / dur if dur > 0 else 0.0
        flops = r.get("flops")
        tflops = (
            float(flops) / dur / 1e12
            if isinstance(flops, (int, float)) and dur > 0
            else None
        )
        mxu = (
            round(tflops / peak_tflops, 4)
            if tflops is not None and peak_tflops
            else None
        )
        per_launch.append(
            {
                "launch": r.get("launch", r.get("batch")),
                "dur_s": round(dur, 4),
                "tflops_per_sec": None if tflops is None else round(tflops, 4),
                "mxu_frac": mxu,
                "stall_frac": round(stall_frac, 4),
                "bound": (
                    "transfer-bound"
                    if stall_frac > TRANSFER_BOUND_FRAC
                    else "compute-bound"
                ),
            }
        )
    with_flops = [
        (float(r["flops"]), float(r["dur_s"]))
        for r in train
        if isinstance(r.get("flops"), (int, float)) and float(r["dur_s"]) > 0
    ]
    tflops_all = (
        sum(f for f, _d in with_flops) / sum(d for _f, d in with_flops) / 1e12
        if with_flops
        else None
    )
    mxu_all = (
        round(tflops_all / peak_tflops, 4)
        if tflops_all is not None and peak_tflops
        else None
    )
    idle_frac = bubbles.get("idle_frac") if bubbles else None
    wall = bubbles.get("wall_s") if bubbles else None
    wait_frac = (
        round(staging["wait_s"] / wall, 4)
        if staging is not None and wall
        else None
    )
    if idle_frac is not None and idle_frac > IDLE_BOUND_FRAC:
        bound = "bubble-bound"
    elif wait_frac is not None and wait_frac > TRANSFER_BOUND_FRAC:
        bound = "transfer-bound"
    else:
        bound = "compute-bound"
    return {
        "peak_tflops": peak_tflops,
        "peak_source": peak_source,
        "tflops_per_sec": None if tflops_all is None else round(tflops_all, 4),
        "mxu_frac": mxu_all,
        "idle_frac": idle_frac,
        "stall_frac": wait_frac,
        "bound": bound,
        "per_launch": per_launch,
    }


# -- service surface -------------------------------------------------------


def stream_idle_frac(path: str) -> Optional[float]:
    """One-shot idle fraction of a metrics stream; None when the stream
    is unreadable or carries no spans — never an exception, a telemetry
    read must not kill its caller. The resident scheduler uses
    :class:`StreamIdleTracker` instead: this re-parses the whole file
    every call, which is O(n^2) over a long-lived tenant's slices."""
    try:
        from mpi_opt_tpu.obs.report import _is_span, load_stream

        spans = [r for r in load_stream(path) if _is_span(r)]
        rep = analyze(spans)
    except (OSError, ValueError, KeyError):
        return None
    return None if rep is None else rep["idle_frac"]


class StreamIdleTracker:
    """Incremental idle fraction over a GROWING metrics stream.

    The scheduler refreshes a tenant's ``idle_frac`` at every slice end;
    re-parsing the whole stream each time would make cumulative status
    cost quadratic in stream length over a resident tenant's lifetime.
    This tracker remembers its byte offset (complete lines only — the
    tenant may be mid-append), folds new busy spans into per-group
    merged interval unions, and derives idle as window minus busy union
    — the same accounting ``analyze`` does, minus cause attribution,
    which the per-slice status field doesn't need. ``poll()`` never
    raises and tolerates a stream that doesn't exist yet."""

    #: compact the per-group interval list once it grows past this — a
    #: merge is O(k log k) and busy spans mostly coalesce, so the list
    #: stays proportional to genuine gaps, not span count
    _COMPACT_AT = 64

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._groups: dict = {}  # group key -> [lo, hi, busy intervals]

    def poll(self) -> Optional[float]:
        import json as _json

        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
        except OSError:
            return self.idle_frac()
        end = data.rfind(b"\n")
        if end >= 0:
            self._offset += end + 1
            for raw in data[:end].splitlines():
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = _json.loads(line)
                except ValueError:
                    continue
                if not (
                    isinstance(rec, dict)
                    and rec.get("event") == "span"
                    and isinstance(rec.get("span"), str)
                    and isinstance(rec.get("dur_s"), (int, float))
                    and isinstance(rec.get("ts"), (int, float))
                ):
                    continue
                b, e = _span_interval(rec)
                g = self._groups.setdefault(_group_key(rec), [b, e, []])
                g[0], g[1] = min(g[0], b), max(g[1], e)
                if rec["span"] in BUSY_SPANS:
                    g[2].append((b, e))
                    if len(g[2]) > self._COMPACT_AT:
                        g[2] = _merge(g[2])
        return self.idle_frac()

    def idle_frac(self) -> Optional[float]:
        wall = busy = 0.0
        for lo, hi, ivs in self._groups.values():
            w = hi - lo
            if w <= 0:
                continue
            wall += w
            busy += min(w, sum(e - b for b, e in _merge(ivs)))
        if wall <= 0:
            return None
        return round(max(0.0, wall - busy) / wall, 4)
