"""The event/span name registry: one table of every legal name.

The metrics stream is a de-facto schema consumed by the trace CLI,
benches, the launch supervisor's relay, and outside log aggregation —
and it has already drifted silently once (``ts`` was added ad hoc in
PR 2). This module is the stop: every ``metrics.log("name", ...)``
event, every ``integrity.notify("name", ...)``, every supervisor
``_event("name", ...)`` and every ``trace.span("name", ...)`` must use
a name registered here. A tier-1 test (tests/test_obs.py) walks the
codebase with ``scan_call_sites`` and fails on any literal call-site
name missing from the tables — adding an event means adding one line
here, which is the point: the schema change becomes a reviewed diff.
"""

from __future__ import annotations

import ast

#: every legal ``event`` value in the JSONL metrics stream (including
#: launch.py's supervisor events and utils/integrity.py observer
#: notifications, which land in the same consumable stream shape)
EVENTS = frozenset(
    {
        # driver / sweep lifecycle
        "batch",
        "resume",
        "retry",
        "retry_exhausted",
        "summary",
        "sweep_aborted",
        "preempt_drain",
        "trial_failed",
        "trial_retry",
        "warm_start",
        "warm_start_skipped",
        # ledger layer
        "ledger_rank_gated",
        "ledger_replay",
        "ledger_replay_unconsumed",
        "ledger_torn_boundary_dropped",
        "ledger_torn_tail_dropped",
        # snapshot-integrity observer (utils/integrity.py)
        "snapshot_corrupt",
        "snapshot_io_retry",
        "snapshot_unverified",
        # resource-exhaustion observer (utils/resources.py):
        # oom_backoff = a device OOM absorbed by halving the wave and
        # re-running the generation (bit-identical); wave_resized = a
        # pre-launch headroom clamp of the wave size against the
        # measured device budget; snapshot_pruned = the ENOSPC
        # retention-prune retry deleted one superseded retained step
        "oom_backoff",
        "wave_resized",
        "snapshot_pruned",
        # launch.py supervisor events
        "launch",
        "done",
        "failed",
        "restart",
        "stall",
        "stall_restart",
        "preempted",
        "preempt_restart",
        # multi-process SPMD coordination (parallel/coord.py + the
        # supervisor's wedge classification): rank_agreed = a boundary
        # decision (drain / wave cap / OOM halving) settled unanimously
        # through the control plane; rank_wedge = a rank (or the
        # supervisor, observing dead-rank-plus-frozen-survivors)
        # concluded a peer never reached the boundary — the collective
        # is wedged and a coordinated restart is the recovery
        "rank_agreed",
        "rank_wedge",
        # sweep service (service/scheduler.py)
        "serve_start",
        "slice_start",
        "slice_end",
        "tenant_admit",
        "tenant_cancelled",
        "tenant_reject",
        # fleet federation (service/leases.py + scheduler):
        # tenant_takeover = an orphaned job claimed from a dead/expired
        # peer's lease; slice_fenced = a zombie slice's end-of-slice
        # writes refused (token mismatch); server_usurped = this
        # server's id was re-registered while it was presumed dead and
        # it stepped down (exit EX_UNAVAILABLE)
        "tenant_takeover",
        "slice_fenced",
        "server_usurped",
        # cross-sweep knowledge corpus (corpus/, ISSUE 14):
        # corpus_skip = one corpus source degraded during --warm-start
        # auto: resolution (stale index entry whose ledger was deleted/
        # rewritten, corrupt entry, unreadable ledger) — a skip, never
        # an error; the suggest_* family is the suggestion service's
        # lifecycle (serve start, one record per served request, the
        # stop/idle summary)
        "corpus_skip",
        # multi-objective search (objectives/, ISSUE 17): pareto_front =
        # a fused MO sweep's final non-dominated front (size,
        # hypervolume, selection kind); objective_degraded = a
        # constrained sweep found NOTHING feasible and typed-degraded
        # its winner to the least-violating member — an outcome to page
        # on, never a silent argmax
        "pareto_front",
        "objective_degraded",
        "suggest_serve",
        "suggest_request",
        "suggest_stop",
        # HTTP front door (service/http.py, ISSUE 16): lifecycle
        # (http_serve/http_stop), one http_request per executed batch,
        # and the overload envelope — http_shed (admission queue full,
        # typed 503), http_replayed (idempotent retry answered from the
        # dedup window), http_expired (past-deadline work expired at
        # dequeue, 504), breaker_open (per-client retry-storm breaker
        # tripped, 429s for the cooldown), http_error (a contained
        # executor fault answered as a typed 500)
        "http_serve",
        "http_request",
        "http_shed",
        "http_replayed",
        "http_expired",
        "breaker_open",
        "http_error",
        "http_stop",
        # span tracing (obs/trace.py): one event kind, span names below
        "span",
    }
)

#: every legal ``span`` name (the ``span`` field of a ``span`` event)
SPANS = frozenset(
    {
        "setup",  # workload data load + trainer/backend construction
        "compile",  # XLA compile (cache attr: cold | persistent)
        "train",  # one fused train launch / one driver evaluate batch
        "boundary",  # exploit / rung cut / generation-boundary op
        "stage_in",  # host->device wave upload (train/staging.py)
        "stage_out",  # device->host wave fetch + pool write
        "stage_wait",  # main-thread drain() block (un-hidden transfer)
        "save",  # orbax snapshot save (digest + enqueue)
        "save_wait",  # checkpointer close: async-save drain
        "restore",  # orbax snapshot restore attempt
        "digest",  # integrity manifest build / verification
        "journal",  # ledger fsync (per final trial / per fused boundary)
        "slice",  # one service scheduling quantum (server side)
        "slice_setup",  # service program-cache acquire + log open
    }
)


#: every legal span ATTRIBUTE key — the kwargs of ``trace.span(...)``
#: calls plus the keys set on the yielded dict (``sp["bytes"] = n``) and
#: the compile listener's synthesized attrs. The trace CLI, the diff
#: layer, and outside aggregation key on these names, so they are
#: schema the same way event/span names are: the ``event-registry``
#: sweeplint checker rejects a literal ``span()`` keyword missing here
#: (dict-set keys are registered by convention — AST can't prove a
#: subscript target is a span dict).
SPAN_ATTRS = frozenset(
    {
        # identity / position
        "launch",  # 1-based launch ordinal (train)
        "batch",  # driver batch ordinal (train)
        "boundary",  # fused journal boundary ordinal (journal)
        "gen",  # PBT generation (boundary op=exploit)
        "gens",  # generations covered by one launch (train)
        "rung",  # SHA rung ordinal (train, boundary op=rung_cut)
        "bracket",  # hyperband/BOHB bracket (boundary op=suggest)
        "waves",  # waves per generation (train, wave mode)
        "step",  # snapshot step (save/restore)
        "job",  # service tenant job id (slice/slice_setup)
        # shape / volume
        "members",  # population members in the phase
        "steps",  # train steps in the segment
        "n",  # generic count (journal records, suggest batch)
        "items",  # manifest items (digest)
        "bytes",  # bytes moved (stage_in/stage_out; set at exit)
        "flops",  # segment FLOPs for achieved TF/s (set at exit)
        # provenance
        "op",  # boundary/digest flavor (exploit/rung_cut/suggest/...)
        "objectives",  # MO sweep: comma-joined objective names (train)
        "backend",  # driver setup backend name
        "workload",  # fused setup workload name
        "cache",  # compile: cold | persistent (listener)
        "during",  # compile: enclosing span name (listener)
        "device",  # local device kind (setup; keys the roofline cap table)
        # device-memory watermark (obs/memory.py; set at exit)
        "mem_bytes",  # steady bytes_in_use at phase exit
        "mem_peak_bytes",  # peak/watermark bytes at phase exit
        "mem_src",  # accounting source: memory_stats | live_arrays
        # staging-overlap accounting (train/staging.py; the engine's
        # CUMULATIVE counters at emit time, so a run killed
        # mid-generation still carries partial overlap evidence)
        "overlap_s",  # hidden transfer seconds (stage_out/stage_wait)
        "wait_s",  # un-hidden drain-block seconds (stage_out/stage_wait)
        # bubble/roofline layer (obs/bubbles.py): synthesized into
        # timeline-export args and budgeted by the diff gate — schema
        # the same way emitted attrs are
        "idle_gap_s",  # one device-idle gap's seconds (timeline idle track)
        "cause",  # the gap's dominant attribution (compile/staging_wait/...)
        "bound",  # verdict: compute-bound | transfer-bound | bubble-bound
        "peak_tflops",  # platform cap the verdict was judged against
        "mxu_frac",  # achieved TF/s over the platform cap
    }
)


def is_event(name: str) -> bool:
    return name in EVENTS


def is_span(name: str) -> bool:
    return name in SPANS


def is_span_attr(name: str) -> bool:
    return name in SPAN_ATTRS


# -- scanner shims (ISSUE 9) ---------------------------------------------
#
# The AST call-site scanner that used to live here was generalized into
# the sweeplint framework (analysis/checkers_registry.EventRegistryChecker
# — one shared parse per file, same suppression/baseline machinery as
# every other invariant). The TABLES above stay here: they are the
# metrics-stream schema's home and what a schema change must diff. These
# shims keep the historical surface (tests/test_obs.py's registry lint,
# outside tooling) working unchanged.


def scan_call_sites(root: str):
    """Yield ``(path, lineno, kind, name)`` for every registered-emitter
    call site with a literal first argument under ``root`` (tests and
    probes excluded — they fabricate names on purpose). Thin shim over
    :mod:`mpi_opt_tpu.analysis.checkers_registry`; see its docstring for
    the emitter shapes gated."""
    from mpi_opt_tpu.analysis.checkers_registry import call_site
    from mpi_opt_tpu.analysis.core import iter_python_files

    for path in iter_python_files(root):
        try:
            with open(path) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                site = call_site(node)
                if site is not None:
                    yield path, node.lineno, site[0], site[1]


def lint(root: str) -> list:
    """Human-readable problems for unregistered names under ``root``
    (empty = clean). Shim over the ``event-registry`` sweeplint checker
    — the same check `mpi_opt_tpu lint` runs; the tier-1 gate wraps
    this."""
    from mpi_opt_tpu.analysis.checkers_registry import EventRegistryChecker
    from mpi_opt_tpu.analysis.core import run_paths

    findings, _n, errors = run_paths([root], [EventRegistryChecker()])
    return [f"{f.file}:{f.line}: {f.message}" for f in findings] + list(errors)
