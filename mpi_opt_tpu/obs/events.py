"""The event/span name registry: one table of every legal name.

The metrics stream is a de-facto schema consumed by the trace CLI,
benches, the launch supervisor's relay, and outside log aggregation —
and it has already drifted silently once (``ts`` was added ad hoc in
PR 2). This module is the stop: every ``metrics.log("name", ...)``
event, every ``integrity.notify("name", ...)``, every supervisor
``_event("name", ...)`` and every ``trace.span("name", ...)`` must use
a name registered here. A tier-1 test (tests/test_obs.py) walks the
codebase with ``scan_call_sites`` and fails on any literal call-site
name missing from the tables — adding an event means adding one line
here, which is the point: the schema change becomes a reviewed diff.
"""

from __future__ import annotations

import ast
import os

#: every legal ``event`` value in the JSONL metrics stream (including
#: launch.py's supervisor events and utils/integrity.py observer
#: notifications, which land in the same consumable stream shape)
EVENTS = frozenset(
    {
        # driver / sweep lifecycle
        "batch",
        "resume",
        "retry",
        "retry_exhausted",
        "summary",
        "sweep_aborted",
        "preempt_drain",
        "trial_failed",
        "trial_retry",
        "warm_start",
        "warm_start_skipped",
        # ledger layer
        "ledger_rank_gated",
        "ledger_replay",
        "ledger_replay_unconsumed",
        "ledger_torn_boundary_dropped",
        "ledger_torn_tail_dropped",
        # snapshot-integrity observer (utils/integrity.py)
        "snapshot_corrupt",
        "snapshot_io_retry",
        "snapshot_unverified",
        # launch.py supervisor events
        "launch",
        "done",
        "failed",
        "restart",
        "stall",
        "stall_restart",
        "preempted",
        "preempt_restart",
        # sweep service (service/scheduler.py)
        "serve_start",
        "slice_start",
        "slice_end",
        "tenant_admit",
        "tenant_cancelled",
        "tenant_recovered",
        "tenant_reject",
        # span tracing (obs/trace.py): one event kind, span names below
        "span",
    }
)

#: every legal ``span`` name (the ``span`` field of a ``span`` event)
SPANS = frozenset(
    {
        "setup",  # workload data load + trainer/backend construction
        "compile",  # XLA compile (cache attr: cold | persistent)
        "train",  # one fused train launch / one driver evaluate batch
        "boundary",  # exploit / rung cut / generation-boundary op
        "stage_in",  # host->device wave upload (train/staging.py)
        "stage_out",  # device->host wave fetch + pool write
        "stage_wait",  # main-thread drain() block (un-hidden transfer)
        "save",  # orbax snapshot save (digest + enqueue)
        "save_wait",  # checkpointer close: async-save drain
        "restore",  # orbax snapshot restore attempt
        "digest",  # integrity manifest build / verification
        "journal",  # ledger fsync (per final trial / per fused boundary)
        "slice",  # one service scheduling quantum (server side)
        "slice_setup",  # service program-cache acquire + log open
    }
)


def is_event(name: str) -> bool:
    return name in EVENTS


def is_span(name: str) -> bool:
    return name in SPANS


def _callee_kind(fn) -> str:
    """"event"/"span"/"" for a call's func node. ``log`` counts only as
    an ATTRIBUTE call (``metrics.log``) — bench.py's bare ``log(msg)``
    stderr helper is not an event emitter; ``notify``/``span``/``traced``
    count in both spellings; ``_event`` is launch.py's bare helper."""
    if isinstance(fn, ast.Attribute):
        name, is_attr = fn.attr, True
    elif isinstance(fn, ast.Name):
        name, is_attr = fn.id, False
    else:
        return ""
    if name == "log" and is_attr:
        return "event"
    if name in ("notify", "_event"):
        return "event"
    if name in ("span", "traced"):
        return "span"
    return ""


def scan_call_sites(root: str):
    """Walk ``root`` for Python files (tests excluded — they fabricate
    names on purpose) and yield ``(path, lineno, kind, name)`` for every
    call site whose first argument is a string literal and whose callee
    is one of the registered emitters:

    - kind ``"event"``: ``*.log("name", ...)``, ``notify("name", ...)``,
      ``*._event(...)`` / ``_event("name", ...)``;
    - kind ``"span"``: ``span("name", ...)`` / ``trace.span(...)`` /
      ``@traced("name")``.

    Non-literal first arguments are skipped (re-emission helpers like
    the integrity observer forward a variable). The tier-1 registry
    lint (tests/test_obs.py) is the one consumer.
    """
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d
            for d in dirnames
            if d not in ("__pycache__", ".git", "tests", "probes", "node_modules")
        ]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                with open(path) as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                first = node.args[0]
                if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
                    continue
                kind = _callee_kind(node.func)
                if kind:
                    yield path, node.lineno, kind, first.value


def lint(root: str) -> list:
    """Human-readable problems for unregistered names under ``root``
    (empty = clean). The tier-1 gate wraps this."""
    problems = []
    for path, lineno, kind, name in scan_call_sites(root):
        table = EVENTS if kind == "event" else SPANS
        if name not in table:
            problems.append(
                f"{path}:{lineno}: unregistered {kind} name {name!r} — "
                f"add it to obs/events.py {'EVENTS' if kind == 'event' else 'SPANS'}"
            )
    return problems
