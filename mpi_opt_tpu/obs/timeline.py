"""``trace --timeline OUT.json`` — Chrome trace-event export.

The span stream is already a timeline (every record carries absolute
``ts`` + ``dur_s``); this module renders it in the trace-event format
Perfetto (https://ui.perfetto.dev) and chrome://tracing load natively,
so a multi-rank / multi-tenant / multi-thread sweep becomes a zoomable
picture instead of a table:

- one PROCESS row per (tenant, rank) group — the same grouping the
  bubble analysis judges (ranks are never compared across clocks);
- one THREAD track per emitting thread (``tid``): the main host loop
  and StagingEngine's background transfer thread render as separate
  lanes, so stage-out overlapping compute is visible as overlap;
- every span is a complete ("X") event whose ``args`` carry the span's
  attrs verbatim (FLOPs, bytes, mem watermarks, launch ordinals...);
  train spans additionally carry the roofline verdict
  (``peak_tflops``/``mxu_frac``/``bound``) when a platform cap is
  known;
- non-span metrics events (batch, preempt_drain, slice_end...) become
  instant ("i") events on the same rows — the lifecycle markers that
  explain why a gap exists;
- a synthetic "device idle" track per process renders the bubble
  analysis itself: one X event per idle gap, named by its dominant
  cause, with ``idle_gap_s`` in args (obs/bubbles.py).

Timestamps are microseconds relative to the earliest record
(``otherData.t0_epoch_s`` keeps the absolute anchor), matching the
trace-event spec. The output is schema-tested (tests/test_obs_timeline
+ the tier-1 TIMELINE_DRILL), and written atomically — a Ctrl-C must
not leave a torn half-document where a dashboard polls.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from mpi_opt_tpu.obs import bubbles

#: record keys that are structure, not span args
_CORE_KEYS = frozenset(
    {"event", "span", "dur_s", "self_s", "ts", "t", "tid", "rank", "tenant"}
)

#: tid of the synthetic per-process idle track (far above real thread
#: ids, which are small allocation ordinals)
IDLE_TID = 10_000


def _us(seconds: float) -> float:
    # clamped at 0: gap boundaries come back from bubbles.analyze
    # rounded to 6 decimals, which can land a sub-microsecond BEFORE
    # the t0 anchor — a negative timestamp would fail the trace-event
    # schema over float dust
    return max(0.0, round(seconds * 1e6, 3))


def _args(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in _CORE_KEYS and v is not None}


def build(streams: dict, peak_tflops=None, attribution=None) -> dict:
    """The trace-event document over ``{label: records}`` streams (the
    same input shape as ``report.attribute``). ``attribution`` is an
    already-built ``attribute()`` result over the SAME streams: its
    staging/roofline sections are reused instead of recomputed — the
    trace CLI computes both anyway, and one analysis cannot drift from
    the other. Only the bubble pass reruns here (with ``include_gaps``:
    the idle track needs the raw gap list the attribution omits)."""
    from mpi_opt_tpu.obs.report import _begin, _is_span

    # deterministic label order (matching report.attribute's merge), so
    # stable sorts downstream break ts ties identically run to run
    merged = [r for label in sorted(streams) for r in streams[label]]
    if not merged:
        return {
            "traceEvents": [],
            "displayTimeUnit": "ms",
            "otherData": {"generator": "mpi_opt_tpu trace --timeline"},
        }
    spans = [r for r in merged if _is_span(r)]
    t0 = min(_begin(r) for r in merged)
    # stable pid per (tenant, rank): sorted so rank 0 renders first
    keys = sorted(
        {bubbles._group_key(r) for r in merged}, key=lambda k: (k[0] or "", k[1])
    )
    pid_of = {key: i + 1 for i, key in enumerate(keys)}
    events: list = []
    for key, pid in pid_of.items():
        tenant, rank = key
        name = f"tenant {tenant} · rank {rank}" if tenant else f"rank {rank}"
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": name},
            }
        )
        events.append(
            {
                "name": "process_sort_index",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "ts": 0,
                "args": {"sort_index": pid},
            }
        )
    # thread names: the staging worker is recognizable by what it emits
    threads: dict = {}
    for r in spans:
        tkey = (pid_of[bubbles._group_key(r)], int(r.get("tid") or 0))
        threads.setdefault(tkey, set()).add(r["span"])
    main_tid = {}
    for (pid, tid), _names in sorted(threads.items()):
        main_tid.setdefault(pid, tid)
    for (pid, tid), names in sorted(threads.items()):
        if "stage_out" in names:
            label = f"staging (tid {tid})"
        elif tid == main_tid[pid]:
            label = f"main (tid {tid})"
        else:
            label = f"tid {tid}"
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "ts": 0,
                "args": {"name": label},
            }
        )
    # roofline verdicts for train-event args: the gap-carrying bubble
    # pass always runs (the idle track needs it); the platform cap is
    # reused from the caller's attribution when given (one resolution,
    # no drift), but the per-launch list is recomputed over THIS
    # builder's own span list — zip pairs by sorted-by-ts position, and
    # only sorting the identical list makes ts ties pair exactly
    # (roofline itself is linear-ish and cheap next to analyze)
    bub = bubbles.analyze(spans, include_gaps=True)
    if attribution is not None:
        a_roof = attribution.get("roofline") or {}
        peak, peak_src = a_roof.get("peak_tflops"), a_roof.get("peak_source")
    else:
        peak, peak_src = bubbles.resolve_peak(spans, peak_tflops)
    roof = bubbles.roofline(spans, bub, bubbles.staging_summary(spans), peak, peak_src)
    launch_verdicts = {}
    if roof is not None:
        train = sorted(
            (r for r in spans if r["span"] == "train"), key=lambda r: float(r["ts"])
        )
        for r, entry in zip(train, roof["per_launch"]):
            launch_verdicts[id(r)] = entry
    for r in merged:
        pid = pid_of[bubbles._group_key(r)]
        if _is_span(r):
            args = _args(r)
            verdict = launch_verdicts.get(id(r))
            if verdict is not None and peak:
                args["peak_tflops"] = peak
                args["bound"] = verdict["bound"]
                if verdict["mxu_frac"] is not None:
                    args["mxu_frac"] = verdict["mxu_frac"]
            events.append(
                {
                    "name": r["span"],
                    "cat": "span",
                    "ph": "X",
                    "pid": pid,
                    "tid": int(r.get("tid") or 0),
                    "ts": _us(_begin(r) - t0),
                    "dur": max(0.0, _us(float(r["dur_s"]))),
                    "args": args,
                }
            )
        else:
            events.append(
                {
                    "name": str(r["event"]),
                    "cat": "event",
                    "ph": "i",
                    "s": "p",  # process-scoped instant marker
                    "pid": pid,
                    "tid": int(r.get("tid") or 0),
                    "ts": _us(float(r["ts"]) - t0),
                    "args": _args(r),
                }
            )
    # the bubble analysis as its own track: one X event per idle gap
    if bub is not None:
        for label, entry in bub["per_rank"].items():
            pid = pid_of[(entry["tenant"], entry["rank"])]
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": IDLE_TID,
                    "ts": 0,
                    "args": {"name": "device idle"},
                }
            )
            for gap in entry.get("gap_list", ()):
                events.append(
                    {
                        "name": f"idle:{gap['cause']}",
                        "cat": "bubble",
                        "ph": "X",
                        "pid": pid,
                        "tid": IDLE_TID,
                        "ts": _us(gap["begin_s"] - t0),
                        "dur": max(0.0, _us(gap["dur_s"])),
                        "args": {"idle_gap_s": gap["dur_s"], "cause": gap["cause"]},
                    }
                )
    other = {
        "generator": "mpi_opt_tpu trace --timeline",
        "t0_epoch_s": round(t0, 6),
        "streams": sorted(streams),
    }
    if peak:
        other["peak_tflops"] = peak
        other["peak_source"] = peak_src
    return {"traceEvents": events, "displayTimeUnit": "ms", "otherData": other}


def write_timeline(streams: dict, path: str, peak_tflops=None, attribution=None) -> int:
    """Build and atomically write the timeline document; returns the
    event count (the CLI's confirmation line)."""
    doc = build(streams, peak_tflops=peak_tflops, attribution=attribution)
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # failed mid-write: no orphan debris
            os.unlink(tmp)
    return len(doc["traceEvents"])


def validate_timeline(doc) -> list:
    """Problems with a trace-event document (empty = loads in Perfetto
    as far as the spec's required fields go). The tier-1 TIMELINE_DRILL
    and the schema test both run THIS, so the export and its gate
    cannot drift apart."""
    problems = []
    if not isinstance(doc, dict):
        return [f"document must be an object, not {type(doc).__name__}"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["missing/non-list 'traceEvents'"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for k in ("name", "ph", "pid", "tid", "ts"):
            if k not in ev:
                problems.append(f"event {i}: missing {k!r}")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M"):
            problems.append(f"event {i}: unexpected phase {ph!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: X event needs dur >= 0, got {dur!r}")
        if ph == "i" and ev.get("s") not in ("g", "p", "t"):
            problems.append(f"event {i}: instant scope {ev.get('s')!r}")
        if not isinstance(ev.get("ts"), (int, float)) or ev.get("ts") < 0:
            problems.append(f"event {i}: ts must be a number >= 0")
    pids = {ev.get("pid") for ev in evs if isinstance(ev, dict) and ev.get("ph") != "M"}
    named = {
        ev.get("pid")
        for ev in evs
        if isinstance(ev, dict)
        and ev.get("ph") == "M"
        and ev.get("name") == "process_name"
    }
    for pid in sorted(p for p in pids - named if p is not None):
        problems.append(f"pid {pid}: no process_name metadata")
    return problems
