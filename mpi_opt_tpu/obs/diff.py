"""Trace diffing: make two phase attributions COMPARABLE, with a gate.

PR 8 made phase time *emittable*; this module makes it *decidable*.
The bench plateau (8.35 -> 8.81 trials/s/chip across BENCH_r01-r05)
was only discoverable by a human re-reading JSON files, and the
raw-speed arc ahead (Pallas kernel, bf16, fused-engine refactor) needs
every round judged by a machine, not an eyeball:

    mpi_opt_tpu trace --diff BASE NEW [--json] [--gate TOL.json]

``BASE``/``NEW`` each load as an attribution from any of:

- a JSONL **metrics stream** (``--metrics-file`` output) or a
  **directory** of streams (launch ``--log-dir``, service
  ``--state-dir`` — every rank/tenant merges, same as ``trace DIR``);
- a ``trace --json`` **attribution file**;
- a **bench record** (``bench.py`` stdout line saved to a file, a
  ``BENCH_r0*.json`` driver wrapper with the record under ``parsed``,
  or a ``BENCH_ALL.json`` list) carrying an embedded ``trace``
  attribution — the BENCH trajectory becomes diffable directly.

Phases align by REGISTERED span name (obs/events.py), so a diff can
never pair unrelated work; a span present on one side only is reported
asymmetrically (``only_in_new`` is usually new instrumentation,
``only_in_base`` is usually lost coverage) and never silently dropped.

**The noise model.** A delta is *significant* only when it clears the
phase's own measured jitter, judged on per-span SELF seconds (exclusive
time — a cold compile nested inside launch 1's train span would
otherwise make every first-launch diff scream):

- with >= 2 spans per side and recorded spread: a z-test on mean self
  time (``z * sqrt(sd_b^2/n_b + sd_n^2/n_n) / mean_b``, z = 3);
- attributions without self-stats (pre-round-7 embeds) fall back to
  the duration percentiles' dispersion ``(p95 - p50)/p50``;
- single-span phases get a coarse ``single_sample_rel`` floor (0.5):
  one sample carries no spread, so only a gross change may flag;
- everything is floored at ``min_rel`` (10%) relative and
  ``min_abs_s`` (2 ms) absolute — a 3% jitter never pages anyone, a
  seeded 2x train-phase slowdown always does.

**The gate** (``--gate TOL.json``) applies per-phase tolerance budgets
on top of significance and exits 1 on regression — bench_all.py calls
the same machinery (``bench_gate``) over whole record sets so the
BENCH trajectory is a machine-checked verdict instead of an
append-only pile of JSON. Tolerance file keys (all optional)::

    {
      "default": 0.25,                  # max rel p50-self increase, any phase
      "phases": {"train": 0.10},        # per-phase overrides
      "ignore": ["journal"],            # phases never gated
      "require_significant": true,      # gate only noise-cleared deltas
      "max_cold_compile_increase": 0,   # extra cold compiles allowed
      "ttft_max_rel_increase": 0.5,     # time-to-first-trial budget
      "tflops_max_rel_decrease": 0.2,   # achieved-TF/s budget
      "wall_max_rel_increase": 0.25,    # whole-run wall budget
      "memory_max_rel_increase": 0.25,  # device-memory watermark budget
      "value_max_rel_regression": 0.25, # bench headline value (bench_gate)
      "idle_frac": 0.25,                # max device-idle fraction (NEW side)
      "min_overlap": 0.6,               # min staging overlap fraction (NEW side)
      "min_mxu_frac": 0.15              # min achieved/cap fraction (NEW side)
    }

The last three (ISSUE 11) budget the NEW run's ABSOLUTE intra-phase
numbers (obs/bubbles.py), not deltas — an idle-fraction ceiling, a
staging-overlap floor, and an MXU-utilization floor. ``idle_frac`` on
an attribution without bubble analysis (a pre-round-8 embed) is a
violation (lost coverage where someone declared they care);
``min_overlap`` skips runs that staged nothing (a resident run has no
transfer to hide); ``min_mxu_frac`` is a violation when achieved TF/s
or the platform cap is unmeasured (pass ``--peak-tflops`` or run on a
calibrated device kind).

Unknown keys are refused (a typo'd budget must not silently gate
nothing). The ``--json`` output is a stable schema mirroring
``fsck``/``report --validate``.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Optional

DIFF_SCHEMA_VERSION = 1

#: bench record schema: version 2 adds ``schema_version`` itself, the
#: embedded ``trace`` attribution (may be null under --no-trace) and the
#: ``device_memory`` watermark (obs/memory.py). Records WITHOUT a
#: schema_version are the pre-round-7 legacy shape (metric/value/unit
#: only) and stay loadable — the BENCH_r01-r05 trajectory must not
#: become unreadable history.
BENCH_SCHEMA_VERSION = 2

_TOL_KEYS = frozenset(
    {
        "default",
        "phases",
        "ignore",
        "require_significant",
        "max_cold_compile_increase",
        "ttft_max_rel_increase",
        "tflops_max_rel_decrease",
        "wall_max_rel_increase",
        "memory_max_rel_increase",
        "value_max_rel_regression",
        "idle_frac",
        "min_overlap",
        "min_mxu_frac",
    }
)

# noise-model defaults (see module docstring)
MIN_REL = 0.10
MIN_ABS_S = 0.002
Z_SCORE = 3.0
SINGLE_SAMPLE_REL = 0.5


# -- loading --------------------------------------------------------------


def _embedded_attribution(doc):
    """The attribution dict inside a parsed JSON document, or None.
    Accepts: an attribution itself (has ``phases``), a bench record
    (``trace`` key), a BENCH_r0*.json driver wrapper (``parsed``), or a
    BENCH_ALL.json list (exactly one record may carry a trace — with
    several, the caller must extract one; ambiguity is an error, not a
    guess)."""
    if isinstance(doc, list):
        hits = [d for d in doc if isinstance(d, dict) and isinstance(d.get("trace"), dict)]
        if len(hits) == 1:
            return _embedded_attribution(hits[0])
        if len(hits) > 1:
            raise ValueError(
                f"record list holds {len(hits)} embedded trace attributions "
                f"(configs {[h.get('config') for h in hits]}); extract one "
                "record, or use bench_all.py --gate-base for whole-set gating"
            )
        return None
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("phases"), dict):
        return doc
    if isinstance(doc.get("trace"), dict):
        return doc["trace"]
    if isinstance(doc.get("parsed"), (dict, list)):
        return _embedded_attribution(doc["parsed"])
    return None


def load_attribution(target: str, peak_tflops=None) -> dict:
    """Attribution for ``target`` (stream file / stream dir / trace
    --json file / bench record file). Raises ValueError/OSError with an
    actionable message. ``peak_tflops`` feeds the roofline when the
    target is a raw stream/dir; embedded attributions keep the cap they
    were built with."""
    from mpi_opt_tpu.obs.report import attribute, discover_streams, load_stream

    if os.path.isdir(target):
        hits = discover_streams(target)
        if not hits:
            raise ValueError(f"{target}: no metrics streams found")
        return attribute(
            {os.path.relpath(p, target): load_stream(p) for p in hits},
            peak_tflops=peak_tflops,
        )
    # stream-vs-document sniff on the FIRST line only: a metrics stream
    # is one complete JSON event object per line, so line 1 decides the
    # common case without reading a (possibly large, multi-rank) stream
    # into one string. Only the ambiguous shapes — a multi-line JSON
    # document, or a rank log with non-JSON preamble lines — pay a
    # whole-file parse attempt before falling back to the stream loader.
    doc = None
    with open(target, "r", errors="replace") as f:
        first = f.readline()
        try:
            head = json.loads(first)
        except json.JSONDecodeError:
            head = None
        if head is not None and not (isinstance(head, dict) and "event" in head):
            # line 1 is a JSON document (bench record line). If MORE
            # JSON lines follow (bench_all stdout saved to a file: one
            # record per line), collect them ALL and let the list rule
            # decide — silently diffing only line 1 of a multi-record
            # file would report one config as if it covered the set
            # ("ambiguity is an error, not a guess")
            rest = []
            jsonl = True
            for line in f:
                if not line.strip():
                    continue
                try:
                    rest.append(json.loads(line))
                except json.JSONDecodeError:
                    jsonl = False
                    break
            doc = [head] + rest if (rest and jsonl) else head
        elif head is None:
            f.seek(0)
            try:
                doc = json.loads(f.read())  # pretty-printed document?
            except json.JSONDecodeError:
                doc = None  # mixed rank log: the stream loader's case
    if doc is not None and not (isinstance(doc, dict) and "event" in doc):
        rep = _embedded_attribution(doc)
        if rep is None:
            raise ValueError(
                f"{target}: JSON document carries no trace attribution "
                "(no 'phases'/'trace' — a pre-BENCH_r06 record was "
                "measured before tracing existed and cannot be diffed)"
            )
        return rep
    records = load_stream(target)
    if not records:
        raise ValueError(f"{target}: no event records (not a metrics stream?)")
    return attribute({os.path.basename(target): records}, peak_tflops=peak_tflops)


# -- the noise model ------------------------------------------------------


def _metric_key(base: dict, new: dict) -> str:
    """The per-span duration this diff compares — chosen JOINTLY:
    median SELF seconds only when BOTH sides carry it (round 7+), else
    median inclusive duration for both. Falling back per side would
    compare exclusive seconds against inclusive ones and invent a
    regression out of metric mixing whenever a new stream is diffed
    against a legacy embed."""
    if base.get("p50_self_s") is not None and new.get("p50_self_s") is not None:
        return "p50_self_s"
    return "p50_s"


def _noise_rel(base: dict, new: dict) -> float:
    """The phase's own measured jitter as a relative band; deltas inside
    it are noise by construction."""
    n_b, n_n = int(base.get("count") or 0), int(new.get("count") or 0)
    sd_b, sd_n = base.get("sd_self_s"), new.get("sd_self_s")
    mean_b = base.get("mean_self_s")
    if (
        min(n_b, n_n) >= 2
        and sd_b is not None
        and sd_n is not None
        and mean_b
    ):
        se = math.sqrt(sd_b**2 / n_b + sd_n**2 / n_n)
        return max(MIN_REL, Z_SCORE * se / mean_b)
    # legacy attributions: dispersion from the duration percentiles
    disp = 0.0
    for p in (base, new):
        p50, p95 = p.get("p50_s") or 0.0, p.get("p95_s") or 0.0
        if p50 > 0:
            disp = max(disp, (p95 - p50) / p50)
    if min(n_b, n_n) <= 1:
        disp = max(disp, SINGLE_SAMPLE_REL)
    return max(MIN_REL, disp)


def _rel(base_v, new_v) -> Optional[float]:
    if base_v is None or new_v is None or base_v == 0:
        return None
    return (new_v - base_v) / abs(base_v)


def _diff_phase(base: dict, new: dict) -> dict:
    metric = _metric_key(base, new)
    b_m, n_m = base.get(metric), new.get(metric)
    delta = None if (b_m is None or n_m is None) else n_m - b_m
    rel = _rel(b_m, n_m)
    noise = _noise_rel(base, new)
    significant = (
        rel is not None
        and delta is not None
        and abs(delta) > MIN_ABS_S
        and abs(rel) > noise
    )
    keep = (
        "count",
        "total_s",
        "self_s",
        "p50_s",
        "p95_s",
        "mean_self_s",
        "sd_self_s",
        "p50_self_s",
        "mem_peak_bytes",
    )
    out = {
        "base": {k: base.get(k) for k in keep},
        "new": {k: new.get(k) for k in keep},
        "delta_total_s": round(float(new.get("total_s", 0)) - float(base.get("total_s", 0)), 4),
        "delta_self_s": round(float(new.get("self_s", 0)) - float(base.get("self_s", 0)), 4),
        "delta_p50_s": None
        if base.get("p50_s") is None or new.get("p50_s") is None
        else round(new["p50_s"] - base["p50_s"], 4),
        "delta_p95_s": None
        if base.get("p95_s") is None or new.get("p95_s") is None
        else round(new["p95_s"] - base["p95_s"], 4),
        "metric": metric,
        "base_metric_s": b_m,
        "new_metric_s": n_m,
        "delta_metric_s": None if delta is None else round(delta, 4),
        "rel": None if rel is None else round(rel, 4),
        "noise_rel": round(noise, 4),
        "significant": significant,
        "direction": (
            "flat"
            if not significant
            else ("regression" if delta > 0 else "improvement")
        ),
    }
    return out


# -- the diff -------------------------------------------------------------


def _only(phases: dict, names) -> list:
    return [
        {
            "span": n,
            "count": phases[n].get("count"),
            "total_s": phases[n].get("total_s"),
        }
        for n in sorted(names)
    ]


def diff_attributions(
    base: dict, new: dict, base_label: str = "base", new_label: str = "new"
) -> dict:
    """The full diff report over two attribution dicts (the ``--json``
    object, minus the ``gate`` section ``apply_gate`` adds)."""
    b_ph, n_ph = base.get("phases") or {}, new.get("phases") or {}
    shared = sorted(set(b_ph) & set(n_ph))
    phases = {name: _diff_phase(b_ph[name], n_ph[name]) for name in shared}
    compile_rep = {}
    for kind in ("cold", "persistent"):
        b = (base.get("compile") or {}).get(kind) or {}
        n = (new.get("compile") or {}).get(kind) or {}
        compile_rep[kind] = {
            "base_count": int(b.get("count") or 0),
            "new_count": int(n.get("count") or 0),
            "delta_count": int(n.get("count") or 0) - int(b.get("count") or 0),
            "base_total_s": float(b.get("total_s") or 0.0),
            "new_total_s": float(n.get("total_s") or 0.0),
            "delta_total_s": round(
                float(n.get("total_s") or 0.0) - float(b.get("total_s") or 0.0), 4
            ),
        }
    b_tr, n_tr = base.get("train"), new.get("train")
    train = None
    if b_tr and n_tr and b_tr.get("tflops_per_sec") and n_tr.get("tflops_per_sec"):
        train = {
            "base_tflops_per_sec": b_tr["tflops_per_sec"],
            "new_tflops_per_sec": n_tr["tflops_per_sec"],
            "rel": round(_rel(b_tr["tflops_per_sec"], n_tr["tflops_per_sec"]), 4),
        }
    ttft = None
    b_t, n_t = base.get("time_to_first_trial_s"), new.get("time_to_first_trial_s")
    if b_t is not None and n_t is not None:
        ttft = {
            "base_s": b_t,
            "new_s": n_t,
            "delta_s": round(n_t - b_t, 4),
            "rel": _rel(b_t, n_t) and round(_rel(b_t, n_t), 4),
        }
    wall = None
    b_w, n_w = base.get("wall_s"), new.get("wall_s")
    if b_w is not None and n_w is not None:
        wall = {
            "base_s": b_w,
            "new_s": n_w,
            "delta_s": round(n_w - b_w, 4),
            "rel": _rel(b_w, n_w) and round(_rel(b_w, n_w), 4),
        }
    memory = None
    b_mem = (base.get("memory") or {}).get("peak_bytes")
    n_mem = (new.get("memory") or {}).get("peak_bytes")
    if b_mem is not None and n_mem is not None:
        memory = {
            "base_peak_bytes": b_mem,
            "new_peak_bytes": n_mem,
            "delta_bytes": n_mem - b_mem,
            "rel": _rel(b_mem, n_mem) and round(_rel(b_mem, n_mem), 4),
        }
    # intra-phase sections (ISSUE 11): present when EITHER side carries
    # them — a one-sided section is how a legacy embed diffs against a
    # round-8+ stream without crashing or hiding the new measurement
    bubbles = None
    b_i = (base.get("bubbles") or {}).get("idle_frac")
    n_i = (new.get("bubbles") or {}).get("idle_frac")
    if b_i is not None or n_i is not None:
        bubbles = {
            "base_idle_frac": b_i,
            "new_idle_frac": n_i,
            "delta": round(n_i - b_i, 4) if b_i is not None and n_i is not None else None,
        }
    staging = None
    b_o = (base.get("staging") or {}).get("overlap_frac")
    n_o = (new.get("staging") or {}).get("overlap_frac")
    if base.get("staging") is not None or new.get("staging") is not None:
        staging = {
            "base_overlap_frac": b_o,
            "new_overlap_frac": n_o,
            "delta": round(n_o - b_o, 4) if b_o is not None and n_o is not None else None,
            "base_wait_s": (base.get("staging") or {}).get("wait_s"),
            "new_wait_s": (new.get("staging") or {}).get("wait_s"),
        }
    roofline = None
    b_r, n_r = base.get("roofline") or {}, new.get("roofline") or {}
    if b_r or n_r:
        b_m, n_m = b_r.get("mxu_frac"), n_r.get("mxu_frac")
        roofline = {
            "base_mxu_frac": b_m,
            "new_mxu_frac": n_m,
            "delta": round(n_m - b_m, 4) if b_m is not None and n_m is not None else None,
            "base_bound": b_r.get("bound"),
            "new_bound": n_r.get("bound"),
        }
    return {
        "tool": "tracediff",
        "schema_version": DIFF_SCHEMA_VERSION,
        "base": {
            "label": base_label,
            "wall_s": b_w,
            "records": base.get("records"),
            "span_records": base.get("span_records"),
        },
        "new": {
            "label": new_label,
            "wall_s": n_w,
            "records": new.get("records"),
            "span_records": new.get("span_records"),
        },
        "phases": phases,
        "only_in_base": _only(b_ph, set(b_ph) - set(n_ph)),
        "only_in_new": _only(n_ph, set(n_ph) - set(b_ph)),
        "compile": compile_rep,
        "train": train,
        "time_to_first_trial": ttft,
        "wall": wall,
        "memory": memory,
        "bubbles": bubbles,
        "staging": staging,
        "roofline": roofline,
        "significant_regressions": [
            n for n in shared if phases[n]["direction"] == "regression"
        ],
        "significant_improvements": [
            n for n in shared if phases[n]["direction"] == "improvement"
        ],
        "gate": None,
    }


# -- the gate -------------------------------------------------------------


def validate_tolerances(tol: dict) -> None:
    """Refuse unknown tolerance keys — a typo'd budget silently gating
    nothing is the CI failure mode this gate exists to prevent."""
    if not isinstance(tol, dict):
        raise ValueError(f"tolerance file must hold a JSON object, not {type(tol).__name__}")
    unknown = sorted(set(tol) - _TOL_KEYS)
    if unknown:
        raise ValueError(
            f"unknown tolerance keys {unknown}; legal keys: {sorted(_TOL_KEYS)}"
        )
    # value TYPES are validated here too: this runs BEFORE a bench run
    # is paid for, and a null/list budget surviving to apply_gate would
    # traceback only after the measurement (bool is an int subclass —
    # excluded: {"default": true} is a typo, not a budget)
    def _num(key, v):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(f"tolerance {key!r} must be a number, got {v!r}")

    for key in _TOL_KEYS - {"phases", "ignore", "require_significant"}:
        if key in tol:
            _num(key, tol[key])
    phases = tol.get("phases", {})
    if not isinstance(phases, dict):
        raise ValueError("'phases' must map span name -> max rel increase")
    for name, v in phases.items():
        _num(f"phases.{name}", v)
    ignore = tol.get("ignore", [])
    if not isinstance(ignore, (list, tuple)) or not all(
        isinstance(i, str) for i in ignore
    ):
        raise ValueError("'ignore' must be a list of span names")
    if "require_significant" in tol and not isinstance(
        tol["require_significant"], bool
    ):
        raise ValueError("'require_significant' must be a boolean")


def apply_gate(report: dict, tol: dict) -> dict:
    """Judge ``report`` against tolerance budgets; returns the ``gate``
    section ({ok, violations, tolerances}) and attaches it to the
    report. Regressions only — an improvement never fails a gate."""
    validate_tolerances(tol)
    default = float(tol.get("default", 0.25))
    per_phase = tol.get("phases", {})
    ignore = set(tol.get("ignore", ()))
    require_sig = bool(tol.get("require_significant", True))
    violations = []
    # a phase the operator EXPLICITLY budgeted that vanished from the
    # new side is lost coverage, not a pass: its regression became
    # unmeasurable exactly where someone declared they care (phases
    # under the default budget only may come and go — instrumentation
    # evolves — and stay visible via only_in_base)
    gone = {p["span"] for p in report.get("only_in_base", ())}
    for name in sorted(set(per_phase) & gone - ignore):
        violations.append(
            f"phase {name}: explicitly budgeted but missing from the new "
            "run (span lost — instrumentation dropped or tracing broken)"
        )
    for name, d in sorted(report["phases"].items()):
        if name in ignore:
            continue
        budget = float(per_phase.get(name, default))
        rel = d.get("rel")
        if rel is None or rel <= budget:
            continue
        if require_sig and not d.get("significant"):
            continue
        violations.append(
            f"phase {name}: {d['metric']} +{rel:.1%} exceeds the "
            f"{budget:.0%} budget (noise band {d['noise_rel']:.1%})"
        )
    if "max_cold_compile_increase" in tol:
        allowed = int(tol["max_cold_compile_increase"])
        delta = report["compile"]["cold"]["delta_count"]
        if delta > allowed:
            violations.append(
                f"compile: {delta} extra cold compile(s) exceeds the "
                f"allowed {allowed} (a warm path went cold)"
            )
    if "ttft_max_rel_increase" in tol and report["time_to_first_trial"]:
        rel = report["time_to_first_trial"].get("rel")
        budget = float(tol["ttft_max_rel_increase"])
        if rel is not None and rel > budget:
            violations.append(
                f"time-to-first-trial +{rel:.1%} exceeds the {budget:.0%} budget"
            )
    if "tflops_max_rel_decrease" in tol and report["train"]:
        rel = report["train"].get("rel")
        budget = float(tol["tflops_max_rel_decrease"])
        if rel is not None and -rel > budget:
            violations.append(
                f"achieved TF/s {rel:.1%} exceeds the -{budget:.0%} budget"
            )
    if "wall_max_rel_increase" in tol and report["wall"]:
        rel = report["wall"].get("rel")
        budget = float(tol["wall_max_rel_increase"])
        if rel is not None and rel > budget:
            violations.append(f"wall +{rel:.1%} exceeds the {budget:.0%} budget")
    if "memory_max_rel_increase" in tol and report["memory"]:
        rel = report["memory"].get("rel")
        budget = float(tol["memory_max_rel_increase"])
        if rel is not None and rel > budget:
            violations.append(
                f"device-memory watermark +{rel:.1%} exceeds the "
                f"{budget:.0%} budget"
            )
    # absolute intra-phase budgets (ISSUE 11): judged on the NEW side's
    # own numbers, not deltas — the diff's base is only context here
    if "idle_frac" in tol:
        budget = float(tol["idle_frac"])
        n_i = (report.get("bubbles") or {}).get("new_idle_frac")
        if n_i is None:
            # explicitly budgeted but unmeasurable: the lost-coverage
            # rule (same as a budgeted phase vanishing)
            violations.append(
                "idle_frac budgeted but the new attribution carries no "
                "bubble analysis (pre-round-8 embed, or a span-less stream)"
            )
        elif n_i > budget:
            violations.append(
                f"device-idle fraction {n_i:.1%} exceeds the {budget:.0%} "
                "budget (bubble-bound: see the trace table's idle-by-cause row)"
            )
    if "min_overlap" in tol:
        budget = float(tol["min_overlap"])
        n_o = (report.get("staging") or {}).get("new_overlap_frac")
        # None skips: a resident run stages nothing, so there is no
        # transfer to hide and no overlap to fall below a floor
        if n_o is not None and n_o < budget:
            violations.append(
                f"staging overlap {n_o:.1%} below the {budget:.0%} floor "
                "(the double buffer stopped hiding the transfer)"
            )
    if "min_mxu_frac" in tol:
        budget = float(tol["min_mxu_frac"])
        n_m = (report.get("roofline") or {}).get("new_mxu_frac")
        if n_m is None:
            violations.append(
                "min_mxu_frac budgeted but achieved TF/s or the platform "
                "cap is unmeasured (traced FLOPs + --peak-tflops or a "
                "calibrated device kind required)"
            )
        elif n_m < budget:
            violations.append(
                f"MXU utilization {n_m:.1%} of the platform cap is below "
                f"the {budget:.0%} floor (the kernel gap widened)"
            )
    gate = {"ok": not violations, "violations": violations, "tolerances": tol}
    report["gate"] = gate
    return gate


# -- rendering ------------------------------------------------------------


def _fmt_rel(rel) -> str:
    return "-" if rel is None else f"{rel:+.1%}"


def render_text(rep: dict) -> str:
    lines = [
        f"trace diff: {rep['base']['label']} (wall {rep['base']['wall_s']}s) "
        f"-> {rep['new']['label']} (wall {rep['new']['wall_s']}s"
        + (
            f", {_fmt_rel(rep['wall']['rel'])}"
            if rep["wall"] and rep["wall"].get("rel") is not None
            else ""
        )
        + ")"
    ]
    if rep["phases"]:
        lines.append(
            f"  {'phase':<12} {'base':>9} {'new':>9} {'delta':>9} "
            f"{'noise':>7}  verdict"
        )
        order = sorted(
            rep["phases"].items(),
            key=lambda kv: -abs(kv[1].get("delta_metric_s") or 0.0),
        )
        for name, d in order:
            b = "-" if d["base_metric_s"] is None else f"{d['base_metric_s']:.4f}"
            n = "-" if d["new_metric_s"] is None else f"{d['new_metric_s']:.4f}"
            verdict = d["direction"].upper() if d["significant"] else "ok"
            lines.append(
                f"  {name:<12} {b:>9} {n:>9} {_fmt_rel(d['rel']):>9} "
                f"{d['noise_rel']:>6.0%}  {verdict}"
            )
    for key, label in (("only_in_base", "removed"), ("only_in_new", "new")):
        for p in rep[key]:
            lines.append(
                f"  {label} phase: {p['span']} ({p['count']} span(s), "
                f"{p['total_s']}s total)"
            )
    c = rep["compile"]
    lines.append(
        f"  compile: cold {c['cold']['base_count']} -> {c['cold']['new_count']} "
        f"({c['cold']['delta_total_s']:+}s), persistent "
        f"{c['persistent']['base_count']} -> {c['persistent']['new_count']}"
    )
    if rep["train"]:
        t = rep["train"]
        lines.append(
            f"  train TF/s: {t['base_tflops_per_sec']} -> "
            f"{t['new_tflops_per_sec']} ({_fmt_rel(t['rel'])})"
        )
    if rep["time_to_first_trial"]:
        t = rep["time_to_first_trial"]
        lines.append(
            f"  time to first trial: {t['base_s']}s -> {t['new_s']}s "
            f"({_fmt_rel(t['rel'])})"
        )
    if rep["memory"]:
        m = rep["memory"]
        lines.append(
            f"  device-memory peak: {m['base_peak_bytes']} -> "
            f"{m['new_peak_bytes']} bytes ({_fmt_rel(m['rel'])})"
        )

    def _fmt_frac(v):
        return "-" if v is None else f"{v:.1%}"

    if rep.get("bubbles"):
        b = rep["bubbles"]
        lines.append(
            f"  idle fraction: {_fmt_frac(b['base_idle_frac'])} -> "
            f"{_fmt_frac(b['new_idle_frac'])}"
        )
    if rep.get("staging"):
        s = rep["staging"]
        lines.append(
            f"  staging overlap: {_fmt_frac(s['base_overlap_frac'])} -> "
            f"{_fmt_frac(s['new_overlap_frac'])}"
        )
    if rep.get("roofline"):
        r = rep["roofline"]
        lines.append(
            f"  roofline: {r['base_bound'] or '-'} -> {r['new_bound'] or '-'}"
            f" (MXU {_fmt_frac(r['base_mxu_frac'])} -> "
            f"{_fmt_frac(r['new_mxu_frac'])})"
        )
    if rep["gate"] is not None:
        if rep["gate"]["ok"]:
            lines.append("  gate: OK")
        else:
            lines.append("  gate: FAIL")
            for v in rep["gate"]["violations"]:
                lines.append(f"    {v}")
    return "\n".join(lines)


def diff_main(targets, json_out: bool, gate_path, error, peak_tflops=None) -> int:
    """The ``trace --diff`` body (``error`` is parser.error-shaped:
    usage problems exit 2; unreadable/undiffable TARGETS are runtime
    failures, rc 1, matching plain ``trace``)."""
    if len(targets) != 2:
        error(f"--diff takes exactly two targets (BASE NEW), got {len(targets)}")
    tol = None
    if gate_path:
        try:
            with open(gate_path) as f:
                tol = json.load(f)
            validate_tolerances(tol)
        except (OSError, ValueError) as e:
            error(f"--gate: {e}")
    sides = []
    for target in targets:
        try:
            sides.append(load_attribution(target, peak_tflops=peak_tflops))
        except (OSError, ValueError) as e:
            print(f"{target}: {e}", file=sys.stderr)
            if json_out:
                print(json.dumps({"tool": "tracediff", "error": str(e)}))
            return 1
    rep = diff_attributions(sides[0], sides[1], targets[0], targets[1])
    rc = 0
    if tol is not None:
        gate = apply_gate(rep, tol)
        if not gate["ok"]:
            rc = 1
    if json_out:
        print(json.dumps(rep))
    else:
        print(render_text(rep))
    if rc and not json_out:
        print("regression: gate budgets exceeded (exit 1)", file=sys.stderr)
    return rc


# -- bench record schema + trajectory gate --------------------------------


def validate_bench_record(rec) -> list:
    """Problems with one bench record (empty = valid). Legacy records
    (no ``schema_version``) need only metric/value/unit — the
    BENCH_r01-r05 history stays valid; version-2 records must also
    carry the ``trace`` and ``device_memory`` keys (null allowed: a
    --no-trace bench, a jax-less validator host) so the trajectory
    comparison can rely on their PRESENCE."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record must be an object, not {type(rec).__name__}"]
    if not isinstance(rec.get("metric"), str):
        problems.append("missing/non-string 'metric'")
    if not isinstance(rec.get("unit"), str):
        problems.append("missing/non-string 'unit'")
    if "value" not in rec:
        problems.append("missing 'value'")
    elif rec["value"] is not None and not isinstance(rec["value"], (int, float)):
        problems.append(f"'value' must be a number or null, got {rec['value']!r}")
    sv = rec.get("schema_version")
    if sv is None:
        return problems  # legacy (pre-round-7) shape
    if not isinstance(sv, int) or sv < 2:
        problems.append(f"'schema_version' must be an int >= 2, got {sv!r}")
        return problems
    if sv > BENCH_SCHEMA_VERSION:
        problems.append(
            f"'schema_version' {sv} is newer than this build's "
            f"{BENCH_SCHEMA_VERSION}"
        )
    for key in ("trace", "device_memory"):
        if key not in rec:
            problems.append(f"schema_version {sv} record missing '{key}' (null allowed)")
    tr = rec.get("trace")
    if tr is not None:
        if not isinstance(tr, dict) or not isinstance(tr.get("phases"), dict):
            problems.append("'trace' must be null or an attribution with 'phases'")
        else:
            for name, p in tr["phases"].items():
                for stat in ("count", "total_s", "self_s", "p50_s", "p95_s"):
                    if stat not in p:
                        problems.append(f"trace phase {name!r} missing {stat!r}")
                        break
            # the round-8 intra-phase sections are OPTIONAL (committed
            # BENCH_r01-r05 history and --no-trace records must keep
            # validating forever), but when present they must be objects
            for opt in ("bubbles", "staging", "roofline"):
                if tr.get(opt) is not None and not isinstance(tr[opt], dict):
                    problems.append(f"trace {opt!r} must be null or an object")
    mem = rec.get("device_memory")
    if mem is not None and (
        not isinstance(mem, dict) or "bytes_in_use" not in mem or "source" not in mem
    ):
        problems.append(
            "'device_memory' must be null or {bytes_in_use, source, ...}"
        )
    # the multi-objective summary (ISSUE 17, bench config 8) is OPTIONAL
    # forever — every scalar record (including the committed history)
    # stays valid without it — but a present 'scores' must be a
    # {objective: number} object so the trajectory comparison can rely
    # on its shape the same way it relies on trace/device_memory
    sc = rec.get("scores")
    if sc is not None and (
        not isinstance(sc, dict)
        or not sc
        or not all(
            isinstance(k, str)
            and isinstance(v, (int, float))
            and not isinstance(v, bool)
            for k, v in sc.items()
        )
    ):
        problems.append("'scores' must be null or a {objective: number} object")
    return problems


def _lower_is_better(rec: dict) -> bool:
    unit = str(rec.get("unit", ""))
    return "seconds" in unit or unit.endswith("_s")


def bench_gate(base_records, new_records, tol: Optional[dict] = None) -> dict:
    """The whole-trajectory verdict: match bench records (by ``config``,
    else by ``metric``), gate each pair's headline value, and — where
    both sides embed a trace attribution — run the full phase diff gate.
    The bench_all.py ``--gate-base`` entrypoint and CI consume this."""
    tol = dict(tol or {})
    validate_tolerances(tol)
    value_budget = float(tol.get("value_max_rel_regression", 0.25))

    def by_key(records):
        if isinstance(records, dict):
            records = [records]
        out = {}
        for r in records:
            if isinstance(r, dict) and isinstance(r.get("parsed"), dict):
                r = r["parsed"]  # BENCH_r0*.json driver wrapper
            if not isinstance(r, dict):
                continue
            key = r.get("config")
            if key is None:
                if "metric" not in r:
                    continue  # not a bench record at all
                key = r["metric"]
            else:
                key = f"config{key}"
            out[str(key)] = r
        return out

    base_by, new_by = by_key(base_records), by_key(new_records)
    configs = {}
    violations = []
    # zero comparable records is a FAILURE, not a clean verdict: a
    # typo'd --gate-base (wrong file, empty list, non-record shapes)
    # would otherwise gate nothing and exit 0 — the silent-CI-pass
    # failure mode this whole layer exists to prevent
    if not base_by or not new_by:
        side = "base" if not base_by else "new"
        violations.append(
            f"{side} record set holds no bench records (empty or "
            "non-record JSON — wrong file?)"
        )
    elif not set(base_by) & set(new_by):
        violations.append(
            f"no comparable records: base keys {sorted(base_by)} share "
            f"nothing with new keys {sorted(new_by)} (wrong --gate-base "
            "file, or this run measured different configs)"
        )
    for key in sorted(set(base_by) & set(new_by)):
        b, n = base_by[key], new_by[key]
        entry: dict = {"unit": n.get("unit")}
        bv, nv = b.get("value"), n.get("value")
        if nv is None and bv is not None:
            # the worst regression shape: the prior round measured a
            # value and this round has none (the config crashed and
            # recorded an error, or its target was never reached) — a
            # gate that shrugged here would pass exactly when a config
            # dies entirely
            note = n.get("error") or "no measured value in the new run"
            entry["value"] = {"base": bv, "new": None, "ok": False, "note": note}
            violations.append(
                f"{key}: no measured value in the new run "
                f"(base had {bv}; {note})"
            )
        elif bv is None:
            entry["value"] = {"ok": None, "note": "value missing in base"}
        else:
            if _lower_is_better(n):
                reg = (nv - bv) / abs(bv) if bv else None
            else:
                reg = (bv - nv) / abs(bv) if bv else None
            ok = reg is None or reg <= value_budget
            entry["value"] = {
                "base": bv,
                "new": nv,
                "regression_rel": None if reg is None else round(reg, 4),
                "budget": value_budget,
                "ok": ok,
            }
            if not ok:
                violations.append(
                    f"{key}: value {bv} -> {nv} regresses "
                    f"{reg:.1%} > {value_budget:.0%} budget"
                )
        if isinstance(b.get("trace"), dict) and isinstance(n.get("trace"), dict):
            rep = diff_attributions(b["trace"], n["trace"], f"{key}:base", f"{key}:new")
            gate = apply_gate(rep, {k: v for k, v in tol.items() if k != "value_max_rel_regression"})
            entry["trace_gate"] = {
                "ok": gate["ok"],
                "violations": gate["violations"],
                "significant_regressions": rep["significant_regressions"],
            }
            violations.extend(f"{key}: {v}" for v in gate["violations"])
        else:
            entry["trace_gate"] = None
        configs[key] = entry
    return {
        "tool": "benchgate",
        "schema_version": DIFF_SCHEMA_VERSION,
        "ok": not violations,
        "configs": configs,
        "unmatched_base": sorted(set(base_by) - set(new_by)),
        "unmatched_new": sorted(set(new_by) - set(base_by)),
        "violations": violations,
    }
