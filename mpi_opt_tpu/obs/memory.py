"""Device-memory watermark telemetry: what the sweep's state actually
costs in HBM, from the system itself.

The blind spot this closes (ISSUE 10): ``estimate_wave_size`` auto mode
sized waves from an 8 GiB env default because NO layer ever measured
device memory, and the bf16/residency plans in PERF_NOTES were built
from hand-derived byte math. This module is the one home for reading
it:

- ``sample()`` — one reading of the device's memory accounting:
  ``device.memory_stats()`` where the backend provides it (TPU: real
  allocator counters including ``peak_bytes_in_use`` and
  ``bytes_limit``), else a **live-array accounting fallback** (sum of
  ``jax.live_arrays()`` byte sizes — exact for the arrays the sweep
  holds, blind to allocator fragmentation and in-program temporaries;
  the ``source`` field says which accounting produced the numbers so a
  consumer never mistakes one for the other). The fallback's
  ``peak_bytes`` is a process-lifetime running max over *samples*, so a
  spike between samples is missed — honest steady-state, not a true
  high-water mark.
- ``note(sp)`` — attach the reading to an active span's attr dict
  (``mem_bytes`` steady / ``mem_peak_bytes`` watermark / ``mem_src``)
  at the phase boundaries that matter: train launches, wave staging,
  snapshot saves. Zero work when tracing is disabled (the
  ``null_logger`` contract — an untraced sweep never pays the
  live-array walk).
- ``measured_budget()`` — the device's reported ``bytes_limit`` for
  ``estimate_wave_size`` auto mode (None where the backend reports
  none; the resolution order — explicit arg, env override, THIS, 8 GiB
  default — lives in train/staging.py).
- ``watermark()`` — the record-shaped snapshot benches and the service
  status embed beside trials/s.

Attr names (``mem_bytes``/``mem_peak_bytes``/``mem_src``) are
registered in obs/events.py SPAN_ATTRS; the trace CLI renders them as
the per-phase memory column.
"""

from __future__ import annotations

import threading
from typing import Optional

from mpi_opt_tpu.obs import trace

# process-lifetime running peak for the live-array fallback (the real
# allocator keeps its own peak; this is the best a host-side account
# can do). Samples arrive from the staging transfer thread (stage_out
# spans note memory) AND the main loop, and the scheduler resets the
# window per slice — `max()` is a read-modify-write, so a racing pair
# could lose the larger reading or resurrect a pre-reset peak into the
# new slice's watermark (racelint guarded-by, ISSUE 15).
_PEAK_LOCK = threading.Lock()
_LIVE_PEAK = 0  # sweeplint: guarded-by(_PEAK_LOCK)


def reset_peak() -> None:
    """Drop the live-array fallback's running peak (tests; the service
    opens a per-slice watermark window; a bench that measures phases
    back-to-back wants each phase's own watermark)."""
    global _LIVE_PEAK
    with _PEAK_LOCK:
        _LIVE_PEAK = 0


def sample(device=None) -> Optional[dict]:
    """One memory reading for ``device`` (default: first local device):
    ``{"bytes_in_use", "peak_bytes", "bytes_limit", "source"}``, or
    None when no accounting exists at all (jax-less environment)."""
    global _LIVE_PEAK
    try:
        import jax

        if device is None:
            device = jax.local_devices()[0]
    except Exception:
        return None
    stats = None
    try:
        stats = device.memory_stats()
    except Exception:  # backends without the method raise, some return None
        stats = None
    if isinstance(stats, dict) and stats.get("bytes_in_use") is not None:
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        return {
            "bytes_in_use": int(stats["bytes_in_use"]),
            "peak_bytes": None if peak is None else int(peak),
            "bytes_limit": None if limit is None else int(limit),
            "source": "memory_stats",
        }
    # live-array fallback: exact for held state, blind to temporaries
    try:
        live = jax.live_arrays()
    except Exception:
        return None
    in_use = 0
    for a in live:
        try:
            in_use += int(a.nbytes)
        except Exception:  # deleted/donated arrays mid-walk
            pass
    with _PEAK_LOCK:
        _LIVE_PEAK = max(_LIVE_PEAK, in_use)
        peak = _LIVE_PEAK
    return {
        "bytes_in_use": in_use,
        "peak_bytes": peak,
        "bytes_limit": None,
        "source": "live_arrays",
    }


def note(sp: dict, device=None) -> None:
    """Attach the current reading to an active span's attr dict (the
    mutable mapping ``trace.span`` yields). No-op when tracing is
    disabled, so instrumented call sites cost nothing untraced."""
    if not trace.enabled():
        return
    m = sample(device)
    if m is None:
        return
    sp["mem_bytes"] = m["bytes_in_use"]
    sp["mem_peak_bytes"] = (
        m["bytes_in_use"] if m["peak_bytes"] is None else m["peak_bytes"]
    )
    sp["mem_src"] = m["source"]


def measured_budget(device=None) -> Optional[int]:
    """The device's reported memory capacity (``bytes_limit``), or None
    when the backend provides no allocator stats (CPU here returns
    None — the live-array fallback counts usage but knows no limit)."""
    m = sample(device)
    if m is None or m["source"] != "memory_stats":
        return None
    # `or None`: a backend reporting bytes_limit=0 has no usable limit —
    # without this guard a zero budget would silently force wave size 1
    # instead of falling through to the conservative default
    return m["bytes_limit"] or None


def watermark(device=None) -> Optional[dict]:
    """The bench/status-record snapshot: ``sample()`` by its consumer-
    facing name (benches embed it as ``device_memory``; the service
    writes it into tenant status after each slice)."""
    return sample(device)
