"""``mpi_opt_tpu trace FILE|DIR`` — phase-time attribution over metrics
streams.

Input is one or more JSONL metrics streams (``--metrics-file`` output;
a DIRECTORY is walked for streams — point it at a launch.py ``--log-dir``
or a service ``--state-dir`` and every rank's/tenant's stream merges).
Records are merged by absolute ``ts`` (the cross-process correlator
every record carries since PR 2) and span records (obs/trace.py) are
attributed:

- per-phase wall: count, total (inclusive) seconds, self (exclusive)
  seconds, percent of wall, p50/p95 span duration;
- compile breakdown: cold XLA compiles vs persistent-cache hits (an
  in-process jit-cache hit emits no compile span — its absence under a
  ``train`` span IS the jit-cache signal);
- achieved TF/s: ``train`` spans carry workload FLOP counts
  (train/common.segment_flops_hint); attribution divides by measured
  span time, per launch and overall — the number PERF_NOTES could only
  get from hand probes;
- time-to-first-trial: first completed train launch / driver batch
  relative to the stream's start — the warm-start metric the ROADMAP
  wants measured.

Coverage (attributed self-seconds / wall) can legitimately exceed 100%
when a background transfer thread overlaps compute — that overlap is
the staging engine doing its job, and burying it would hide the win.

Phase rows also carry per-span SELF statistics (mean/sd/p50/p95 of
exclusive seconds — what ``trace --diff``'s noise model judges) and a
device-memory watermark column where spans carried obs/memory.py attrs.

Intra-phase attribution (ISSUE 11, obs/bubbles.py) rides in three more
sections: ``bubbles`` (device-idle gaps between busy spans, attributed
to compile / staging wait / journal / checkpoint / setup /
unattributed, per rank), ``staging`` (the wave engine's
overlap/wait/transfer accounting promoted from summary counters to
per-run trace evidence), and ``roofline`` (achieved TF/s against a
platform cap: compute-bound / transfer-bound / bubble-bound, per train
launch and for the run; cap from ``--peak-tflops`` or the calibration
table keyed by the setup span's device kind).

``--json`` prints one machine-readable object (the bench/CI surface);
text mode renders the table. ``--timeline OUT.json`` additionally
exports the merged streams as Chrome trace-event JSON
(Perfetto-loadable; obs/timeline.py). ``--diff BASE NEW [--gate
TOL.json]`` dispatches to obs/diff.py: two attributions become
per-phase deltas with a significance verdict, and the gate turns them
into an exit code.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Optional

_MAX_SNIFF_LINES = 20


def sniff_stream(path: str) -> bool:
    """Is ``path`` a metrics stream? One JSON object per line carrying
    an ``event`` key (a ledger's lines carry ``kind`` instead — the
    trace CLI must not ingest journals as phase data). Mixed files
    (rank logs with non-JSON lines around the stream) still sniff true
    if any early line matches."""
    try:
        with open(path, "r", errors="replace") as f:
            for _ in range(_MAX_SNIFF_LINES):
                line = f.readline()
                if not line:
                    break
                line = line.strip()
                if not line or not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "event" in rec:
                    return True
    except OSError:
        return False
    return False


def discover_streams(directory: str) -> list:
    """Metrics streams under ``directory``: ``.jsonl``/``.out``/``.log``
    files that sniff as streams (launch.py rank logs are ``rank{i}.out``;
    service tenants write ``metrics.jsonl``).

    A service tenant's ``run.log`` captures the tenant's STDOUT copy of
    the same stream its ``metrics.jsonl`` holds (stdout_logger writes
    both) — ingesting both would double-count every span, so when a
    directory holds a sniffing ``metrics.jsonl``, its ``run.log`` is
    skipped. Rank ``.out`` logs have no metrics-file sibling and are
    kept."""
    found = []
    for root, _dirs, files in os.walk(directory):
        has_metrics = "metrics.jsonl" in files and sniff_stream(
            os.path.join(root, "metrics.jsonl")
        )
        for f in files:
            if not f.endswith((".jsonl", ".out", ".log")):
                continue
            if f == "run.log" and has_metrics:
                continue
            path = os.path.join(root, f)
            if sniff_stream(path):
                found.append(path)
    return sorted(found)


def load_stream(path: str) -> list:
    """Every parseable event record in ``path`` (non-JSON lines and
    non-event JSON — summaries' sibling shapes, stray prints — are
    skipped: a rank log legitimately mixes streams)."""
    records = []
    with open(path, "r", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line or not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "event" in rec and "ts" in rec:
                records.append(rec)
    return records


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _is_span(rec: dict) -> bool:
    return (
        rec.get("event") == "span"
        and isinstance(rec.get("span"), str)
        and isinstance(rec.get("dur_s"), (int, float))
    )


def _begin(rec: dict) -> float:
    ts = float(rec["ts"])
    return ts - float(rec["dur_s"]) if _is_span(rec) else ts


def _phase_table(spans: list, wall: float) -> dict:
    phases: dict = {}
    for r in spans:
        phases.setdefault(r["span"], []).append(r)
    out = {}
    for name in sorted(phases):
        group = phases[name]
        durs = sorted(float(r["dur_s"]) for r in group)
        selfs = sorted(float(r.get("self_s", r["dur_s"])) for r in group)
        self_s = sum(selfs)
        n = len(group)
        mean_self = self_s / n
        # per-span SELF spread: what trace --diff's noise model judges
        # significance against (self, not dur — a cold compile nested in
        # launch 1's train span must not look like train-phase jitter)
        sd_self = (
            math.sqrt(sum((v - mean_self) ** 2 for v in selfs) / (n - 1))
            if n >= 2
            else None
        )
        # per-phase device-memory watermark (obs/memory.py span attrs):
        # max over the phase's spans; None when untracked (CPU without
        # accounting, pre-round-7 streams)
        mem_peak = [r["mem_peak_bytes"] for r in group if isinstance(r.get("mem_peak_bytes"), (int, float))]
        mem_steady = [r["mem_bytes"] for r in group if isinstance(r.get("mem_bytes"), (int, float))]
        out[name] = {
            "count": n,
            "total_s": round(sum(durs), 4),
            "self_s": round(self_s, 4),
            "wall_pct": round(100.0 * self_s / wall, 2) if wall > 0 else None,
            "p50_s": round(_percentile(durs, 0.50), 4),
            "p95_s": round(_percentile(durs, 0.95), 4),
            "mean_self_s": round(mean_self, 6),
            "sd_self_s": None if sd_self is None else round(sd_self, 6),
            "p50_self_s": round(_percentile(selfs, 0.50), 6),
            "p95_self_s": round(_percentile(selfs, 0.95), 6),
            "mem_peak_bytes": max(mem_peak) if mem_peak else None,
            "mem_bytes": max(mem_steady) if mem_steady else None,
        }
    return out


def _memory_summary(spans: list) -> Optional[dict]:
    """The run-level device-memory watermark: the max over every span's
    memory attrs (None when nothing carried them)."""
    peaks = [r["mem_peak_bytes"] for r in spans if isinstance(r.get("mem_peak_bytes"), (int, float))]
    if not peaks:
        return None
    steady = [r["mem_bytes"] for r in spans if isinstance(r.get("mem_bytes"), (int, float))]
    srcs = sorted({r["mem_src"] for r in spans if isinstance(r.get("mem_src"), str)})
    return {
        "peak_bytes": int(max(peaks)),
        "bytes_in_use": int(max(steady)) if steady else None,
        # stable string|null schema even when merged streams mixed
        # accountings (a TPU rank beside a CPU fallback stream)
        "source": "+".join(srcs) if srcs else None,
    }


def _train_throughput(spans: list) -> Optional[dict]:
    """Achieved TF/s from flops-carrying train spans (None when no span
    carried a FLOP count — e.g. the backend's cost analysis was
    unavailable)."""
    train = [
        r
        for r in spans
        if r["span"] == "train" and isinstance(r.get("flops"), (int, float))
    ]
    if not train:
        return None
    per_launch = []
    for r in sorted(train, key=lambda r: (r.get("ts", 0.0))):
        d = float(r["dur_s"])
        per_launch.append(
            {
                "launch": r.get("launch"),
                "dur_s": round(d, 4),
                "flops": float(r["flops"]),
                "tflops_per_sec": round(float(r["flops"]) / d / 1e12, 4)
                if d > 0
                else None,
            }
        )
    flops = sum(e["flops"] for e in per_launch)
    dur = sum(e["dur_s"] for e in per_launch)
    return {
        "flops": flops,
        "train_s": round(dur, 4),
        "tflops_per_sec": round(flops / dur / 1e12, 4) if dur > 0 else None,
        "per_launch": per_launch,
    }


def _time_to_first_trial(records: list, t_start: float) -> Optional[float]:
    """Seconds from the stream's first record to the first completed
    trial evidence: the end of the first ``train`` span (a fused launch
    completes population x generations member-trials) or the first
    driver ``batch`` event."""
    marks = [
        float(r["ts"])
        for r in records
        if (_is_span(r) and r["span"] == "train") or r.get("event") == "batch"
    ]
    if not marks:
        return None
    return round(min(marks) - t_start, 4)


def _stream_summary(label: str, records: list) -> Optional[dict]:
    if not records:
        return None
    t_start = min(_begin(r) for r in records)
    t_end = max(float(r["ts"]) for r in records)
    wall = max(0.0, t_end - t_start)
    spans = [r for r in records if _is_span(r)]
    self_total = sum(float(r.get("self_s", r["dur_s"])) for r in spans)
    ranks = sorted({r["rank"] for r in spans if "rank" in r})
    tenants = sorted({r["tenant"] for r in spans if "tenant" in r})
    return {
        "label": label,
        "records": len(records),
        "span_records": len(spans),
        "wall_s": round(wall, 4),
        "t_start": round(t_start, 4),
        "t_end": round(t_end, 4),
        "rank": ranks[0] if len(ranks) == 1 else (ranks or None),
        "tenant": tenants[0] if len(tenants) == 1 else (tenants or None),
        "coverage": round(self_total / wall, 4) if wall > 0 else None,
        "time_to_first_trial_s": _time_to_first_trial(records, t_start),
    }


def attribute(streams: dict, peak_tflops=None) -> dict:
    """The full attribution over ``{label: records}`` streams, merged by
    absolute ``ts``. Returns the ``--json`` object. ``peak_tflops``
    overrides the roofline's platform cap (default: the calibration
    table keyed by the setup span's recorded device kind)."""
    from mpi_opt_tpu.obs import bubbles as _bubbles
    merged = []
    stream_summaries = []
    for label in sorted(streams):
        records = streams[label]
        s = _stream_summary(label, records)
        if s is not None:
            stream_summaries.append(s)
        merged.extend(records)
    merged.sort(key=lambda r: float(r["ts"]))
    spans = [r for r in merged if _is_span(r)]
    if merged:
        t_start = min(_begin(r) for r in merged)
        t_end = max(float(r["ts"]) for r in merged)
        wall = max(0.0, t_end - t_start)
    else:
        wall = 0.0
    self_total = sum(float(r.get("self_s", r["dur_s"])) for r in spans)
    compile_spans = [r for r in spans if r["span"] == "compile"]
    compile_rep = {}
    for kind in ("cold", "persistent"):
        group = [r for r in compile_spans if r.get("cache") == kind]
        compile_rep[kind] = {
            "count": len(group),
            "total_s": round(sum(float(r["dur_s"]) for r in group), 4),
        }
    tenants = sorted({r["tenant"] for r in spans if "tenant" in r})
    per_tenant = None
    if tenants:
        per_tenant = {
            t: _phase_table([r for r in spans if r.get("tenant") == t], wall)
            for t in tenants
        }
    ttft = [
        (s["label"], s["time_to_first_trial_s"])
        for s in stream_summaries
        if s["time_to_first_trial_s"] is not None
    ]
    # intra-phase attribution (obs/bubbles.py): idle gaps, staging
    # overlap, and the roofline verdict the diff gate budgets
    bubbles_rep = _bubbles.analyze(spans)
    staging_rep = _bubbles.staging_summary(spans)
    peak, peak_src = _bubbles.resolve_peak(spans, peak_tflops)
    roofline_rep = _bubbles.roofline(spans, bubbles_rep, staging_rep, peak, peak_src)
    return {
        "streams": stream_summaries,
        "records": len(merged),
        "span_records": len(spans),
        "wall_s": round(wall, 4),
        "attributed_s": round(self_total, 4),
        "coverage": round(self_total / wall, 4) if wall > 0 else None,
        "phases": _phase_table(spans, wall),
        "compile": compile_rep,
        "train": _train_throughput(spans),
        "time_to_first_trial_s": min((v for _l, v in ttft), default=None),
        "memory": _memory_summary(spans),
        "bubbles": bubbles_rep,
        "staging": staging_rep,
        "roofline": roofline_rep,
        "tenants": per_tenant,
    }


def bench_attribution(path: str, peak_tflops=None) -> dict:
    """The compact attribution subset benches embed beside trials/s
    (bench.py and bench_all.py both consume THIS, so the record shape
    cannot drift between the two harnesses). ``peak_tflops`` feeds the
    roofline — bench.py passes its MEASURED platform cap on TPU, the
    strongest possible roof; elsewhere the calibration table applies."""
    rep = attribute({os.path.basename(path): load_stream(path)}, peak_tflops=peak_tflops)
    return {
        k: rep.get(k)
        for k in (
            "wall_s",
            "coverage",
            "phases",
            "compile",
            "train",
            "time_to_first_trial_s",
            "memory",
            "bubbles",
            "staging",
            "roofline",
        )
    }


def _render_text(rep: dict) -> str:
    lines = [
        f"trace: {len(rep['streams'])} stream(s), {rep['records']} records "
        f"({rep['span_records']} spans), wall {rep['wall_s']}s"
        + (
            f", {round(100.0 * rep['coverage'], 1)}% attributed"
            if rep["coverage"] is not None
            else ""
        )
    ]
    if rep["phases"]:
        # memory column only when some phase carried a watermark (an
        # untraced-memory stream keeps the narrow historical table)
        has_mem = any(p.get("mem_peak_bytes") for p in rep["phases"].values())
        header = (
            f"  {'phase':<12} {'count':>6} {'total s':>9} {'self s':>9} "
            f"{'wall %':>7} {'p50 s':>8} {'p95 s':>8}"
        )
        if has_mem:
            header += f" {'mem MiB':>8}"
        lines.append(header)
        for name, p in sorted(
            rep["phases"].items(), key=lambda kv: -kv[1]["self_s"]
        ):
            pct = "-" if p["wall_pct"] is None else f"{p['wall_pct']:.1f}"
            row = (
                f"  {name:<12} {p['count']:>6} {p['total_s']:>9.3f} "
                f"{p['self_s']:>9.3f} {pct:>7} {p['p50_s']:>8.4f} {p['p95_s']:>8.4f}"
            )
            if has_mem:
                mem = p.get("mem_peak_bytes")
                row += f" {'-' if mem is None else format(mem / (1 << 20), '.1f'):>8}"
            lines.append(row)
    c = rep["compile"]
    if c.get("cold", {}).get("count") or c.get("persistent", {}).get("count"):
        lines.append(
            f"  compile: {c['cold']['count']} cold ({c['cold']['total_s']}s), "
            f"{c['persistent']['count']} persistent-cache hits "
            f"({c['persistent']['total_s']}s); train launches without a "
            "compile span hit the in-process jit cache"
        )
    t = rep["train"]
    roof = rep.get("roofline")
    # launch ordinals repeat across ranks/tenants in a merged stream —
    # annotate a throughput row only when its ordinal maps to exactly
    # ONE roofline entry, else the row would wear an arbitrary rank's
    # verdict (the --json per_launch list stays complete either way)
    launch_bound: dict = {}
    if roof is not None:
        for e in roof["per_launch"]:
            if e["launch"] is not None:
                launch_bound.setdefault(e["launch"], []).append(e)
    launch_bound = {k: v[0] for k, v in launch_bound.items() if len(v) == 1}
    if t is not None and t["tflops_per_sec"] is not None:
        lines.append(
            f"  train: {t['tflops_per_sec']} TF/s achieved "
            f"({t['flops']:.3e} FLOPs over {t['train_s']}s)"
        )
        for e in t["per_launch"]:
            if e["launch"] is not None:
                row = (
                    f"    launch {e['launch']}: {e['dur_s']}s, "
                    f"{e['tflops_per_sec']} TF/s"
                )
                v = launch_bound.get(e["launch"])
                if v is not None:
                    row += f", {v['bound']}"
                    if v["mxu_frac"] is not None:
                        row += f" ({round(100.0 * v['mxu_frac'], 1)}% of cap)"
                lines.append(row)
    stg = rep.get("staging")
    if stg is not None:
        pct = (
            "-"
            if stg["overlap_frac"] is None
            else f"{round(100.0 * stg['overlap_frac'], 1)}%"
        )
        lines.append(
            f"  staging: {stg['staged_bytes'] / 1e9:.3f} GB moved, transfer "
            f"{stg['transfer_s']}s, hidden {stg['overlap_s']}s ({pct} overlap), "
            f"wait {stg['wait_s']}s over {stg['drains']} drain(s)"
        )
    bub = rep.get("bubbles")
    if bub is not None and bub["wall_s"]:
        pct = (
            "-"
            if bub["idle_frac"] is None
            else f"{round(100.0 * bub['idle_frac'], 1)}%"
        )
        lines.append(
            f"  bubbles: {bub['idle_s']}s device-idle ({pct} of wall) over "
            f"{bub['gaps']} gap(s), largest {bub['largest_gap_s']}s"
        )
        if bub["by_cause"]:
            causes = ", ".join(
                f"{c} {v}s"
                for c, v in sorted(bub["by_cause"].items(), key=lambda kv: -kv[1])
            )
            lines.append(f"    idle by cause: {causes}")
    if roof is not None:
        if roof["mxu_frac"] is not None:
            detail = (
                f"{roof['tflops_per_sec']} TF/s = "
                f"{round(100.0 * roof['mxu_frac'], 1)}% of "
                f"{roof['peak_tflops']} TF/s cap [{roof['peak_source']}]"
            )
        elif roof["tflops_per_sec"] is not None:
            detail = f"{roof['tflops_per_sec']} TF/s achieved, no platform cap (--peak-tflops)"
        else:
            detail = "no traced FLOPs"
        lines.append(f"  roofline: {roof['bound']} ({detail})")
    if rep["time_to_first_trial_s"] is not None:
        lines.append(f"  time to first trial: {rep['time_to_first_trial_s']}s")
    mem = rep.get("memory")
    if mem is not None:
        steady = mem.get("bytes_in_use")
        lines.append(
            f"  device memory: peak {mem['peak_bytes'] / (1 << 20):.1f} MiB"
            + (
                f", steady {steady / (1 << 20):.1f} MiB"
                if steady is not None
                else ""
            )
            + f" ({mem['source']})"
        )
    if rep["tenants"]:
        for name, table in sorted(rep["tenants"].items()):
            busy = round(sum(p["self_s"] for p in table.values()), 3)
            top = sorted(table.items(), key=lambda kv: -kv[1]["self_s"])[:3]
            top_s = ", ".join(f"{n} {p['self_s']}s" for n, p in top)
            lines.append(f"  tenant {name}: {busy}s attributed ({top_s})")
    for s in rep["streams"]:
        if len(rep["streams"]) > 1:
            lines.append(
                f"  stream {s['label']}: wall {s['wall_s']}s, "
                f"{s['span_records']} spans"
                + (
                    f", first trial at {s['time_to_first_trial_s']}s"
                    if s["time_to_first_trial_s"] is not None
                    else ""
                )
            )
    return "\n".join(lines)


def trace_main(argv=None) -> int:
    """The ``mpi_opt_tpu trace`` subcommand (see cli.main dispatch)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu trace",
        description="phase-time attribution over JSONL metrics streams "
        "(see README: Observability)",
    )
    p.add_argument(
        "targets",
        nargs="+",
        metavar="FILE|DIR",
        help="metrics stream(s) (--metrics-file output), or directories "
        "to discover streams under (a launch --log-dir merges all ranks; "
        "a service --state-dir merges all tenants). With --diff: exactly "
        "two targets, each a stream/dir, a `trace --json` attribution "
        "file, or a bench record with an embedded trace",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--timeline",
        default=None,
        metavar="OUT.json",
        help="also export the merged streams as Chrome trace-event JSON "
        "(load in https://ui.perfetto.dev or chrome://tracing): per-rank "
        "process rows, per-thread tracks, span attrs as args, plus a "
        "'device idle' track rendering the bubble analysis",
    )
    p.add_argument(
        "--peak-tflops",
        type=float,
        default=None,
        help="platform matmul cap for the roofline verdict (TF/s); "
        "default: the obs/bubbles.py calibration table keyed by the "
        "device kind the setup span recorded",
    )
    p.add_argument(
        "--diff",
        action="store_true",
        help="compare two attributions (BASE NEW): per-phase deltas "
        "judged against each phase's own measured jitter, compile "
        "cold/persistent deltas, achieved-TF/s, time-to-first-trial and "
        "device-memory watermark deltas (obs/diff.py)",
    )
    p.add_argument(
        "--gate",
        default=None,
        metavar="TOL.json",
        help="with --diff: apply per-phase tolerance budgets from this "
        "file and exit 1 on regression (the bench-trajectory/CI gate; "
        "see README: Observability for the file format)",
    )
    args = p.parse_args(argv)
    if args.gate and not args.diff:
        p.error("--gate requires --diff")
    if args.timeline and args.diff:
        p.error("--timeline renders ONE run's streams; it cannot combine "
                "with --diff (export each side separately)")
    if args.peak_tflops is not None and args.peak_tflops <= 0:
        p.error(f"--peak-tflops must be > 0, got {args.peak_tflops}")
    if args.diff:
        from mpi_opt_tpu.obs.diff import diff_main

        return diff_main(
            args.targets,
            json_out=args.json,
            gate_path=args.gate,
            error=p.error,
            peak_tflops=args.peak_tflops,
        )

    streams: dict = {}

    def add(label, path):
        # labels must stay UNIQUE: two directory targets can both hold
        # a "metrics.jsonl", and a silent dict overwrite would report
        # one tenant's records as if they covered both — disambiguate
        # with the full path instead
        if label in streams:
            label = path
        streams[label] = load_stream(path)

    rc = 0
    for target in args.targets:
        if os.path.isdir(target):
            hits = discover_streams(target)
            if not hits:
                print(f"{target}: no metrics streams found", file=sys.stderr)
                rc = 1
            for path in hits:
                add(os.path.relpath(path, target), path)
        else:
            try:
                add(target, target)
            except OSError as e:
                print(f"{target}: {e}", file=sys.stderr)
                rc = 1
    if not any(streams.values()):
        if streams:
            print("no event records found in the given streams", file=sys.stderr)
            rc = 1
        if args.json:
            print(json.dumps({"streams": [], "records": 0, "phases": {}}))
        return rc
    rep = attribute(streams, peak_tflops=args.peak_tflops)
    if args.timeline:
        from mpi_opt_tpu.obs.timeline import write_timeline

        try:
            n = write_timeline(
                streams, args.timeline, peak_tflops=args.peak_tflops, attribution=rep
            )
        except OSError as e:
            print(f"--timeline {args.timeline}: {e}", file=sys.stderr)
            rc = 1
        else:
            # stderr: --json's stdout must stay one machine-parseable object
            print(f"timeline: {n} events -> {args.timeline}", file=sys.stderr)
    if args.json:
        print(json.dumps(rep))
    else:
        print(_render_text(rep))
    return rc
