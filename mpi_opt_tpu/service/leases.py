"""Per-job leases: fleet admission arbitration for a shared spool.

PR 7's service guarded the whole spool with ONE exclusive server claim
— correct for one device, but it means one dead host strands every
queued tenant until an operator intervenes. This module replaces that
with per-job leases so N ``serve`` processes (one per host/chip) share
one spool and arbitrate admission per tenant:

- **claim** — a server claims a tenant by atomically creating
  ``tenants/<job>/lease.json`` (``O_EXCL``; an expired lease is
  replaced through a rename-tomb protocol, never read-modify-write),
  carrying the server's identity, a fencing token, and a TTL deadline.
- **refresh** — the holder re-extends the deadline on a monotonic
  cadence well under the TTL, riding the tenant's existing heartbeat
  path (health/heartbeat.py beat listener) so refresh granularity is
  sub-launch, not per-boundary.
- **takeover** — any live server may claim a job whose lease expired
  (or whose holder is provably dead: same host, pid gone or /proc
  start time mismatching — pid reuse cannot fake liveness). The
  takeover itself is just the existing verified-snapshot +
  journal-prefix ``--resume``, so a tenant whose server was SIGKILLed
  mid-slice finishes on a survivor with a ledger record-identical to a
  solo run.
- **fencing** — every lease carries a unique token; the holder's
  tenant-metadata writes (status, terminal transitions) compare-and-
  check the token first, so a presumed-dead server that wakes up after
  a takeover has its late writes REFUSED instead of clobbering the new
  owner's record.

Clock honesty: the on-disk deadline is wall-clock ``time.time()`` (the
only clock shared through a filesystem); the HOLDER schedules its
refreshes against ``time.monotonic()`` so a suspend/step never makes it
think it refreshed recently. Takeover therefore requires expiry as
judged by the taker's wall clock — modest skew degrades to takeover
latency, never to double execution, because acquisition stays exclusive
(``O_EXCL`` / rename wins for exactly one claimant) and the TTL is the
operator's skew budget (see README: TTL tuning).

Residual window, stated honestly: a holder stalled LONGER than the TTL
(SIGSTOP, multi-second GC on a dying box) can still be executing one
in-flight launch while the taker resumes from the last boundary. The
fence turns the zombie's metadata writes into refusals and its own
drain request fires at the first beat after it wakes; the journal's
verify-don't-rewrite resume refuses divergence (exit 65) rather than
double-recording. Size the TTL above the longest beat gap (one launch)
to keep that window theoretical.

This module is the ONLY writer of lease files — a sweeplint checker
(``lease-write``) machine-enforces that, because a lease written any
other way (read-modify-write, non-atomic) silently breaks the
exactly-one-claimant argument everything above rests on.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from mpi_opt_tpu.service.spool import (
    _local_host,
    _pid_start,
    _read_json,
    claim_file,
    excl_write_json,
    tomb_discard,
    tomb_take,
)


class LeaseFenced(RuntimeError):
    """The caller's lease token no longer matches the lease file: the
    job was taken over while the caller was presumed dead. Every write
    the caller intended for this tenant must be abandoned — the new
    owner's record is authoritative."""


_TOKEN_SEQ = [0]
_TOKEN_LOCK = threading.Lock()


@dataclass(frozen=True)
class ServerIdentity:
    """Who is claiming: the fencing identity a lease (and a server
    registration) records. ``pid_start`` is the kernel's /proc start
    time — pid + start time is collision-proof against pid reuse, the
    exact hole a bare-pid liveness check leaves open."""

    server_id: str
    pid: int
    pid_start: Optional[str]
    host: str

    @classmethod
    def local(cls, server_id: str) -> "ServerIdentity":
        pid = os.getpid()
        return cls(server_id, pid, _pid_start(pid), _local_host())

    def new_token(self) -> str:
        """A token unique per ACQUISITION, not just per process: the
        sequence suffix keeps re-acquire-after-release by the same
        process distinguishable, so fencing judgements never alias two
        different ownership epochs of one server."""
        with _TOKEN_LOCK:
            _TOKEN_SEQ[0] += 1
            seq = _TOKEN_SEQ[0]
        return f"{self.server_id}@{self.host}:{self.pid}:{self.pid_start}#{seq}"


def read_lease(path: str) -> Optional[dict]:
    """The lease record at ``path`` or None (absent/unreadable — an
    unreadable lease is treated as expired by ``acquire``, because a
    torn file can only result from a crashed writer)."""
    return _read_json(path)


def holder_dead(lease: dict) -> bool:
    """Is the lease's holder PROVABLY dead? Only judgeable on the
    holder's own host (a pid means nothing across machines): pid gone,
    or alive but with a different /proc start time (the kernel recycled
    the pid for an unrelated process)."""
    if lease.get("host") != _local_host():
        return False
    try:
        pid = int(lease["pid"])
    except (KeyError, TypeError, ValueError):
        return True
    try:
        os.kill(pid, 0)
    except PermissionError:
        pass  # EPERM: alive, owned by someone else
    except OSError:
        return True
    recorded = lease.get("pid_start")
    if recorded is not None:
        current = _pid_start(pid)
        if current is not None and current != recorded:
            return True
    return False


def expired(lease: dict, now: Optional[float] = None) -> bool:
    """May this lease be taken over? True past the wall-clock deadline,
    or immediately when the holder is provably dead (the SIGKILL fast
    path: no reason to wait out a TTL for a corpse)."""
    if holder_dead(lease):
        return True
    try:
        deadline = float(lease["expires_ts"])
    except (KeyError, TypeError, ValueError):
        return True  # a lease without a deadline is not a lease
    return (time.time() if now is None else now) > deadline


def _fresh(ident: ServerIdentity, ttl_s: float, token: Optional[str] = None) -> dict:
    now = time.time()
    return {
        "server_id": ident.server_id,
        "pid": ident.pid,
        "pid_start": ident.pid_start,
        "host": ident.host,
        "token": token or ident.new_token(),
        "ttl_s": float(ttl_s),
        "acquired_ts": round(now, 4),
        "expires_ts": round(now + float(ttl_s), 4),
        "refreshes": 0,
    }


def acquire(path: str, ident: ServerIdentity, ttl_s: float) -> Optional[dict]:
    """Claim the lease at ``path`` for ``ident``; returns the lease
    record we now hold, or None when a live peer holds it.

    Never read-modify-write: delegates to ``spool.claim_file`` — the
    ONE exclusive-claim protocol (O_EXCL create, rename-tomb steal of
    an expired claim, inspect-after-steal restore-and-concede) that
    server registrations also ride, with "stealable" meaning *expired*
    here (an unreadable lease reads as expired too: a torn file can
    only result from a crashed writer)."""
    return claim_file(
        path,
        _fresh(ident, ttl_s),
        stealable=lambda cur: expired(cur),
    )


def held(path: str, lease: dict) -> bool:
    """The compare-and-check fence: does the lease file still carry OUR
    token? Every tenant-metadata write a holder makes must pass this
    first, so a taken-over server's late writes are refused."""
    cur = _read_json(path)
    return cur is not None and cur.get("token") == lease.get("token")


def check_fence(path: str, lease: dict) -> None:
    """``held`` or raise :class:`LeaseFenced`."""
    if not held(path, lease):
        raise LeaseFenced(
            f"lease {path} no longer carries token {lease.get('token')!r} "
            "— the job was taken over; abandoning all writes for it"
        )


def refresh(path: str, lease: dict, ttl_s: Optional[float] = None) -> dict:
    """Extend the deadline of a lease we hold. Raises
    :class:`LeaseFenced` when the file no longer carries our token —
    the holder must stop touching the tenant and drain. Returns the
    refreshed record (the caller's new ``lease``).

    EXCLUSIVE, not check-then-write: the file is taken into a tomb
    first (rename wins for exactly one process — ``spool.tomb_take``),
    inspected, and only then rewritten via ``O_EXCL`` create. A
    check-then-write refresh would let a holder that stalled past its
    TTL clobber a taker's fresh lease with its own token — re-arming
    the zombie and fencing the rightful new owner, the exact inversion
    fencing exists to prevent. The cost is a microsecond window where
    the lease reads as absent; a peer that claims it in that window
    simply wins (our ``O_EXCL`` re-create fails and we fence
    OURSELVES) — a rare spurious handoff, never a safety loss."""
    ttl = float(ttl_s if ttl_s is not None else lease.get("ttl_s") or 0.0)
    taken = tomb_take(path)
    if taken is None:
        raise LeaseFenced(f"lease {path} vanished (taken over and released)")
    tomb, cur = taken
    if cur is None or cur.get("token") != lease.get("token"):
        # not ours: put the rightful owner's record back where we found
        # it (a torn tomb — cur None — was garbage and stays gone:
        # absent reads as claimable, which is what torn already meant)
        if cur is not None:
            try:
                excl_write_json(path, cur)
            except OSError:
                pass  # can't restore: absent is still claimable
        tomb_discard(tomb)
        raise LeaseFenced(
            f"lease {path} was taken over (token mismatch on refresh)"
        )
    now = time.time()
    new = dict(
        cur,
        expires_ts=round(now + ttl, 4),
        refreshed_ts=round(now, 4),
        refreshes=int(cur.get("refreshes") or 0) + 1,
    )
    try:
        created = excl_write_json(path, new)
    except OSError:
        # the re-create failed AFTER the rename emptied the path: put
        # the original record back (best-effort) so one transient I/O
        # burst doesn't turn into a vanished lease that self-fences a
        # healthy holder on its next beat — then let the error reach
        # the Refresher, whose throttle rewind retries immediately
        try:
            excl_write_json(path, cur)
        except OSError:
            pass  # truly sick: absent is claimable, the TTL re-heals
        tomb_discard(tomb)
        raise
    if not created:
        # a peer claimed the absence window our rename opened — it
        # holds a fresh valid lease now; concede and self-fence
        tomb_discard(tomb)
        raise LeaseFenced(f"lease {path} was re-claimed mid-refresh; conceding")
    tomb_discard(tomb)
    return new


def release(path: str, lease: dict) -> bool:
    """Give the lease up (slice end: parked, or terminal). Token-checked
    through the same rename-tomb protocol as ``acquire`` so a racing
    taker's fresh lease is never unlinked by a stale releaser: rename
    claims the file exclusively, the tomb is inspected, and a lease
    that turned out not to be ours is restored. Returns whether WE
    released it.

    Best-effort by contract: transient I/O rides ``retry_io`` (inside
    the shared primitives) and a PERSISTENT failure returns False
    instead of raising — release runs on the server's scheduling path,
    where crashing over an unreleased lease would strand every tenant
    to save one file the TTL (or the next acquirer's steal) reclaims
    anyway."""
    try:
        taken = tomb_take(path)
    except OSError:
        return False  # sick filesystem: the TTL is the backstop
    if taken is None:
        return False
    tomb, cur = taken
    if cur is not None and cur.get("token") != lease.get("token"):
        # not ours (we were fenced and a new owner wrote this): restore
        try:
            excl_write_json(path, cur)
        except OSError:
            pass  # can't restore: absent is still claimable
        tomb_discard(tomb)
        return False
    tomb_discard(tomb)
    return True


class Refresher:
    """The per-slice lease keeper: installed as the heartbeat beat
    listener (health/heartbeat.py) so every unit of tenant progress —
    driver batch, fused launch, wave sub-segment, staging transfer —
    gives the lease a chance to re-extend. Throttled on a MONOTONIC
    cadence of ttl/3 so beats cost a clock read, not a file write.

    On fencing (the lease stopped carrying our token: we were presumed
    dead and taken over) the refresher latches ``fenced`` and calls
    ``on_fenced`` once — the scheduler passes ``shutdown.request`` so
    the zombie slice drains at its next boundary instead of running to
    completion against a tenant it no longer owns. Never raises into
    the beating thread; transient I/O errors are absorbed (the next
    beat retries) because a heartbeat must never kill the sweep it
    reports on."""

    def __init__(
        self,
        path: str,
        lease: dict,
        ttl_s: float,
        on_fenced: Optional[Callable[[], object]] = None,
    ):
        self.path = path
        self.lease = lease
        self.ttl_s = float(ttl_s)
        self.on_fenced = on_fenced
        self.fenced = False
        self._stopped = False
        self._next = time.monotonic() + self.ttl_s / 3.0
        self._lock = threading.Lock()

    def __call__(self, *_args, **_kw) -> None:
        # non-blocking: a beat that loses the lock SKIPS (the winner is
        # already refreshing) instead of stalling the sweep's hot path
        # behind a shared-filesystem fsync round-trip
        if not self._lock.acquire(blocking=False):
            return
        try:
            if self._stopped or self.fenced or time.monotonic() < self._next:
                return
            self._next = time.monotonic() + self.ttl_s / 3.0
            try:
                self.lease = refresh(self.path, self.lease, self.ttl_s)
            except LeaseFenced:
                self.fenced = True
            except OSError:
                # transient shared-fs hiccup: rewind the throttle so the
                # VERY NEXT beat retries — waiting a whole ttl/3 window
                # after a failure burns deadline margin exactly when the
                # filesystem is already being slow
                self._next = 0.0
                return
        finally:
            self._lock.release()
        if self.fenced and self.on_fenced is not None:
            try:
                self.on_fenced()
            except Exception:  # pragma: no cover - defensive: never raise into a beat
                pass

    def stop(self) -> dict:
        """Settle the refresher at slice end: BLOCK until any in-flight
        refresh finishes (refresh opens a momentary absence window on
        the lease file — an end-of-slice ``held``/``release`` racing it
        would falsely read fenced, and the in-flight refresh would then
        re-create a lease nobody ever releases), then disable all
        future refreshes (a staging thread that outlives its join
        timeout may still beat after the listener is cleared). Returns
        the FINAL lease record — the token the end-of-slice fence must
        judge."""
        with self._lock:
            self._stopped = True
            return self.lease
