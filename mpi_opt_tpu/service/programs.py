"""Compiled-program reuse across tenants: the service's warmup killer.

Why a resident server at all: one CLI invocation = one process = one
cold XLA compile (140–210 s measured per bench round) for minutes of
useful search. Inside ONE process, jax's jit cache already keys
compiled programs by (function identity, abstract shapes) — but the
CLI rebuilds its workload per invocation, and with it the trainer and
the jitted callables, so identity never matches and nothing is reused.

This layer closes that gap with two moves:

- **shared workload instances**: one instance per registry name for
  the server's lifetime, injected into ``cli.main(_workload=...)``.
  The fused drivers cache (trainer, space, arrays) ON the instance
  (``train.common.workload_arrays``), so a second tenant with a
  matching (member_chunk, mesh, momentum-dtype) key gets the same
  trainer object — and a matching population shape then hits the jit
  cache outright: its marginal cost is the 3–5 ms dispatch floor
  (PERF_NOTES §2), not compilation.
- **hit/miss accounting** keyed by (workload, pop-shape, chunking):
  the scheduler records, per tenant, whether the programs its sweep
  needs were already compiled in this server, and surfaces the
  counters in status.json and the server metrics summary — the
  operator-visible proof that tenant N+1 skipped compile.

A key is a conservative superset of everything that shapes the fused
programs; matching keys therefore guarantee program reuse, while a
mismatched key may still partially reuse (same trainer, new shapes).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple


def _warm_identity(path: Optional[str]):
    """Key component for --warm-start: fused TPE sizes its compiled obs
    ring as n_trials + n_warm, where n_warm is the PRIOR ledger's record
    count — so a warm-starting tenant's programs are not the cold
    tenant's, and two priors of different length differ again. (path,
    size, mtime) is the conservative stand-in for n_warm without
    reading the file: it only ever splits keys, never aliases."""
    if path is None:
        return None
    try:
        st = os.stat(path)
        return (path, st.st_size, st.st_mtime_ns)
    except OSError:
        return (path, None, None)


def program_key(args) -> tuple:
    """The (workload, pop-shape, chunking) identity of a parsed sweep's
    compiled programs (args: the CLI parser's namespace)."""
    return (
        args.workload,
        args.backend,
        bool(args.fused),
        args.algorithm,
        # pop-shape: which of these bind depends on the algorithm, but
        # including the superset only ever splits keys, never aliases
        args.population,
        args.trials,
        args.budget,
        args.generations,
        args.steps_per_generation,
        args.min_budget,
        args.max_budget,
        args.eta,
        # statically baked into the jitted programs: PBTConfig's
        # truncation_frac sizes the exploit's n_cut at trace time, and
        # the driver path's eval batches are shaped by worker capacity
        args.truncation,
        args.workers,
        _warm_identity(args.warm_start),
        # chunking / residency: each changes the compiled program split
        args.member_chunk,
        args.gen_chunk,
        args.step_chunk,
        str(args.wave_size),
        # mesh shape: a different device split is a different program
        bool(args.no_mesh),
        args.n_data,
        args.n_pop,
    )


class ProgramCache:
    def __init__(self):
        self._workloads: dict = {}
        self._seen: set = set()

    def acquire(self, argv: list) -> Tuple[Optional[tuple], bool, Optional[object]]:
        """(key, hit, workload) for one slice's argv.

        ``workload`` is the shared instance to inject into ``cli.main``
        (None when the argv doesn't parse — the slice will fail as a
        usage error on its own — or names a --chaos drill, whose
        wrapper is rebuilt per run by design). ``hit`` is whether this
        key's programs were already built in this server process; the
        first slice of a shape is the miss that pays the compile, and
        every later slice — same tenant resuming, or a shape-matching
        new tenant — is a hit. A key only enters the seen set via
        ``commit`` (the scheduler calls it when the slice demonstrably
        ran: completed or drained at a boundary) — a slice that died
        BEFORE compiling must not make the next same-shape slice
        report a warm start that never happened."""
        import contextlib
        import io

        from mpi_opt_tpu.cli import build_parser

        # probe parse (micro-cost against a multi-second slice): ALL its
        # output is suppressed — stderr (usage errors) and stdout too
        # (`--help` prints multi-KB help, and the server's stdout is its
        # JSONL metrics stream). The slice's OWN parse of the same argv
        # re-emits everything inside the tenant's log redirect, so the
        # text lands in run.log where it's attributable.
        try:
            with contextlib.redirect_stdout(io.StringIO()), contextlib.redirect_stderr(
                io.StringIO()
            ):
                args = build_parser().parse_args(list(argv))
        except SystemExit:
            return None, False, None
        if args.chaos is not None:
            # chaos wrappers are rebuilt per run by design (one tenant's
            # fault schedule must not leak into another), so a chaos
            # slice's programs are NEVER warm: no key (a committed
            # chaos-blind key would falsely warm-start the fault-free
            # tenant of the same shape), no hit (its own resumed slices
            # recompile every time), no shared workload
            return None, False, None
        key = program_key(args)
        # hit/miss tallies live with their consumers — per-tenant in
        # status.json and server-wide in MetricsLogger (the scheduler
        # records both from this bool); a third copy here would drift
        hit = key in self._seen
        workload = self._workloads.get(args.workload)
        if workload is None:
            from mpi_opt_tpu.workloads import get_workload

            workload = get_workload(args.workload)
            self._workloads[args.workload] = workload
        return key, hit, workload

    def commit(self, key: Optional[tuple]) -> None:
        """Record that ``key``'s programs were actually built (the
        slice completed or parked at a boundary — both are past the
        compile)."""
        if key is not None:
            self._seen.add(key)
