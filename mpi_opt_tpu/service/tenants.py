"""Tenant state machine: what one submitted sweep is doing right now.

States::

    queued ──admit──> (runnable) ──slice──> running
    running ──rc 0───────────────> done
    running ──rc 75 (SLICE)──────> parked      (runnable again)
    running ──rc 75 + cancel─────> cancelled
    running ──rc 75 (SIGTERM)────> parked      (server is draining)
    running ──rc 74──────────────> parked      (resource exhausted:
                                   state intact; re-picked only after a
                                   cooldown so a full disk is not spun)
    running ──rc 65──────────────> data_error  (terminal, never retried)
    running ──rc 2───────────────> failed      (usage: deterministic)
    running ──rc other───────────> failed
    queued/parked ──cancel───────> cancelled
    running + dead/expired lease─> (takeover)  (any live fleet server
                                   claims the lease and resumes it)

The rc classification is ``utils.exitcodes.classify`` — the SAME map
the launch supervisor uses, so a sweep's exit means one thing
everywhere. ``parked`` is the service's load-bearing state: by the
graceful-drain contract (health/shutdown.py) a parked tenant's ledger
and snapshot are flushed at a natural boundary, so resuming it is the
existing ``--resume`` + verified-snapshot + journal-prefix machinery —
time-slicing never invents a new recovery path.
"""

from __future__ import annotations

from mpi_opt_tpu.utils.exitcodes import classify

QUEUED = "queued"
RUNNING = "running"
PARKED = "parked"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"
DATA_ERROR = "data_error"

#: states a tenant never leaves
TERMINAL = frozenset({DONE, FAILED, CANCELLED, DATA_ERROR})

#: states the scheduler may pick for the next slice
RUNNABLE = frozenset({QUEUED, PARKED})


def after_slice(rc: int, cancel_requested: bool) -> str:
    """The state a tenant lands in when its slice returns ``rc``.

    ``cancel_requested`` is whether the tenant's cancel flag was up —
    a drained (rc 75) slice with the flag up parked ON PURPOSE so the
    cancel could take effect at a boundary: the tenant is cancelled,
    with its ledger/snapshots intact and valid (nothing was killed, so
    nothing needs quarantine)."""
    outcome = classify(rc)
    if outcome == "ok":
        return DONE
    if outcome == "preempted":
        return CANCELLED if cancel_requested else PARKED
    if outcome == "io_error":
        # resource exhaustion (EX_IOERR=74, utils/resources.py): the
        # tenant's durable state is INTACT — the failed write never
        # landed and the newest verified step was never touched — so
        # this is PARKED, not terminal-failed: freeing disk + the
        # ordinary --resume slice recovers fsck-clean. The scheduler
        # stamps a cooldown so a still-full disk is re-probed, not spun.
        return CANCELLED if cancel_requested else PARKED
    if outcome == "data_error":
        return DATA_ERROR
    # "usage" and the generic "failure" are both terminal for a tenant:
    # usage is deterministic (a retry re-refuses), and a failed sweep's
    # retry policy belongs to the sweep's own --retries, which already
    # ran inside the slice
    return FAILED
