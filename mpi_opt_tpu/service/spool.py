"""Filesystem job spool: the service's durable queue + control plane.

No network dependency (this container has none to offer): clients and
server rendezvous on a shared ``--state-dir``. Every write is atomic
(tmp + rename, the heartbeat pattern), every decision the scheduler
makes is re-derivable from the files — so the spool IS the queue
checkpoint: a SIGKILLed server restarts, reads the tree, and continues
where it left off with no separate recovery file.

Layout::

    state-dir/
      server.json           # the live server's pid + heartbeat (liveness)
      server-metrics.jsonl  # the server's own JSONL metrics stream
      control/drain         # flag: finish the active slice, park, exit
      queue/<job>.json      # submitted jobs not yet admitted
      tenants/<job>/
        job.json            # the submitted spec (argv, tenant, ts)
        status.json         # tenant state machine record (tenants.py)
        cancel              # flag: cancel this job at its next boundary
        ledger.jsonl        # per-tenant durable trial journal
        ckpt/               # per-tenant snapshot root
        run.log             # captured stdout/stderr of every slice

Job ids are zero-padded submit-nanosecond stamps, so lexicographic
order IS submission order (the FIFO tiebreak needs no extra index).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

#: sweep flags the server owns per tenant; a submitted job naming one
#: would fight the server over the tenant's durable-state layout (or,
#: for the SPMD flags, over the device itself)
RESERVED_FLAGS = (
    "--ledger",
    "--checkpoint-dir",
    "--resume",
    "--metrics-file",
    "--heartbeat-file",
    "--coordinator",
    "--num-processes",
    "--process-id",
    "--multihost",
    # the server owns the device: platform pinning happens ONCE at
    # `serve` bring-up, not per tenant (a mid-process re-pin would
    # either fail or fight the resident programs)
    "--platform",
    "--local-devices",
)


class SpoolError(ValueError):
    """Malformed spool content or an invalid client request."""


class ServerClaimError(RuntimeError):
    """Another live server already owns this spool (one device, one
    server). The ONE serve failure that is usage-shaped: the operator
    pointed a second server at a claimed state-dir."""


def _write_json_atomic(path: str, obj: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _pid_start(pid: int) -> Optional[str]:
    """The kernel's start-time identity for a pid (Linux /proc; None
    where unavailable). pid + starttime is collision-proof against pid
    reuse; a bare pid is not — the kernel recycles them."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
        # comm (field 2) may itself contain spaces and parens: the
        # numeric fields resume after the LAST ')', where state is
        # field 3 — starttime is field 22, i.e. index 19 from there
        return stat.rsplit(")", 1)[1].split()[19]
    except (OSError, IndexError):
        return None


def check_argv(argv: list) -> None:
    """Client-side admission gate: refuse reserved / server-owned flags
    at submit time, where the error is cheap and attributable."""
    for a in argv:
        flag = a.split("=", 1)[0]
        if not flag.startswith("--"):
            continue
        # prefix match, not equality: argparse resolves unambiguous
        # abbreviations (allow_abbrev), so `--platfor` would reach the
        # slice's parser as --platform and bypass an exact-string gate
        for reserved in RESERVED_FLAGS:
            if len(flag) > 2 and reserved.startswith(flag):
                raise SpoolError(
                    f"{flag} is (or abbreviates) server-owned {reserved} "
                    "(the service assigns each tenant its own "
                    "ledger/checkpoint root and owns the device "
                    "bring-up); submit the sweep without it"
                )


class TenantDir:
    """One tenant's slice of the spool: paths + status accessors."""

    def __init__(self, root: str, job_id: str):
        self.job_id = job_id
        self.dir = os.path.join(root, job_id)
        self.job_path = os.path.join(self.dir, "job.json")
        self.status_path = os.path.join(self.dir, "status.json")
        self.cancel_path = os.path.join(self.dir, "cancel")
        self.ledger = os.path.join(self.dir, "ledger.jsonl")
        self.ckpt = os.path.join(self.dir, "ckpt")
        self.log = os.path.join(self.dir, "run.log")
        # observability surfaces (both server-owned, like ledger/ckpt):
        # the heartbeat's phase field is the ACTIVE tenant's live-phase
        # source; metrics.jsonl is the tenant's span-trace stream under
        # `serve --trace` (renders with `mpi_opt_tpu trace STATE_DIR`)
        self.heartbeat = os.path.join(self.dir, "heartbeat.json")
        self.metrics = os.path.join(self.dir, "metrics.jsonl")

    @property
    def job(self) -> dict:
        job = _read_json(self.job_path)
        if job is None:
            raise SpoolError(f"{self.job_path}: unreadable job spec")
        return job

    @property
    def status(self) -> dict:
        return _read_json(self.status_path) or {}

    def write_status(self, status: dict) -> None:
        status = dict(status, updated_ts=round(time.time(), 4))
        _write_json_atomic(self.status_path, status)

    def cancel_requested(self) -> bool:
        return os.path.exists(self.cancel_path)

    def request_cancel(self) -> None:
        with open(self.cancel_path, "w") as f:
            f.write("")


def live_phase(tenant_dir: str, status: dict) -> Optional[dict]:
    """An ACTIVE tenant's live phase + slice-elapsed, for the status and
    report surfaces: ``{"phase": ..., "slice_elapsed_s": ...}`` when the
    status says ``running``, else None.

    The phase comes from the tenant's heartbeat file (the scheduler
    wires ``--heartbeat-file`` into every slice): each beat carries the
    rank's active trace span (health/heartbeat.py ``phase``) with the
    beat's progress ``stage`` label as fallback. Slice elapsed is
    against the ``slice_started_ts`` the scheduler stamps into the
    RUNNING status write. Read-only and best-effort — a pre-upgrade
    status or a beat-less slice reports None fields, never an error."""
    if status.get("state") != "running":
        return None
    from mpi_opt_tpu.health.heartbeat import read_beat

    rec = read_beat(os.path.join(tenant_dir, "heartbeat.json")) or {}
    out = {
        "phase": rec.get("phase") or (rec.get("progress") or {}).get("stage"),
        "slice_elapsed_s": None,
    }
    started = status.get("slice_started_ts")
    if started is not None:
        out["slice_elapsed_s"] = round(max(0.0, time.time() - float(started)), 3)
    return out


class Spool:
    def __init__(self, state_dir: str, create: bool = True):
        """``create=False`` is the read-only clients' mode (status /
        cancel / drain): they must refuse a path that is not already a
        spool — silently fabricating an empty tree at a mistyped
        ``--state-dir`` would answer "server down, no jobs" about a
        spool that does not exist (and drop drain flags no server
        watches). ``serve`` and ``submit`` create: submitting to a
        not-yet-started spool is the documented queue-ahead shape."""
        self.state_dir = state_dir
        self.queue_dir = os.path.join(state_dir, "queue")
        self.tenants_dir = os.path.join(state_dir, "tenants")
        self.control_dir = os.path.join(state_dir, "control")
        self.server_path = os.path.join(state_dir, "server.json")
        self.metrics_path = os.path.join(state_dir, "server-metrics.jsonl")
        self._drain_path = os.path.join(self.control_dir, "drain")
        if create:
            for d in (self.queue_dir, self.tenants_dir, self.control_dir):
                os.makedirs(d, exist_ok=True)
        elif not os.path.isdir(self.queue_dir):
            raise SpoolError(
                f"{state_dir}: not a service spool (no queue/ underneath) "
                "— mistyped --state-dir?"
            )

    # -- client side -------------------------------------------------

    def submit(self, argv: list, tenant: str = "default") -> str:
        """Drop a job file in the queue; returns the job id. The id's
        nanosecond stamp makes collisions impossible within a process
        and sorts by submission time across processes."""
        check_argv(argv)
        job_id = f"job-{time.time_ns():020d}-{os.getpid() % 100000:05d}"
        spec = {
            "id": job_id,
            "tenant": tenant,
            "argv": list(argv),
            "submitted_ts": round(time.time(), 4),
        }
        _write_json_atomic(os.path.join(self.queue_dir, f"{job_id}.json"), spec)
        return job_id

    def cancel(self, job_id: str) -> str:
        """Cancel a job wherever it lives. Queued jobs cancel
        immediately (they never ran: the queue file becomes a terminal
        tenant record); admitted jobs get a cancel flag the server
        honors at the tenant's next boundary — nothing is killed, so
        nothing needs quarantine. Returns the resulting state."""
        from mpi_opt_tpu.service import tenants as tstates

        qpath = os.path.join(self.queue_dir, f"{job_id}.json")
        if os.path.exists(qpath):
            try:
                t = self._materialize(qpath)
            except SpoolError:
                # lost the claim race to the server's admission — the
                # tenant dir exists now; fall through and cancel it there
                t = None
            if t is not None:
                # flag FIRST: if the server's racing QUEUED status write
                # lands after our CANCELLED one, the flag still cancels
                # the tenant at admission or its first boundary
                t.request_cancel()
                t.write_status(
                    dict(
                        t.status,
                        state=tstates.CANCELLED,
                        note="cancelled while queued",
                    )
                )
                return tstates.CANCELLED
        t = self.tenant(job_id)
        if t is None:
            raise SpoolError(f"unknown job {job_id!r}")
        state = t.status.get("state")
        if state in tstates.TERMINAL:
            return state
        if state in (tstates.QUEUED, tstates.PARKED):
            # not on the device: terminal immediately — but raise the
            # flag FIRST, so a server that picked this tenant between
            # our state read and the status write still drains it at
            # the next boundary instead of silently overwriting the
            # CANCELLED record at slice end
            t.request_cancel()
            t.write_status(dict(t.status, state=tstates.CANCELLED))
            return tstates.CANCELLED
        t.request_cancel()
        return state or tstates.QUEUED

    def request_drain(self) -> None:
        with open(self._drain_path, "w") as f:
            f.write("")

    def drain_requested(self) -> bool:
        return os.path.exists(self._drain_path)

    def clear_drain(self) -> None:
        try:
            os.unlink(self._drain_path)
        except FileNotFoundError:
            pass

    # -- server side -------------------------------------------------

    def pending_jobs(self) -> list:
        """Queue files in submission (= lexicographic) order."""
        return sorted(
            os.path.join(self.queue_dir, f)
            for f in os.listdir(self.queue_dir)
            if f.endswith(".json")
        )

    def _materialize(self, queue_path: str) -> TenantDir:
        """Move a queue file into a tenant dir (the admission step's
        mechanical half; scheduler.py decides WHEN)."""
        from mpi_opt_tpu.service import tenants as tstates

        spec = _read_json(queue_path)
        if spec is None or "id" not in spec or "argv" not in spec:
            if not os.path.exists(queue_path):
                # lost a race: the other side of a concurrent
                # cancel-while-queued / admission already took it
                raise SpoolError(f"{queue_path}: already claimed by a peer")
            # a torn/garbage submit: park it out of the queue loudly
            bad = queue_path + ".malformed"
            try:
                os.replace(queue_path, bad)
            except FileNotFoundError:
                raise SpoolError(f"{queue_path}: already claimed by a peer")
            raise SpoolError(f"malformed job file moved to {bad}")
        t = TenantDir(self.tenants_dir, spec["id"])
        os.makedirs(t.dir, exist_ok=True)
        _write_json_atomic(t.job_path, spec)
        t.write_status(
            {
                "id": spec["id"],
                "tenant": spec.get("tenant", "default"),
                "state": tstates.QUEUED,
                "slices": 0,
                "preemptions": 0,
                "boundaries": 0,
                "rc_history": [],
                "program_cache": {"hits": 0, "misses": 0},
                "submitted_ts": spec.get("submitted_ts"),
            }
        )
        try:
            os.unlink(queue_path)
        except FileNotFoundError:
            pass  # a racing peer already removed it; the tenant dir wins
        return t

    def admit(self, queue_path: str) -> TenantDir:
        return self._materialize(queue_path)

    def tenant(self, job_id: str) -> Optional[TenantDir]:
        t = TenantDir(self.tenants_dir, job_id)
        return t if os.path.isdir(t.dir) else None

    def tenants(self) -> list:
        """All admitted tenants, submission-ordered."""
        return [
            TenantDir(self.tenants_dir, d)
            for d in sorted(os.listdir(self.tenants_dir))
            if os.path.isdir(os.path.join(self.tenants_dir, d))
        ]

    # -- server liveness ---------------------------------------------

    def read_server(self) -> Optional[dict]:
        return _read_json(self.server_path)

    def server_alive(self) -> bool:
        return self._pid_alive(self.read_server())

    def _claim_fields(self, **fields) -> dict:
        return {
            "pid": os.getpid(),
            "pid_start": _pid_start(os.getpid()),
            "ts": round(time.time(), 4),
            **fields,
        }

    def write_server(self, **fields) -> None:
        _write_json_atomic(self.server_path, self._claim_fields(**fields))

    def _pid_alive(self, info: Optional[dict]) -> bool:
        if not info or "pid" not in info:
            return False
        try:
            pid = int(info["pid"])
            os.kill(pid, 0)
        except PermissionError:
            # EPERM is a LIVE process owned by someone else — on a
            # shared state-dir the one-server-per-spool refusal must
            # still see it (and /proc/<pid>/stat below stays readable)
            pass
        except (OSError, ValueError):
            return False
        # the pid exists — but is it the SAME process? A SIGKILLed
        # server never clears its claim, and the kernel eventually
        # recycles its pid for an unrelated process, which would hold
        # the spool hostage until an operator deleted server.json by
        # hand. The recorded start time settles it; claims without one
        # (older files, non-Linux hosts) keep the bare-pid behavior.
        recorded = info.get("pid_start")
        if recorded is not None:
            current = _pid_start(pid)
            if current is not None and current != recorded:
                return False
        return True

    def claim_server(self, **fields) -> bool:
        """Atomically claim the spool for THIS process (O_EXCL create of
        server.json — a check-then-write would let two servers racing
        through the same window both believe they own the device).

        A claim held by a dead pid (SIGKILLed server) is broken via
        rename-takeover: rename wins for exactly ONE claimant, and the
        renamed file is inspected AFTER the steal — if it turns out to
        be a peer's fresh LIVE claim (the peer broke the stale one and
        re-claimed between our read and our rename), it is restored and
        we lose. Returns False when a live server holds the spool."""
        for _ in range(8):  # bounded: every retry means the file changed
            try:
                fd = os.open(
                    self.server_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY
                )
            except FileExistsError:
                if self.server_alive():
                    return False
                tomb = f"{self.server_path}.stale.{os.getpid()}"
                try:
                    os.rename(self.server_path, tomb)
                except FileNotFoundError:
                    continue  # another claimant removed it; retry O_EXCL
                stolen = _read_json(tomb)
                try:
                    os.unlink(tomb)
                except FileNotFoundError:
                    pass
                if self._pid_alive(stolen):
                    # we stole a live claim — put it back and concede
                    try:
                        restore = os.open(
                            self.server_path,
                            os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                        )
                    except FileExistsError:
                        return False
                    with os.fdopen(restore, "w") as f:
                        json.dump(stolen, f)
                    return False
                continue  # the claim really was dead; retry O_EXCL
            with os.fdopen(fd, "w") as f:
                json.dump(self._claim_fields(**fields), f)
                f.flush()
                os.fsync(f.fileno())
            return True
        return False

    def clear_server(self) -> None:
        try:
            os.unlink(self.server_path)
        except FileNotFoundError:
            pass
