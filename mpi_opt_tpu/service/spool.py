"""Filesystem job spool: the service's durable queue + control plane.

No network dependency (this container has none to offer): clients and
server rendezvous on a shared ``--state-dir``. Every write is atomic
(tmp + rename, the heartbeat pattern), every decision the scheduler
makes is re-derivable from the files — so the spool IS the queue
checkpoint: a SIGKILLed server restarts, reads the tree, and continues
where it left off with no separate recovery file.

Layout::

    state-dir/
      servers/<id>.json     # one registration per live server (fleet)
      server-metrics.jsonl  # the server's own JSONL metrics stream
      control/drain         # flag: finish the active slice, park, exit
      queue/<job>.json      # submitted jobs not yet admitted
      tenants/<job>/
        job.json            # the submitted spec (argv, tenant, ts)
        status.json         # tenant state machine record (tenants.py)
        lease.json          # per-job claim (service/leases.py ONLY)
        cancel              # flag: cancel this job at its next boundary
        ledger.jsonl        # per-tenant durable trial journal
        ckpt/               # per-tenant snapshot root
        run.log             # captured stdout/stderr of every slice

Job ids are zero-padded submit-nanosecond stamps, so lexicographic
order IS submission order (the FIFO tiebreak needs no extra index).

Fleet federation (ISSUE 12): N servers share one spool. Each registers
under ``servers/<server-id>.json`` (a server-id collision is the ONE
refusal left — two processes claiming the same identity is operator
error, and the default id keeps PR 7's one-server-per-spool behavior);
per-JOB admission is arbitrated by ``tenants/<job>/lease.json``, owned
end to end by :mod:`mpi_opt_tpu.service.leases`.

Spool metadata I/O rides :func:`retry_io` — bounded, jitter-backed
retries on transient ``OSError`` — so a slow or contended shared
filesystem (the multi-server deployment's substrate) degrades to
latency, not crashes.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Optional

#: sweep flags the server owns per tenant; a submitted job naming one
#: would fight the server over the tenant's durable-state layout (or,
#: for the SPMD flags, over the device itself)
RESERVED_FLAGS = (
    "--ledger",
    "--checkpoint-dir",
    "--resume",
    "--metrics-file",
    "--heartbeat-file",
    "--coordinator",
    "--num-processes",
    "--process-id",
    "--multihost",
    # the server owns the device: platform pinning happens ONCE at
    # `serve` bring-up, not per tenant (a mid-process re-pin would
    # either fail or fight the resident programs)
    "--platform",
    "--local-devices",
)


class SpoolError(ValueError):
    """Malformed spool content or an invalid client request."""


class ServerClaimError(RuntimeError):
    """Another live server already owns this server-id on this spool.
    The ONE serve failure that is usage-shaped: the operator pointed a
    second server at an identity that is still alive — federating needs
    a distinct ``--server-id`` per server, not a shared one."""


#: answers, not faults: the retry layer must never spin on a path that
#: is genuinely absent/present/misshaped — those outcomes are what the
#: caller is asking about (O_EXCL losing a race, a missing status file)
_NON_TRANSIENT_OS = (
    FileNotFoundError,
    FileExistsError,
    IsADirectoryError,
    NotADirectoryError,
    PermissionError,
)

#: chaos seam (workloads/chaos.py inject_spool_faults): when installed,
#: called as ``fn(op, path)`` before every spool metadata primitive
#: ("replace" before os.replace, "read" before a JSON read, "list"
#: before a directory listing) and may raise OSError or sleep — INSIDE
#: the retry wrapper, so each attempt re-consults the schedule
_FAULTS: Optional[Callable[[str, str], None]] = None


def set_fault_injector(fn: Optional[Callable[[str, str], None]]) -> None:
    global _FAULTS
    _FAULTS = fn


def _fault(op: str, path: str) -> None:
    if _FAULTS is not None:
        _FAULTS(op, path)


def retry_io(fn, attempts: int = 4, base_s: float = 0.02, sleep=time.sleep):
    """Run ``fn`` with bounded retry-with-jittered-backoff on transient
    ``OSError`` (EIO under load, NFS ESTALE, EAGAIN — the weather of a
    contended shared filesystem). Non-transient shapes
    (``FileNotFoundError``, ``FileExistsError``, permission refusals)
    raise immediately: they are answers the caller's protocol depends
    on, and "retrying" an O_EXCL loss would turn a lost race into a
    4x-slower lost race. Storage exhaustion (ENOSPC/EDQUOT,
    ``utils.resources.is_storage_full``) is ALSO an answer, not
    weather: a full disk does not heal on a jittered backoff — spinning
    on it only delays the diagnosis — so it raises immediately into the
    resource-exhaustion classification (ISSUE 13). The last attempt's
    error propagates raw."""
    from mpi_opt_tpu.utils.resources import is_storage_full

    for i in range(attempts):
        try:
            return fn()
        except _NON_TRANSIENT_OS:
            raise
        except OSError as e:
            if is_storage_full(e) or i == attempts - 1:
                raise
            sleep(base_s * (2**i) * (0.5 + random.random()))


def _write_json_atomic(path: str, obj: dict) -> None:
    def _go():
        # pid AND thread in the tmp name: writers on different threads
        # (the serve loop vs the heartbeat-riding refresh) must never
        # truncate each other's half-written tmp out from under its
        # rename (the heartbeat module learned this the hard way)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "w") as f:
                json.dump(obj, f)
                f.flush()
                os.fsync(f.fileno())
            _fault("replace", path)
            os.replace(tmp, path)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    retry_io(_go)


def _read_json(path: str) -> Optional[dict]:
    def _go():
        _fault("read", path)
        try:
            with open(path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    try:
        return retry_io(_go)
    except OSError:
        # persistently unreadable == unreadable: every caller treats
        # None as "no usable record here", which is the degraded truth
        return None


def _pid_start(pid: int) -> Optional[str]:
    """The kernel's start-time identity for a pid (Linux /proc; None
    where unavailable). pid + starttime is collision-proof against pid
    reuse; a bare pid is not — the kernel recycles them."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
        # comm (field 2) may itself contain spaces and parens: the
        # numeric fields resume after the LAST ')', where state is
        # field 3 — starttime is field 22, i.e. index 19 from there
        return stat.rsplit(")", 1)[1].split()[19]
    except (OSError, IndexError):
        return None


# -- the exclusive-claim primitives ----------------------------------------
#
# ONE home for the subtle parts of every claim-file transaction (server
# registrations, and — via service/leases.py — per-job lease acquire,
# refresh, and release all ride these; diverging copies of this dance
# is how fencing bugs are born): an O_EXCL fsync'd create that exactly
# one process can win, and a rename-into-tomb that exactly one process
# can perform. Composed, they give check-free exclusivity — never
# read-modify-write.


def excl_write_json(path: str, record: dict) -> bool:
    """Atomically create ``path`` holding ``record`` iff absent
    (``O_EXCL``, fsync'd). False = the path exists (the caller lost the
    race and must concede); transient I/O rides :func:`retry_io` and a
    persistently sick filesystem raises raw."""
    try:
        fd = retry_io(lambda: os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
    except FileExistsError:
        return False
    with os.fdopen(fd, "w") as f:
        json.dump(record, f)
        f.flush()
        os.fsync(f.fileno())
    return True


def tomb_take(path: str) -> Optional[tuple]:
    """Exclusively move ``path`` into a caller-owned tomb (rename wins
    for exactly ONE process) and read it: ``(tomb_path, record_or_None)``,
    or None when the path did not exist. The caller must end with
    :func:`tomb_discard` (and restore via :func:`excl_write_json` first
    when the record turns out not to be its to take)."""
    tomb = f"{path}.tomb.{os.getpid()}.{threading.get_ident()}"
    try:
        retry_io(lambda: os.rename(path, tomb))
    except FileNotFoundError:
        return None
    return tomb, _read_json(tomb)


def tomb_discard(tomb: str) -> None:
    """Best-effort tomb cleanup — orphaned tomb debris is inert (it is
    never a claim), so failure here is never worth raising over."""
    try:
        os.unlink(tomb)
    except OSError:
        pass


def claim_file(path: str, payload: dict, stealable, attempts: int = 8) -> Optional[dict]:
    """The exclusive-claim protocol: atomically create ``path`` holding
    ``payload`` iff it is absent or ``stealable(current)``. A stealable
    claim is replaced via rename-tomb, and the tomb is inspected AFTER
    the steal so a peer's fresh re-claim that raced our staleness read
    is restored and conceded, never destroyed. Returns ``payload`` on
    win, None on concede."""
    for _ in range(attempts):  # bounded: every retry means the file changed
        if excl_write_json(path, payload):
            return payload
        cur = _read_json(path)
        if cur is not None and not stealable(cur):
            return None  # live holder; we lose
        taken = tomb_take(path)
        if taken is None:
            continue  # another claimant removed it; retry the create
        tomb, stolen = taken
        tomb_discard(tomb)
        if stolen is not None and not stealable(stolen):
            # we stole a LIVE claim (the holder refreshed between our
            # read and our rename) — put it back and concede
            try:
                excl_write_json(path, stolen)
            except OSError:
                pass  # can't restore: still concede; TTL re-heals
            return None
        continue  # the claim really was stealable; retry the create
    return None


_HOST_ID: Optional[str] = None


def _local_host() -> str:
    """This machine's identity, for cross-host liveness judgement (a
    pid recorded by another host is not a pid here). The nodename alone
    is NOT unique enough to gate a "provably dead, take over now"
    verdict — cloned VMs and templated containers ship identical
    hostnames, and a collision would let a peer probe a REMOTE holder's
    pid locally, find it absent, and steal a live lease with no TTL
    wait. The kernel boot id (random per boot) disambiguates; it also
    makes a rebooted host read as "different host", which is correct —
    its old pids mean nothing after the reboot, so freshness/TTL (not
    pid probing) is the right judgement there. Hosts without the proc
    file (non-Linux) fall back to the bare nodename, keeping the old
    behavior and its documented residual risk."""
    global _HOST_ID
    if _HOST_ID is None:
        try:
            node = os.uname().nodename
        except (AttributeError, OSError):  # pragma: no cover - non-posix
            node = "unknown-host"
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                _HOST_ID = f"{node}/{f.read().strip()[:13]}"
            # boot_id is KERNEL-wide: two containers sharing a kernel
            # (and, via a templated config, a nodename) would still
            # collide — and a pid probed across PID namespaces is just
            # as meaningless as one probed across machines. The pid-ns
            # inode completes the "same pid world" judgement.
            _HOST_ID += f"/{os.stat('/proc/self/ns/pid').st_ino}"
        except OSError:  # pragma: no cover - non-linux
            _HOST_ID = node
    return _HOST_ID


def check_argv(argv: list) -> None:
    """Client-side admission gate: refuse reserved / server-owned flags
    at submit time, where the error is cheap and attributable."""
    for a in argv:
        flag = a.split("=", 1)[0]
        if not flag.startswith("--"):
            continue
        # prefix match, not equality: argparse resolves unambiguous
        # abbreviations (allow_abbrev), so `--platfor` would reach the
        # slice's parser as --platform and bypass an exact-string gate
        for reserved in RESERVED_FLAGS:
            if len(flag) > 2 and reserved.startswith(flag):
                raise SpoolError(
                    f"{flag} is (or abbreviates) server-owned {reserved} "
                    "(the service assigns each tenant its own "
                    "ledger/checkpoint root and owns the device "
                    "bring-up); submit the sweep without it"
                )


class TenantDir:
    """One tenant's slice of the spool: paths + status accessors."""

    def __init__(self, root: str, job_id: str):
        self.job_id = job_id
        self.dir = os.path.join(root, job_id)
        self.job_path = os.path.join(self.dir, "job.json")
        self.status_path = os.path.join(self.dir, "status.json")
        # per-job claim file (fleet federation): written ONLY by
        # service/leases.py — the path lives here so readers (status,
        # report) and the lease helpers agree on one location
        self.lease = os.path.join(self.dir, "lease.json")
        self.cancel_path = os.path.join(self.dir, "cancel")
        self.ledger = os.path.join(self.dir, "ledger.jsonl")
        self.ckpt = os.path.join(self.dir, "ckpt")
        self.log = os.path.join(self.dir, "run.log")
        # observability surfaces (both server-owned, like ledger/ckpt):
        # the heartbeat's phase field is the ACTIVE tenant's live-phase
        # source; metrics.jsonl is the tenant's span-trace stream under
        # `serve --trace` (renders with `mpi_opt_tpu trace STATE_DIR`)
        self.heartbeat = os.path.join(self.dir, "heartbeat.json")
        self.metrics = os.path.join(self.dir, "metrics.jsonl")

    @property
    def job(self) -> dict:
        job = _read_json(self.job_path)
        if job is None:
            raise SpoolError(f"{self.job_path}: unreadable job spec")
        return job

    @property
    def status(self) -> dict:
        return _read_json(self.status_path) or {}

    def write_status(self, status: dict) -> None:
        status = dict(status, updated_ts=round(time.time(), 4))
        _write_json_atomic(self.status_path, status)

    def create_status(self, status: dict) -> bool:
        """Write the INITIAL status record only if none exists yet
        (``excl_write_json``: with N servers racing the same admission,
        exactly one initial write wins and a peer's later duplicate
        admission can never reset a tenant that is already running —
        and the shared primitive carries the retry budget, so transient
        admission-time I/O degrades to latency like every other spool
        metadata op). Returns whether THIS call created it."""
        return excl_write_json(
            self.status_path, dict(status, updated_ts=round(time.time(), 4))
        )

    def cancel_requested(self) -> bool:
        return os.path.exists(self.cancel_path)

    def request_cancel(self) -> None:
        with open(self.cancel_path, "w") as f:
            f.write("")


def live_phase(tenant_dir: str, status: dict) -> Optional[dict]:
    """An ACTIVE tenant's live phase + slice-elapsed, for the status and
    report surfaces: ``{"phase": ..., "slice_elapsed_s": ...}`` when the
    status says ``running``, else None.

    The phase comes from the tenant's heartbeat file (the scheduler
    wires ``--heartbeat-file`` into every slice): each beat carries the
    rank's active trace span (health/heartbeat.py ``phase``) with the
    beat's progress ``stage`` label as fallback. Slice elapsed is
    against the ``slice_started_ts`` the scheduler stamps into the
    RUNNING status write. Read-only and best-effort — a pre-upgrade
    status or a beat-less slice reports None fields, never an error."""
    if status.get("state") != "running":
        return None
    from mpi_opt_tpu.health.heartbeat import read_beat

    rec = read_beat(os.path.join(tenant_dir, "heartbeat.json")) or {}
    out = {
        "phase": rec.get("phase") or (rec.get("progress") or {}).get("stage"),
        "slice_elapsed_s": None,
    }
    started = status.get("slice_started_ts")
    if started is not None:
        out["slice_elapsed_s"] = round(max(0.0, time.time() - float(started)), 3)
    return out


class Spool:
    def __init__(self, state_dir: str, create: bool = True):
        """``create=False`` is the read-only clients' mode (status /
        cancel / drain): they must refuse a path that is not already a
        spool — silently fabricating an empty tree at a mistyped
        ``--state-dir`` would answer "server down, no jobs" about a
        spool that does not exist (and drop drain flags no server
        watches). ``serve`` and ``submit`` create: submitting to a
        not-yet-started spool is the documented queue-ahead shape."""
        self.state_dir = state_dir
        self.queue_dir = os.path.join(state_dir, "queue")
        self.tenants_dir = os.path.join(state_dir, "tenants")
        self.control_dir = os.path.join(state_dir, "control")
        self.servers_dir = os.path.join(state_dir, "servers")
        self.metrics_path = os.path.join(state_dir, "server-metrics.jsonl")
        self._drain_path = os.path.join(self.control_dir, "drain")
        if create:
            for d in (
                self.queue_dir,
                self.tenants_dir,
                self.control_dir,
                self.servers_dir,
            ):
                os.makedirs(d, exist_ok=True)
        elif not os.path.isdir(self.queue_dir):
            raise SpoolError(
                f"{state_dir}: not a service spool (no queue/ underneath) "
                "— mistyped --state-dir?"
            )

    # -- client side -------------------------------------------------

    def submit(
        self,
        argv: list,
        tenant: str = "default",
        priority: int = 0,
        deadline_ts: Optional[float] = None,
    ) -> str:
        """Drop a job file in the queue; returns the job id. The id's
        nanosecond stamp makes collisions impossible within a process
        and sorts by submission time across processes.

        ``priority`` (higher admits first) and ``deadline_ts`` (absolute
        epoch seconds; earlier admits first within a priority class) are
        the scheduler's sort keys ahead of fair-share — see
        ``_pick_next``; its starvation floor promotes long-waiting
        low-priority jobs so a priority class cannot starve the rest."""
        check_argv(argv)
        job_id = f"job-{time.time_ns():020d}-{os.getpid() % 100000:05d}"
        spec = {
            "id": job_id,
            "tenant": tenant,
            "argv": list(argv),
            "priority": int(priority),
            "deadline_ts": None if deadline_ts is None else float(deadline_ts),
            "submitted_ts": round(time.time(), 4),
        }
        _write_json_atomic(os.path.join(self.queue_dir, f"{job_id}.json"), spec)
        return job_id

    def cancel(self, job_id: str) -> str:
        """Cancel a job wherever it lives. Queued jobs cancel
        immediately (they never ran: the queue file becomes a terminal
        tenant record); admitted jobs get a cancel flag the server
        honors at the tenant's next boundary — nothing is killed, so
        nothing needs quarantine. Returns the resulting state."""
        from mpi_opt_tpu.service import tenants as tstates

        qpath = os.path.join(self.queue_dir, f"{job_id}.json")
        if os.path.exists(qpath):
            try:
                t = self._materialize(qpath)
            except SpoolError:
                # lost the claim race to the server's admission — the
                # tenant dir exists now; fall through and cancel it there
                t = None
            if t is not None:
                # flag FIRST: if the server's racing QUEUED status write
                # lands after our CANCELLED one, the flag still cancels
                # the tenant at admission or its first boundary
                t.request_cancel()
                t.write_status(
                    dict(
                        t.status,
                        state=tstates.CANCELLED,
                        note="cancelled while queued",
                    )
                )
                return tstates.CANCELLED
        t = self.tenant(job_id)
        if t is None:
            raise SpoolError(f"unknown job {job_id!r}")
        state = t.status.get("state")
        if state in tstates.TERMINAL:
            return state
        if state in (tstates.QUEUED, tstates.PARKED):
            # not on the device: terminal immediately — but raise the
            # flag FIRST, so a server that picked this tenant between
            # our state read and the status write still drains it at
            # the next boundary instead of silently overwriting the
            # CANCELLED record at slice end
            t.request_cancel()
            t.write_status(dict(t.status, state=tstates.CANCELLED))
            return tstates.CANCELLED
        t.request_cancel()
        return state or tstates.QUEUED

    def request_drain(self) -> None:
        with open(self._drain_path, "w") as f:
            f.write("")

    def drain_requested(self) -> bool:
        return os.path.exists(self._drain_path)

    def clear_drain(self) -> None:
        try:
            os.unlink(self._drain_path)
        except FileNotFoundError:
            pass

    # -- server side -------------------------------------------------

    def pending_jobs(self) -> list:
        """Queue files in submission (= lexicographic) order."""

        def _go():
            _fault("list", self.queue_dir)
            return sorted(
                os.path.join(self.queue_dir, f)
                for f in os.listdir(self.queue_dir)
                if f.endswith(".json")
            )

        return retry_io(_go)

    def _materialize(self, queue_path: str) -> TenantDir:
        """Move a queue file into a tenant dir (the admission step's
        mechanical half; scheduler.py decides WHEN)."""
        from mpi_opt_tpu.service import tenants as tstates

        spec = _read_json(queue_path)
        if spec is None or "id" not in spec or "argv" not in spec:
            if not os.path.exists(queue_path):
                # lost a race: the other side of a concurrent
                # cancel-while-queued / admission already took it
                raise SpoolError(f"{queue_path}: already claimed by a peer")
            # a torn/garbage submit: park it out of the queue loudly
            bad = queue_path + ".malformed"
            try:
                os.replace(queue_path, bad)
            except FileNotFoundError:
                raise SpoolError(f"{queue_path}: already claimed by a peer")
            raise SpoolError(f"malformed job file moved to {bad}")
        t = TenantDir(self.tenants_dir, spec["id"])
        os.makedirs(t.dir, exist_ok=True)
        _write_json_atomic(t.job_path, spec)
        # create-if-absent: with N servers sharing the spool, a slow
        # peer re-running this admission (it read the queue file before
        # we unlinked it) must not RESET a tenant that already ran —
        # only the first initial-status write lands
        t.create_status(
            {
                "id": spec["id"],
                "tenant": spec.get("tenant", "default"),
                "state": tstates.QUEUED,
                "slices": 0,
                "preemptions": 0,
                "boundaries": 0,
                "takeovers": 0,
                "rc_history": [],
                "program_cache": {"hits": 0, "misses": 0},
                "priority": int(spec.get("priority") or 0),
                "deadline_ts": spec.get("deadline_ts"),
                "submitted_ts": spec.get("submitted_ts"),
            }
        )
        try:
            os.unlink(queue_path)
        except FileNotFoundError:
            pass  # a racing peer already removed it; the tenant dir wins
        return t

    def admit(self, queue_path: str) -> TenantDir:
        return self._materialize(queue_path)

    def tenant(self, job_id: str) -> Optional[TenantDir]:
        t = TenantDir(self.tenants_dir, job_id)
        return t if os.path.isdir(t.dir) else None

    def tenants(self) -> list:
        """All admitted tenants, submission-ordered."""

        def _go():
            _fault("list", self.tenants_dir)
            return [
                TenantDir(self.tenants_dir, d)
                for d in sorted(os.listdir(self.tenants_dir))
                if os.path.isdir(os.path.join(self.tenants_dir, d))
            ]

        return retry_io(_go)

    # -- server registry (fleet liveness) ----------------------------

    #: the id a server registers under when the operator names none —
    #: a FIXED default on purpose: two default-id servers collide, so
    #: PR 7's one-server-per-spool behavior is preserved until the
    #: operator opts into federation with distinct --server-id values
    DEFAULT_SERVER_ID = "server"

    #: a registration whose refresh timestamp is older than this many
    #: seconds is treated as dead when its pid cannot be judged (the
    #: holder runs on another host); local pids are judged directly.
    #: GENEROUS on purpose: the refresh rides the serve loop AND the
    #: active tenant's heartbeat beats, whose longest gap is the cold
    #: XLA-compile window (140-210 s measured) — judging a remote
    #: server dead mid-compile would let a same-id peer usurp a live
    #: process. The cost of the slack is only that a genuinely dead
    #: REMOTE server's id stays refused this long (same-host death is
    #: pid-judged instantly, and per-job leases — not registrations —
    #: gate the actual work).
    SERVER_STALE_S = 600.0

    def server_file(self, server_id: str) -> str:
        return os.path.join(self.servers_dir, f"{server_id}.json")

    @property
    def server_path(self) -> str:
        """The default-id registration path (the single-server shape
        tests and drills forge against)."""
        return self.server_file(self.DEFAULT_SERVER_ID)

    def read_servers(self) -> list:
        """Every registration on the spool (live or stale), sorted by
        server id. Missing servers/ (a pre-fleet spool a read-only
        client points at) reads as an empty fleet, not an error."""

        def _go():
            if not os.path.isdir(self.servers_dir):
                return []
            _fault("list", self.servers_dir)
            return sorted(
                f for f in os.listdir(self.servers_dir) if f.endswith(".json")
            )

        out = []
        for fname in retry_io(_go):
            rec = _read_json(os.path.join(self.servers_dir, fname))
            if rec is not None:
                rec.setdefault("server_id", fname[: -len(".json")])
                out.append(rec)
        return out

    def read_server(self) -> Optional[dict]:
        """The most recently refreshed registration, or None — the
        aggregate single-server view ``drain --wait`` and the status
        header key on."""
        servers = self.read_servers()
        if not servers:
            return None
        return max(servers, key=lambda r: float(r.get("ts") or 0.0))

    def server_alive(self, info: Optional[dict] = None) -> bool:
        """Is any server (or the given registration) live?"""
        if info is not None:
            return self._server_live(info)
        return any(self._server_live(r) for r in self.read_servers())

    def _claim_fields(self, server_id: str, **fields) -> dict:
        return {
            "server_id": server_id,
            "pid": os.getpid(),
            "pid_start": _pid_start(os.getpid()),
            "host": _local_host(),
            "ts": round(time.time(), 4),
            **fields,
        }

    def write_server(self, server_id: str = DEFAULT_SERVER_ID, **fields) -> None:
        """Forge/refresh a registration AS THIS PROCESS (tests, and the
        serve loop's refresh path goes through refresh_server below)."""
        _write_json_atomic(self.server_file(server_id), self._claim_fields(server_id, **fields))

    def _server_live(self, info: Optional[dict]) -> bool:
        if not info or "pid" not in info:
            return False
        host = info.get("host")
        if host is not None and host != _local_host():
            # a pid means nothing across machines: judge a remote
            # server by registration freshness only
            try:
                return (time.time() - float(info["ts"])) <= self.SERVER_STALE_S
            except (KeyError, TypeError, ValueError):
                return False
        try:
            pid = int(info["pid"])
            os.kill(pid, 0)
        except PermissionError:
            # EPERM is a LIVE process owned by someone else — on a
            # shared state-dir the same-id refusal must still see it
            # (and /proc/<pid>/stat below stays readable)
            pass
        except (OSError, ValueError):
            return False
        # the pid exists — but is it the SAME process? A SIGKILLed
        # server never clears its registration, and the kernel
        # eventually recycles its pid for an unrelated process, which
        # would hold the server-id hostage until an operator deleted
        # the file by hand. The recorded start time settles it; records
        # without one (non-Linux hosts) keep the bare-pid behavior.
        recorded = info.get("pid_start")
        if recorded is not None:
            current = _pid_start(pid)
            if current is not None and current != recorded:
                return False
        return True

    # back-compat alias (pre-fleet name; scheduler/tests used it)
    def _pid_alive(self, info: Optional[dict]) -> bool:
        return self._server_live(info)

    def register_server(self, server_id: str = DEFAULT_SERVER_ID, **fields) -> bool:
        """Atomically register THIS process under ``server_id`` (O_EXCL
        create — a check-then-write would let two servers racing through
        the same window both believe they own the identity).

        A registration held by a dead pid (SIGKILLed server) is broken
        via rename-takeover: rename wins for exactly ONE claimant, and
        the renamed file is inspected AFTER the steal — if it turns out
        to be a peer's fresh LIVE registration (the peer broke the stale
        one and re-registered between our read and our rename), it is
        restored and we lose. Returns False when a live server holds
        the id."""
        won = claim_file(
            self.server_file(server_id),
            self._claim_fields(server_id, **fields),
            stealable=lambda cur: not self._server_live(cur),
        )
        return won is not None

    def _registration_is_mine(self, cur: Optional[dict]) -> bool:
        return (
            cur is not None
            and cur.get("pid") == os.getpid()
            and cur.get("pid_start") == _pid_start(os.getpid())
        )

    def refresh_server(self, server_id: str, **fields) -> Optional[bool]:
        """Re-stamp our registration's heartbeat ``ts`` (and any counter
        fields). Identity-checked against THIS process before AND after
        the write. Tri-state: True = refreshed and still ours; False =
        the file READABLY records someone else (or is gone) — another
        process claimed the id while we were presumed dead, the caller
        (the serve loop) must STEP DOWN rather than fight; None = the
        file is present but unreadable (torn read, persistent EIO the
        retry budget couldn't clear) — CANNOT TELL, and a caller that
        treated it as usurped would have a healthy server abandon its
        fleet slot over one NFS blip. Retry later instead.

        Honesty note: check-write-verify is not fully exclusive (a
        usurper's registration landing inside the write window is
        clobbered, detected only by whoever verifies last). Making it
        so would rename the file away mid-refresh, and a concurrent
        ``server_alive`` poll would see a live server flicker absent.
        The race is survivable by construction: usurping requires the
        registration to be STALE (``SERVER_STALE_S`` with refresh
        riding both the serve loop and the tenant's heartbeat beats),
        so a clobber needs a >10-minute-hung process — and per-job
        leases, not registrations, gate the actual work either way."""
        path = self.server_file(server_id)
        cur = _read_json(path)
        if cur is None:
            return None if os.path.exists(path) else False
        if not self._registration_is_mine(cur):
            return False
        _write_json_atomic(path, dict(cur, ts=round(time.time(), 4), **fields))
        after = _read_json(path)
        if after is None:
            return None if os.path.exists(path) else False
        return True if self._registration_is_mine(after) else False

    def clear_server(self, server_id: str = DEFAULT_SERVER_ID) -> None:
        try:
            os.unlink(self.server_file(server_id))
        except FileNotFoundError:
            pass

    def clear_server_if_mine(self, server_id: str) -> bool:
        """Deregister on the way out — but ONLY if the file still
        records this process. A stepped-down zombie must not unlink the
        usurper's live registration as its parting act."""
        if not self._registration_is_mine(_read_json(self.server_file(server_id))):
            return False
        self.clear_server(server_id)
        return True
