"""The overload-safe HTTP front door (stdlib ``http.server`` only).

A REST shim over the engine's two spool surfaces — the suggestion
service (suggest/report/lookup, corpus/serve.SuggestServer) and the
sweep service (submit/status/cancel, service/spool.Spool) — in which
the SPOOL remains the durability layer and fencing tokens remain the
authority: the front door holds no durable state of its own. What it
adds is the transport the ROADMAP's front-door item names (PR 14's
one-file-round-trip-per-request spool measured 46.6 suggestions/s
against a ~2176/s acquisition ceiling) and, inseparably, the failure
envelope that makes a front door production-grade:

- **batched wire protocol** — one ``POST /v1/batch`` carries many ops
  and the whole batch shares ONE journal fsync
  (``SweepLedger.batched()``), amortizing the p95 driver PR 14
  measured;
- **bounded admission** — a fixed-depth queue between the HTTP handler
  threads and the single executor thread that owns the jitted
  acquisition state; past the bound the server SHEDS with a typed 503
  + Retry-After (``http_shed``) instead of queueing without bound;
- **idempotency window** — every envelope carries a client-generated
  key; a byte-identical retry is answered from a bounded dedup window
  (``http_replayed``) so reports journal exactly once; the SAME key
  with a DIFFERENT body digest is refused (409), never replayed. For
  report ops the ledger itself is the durable half of the window: each
  journaled report carries ``(idem_key, idem_op)``, and a restarted
  server rebuilds the index from its own journal — a client retrying
  into the restart cannot double-journal;
- **deadline scheduling** — an envelope's ``deadline_ts`` is enforced
  at DEQUEUE time: work that aged past its deadline in the queue is
  expired with a typed 504 (``http_expired``) instead of served late;
- **circuit breaker** — a per-client strike window over sheds and key
  conflicts; a retry storm trips the breaker (``breaker_open``) and
  the client eats fast 429s for the cooldown instead of amplifying the
  overload.

Threading: handler threads (ThreadingHTTPServer) parse, run the
breaker/window checks, and enqueue; the CALLER's thread runs
``serve_http``'s executor loop, which is the only thread touching the
SuggestServer, the ledger and the Spool — so the acquisition ring
needs no locking and the drain protocol works exactly like
corpus/serve.serve_loop (heartbeat beat + cooperative slice poll per
batch; a drain raises SweepInterrupted out of ``serve_http``).

Every ``do_*`` handler body is one ``try/except Exception`` that
answers a typed 500 — machine-checked by the ``http-handler-contained``
sweeplint checker: a handler raise must answer an error, never kill
the serving thread.
"""

from __future__ import annotations

import contextlib
import json
import os
import queue
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from mpi_opt_tpu.corpus.serve import ensure_spool, stop_path
from mpi_opt_tpu.corpus.transport import WIRE_VERSION, ops_digest
from mpi_opt_tpu.service.spool import _write_json_atomic

#: hard cap on ops per batch (bounds executor hold time and body size)
MAX_BATCH_OPS = 1024
#: hard cap on request body bytes (a malformed giant upload must cost a
#: bounded read to refuse)
MAX_BODY_BYTES = 8 << 20

ENDPOINT_FILE = "http.json"


def endpoint_path(sdir: str) -> str:
    return os.path.join(sdir, "control", ENDPOINT_FILE)


class _Work:
    """One admitted batch: the handler thread parks on ``event``; the
    executor fills ``status``/``response`` then sets it. ``waiters``
    counts handler threads sharing this work (a concurrent retry of
    the same key attaches instead of re-enqueueing)."""

    __slots__ = (
        "key", "client", "digest", "deadline_ts", "ops",
        "enqueued_at", "event", "status", "response", "waiters",
    )

    def __init__(self, env: dict):
        self.key = str(env["key"])
        self.client = str(env.get("client") or "unknown")
        self.digest = str(env["digest"])
        self.deadline_ts = env.get("deadline_ts")
        self.ops = env["ops"]
        self.enqueued_at = time.monotonic()
        self.event = threading.Event()
        self.status = None
        self.response = None
        self.waiters = 1


def _error_body(kind: str, detail: str) -> dict:
    return {"error": {"kind": kind, "detail": detail}}


class FrontDoor:
    """Transport-free core: admission, dedup, breaker, execution. The
    HTTP handler calls :meth:`admit` / :meth:`peek_status`; the
    executor loop calls :meth:`run_one`. Unit-testable without a
    socket."""

    def __init__(
        self,
        suggest=None,
        ledger=None,
        spool=None,
        metrics=None,
        queue_depth: int = 64,
        window_size: int = 512,
        shed_retry_after_s: float = 0.25,
        breaker_strikes: int = 32,
        breaker_window_s: float = 10.0,
        breaker_cooldown_s: float = 5.0,
        max_wait_s: float = 120.0,
    ):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if window_size < 1:
            raise ValueError(f"window_size must be >= 1, got {window_size}")
        self.suggest = suggest
        self.ledger = ledger
        self.spool = spool
        self.metrics = metrics
        self.queue: queue.Queue = queue.Queue(maxsize=queue_depth)
        self.window_size = window_size
        self.shed_retry_after_s = shed_retry_after_s
        self.breaker_strikes = breaker_strikes
        self.breaker_window_s = breaker_window_s
        self.breaker_cooldown_s = breaker_cooldown_s
        self.max_wait_s = max_wait_s
        # handler-side shared state; the executor touches it too, so
        # every access is under this one lock (never held across an
        # execute or a metrics write)
        self._lock = threading.Lock()
        self._window: OrderedDict = OrderedDict()  # key -> {digest, response}
        self._pending: dict = {}  # key -> _Work
        self._strikes: dict = {}  # client -> deque[monotonic ts]
        self._breaker_until: dict = {}  # client -> monotonic deadline
        # metrics handles are not promised thread-safe; one small lock
        # serializes handler-thread and executor-thread log calls
        self._mlock = threading.Lock()
        # durable idempotency index for REPORT ops: (key, op_idx) ->
        # {"trial_id", "status"}; seeded from the ledger's own records
        # so the window survives a server SIGKILL (executor-only state)
        self._journal_index: dict = {}
        if ledger is not None:
            for rec in getattr(ledger, "records", []):
                k, i = rec.get("idem_key"), rec.get("idem_op")
                if k is not None and i is not None:
                    self._journal_index[(str(k), int(i))] = {
                        "trial_id": rec.get("trial_id"),
                        "status": rec.get("status"),
                    }
        self.counters = {
            "batches": 0, "ops": 0, "suggestions": 0, "reports": 0,
            "shed": 0, "replayed": 0, "expired": 0, "conflicts": 0,
            "breaker_trips": 0, "errors": 0,
        }

    # -- observability ----------------------------------------------------

    def _log(self, _event: str, **fields) -> None:
        if self.metrics is None:
            return
        with self._mlock:
            self.metrics.log(_event, **fields)

    # -- breaker ----------------------------------------------------------

    def _strike(self, client: str, now: float) -> bool:
        """One abuse mark (a shed, a key conflict) against ``client``;
        called under ``self._lock``. Past the threshold inside the
        window, the breaker opens for the cooldown; returns True on the
        trip (the caller logs breaker_open outside the lock)."""
        dq = self._strikes.setdefault(client, deque())
        dq.append(now)
        while dq and now - dq[0] > self.breaker_window_s:
            dq.popleft()
        if len(dq) >= self.breaker_strikes and client not in self._breaker_until:
            self._breaker_until[client] = now + self.breaker_cooldown_s
            self.counters["breaker_trips"] += 1
            dq.clear()
            return True
        return False

    def _breaker_open_for(self, client: str, now: float) -> Optional[float]:
        """Seconds until this client's breaker closes, or None."""
        until = self._breaker_until.get(client)
        if until is None:
            return None
        if now >= until:
            del self._breaker_until[client]
            return None
        return until - now

    # -- admission (handler threads) --------------------------------------

    def validate(self, env) -> Optional[tuple]:
        """Envelope schema check; returns a (status, body, retry_after)
        refusal or None when the envelope is admissible."""
        if not isinstance(env, dict):
            return 400, _error_body("malformed", "body must be a JSON object"), None
        try:
            if int(env.get("version") or 1) > WIRE_VERSION:
                return 400, _error_body(
                    "malformed",
                    f"wire version {env['version']} is newer than this "
                    f"server's {WIRE_VERSION}",
                ), None
        except (TypeError, ValueError):
            return 400, _error_body("malformed", "version must be an integer"), None
        key = env.get("key")
        if not isinstance(key, str) or not key or len(key) > 128:
            return 400, _error_body(
                "malformed", "need a non-empty string idempotency 'key'"
            ), None
        ops = env.get("ops")
        if not isinstance(ops, list) or not ops:
            return 400, _error_body("malformed", "need a non-empty 'ops' list"), None
        if len(ops) > MAX_BATCH_OPS:
            return 400, _error_body(
                "malformed", f"{len(ops)} ops exceed the {MAX_BATCH_OPS}-op batch cap"
            ), None
        if not all(isinstance(o, dict) for o in ops):
            return 400, _error_body("malformed", "every op must be an object"), None
        digest = ops_digest(ops)
        if env.get("digest") is not None and env["digest"] != digest:
            return 400, _error_body(
                "malformed", "digest does not match the ops body"
            ), None
        env["digest"] = digest
        ddl = env.get("deadline_ts")
        if ddl is not None:
            try:
                env["deadline_ts"] = float(ddl)
            except (TypeError, ValueError):
                return 400, _error_body("malformed", "deadline_ts must be a number"), None
        return None

    def admit(self, env: dict) -> tuple:
        """The handler-thread path: breaker -> dedup window -> pending
        attach -> bounded enqueue -> wait. Returns ``(status, body,
        retry_after)`` — always an answer, never an unbounded block."""
        refused = self.validate(env)
        if refused is not None:
            return refused
        key = str(env["key"])
        client = str(env.get("client") or "unknown")
        now = time.monotonic()
        tripped = False
        with self._lock:
            wait_s = self._breaker_open_for(client, now)
            if wait_s is not None:
                body = _error_body(
                    "breaker_open",
                    f"client {client!r} tripped the retry-storm breaker; "
                    f"retry after {wait_s:.2f}s",
                )
                return 429, body, wait_s
            hit = self._window.get(key)
            if hit is not None:
                if hit["digest"] != env["digest"]:
                    self.counters["conflicts"] += 1
                    tripped = self._strike(client, now)
                    status_body = (
                        409,
                        _error_body(
                            "key_conflict",
                            "idempotency key reused with a different body "
                            "— retries must be byte-identical",
                        ),
                        None,
                    )
                else:
                    self.counters["replayed"] += 1
                    status_body = (200, dict(hit["response"], replayed=True), None)
            else:
                work = self._pending.get(key)
                if work is not None:
                    if work.digest != env["digest"]:
                        self.counters["conflicts"] += 1
                        tripped = self._strike(client, now)
                        status_body = (
                            409,
                            _error_body(
                                "key_conflict",
                                "idempotency key already in flight with a "
                                "different body",
                            ),
                            None,
                        )
                    else:
                        # a concurrent retry of an in-flight batch rides
                        # the SAME work item: both waiters get the one
                        # executed answer — exactly-once by construction
                        work.waiters += 1
                        self.counters["replayed"] += 1
                        status_body = ("wait-replay", work, None)
                else:
                    work = _Work(env)
                    try:
                        self.queue.put_nowait(work)
                    except queue.Full:
                        self.counters["shed"] += 1
                        tripped = self._strike(client, now)
                        body = _error_body(
                            "overloaded",
                            f"admission queue full ({self.queue.maxsize}); "
                            f"retry after {self.shed_retry_after_s}s",
                        )
                        status_body = (503, body, self.shed_retry_after_s)
                    else:
                        self._pending[key] = work
                        status_body = ("wait", work, None)
        # log OUTSIDE the lock (metrics handles do I/O)
        if tripped:
            self._log("breaker_open", client=client, cooldown_s=self.breaker_cooldown_s)
        status, body, retry_after = status_body
        if status == 503:
            self._log("http_shed", client=client, queue_depth=self.queue.maxsize)
            return 503, body, retry_after
        if status == 200 and body.get("replayed"):
            self._log("http_replayed", client=client)
            return 200, body, None
        if status in ("wait", "wait-replay"):
            replay = status == "wait-replay"
            if replay:
                self._log("http_replayed", client=client)
            return self._await(body, replay=replay)
        return status, body, retry_after

    def _await(self, work: _Work, replay: bool = False) -> tuple:
        """Park the handler thread until the executor answers (bounded:
        the deadline plus grace, else ``max_wait_s``)."""
        if work.deadline_ts is not None:
            timeout = max(0.0, work.deadline_ts - time.time()) + 10.0
        else:
            timeout = self.max_wait_s
        if not work.event.wait(timeout):
            # the executor is wedged or the wait budget is gone; answer
            # overloaded (typed, retryable) — if the work does execute
            # later, the window replays it to the retry
            return 503, _error_body(
                "overloaded", f"no executor answer within {timeout:.0f}s"
            ), self.shed_retry_after_s
        body = work.response
        if replay and work.status == 200:
            body = dict(body, replayed=True)
        return work.status, body, None

    # -- execution (the one executor thread) -------------------------------

    def run_one(self, work: _Work) -> None:
        """Execute one admitted batch and answer its waiters. One
        journal fsync for the whole batch; the fsync happens BEFORE the
        answer is published (journal-before-ack at batch granularity)."""
        now = time.time()
        wait_s = time.monotonic() - work.enqueued_at
        if work.deadline_ts is not None and now > work.deadline_ts:
            self.counters["expired"] += 1
            self._finish(
                work,
                504,
                _error_body(
                    "deadline_expired",
                    f"batch aged {wait_s:.3f}s in queue, past its deadline — "
                    "expired instead of served late",
                ),
                record=False,
            )
            self._log("http_expired", client=work.client, queue_wait_s=round(wait_s, 4))
            return
        results = []
        failed = None
        try:
            batch_cm = (
                self.ledger.batched()
                if self.ledger is not None and any(
                    o.get("op") == "report" for o in work.ops
                )
                else contextlib.nullcontext()
            )
            with batch_cm:
                for i, op_req in enumerate(work.ops):
                    results.append(self._execute_op(op_req, work.key, i))
        except Exception as e:  # noqa: BLE001 - containment, see below
            from mpi_opt_tpu.health.shutdown import SweepInterrupted

            if isinstance(e, SweepInterrupted):
                # the drain signal must reach serve_http's caller; the
                # waiters get a typed retryable answer first so clients
                # fail over to the restarted/peer server immediately
                self._finish(
                    work, 503, _error_body("overloaded", "server draining"),
                    record=False,
                )
                raise
            self.counters["errors"] += 1
            self._finish(
                work, 500,
                _error_body("internal", f"{type(e).__name__}: {e}"),
                record=False,
            )
            self._log("http_error", client=work.client, detail=f"{type(e).__name__}: {e}")
            return
        n_sugg = sum(
            len(r.get("params") or [])
            for r, o in zip(results, work.ops)
            if o.get("op") == "suggest"
        )
        n_rep = sum(1 for o in work.ops if o.get("op") == "report")
        self.counters["batches"] += 1
        self.counters["ops"] += len(work.ops)
        self.counters["suggestions"] += n_sugg
        self.counters["reports"] += n_rep
        response = {
            "key": work.key,
            "replayed": False,
            "queue_wait_s": round(wait_s, 6),
            "results": results,
        }
        self._finish(work, 200, response, record=True)
        self._log(
            "http_request",
            client=work.client,
            ops=len(work.ops),
            suggestions=n_sugg,
            reports=n_rep,
            queue_wait_s=round(wait_s, 4),
        )

    def _finish(self, work: _Work, status: int, body: dict, record: bool) -> None:
        with self._lock:
            if record:
                self._window[work.key] = {"digest": work.digest, "response": body}
                while len(self._window) > self.window_size:
                    self._window.popitem(last=False)
            self._pending.pop(work.key, None)
        work.status = status
        work.response = body
        work.event.set()

    def _execute_op(self, req: dict, key: str, op_idx: int) -> dict:
        op = req.get("op")
        if op in ("suggest", "report", "lookup"):
            if self.suggest is None:
                return {"error": "no suggestion backend on this front door"}
            if op == "report" and self.ledger is not None:
                prior = self._journal_index.get((key, op_idx))
                if prior is not None:
                    # the durable half of the idempotency window: this
                    # exact (key, op) is already journaled — answer from
                    # the journal, never re-journal (exactly-once even
                    # across a server SIGKILL + restart)
                    return {
                        "ok": prior.get("status") == "ok",
                        "trial_id": prior.get("trial_id"),
                        "n_obs": self.suggest._n_obs,
                        "journal_replayed": True,
                    }
                ans = self.suggest.handle(
                    req, ledger=self.ledger,
                    meta={"idem_key": key, "idem_op": op_idx},
                )
                if not ans.get("error") and ans.get("trial_id") is not None:
                    self._journal_index[(key, op_idx)] = {
                        "trial_id": ans["trial_id"],
                        "status": "ok" if ans.get("ok") else "failed",
                    }
                return ans
            return self.suggest.handle(req, ledger=self.ledger)
        if op in ("submit", "status", "cancel"):
            return self._service_op(req)
        return {"error": f"unknown op {op!r}"}

    def _service_op(self, req: dict) -> dict:
        from mpi_opt_tpu.service.spool import SpoolError

        if self.spool is None:
            return {
                "error": "no service spool on this front door "
                "(start the server with --http-state-dir DIR)"
            }
        op = req.get("op")
        try:
            if op == "submit":
                argv = req.get("argv")
                if not isinstance(argv, list) or not argv:
                    return {"error": "submit needs a non-empty 'argv' list"}
                deadline_ts = req.get("deadline_ts")
                job = self.spool.submit(
                    [str(a) for a in argv],
                    tenant=str(req.get("tenant") or "default"),
                    priority=int(req.get("priority") or 0),
                    deadline_ts=None if deadline_ts is None else float(deadline_ts),
                )
                return {"job": job, "tenant": req.get("tenant") or "default",
                        "state": "queued"}
            if op == "status":
                return self.service_status()
            if op == "cancel":
                job = req.get("job")
                if not job:
                    return {"error": "cancel needs a 'job' id"}
                return {"job": job, "state": self.spool.cancel(str(job)),
                        "cancel": True}
        except (SpoolError, TypeError, ValueError) as e:
            # a bad job id / malformed field is the CLIENT's error:
            # answer it (the tenant_reject moral), never crash the
            # executor every other client is riding on
            return {"error": f"{type(e).__name__}: {e}"}
        return {"error": f"unknown service op {op!r}"}

    def service_status(self) -> dict:
        if self.spool is None:
            return {"error": "no service spool on this front door"}
        from mpi_opt_tpu.service.client import _collect_status

        return _collect_status(self.spool)

    def health(self) -> dict:
        return {
            "ok": True,
            "queue": self.queue.qsize(),
            "queue_depth": self.queue.maxsize,
            "counters": dict(self.counters),
        }


class FrontDoorHandler(BaseHTTPRequestHandler):
    """Thin HTTP skin over :class:`FrontDoor` (reachable as
    ``self.server.front``). Contract (machine-checked by sweeplint's
    ``http-handler-contained``): each ``do_*`` body is ONE try/except
    Exception that answers a typed error — a handler bug must cost one
    500 answer, never the serving thread."""

    server_version = "mpi-opt-frontdoor/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # the metrics stream is the access log; stderr stays quiet

    def _answer(self, status: int, body: dict, retry_after=None) -> None:
        raw = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        if retry_after is not None:
            self.send_header("Retry-After", f"{retry_after:.3f}")
        self.end_headers()
        self.wfile.write(raw)

    def _read_body(self):
        length = int(self.headers.get("Content-Length") or 0)
        if length > MAX_BODY_BYTES:
            return None
        raw = self.rfile.read(length) if length else b""
        try:
            return json.loads(raw) if raw else {}
        except ValueError:
            return None

    def do_POST(self) -> None:  # noqa: N802 - stdlib casing
        try:
            front = self.server.front
            path = self.path.split("?", 1)[0].rstrip("/")
            if path == "/v1/stop":
                self.server.stop_requested.set()
                self._answer(200, {"stop": True})
                return
            single = {
                "/v1/suggest": "suggest", "/v1/report": "report",
                "/v1/lookup": "lookup", "/v1/submit": "submit",
                "/v1/cancel": "cancel",
            }
            if path != "/v1/batch" and path not in single:
                self._answer(404, _error_body("malformed", f"no endpoint {path}"))
                return
            body = self._read_body()
            if body is None:
                self._answer(
                    400, _error_body("malformed", "body must be JSON under 8 MiB")
                )
                return
            if path == "/v1/batch":
                env = body
            else:
                # single-op REST shape: envelope fields ride beside the
                # op's own; the answer shape is the batch's (one result)
                from mpi_opt_tpu.corpus.transport import make_key

                op_fields = {
                    k: v for k, v in body.items()
                    if k not in ("key", "client", "deadline_ts", "version", "digest")
                }
                env = {
                    "version": WIRE_VERSION,
                    "key": body.get("key") or make_key(),
                    "client": body.get("client"),
                    "deadline_ts": body.get("deadline_ts"),
                    "ops": [dict(op_fields, op=single[path])],
                }
            status, out, retry_after = front.admit(env)
            self._answer(status, out, retry_after)
        except Exception as e:  # noqa: BLE001 - handler containment
            with contextlib.suppress(Exception):
                self._answer(500, _error_body("internal", f"{type(e).__name__}: {e}"))

    def do_GET(self) -> None:  # noqa: N802 - stdlib casing
        try:
            front = self.server.front
            path = self.path.split("?", 1)[0].rstrip("/")
            if path == "/v1/healthz":
                self._answer(200, front.health())
            elif path == "/v1/status":
                # read-only spool scan: safe from a handler thread (the
                # spool's primitives are atomic reads), so status never
                # queues behind suggestion traffic
                self._answer(200, front.service_status())
            else:
                self._answer(404, _error_body("malformed", f"no endpoint {path}"))
        except Exception as e:  # noqa: BLE001 - handler containment
            with contextlib.suppress(Exception):
                self._answer(500, _error_body("internal", f"{type(e).__name__}: {e}"))


def serve_http(
    front: FrontDoor,
    sdir: str,
    metrics,
    host: str = "127.0.0.1",
    port: int = 0,
    poll_seconds: float = 0.05,
    idle_timeout: Optional[float] = None,
    max_batches: Optional[int] = None,
) -> dict:
    """Bind, publish the endpoint file, and run the executor loop in
    THIS thread until stop/idle/drain — the same lifecycle contract as
    corpus/serve.serve_loop: a drain request raises SweepInterrupted
    (the caller maps it to the EX_TEMPFAIL park), the stop flag (POST
    /v1/stop, or the spool's control/stop file) and the idle timeout
    complete it. Returns the summary dict.

    The bound port is published atomically to ``SDIR/control/http.json``
    so clients (and the bench/drill) discover ``--http-port 0``
    ephemeral binds without racing the bind itself."""
    from mpi_opt_tpu.health import heartbeat, shutdown
    from mpi_opt_tpu.health.shutdown import SweepInterrupted

    ensure_spool(sdir)

    class _Server(ThreadingHTTPServer):
        # the default socketserver backlog (5) makes the KERNEL the shed
        # point under a connection burst — clients see RSTs instead of
        # the admission queue's typed 503 + Retry-After. A deep listen
        # backlog keeps the bounded queue the one place overload is
        # answered; the handler threads it admits are parked waiters,
        # not runnable work
        request_queue_size = 128

    httpd = _Server((host, port), FrontDoorHandler)
    httpd.daemon_threads = True
    httpd.front = front
    httpd.stop_requested = threading.Event()
    front.metrics = metrics
    bound_port = httpd.server_address[1]
    _write_json_atomic(
        endpoint_path(sdir),
        {"host": host, "port": bound_port,
         "url": f"http://{host}:{bound_port}", "pid": os.getpid()},
    )
    metrics.log("http_serve", port=bound_port, queue_depth=front.queue.maxsize)
    server_thread = threading.Thread(
        target=httpd.serve_forever, kwargs={"poll_interval": 0.1},
        name="frontdoor-http", daemon=True,
    )
    server_thread.start()
    last_activity = time.monotonic()
    stop_seen = stopped = False
    try:
        while True:
            if not stop_seen and (
                httpd.stop_requested.is_set() or os.path.exists(stop_path(sdir))
            ):
                # latch AND consume, like serve_loop: finish what is
                # admitted, then exit 0; a stale flag must not stop the
                # NEXT server on this spool
                stop_seen = True
                try:
                    os.unlink(stop_path(sdir))
                except OSError:
                    pass
            try:
                work = front.queue.get(timeout=poll_seconds)
            except queue.Empty:
                if stop_seen:
                    stopped = True
                    break
                if shutdown.requested():
                    raise SweepInterrupted(
                        shutdown.active_signal(),
                        at=f"batch {front.counters['batches']}",
                    )
                if max_batches is not None and front.counters["batches"] >= max_batches:
                    stopped = True
                    break
                if (
                    idle_timeout is not None
                    and time.monotonic() - last_activity >= idle_timeout
                ):
                    stopped = True
                    break
                continue
            front.run_one(work)
            last_activity = time.monotonic()
            # the tenant's liveness pulse + cooperative slice point:
            # every answered batch is a natural boundary, so the sweep
            # service can time-slice an HTTP front door like a sweep
            heartbeat.beat(
                stage="http",
                served=front.counters["batches"],
                reports=front.counters["reports"],
            )
            shutdown.poll_slice(f"batch {front.counters['batches']}")
            if shutdown.requested():
                raise SweepInterrupted(
                    shutdown.active_signal(),
                    at=f"batch {front.counters['batches']}",
                )
            if max_batches is not None and front.counters["batches"] >= max_batches:
                stopped = True
                break
    finally:
        httpd.shutdown()
        httpd.server_close()
        try:
            os.unlink(endpoint_path(sdir))
        except OSError:
            pass
    summary = {
        "served": front.counters["batches"],
        "ops": front.counters["ops"],
        "suggestions": front.counters["suggestions"],
        "reports": front.counters["reports"],
        "shed": front.counters["shed"],
        "replayed": front.counters["replayed"],
        "expired": front.counters["expired"],
        "breaker_trips": front.counters["breaker_trips"],
        "n_obs": None if front.suggest is None else front.suggest._n_obs,
        "stopped": stopped,
    }
    metrics.log("http_stop", **summary)
    return summary
