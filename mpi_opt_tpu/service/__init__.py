"""Sweep-as-a-service: a resident scheduler multiplexing one device.

The batch CLI's economics are upside down for many small sweeps: every
invocation pays a full compile+warmup (140–210 s on this device) for
~2 minutes of search. This package inverts that — ONE long-lived
server (``mpi_opt_tpu serve``) owns the device and time-slices it
across submitted sweeps at their natural drain boundaries, so the
marginal cost of tenant N+1 is program dispatch, not recompilation.

Pieces:

- ``spool``    — filesystem queue + control plane (no network needed)
- ``tenants``  — the per-job state machine over exit-code outcomes
- ``leases``   — fleet federation: per-job lease claims with fencing
  tokens, heartbeat-ridden TTL refresh, crash-safe takeover
- ``programs`` — compiled-program reuse across shape-matching tenants
- ``scheduler``— the server loop: admit, fair-share pick, slice, park
- ``client``   — ``submit`` / ``status`` / ``cancel`` / ``drain``

Every mechanism the scheduler leans on already existed for robustness:
preemption IS the graceful-drain protocol, parking IS exit-75, resume
IS verified snapshots + ledger journal prefixes. The service adds
policy, not new failure modes.
"""

from __future__ import annotations


def service_main(argv) -> int:
    """Dispatch the service subcommands (see cli.main). Lazy imports
    keep `submit`/`status`/`cancel`/`drain` jax-free and fast."""
    cmd, rest = argv[0], argv[1:]
    if cmd == "serve":
        from mpi_opt_tpu.service.client import serve_main

        return serve_main(rest)
    if cmd == "submit":
        from mpi_opt_tpu.service.client import submit_main

        return submit_main(rest)
    if cmd == "status":
        from mpi_opt_tpu.service.client import status_main

        return status_main(rest)
    if cmd == "cancel":
        from mpi_opt_tpu.service.client import cancel_main

        return cancel_main(rest)
    if cmd == "drain":
        from mpi_opt_tpu.service.client import drain_main

        return drain_main(rest)
    raise ValueError(f"unknown service subcommand {cmd!r}")
