"""Thin spool clients + the ``serve`` entrypoint (CLI subcommands).

Everything here talks to the service through the filesystem spool —
``submit``/``status``/``cancel``/``drain`` never import jax and work
whether or not a server is currently alive (a dead server's spool is
still a readable queue; jobs submitted to it run when one starts).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from mpi_opt_tpu.service import tenants as tstates
from mpi_opt_tpu.service.spool import ServerClaimError, Spool, SpoolError
from mpi_opt_tpu.utils.exitcodes import EX_IOERR, EX_USAGE


def _nonempty_dir(value: str) -> str:
    # `--state-dir ""` (a classic unset-shell-var slip) would otherwise
    # build the spool tree relative to the caller's cwd
    if not value:
        raise argparse.ArgumentTypeError("must be a non-empty path")
    return value


def _state_dir_parser(prog: str, description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=f"mpi_opt_tpu {prog}", description=description)
    p.add_argument(
        "--state-dir",
        required=True,
        type=_nonempty_dir,
        metavar="DIR",
        help="the service spool directory (shared by server and clients)",
    )
    return p


def serve_main(argv) -> int:
    p = _state_dir_parser(
        "serve",
        "resident multi-tenant sweep server: owns the device, multiplexes "
        "it across submitted sweeps by time-slicing at natural boundaries",
    )
    p.add_argument(
        "--slice-boundaries",
        type=int,
        default=8,
        metavar="N",
        help="scheduling quantum: preempt the running tenant after N "
        "natural boundaries (gen_chunk/rung/TPE-batch/wave/driver-batch); "
        "the drain flushes a boundary snapshot so the park is free",
    )
    p.add_argument(
        "--slice-seconds",
        type=float,
        default=None,
        metavar="S",
        help="additional wall-clock quantum: preempt at the FIRST boundary "
        "past S seconds (whichever of the two budgets trips first)",
    )
    p.add_argument(
        "--max-active-per-tenant",
        type=int,
        default=2,
        metavar="N",
        help="admission cap: at most N non-terminal jobs per tenant name; "
        "excess jobs wait in the queue",
    )
    p.add_argument(
        "--server-id",
        default=None,
        metavar="ID",
        help="this server's fleet identity (registered under "
        "servers/<ID>.json; a live same-id collision is refused). Give "
        "each server of a multi-server spool a distinct id; the default "
        "id deliberately collides, preserving one-server-per-spool",
    )
    p.add_argument(
        "--lease-ttl",
        type=float,
        default=600.0,
        metavar="S",
        help="per-job lease deadline: a job whose lease went this long "
        "without a refresh may be taken over by any live server (a "
        "provably dead same-host holder is taken over immediately, so "
        "a generous TTL costs only cross-host takeover latency). Size "
        "it above the longest gap between heartbeat beats — in practice "
        "the cold-compile window, 140-210 s measured, which is why the "
        "default is 600 (see README: TTL tuning)",
    )
    p.add_argument(
        "--starvation-floor",
        type=float,
        default=300.0,
        metavar="S",
        help="priority aging interval: every S seconds a queued job "
        "waits promotes it one effective priority class, so a "
        "saturating high-priority stream delays low-priority tenants "
        "by a bounded number of floors, never forever",
    )
    p.add_argument(
        "--poll-seconds", type=float, default=0.5, help="idle spool poll interval"
    )
    p.add_argument(
        "--drain-on-empty",
        action="store_true",
        help="exit once the queue is empty and every tenant is terminal "
        "(batch/drill mode; without it the server stays resident)",
    )
    p.add_argument(
        "--trace",
        action="store_true",
        help="span-trace every tenant slice into the tenant's own "
        "metrics.jsonl (tenant-tagged records) and the server's "
        "scheduling into server-metrics.jsonl; render the whole "
        "multi-tenant picture with `mpi_opt_tpu trace STATE_DIR`",
    )
    p.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu"],
        help="pin the jax platform ONCE at server bring-up (tenants may "
        "not: the server owns the device)",
    )
    p.add_argument(
        "--local-devices",
        type=int,
        default=None,
        help="with --platform cpu: virtual device count for the server",
    )
    args = p.parse_args(argv)
    if args.slice_boundaries < 1:
        p.error(f"--slice-boundaries must be >= 1, got {args.slice_boundaries}")
    if args.slice_seconds is not None and args.slice_seconds <= 0:
        p.error(f"--slice-seconds must be > 0, got {args.slice_seconds}")
    if args.max_active_per_tenant < 1:
        p.error(
            f"--max-active-per-tenant must be >= 1, got {args.max_active_per_tenant}"
        )
    if args.lease_ttl <= 0:
        p.error(f"--lease-ttl must be > 0, got {args.lease_ttl}")
    if args.starvation_floor <= 0:
        p.error(f"--starvation-floor must be > 0, got {args.starvation_floor}")
    if args.server_id is not None and (
        not args.server_id
        or not all(c.isalnum() or c in "._-" for c in args.server_id)
    ):
        # the id becomes a filename under servers/ — a separator or
        # shell glob in it would scatter registrations around the tree
        p.error(
            f"--server-id {args.server_id!r} must be non-empty "
            "letters/digits/._- only"
        )
    # device bring-up happens HERE, once, before any tenant runs, via
    # the SAME validate-and-pin helper the flat CLI uses (a serve-local
    # copy once dropped its --local-devices >= 1 guard and turned a
    # usage error into a deferred backend crash); the persistent
    # compile cache multiplies across every tenant of the server
    from mpi_opt_tpu.cli import pin_platform, wire_compile_cache

    wire_compile_cache()
    pin_platform(args.platform, args.local_devices, p.error)
    from mpi_opt_tpu.service.scheduler import SweepService

    service = SweepService(
        args.state_dir,
        slice_boundaries=args.slice_boundaries,
        slice_seconds=args.slice_seconds,
        max_active_per_tenant=args.max_active_per_tenant,
        poll_seconds=args.poll_seconds,
        drain_on_empty=args.drain_on_empty,
        metrics_stream=sys.stdout,
        trace=args.trace,
        server_id=args.server_id,
        lease_ttl=args.lease_ttl,
        starvation_floor_s=args.starvation_floor,
    )
    try:
        return service.serve()
    except ServerClaimError as e:
        # ONLY the one-server-per-spool refusal is usage-shaped; any
        # other exception is a server crash and must keep its traceback
        print(str(e), file=sys.stderr)
        return EX_USAGE
    except OSError as e:
        from mpi_opt_tpu.utils.resources import is_storage_full

        if not is_storage_full(e):
            raise
        # the SPOOL's disk filled (a tenant-status write, a queue
        # admission): retry_io answered immediately instead of
        # spinning, and the spool on disk IS the queue checkpoint —
        # nothing is lost. Park the whole server with the classified
        # code: free disk, restart, and every in-flight tenant resumes
        # through the ordinary recovery (ISSUE 13).
        print(
            f"{e}\nspool disk full: server parked (exit {EX_IOERR}); "
            "free disk space and restart `serve` — the spool state on "
            "disk is the queue checkpoint, in-flight tenants resume",
            file=sys.stderr,
        )
        return EX_IOERR


def submit_main(argv) -> int:
    p = _state_dir_parser(
        "submit",
        "queue a sweep on a service spool; everything after `--` is the "
        "sweep's own CLI arguments (the flat mpi_opt_tpu surface, minus "
        "the server-owned flags)",
    )
    p.add_argument(
        "--tenant",
        default="default",
        help="tenant name for fair-share scheduling and concurrency caps",
    )
    p.add_argument(
        "--priority",
        type=int,
        default=0,
        metavar="N",
        help="priority class (higher admits first, default 0; the "
        "server's starvation floor ages waiting jobs upward so no "
        "class starves the rest)",
    )
    p.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="S",
        help="soft deadline S seconds from now: orders admission "
        "WITHIN a priority class (earliest deadline first); surfaced "
        "in status/report",
    )
    p.add_argument(
        "sweep_args",
        nargs=argparse.REMAINDER,
        metavar="-- ARGS",
        help="sweep CLI arguments (prefix with `--`)",
    )
    args = p.parse_args(argv)
    if args.deadline is not None and args.deadline <= 0:
        p.error(f"--deadline must be > 0 seconds from now, got {args.deadline}")
    sweep = list(args.sweep_args)
    if sweep and sweep[0] == "--":
        sweep = sweep[1:]
    if not sweep:
        p.error("no sweep arguments given (append `-- --workload ... [flags]`)")
    spool = Spool(args.state_dir)
    deadline_ts = None if args.deadline is None else time.time() + args.deadline
    try:
        job_id = spool.submit(
            sweep,
            tenant=args.tenant,
            priority=args.priority,
            deadline_ts=deadline_ts,
        )
    except SpoolError as e:
        p.error(str(e))
    print(
        json.dumps(
            {
                "job": job_id,
                "tenant": args.tenant,
                "state": "queued",
                "priority": args.priority,
                "deadline_ts": deadline_ts,
            }
        )
    )
    return 0


def _collect_servers(records: list, spool: Spool, owners: dict) -> list:
    """The fleet table: one row per registration (``records`` is ONE
    ``read_servers()`` scan, shared with the aggregate header — status
    runs against the contended shared filesystems fleets live on, so
    the directory is listed once, not per consumer), live or dead (a
    dead row is evidence — its jobs are the takeover candidates).
    ``owners`` maps server_id -> list of job ids whose LIVE lease
    names it (computed by the caller from the lease scan, so the
    tenant walk happens once too)."""
    out = []
    now = time.time()
    for rec in records:
        sid = rec.get("server_id")
        row = {
            "server_id": sid,
            "pid": rec.get("pid"),
            "pid_start": rec.get("pid_start"),
            "host": rec.get("host"),
            "alive": spool.server_alive(rec),
            "lease_ttl": rec.get("lease_ttl"),
            "takeovers": rec.get("takeovers"),
            "slices": rec.get("slices"),
            "tenants": owners.get(sid, []),
        }
        try:
            row["refreshed_age_s"] = round(max(0.0, now - float(rec["ts"])), 3)
        except (KeyError, TypeError, ValueError):
            row["refreshed_age_s"] = None
        out.append(row)
    return out


def _collect_status(spool: Spool) -> dict:
    from mpi_opt_tpu.service import leases

    server_records = spool.read_servers()
    server = (
        max(server_records, key=lambda r: float(r.get("ts") or 0.0))
        if server_records
        else None
    )
    jobs = []
    for qpath in spool.pending_jobs():
        from mpi_opt_tpu.service.spool import _read_json

        spec = _read_json(qpath) or {}
        jobs.append(
            {
                "job": spec.get("id", os.path.basename(qpath)[:-5]),
                "tenant": spec.get("tenant", "default"),
                # same label submit printed and admission will write:
                # "queued" means "not yet running" on every surface —
                # a script polling right after submit must not see a
                # third state the lifecycle diagram doesn't have
                "state": tstates.QUEUED,
                "priority": int(spec.get("priority") or 0),
                "deadline_ts": spec.get("deadline_ts"),
            }
        )
    from mpi_opt_tpu.service.spool import live_phase

    owners: dict = {}
    for t in spool.tenants():
        s = t.status
        # the job's lease, surfaced raw-ish: who holds it and whether
        # the hold is still live — `status` is the operator's first
        # stop when deciding if a "running" job is real work or an
        # orphan a surviving server is about to take over
        lease = leases.read_lease(t.lease)
        lease_view = None
        if lease is not None:
            live = not leases.expired(lease)
            lease_view = {
                "server_id": lease.get("server_id"),
                "live": live,
                "expires_ts": lease.get("expires_ts"),
            }
            if live:
                owners.setdefault(lease.get("server_id"), []).append(t.job_id)
        job = {
            "job": t.job_id,
            "tenant": s.get("tenant", "default"),
            "state": s.get("state"),
            "priority": int(s.get("priority") or 0),
            "deadline_ts": s.get("deadline_ts"),
            "slices": s.get("slices"),
            "preemptions": s.get("preemptions"),
            "boundaries": s.get("boundaries"),
            "best_score": s.get("best_score"),
            "program_cache": s.get("program_cache"),
            "first_slice_wall_s": s.get("first_slice_wall_s"),
            # post-slice device-memory watermark (obs/memory.py via the
            # scheduler): what this tenant's residency costs the device
            "device_memory": s.get("device_memory"),
            # cumulative device-idle fraction from the tenant's span
            # stream (obs/bubbles.py; written per slice end under
            # serve --trace) — the co-residency signal beside memory
            "idle_frac": s.get("idle_frac"),
            # fleet fields: which server ran the last slice, how many
            # times the job changed hands, and the current lease hold
            "server": s.get("server"),
            "takeovers": s.get("takeovers"),
            "lease": lease_view,
        }
        # an ACTIVE tenant surfaces what it is doing right now: the
        # phase from its heartbeat (fed by the active trace span) and
        # how long the current slice has been on the device
        live = live_phase(t.dir, s)
        if live is not None:
            job.update(live)
        jobs.append(job)
    servers = _collect_servers(server_records, spool, owners)
    return {
        "state_dir": spool.state_dir,
        # aggregate single-server view kept for scripts that predate
        # the fleet: alive = ANY live registration, fields from the
        # most recently refreshed one
        "server": {
            "alive": any(s["alive"] for s in servers),
            **({} if server is None else server),
        },
        "servers": servers,
        "draining": spool.drain_requested(),
        "jobs": jobs,
    }


def status_main(argv) -> int:
    p = _state_dir_parser("status", "one view of a service spool's jobs")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    args = p.parse_args(argv)
    try:
        spool = Spool(args.state_dir, create=False)
    except SpoolError as e:
        p.error(str(e))
    info = _collect_status(spool)
    if args.json:
        print(json.dumps(info))
        return 0
    servers = info["servers"]
    n_up = sum(1 for s in servers if s["alive"])
    if len(servers) > 1 or (servers and not servers[0]["alive"]):
        head = f"{n_up}/{len(servers)} servers up"
    else:
        head = "server up" if n_up else "server down"
    print(
        f"service {info['state_dir']}: {head}"
        + (" [draining]" if info["draining"] else "")
    )
    # the fleet table: per-server liveness (registration freshness +
    # pid/proc-start identity), owned jobs, and takeover counts — the
    # operator's answer to "which host is doing what, and is the dead
    # one's work safe" without grepping server logs
    for s in servers:
        state = "up" if s["alive"] else "DEAD"
        age = s.get("refreshed_age_s")
        owned = s.get("tenants") or []
        line = (
            f"  server {s['server_id']}  {state}  "
            f"pid={s.get('pid')}@{s.get('host')}"
            f" start={s.get('pid_start')}"
        )
        if age is not None:
            line += f" refreshed={age}s ago"
        if s.get("takeovers"):
            line += f" takeovers={s['takeovers']}"
        if owned:
            line += f" owns={','.join(owned)}"
        print(line)
    if not info["jobs"]:
        print("  no jobs")
    now = time.time()
    for j in info["jobs"]:
        extra = ""
        if j.get("priority"):
            extra += f"  prio={j['priority']}"
        if j.get("deadline_ts"):
            try:
                left = float(j["deadline_ts"]) - now
                extra += (
                    f" deadline={left:+.0f}s" if left >= 0
                    else f" deadline=OVERDUE {-left:.0f}s"
                )
            except (TypeError, ValueError):
                pass
        if j.get("slices") is not None:
            extra = (
                f"  slices={j['slices']} preemptions={j.get('preemptions')}"
                f" best={j.get('best_score')}"
            )
            pc = j.get("program_cache") or {}
            if pc.get("hits") or pc.get("misses"):
                extra += f" cache={pc.get('hits', 0)}h/{pc.get('misses', 0)}m"
            mem = j.get("device_memory") or {}
            if mem.get("peak_bytes"):
                extra += f" mem={mem['peak_bytes'] / (1 << 20):.0f}MiB"
            if j.get("idle_frac") is not None:
                extra += f" idle={j['idle_frac']:.0%}"
            if j.get("server"):
                extra += f" on={j['server']}"
            if j.get("takeovers"):
                extra += f" takeovers={j['takeovers']}"
        lease = j.get("lease")
        if j.get("state") == "running" and lease is not None and not lease["live"]:
            # the fleet's load-bearing warning: "running" with a dead
            # hold is an orphan awaiting takeover, not live work
            extra += f" lease=EXPIRED (was {lease.get('server_id')})"
        if j.get("state") == "running" and (
            j.get("phase") or j.get("slice_elapsed_s") is not None
        ):
            extra += (
                f" phase={j.get('phase')}"
                f" slice_elapsed={j.get('slice_elapsed_s')}s"
            )
        print(f"  {j['job']}  tenant={j['tenant']}  {j['state']}{extra}")
    return 0


def cancel_main(argv) -> int:
    p = _state_dir_parser(
        "cancel",
        "cancel a job: queued jobs cancel immediately; a running job "
        "drains at its next natural boundary (snapshot + ledger intact — "
        "nothing is killed, nothing quarantined) and frees the device",
    )
    p.add_argument("job", help="job id (see `mpi_opt_tpu status`)")
    args = p.parse_args(argv)
    try:
        state = Spool(args.state_dir, create=False).cancel(args.job)
    except SpoolError as e:
        p.error(str(e))
    print(json.dumps({"job": args.job, "state": state, "cancel": True}))
    return 0


def drain_main(argv) -> int:
    p = _state_dir_parser(
        "drain",
        "ask the server to stop: it finishes the active slice (parking "
        "the tenant at a boundary) and exits; the spool keeps the queue, "
        "so a restarted server continues where this one left off",
    )
    p.add_argument(
        "--wait",
        type=float,
        default=None,
        metavar="S",
        help="block up to S seconds for the server to exit",
    )
    args = p.parse_args(argv)
    try:
        spool = Spool(args.state_dir, create=False)
    except SpoolError as e:
        p.error(str(e))
    spool.request_drain()
    if args.wait is not None:
        deadline = time.monotonic() + args.wait
        while spool.server_alive():
            if time.monotonic() >= deadline:
                print(
                    f"server still alive after {args.wait}s", file=sys.stderr
                )
                return 1
            time.sleep(0.2)
    print(json.dumps({"drain": True, "server_alive": spool.server_alive()}))
    return 0
