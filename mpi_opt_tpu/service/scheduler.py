"""The resident multi-tenant sweep server (``mpi_opt_tpu serve``).

One long-lived process owns the JAX device and multiplexes it across
many concurrent sweeps. The scheduler loop:

1. **admission** — queued job files move into tenant dirs, throttled
   by a per-tenant concurrency cap (``--max-active-per-tenant``).
2. **pick** — fair-share over runnable tenants: the tenant NAME that
   has consumed the fewest slices goes first, FIFO (submit order)
   within a name. A lone tenant simply keeps getting re-picked.
3. **slice** — the chosen sweep runs IN-PROCESS via ``cli.main`` with
   server-owned ``--ledger``/``--checkpoint-dir`` (and ``--resume``
   after its first slice), under a cooperative slice hook
   (health/shutdown.py) that counts natural boundaries — gen_chunk /
   rung / TPE batch / wave / driver batch — and, at the budget, sets
   the SAME drain flag a platform SIGTERM sets. The sweep flushes a
   boundary snapshot and exits 75 through the existing drain path, so
   a time-sliced tenant's ledger is bit-identical to a solo run's.
4. **classify** — the slice's exit code drives the tenant state
   machine (tenants.py, codes from utils/exitcodes.py).

Running tenants in-process is what makes admission cheap: workload
instances (and with them trainers and jit-compiled programs) are
cached for the server's lifetime (programs.py), so a shape-matching
tenant skips XLA compilation and its time-to-first-trial is dominated
by dispatch, not compile.

Shutdown: a real SIGTERM/SIGINT drains the ACTIVE tenant at its next
boundary (the tenant's own guard handles the signal; the server reads
``shutdown.delivered_signal()`` after the slice to tell platform
death from its own slice expiry), parks it, and exits 0 — the spool on
disk IS the queue checkpoint, so a restarted server resumes every
in-flight tenant via the verified-snapshot + journal-prefix machinery.
A SIGKILLed server leaves a tenant marked ``running``; any surviving
fleet peer (or a restart) claims its expired/dead-holder lease and the
same resume path recovers it.

Fleet federation (ISSUE 12): N servers — one per host/chip — share one
spool. Each registers under ``servers/<--server-id>.json`` (a live
same-id collision is refused; the default id preserves the old
one-server-per-spool behavior), and per-JOB admission is arbitrated by
``tenants/<job>/lease.json`` (service/leases.py): ``_pick_next``
acquires the pick's lease (a peer's live lease just skips the job), a
lease-refresh keeper rides the tenant's heartbeat path during the
slice, and every end-of-slice metadata write is fenced on the lease
token so a taken-over zombie's late writes are refused rather than
racing the new owner. Takeover is not a new recovery path: it is the
ordinary ``--resume`` against whatever the dead server's last boundary
flushed.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import traceback
from typing import Callable, Optional

from mpi_opt_tpu.obs import memory as obs_memory
from mpi_opt_tpu.service import leases, tenants as tstates
from mpi_opt_tpu.service.programs import ProgramCache
from mpi_opt_tpu.service.spool import Spool, TenantDir
from mpi_opt_tpu.utils.exitcodes import EX_UNAVAILABLE, classify


def _read_summary(log_path: str, start: int) -> Optional[dict]:
    """The last summary-shaped JSON line THIS slice appended to the
    tenant's run.log (same shape rule as launch.py's supervisor relay).

    ``start`` is the log's size when the slice began: run.log is
    append-only across the tenant's whole lifetime, and scanning past
    it would attribute a PREVIOUS slice's summary (and best_score) to
    a slice that crashed before printing its own."""
    from mpi_opt_tpu.launch import _find_summary_line

    try:
        # errors="replace": the seek may land mid-multibyte-character in
        # some library's non-ASCII log line; summary lines themselves
        # are pure-ASCII json.dumps output, so replacement never
        # damages the line we want
        with open(log_path, errors="replace") as f:
            f.seek(max(start, os.path.getsize(log_path) - 100_000))
            line = _find_summary_line(f.read())
    except OSError:
        return None
    return json.loads(line) if line else None


class SweepService:
    #: how long a resource-exhaustion park (slice rc 74: disk full /
    #: device OOM — utils/resources.py) keeps the tenant OUT of the
    #: pick rotation. PARKED is deliberately non-terminal (freeing disk
    #: + the ordinary --resume slice recovers), but re-picking it
    #: immediately would spin the scheduler against a disk that is
    #: still full; the cooldown turns the spin into a bounded re-probe.
    IO_PARK_COOLDOWN_S = 60.0

    def __init__(
        self,
        state_dir: str,
        slice_boundaries: int = 8,
        slice_seconds: Optional[float] = None,
        max_active_per_tenant: int = 2,
        poll_seconds: float = 0.5,
        drain_on_empty: bool = False,
        metrics=None,
        metrics_stream=None,
        on_boundary: Optional[Callable] = None,
        on_slice_end: Optional[Callable] = None,
        trace: bool = False,
        server_id: Optional[str] = None,
        lease_ttl: float = 600.0,
        starvation_floor_s: float = 300.0,
    ):
        if slice_boundaries < 1:
            raise ValueError(f"slice_boundaries must be >= 1, got {slice_boundaries}")
        if starvation_floor_s <= 0:
            raise ValueError(
                f"starvation_floor_s must be > 0, got {starvation_floor_s}"
            )
        if max_active_per_tenant < 1:
            raise ValueError(
                f"max_active_per_tenant must be >= 1, got {max_active_per_tenant}"
            )
        if lease_ttl <= 0:
            raise ValueError(f"lease_ttl must be > 0, got {lease_ttl}")
        self.spool = Spool(state_dir)
        # fleet identity: the default id COLLIDES on purpose (two
        # default-id servers refuse each other, preserving the PR 7
        # one-server-per-spool behavior); federation is opted into with
        # distinct --server-id values. pid + /proc start time is the
        # fencing identity every lease this server takes will carry.
        self.server_id = server_id or Spool.DEFAULT_SERVER_ID
        self.lease_ttl = float(lease_ttl)
        self.starvation_floor_s = float(starvation_floor_s)
        self.ident = leases.ServerIdentity.local(self.server_id)
        self._takeovers = 0
        # server-registration heartbeat throttle (monotonic): refreshed
        # from the serve loop between slices AND from the active
        # tenant's beats during one (a long slice must not let the
        # registration go stale — a remote peer judges us by its ts),
        # capped so an enormous TTL still keeps the fleet view usable
        self._server_refresh_every = min(self.lease_ttl / 3.0, 10.0)
        self._server_refresh_next = 0.0
        self._usurped = False
        self._reg_lock = threading.Lock()
        self.slice_boundaries = slice_boundaries
        self.slice_seconds = slice_seconds
        self.max_active_per_tenant = max_active_per_tenant
        self.poll_seconds = poll_seconds
        self.drain_on_empty = drain_on_empty
        self.programs = ProgramCache()
        # serve --trace: every tenant slice runs with span tracing into
        # its own tenant-dir metrics stream (tenant-tagged records), and
        # the server's own scheduling spans go to server-metrics.jsonl —
        # `mpi_opt_tpu trace STATE_DIR` merges the lot by ts
        self.trace = bool(trace)
        # test/drill seams: on_boundary(tenant, stage, n) fires from the
        # slice hook (deterministic injection point for drills that need
        # "mid-slice" timing); on_slice_end(tenant) after classification
        self.on_boundary = on_boundary
        self.on_slice_end = on_slice_end
        if metrics is None:
            from mpi_opt_tpu.utils.metrics import MetricsLogger

            metrics = MetricsLogger(path=self.spool.metrics_path, stream=metrics_stream)
        self.metrics = metrics
        # terminal tenants never change state again, but they stay in
        # the spool as the durable record — cache their status so the
        # loop's cost tracks LIVE tenants, not all-time spool history
        self._terminal_cache: dict = {}
        # per-job incremental idle trackers (serve --trace): each slice
        # end refreshes the tenant's idle_frac from its span stream, and
        # re-parsing the whole file every slice would be O(n^2) over a
        # resident tenant's lifetime — the tracker reads only the bytes
        # appended since its last poll (obs/bubbles.StreamIdleTracker).
        # Dropped when the job goes terminal.
        self._idle_trackers: dict = {}
        # per-loop-iteration memos: the scheduling steps (_admit_pending,
        # _apply_queued_cancels, _pick_next, _all_quiet) each scan the
        # spool, and neither the tenants/ directory listing nor a live
        # tenant's status.json should be re-read three-plus times per
        # 0.1 s poll; cleared at the top of every iteration, invalidated
        # on every scheduler-side write (status) / admission (listing —
        # clients also materialize tenant dirs via cancel-while-queued,
        # which the next iteration's fresh listing picks up)
        self._status_memo: dict = {}
        self._tenants_memo: Optional[list] = None
        # queue files are written ONCE (atomic submit) and only ever
        # removed, so the tenant name — all the admission cap check
        # needs — is cached by path across iterations: a long queue
        # waiting behind a capped tenant must not cost one JSON parse
        # per file per poll tick
        self._queued_name_cache: dict = {}
        # fair-share usage is SESSION-scoped: seeded from live (parked/
        # running) jobs' slice counts so a restart resumes fairness for
        # in-flight work, but a tenant's long-finished history does not
        # starve its next job for as many slices as it ever consumed
        self._usage: dict = {}
        # jobs already terminal at bring-up never entered the tally, so
        # pre-mark them retired — _retire_usage must not subtract their
        # history from a LIVE sibling job's seeded usage
        self._retired: set = set()
        for t in self.spool.tenants():
            s = t.status
            if s.get("state") in tstates.TERMINAL:
                self._retired.add(s.get("id") or t.job_id)
            else:
                name = s.get("tenant", "default")
                self._usage[name] = self._usage.get(name, 0) + int(
                    s.get("slices") or 0
                )

    # -- scheduling --------------------------------------------------

    def _tenant_status(self, t: TenantDir) -> dict:
        s = self._terminal_cache.get(t.job_id)
        if s is not None:
            return s
        s = self._status_memo.get(t.job_id)
        if s is not None:
            return s
        s = t.status
        if s.get("state") in tstates.TERMINAL:
            self._terminal_cache[t.job_id] = s
            # a terminal the scheduler didn't produce (client cancelled a
            # parked job directly) still retires its fair-share usage
            self._retire_usage(s)
        else:
            self._status_memo[t.job_id] = s
        return s

    def _wrote_status(self, t: TenantDir) -> None:
        self._status_memo.pop(t.job_id, None)

    def _tenants(self) -> list:
        if self._tenants_memo is None:
            self._tenants_memo = self.spool.tenants()
        return self._tenants_memo

    def _active_counts(self) -> dict:
        counts: dict = {}
        for t in self._tenants():
            s = self._tenant_status(t)
            if s.get("state") not in tstates.TERMINAL:
                counts[s.get("tenant", "default")] = (
                    counts.get(s.get("tenant", "default"), 0) + 1
                )
        return counts

    def _admit_pending(self) -> None:
        """Queue -> tenant dirs, oldest first, honoring the per-tenant
        concurrency cap (capped jobs stay queued — admission order is
        re-derived every loop, so a cap freed by one tenant finishing
        admits the next job with no bookkeeping)."""
        from mpi_opt_tpu.service.spool import SpoolError, _read_json

        counts = self._active_counts()
        pending = self.spool.pending_jobs()
        cache = self._queued_name_cache
        for stale in set(cache) - set(pending):
            del cache[stale]  # admitted, cancelled, or quarantined
        for qpath in pending:
            name = cache.get(qpath)
            if name is None:
                spec = _read_json(qpath) or {}
                name = spec.get("tenant", "default")
                cache[qpath] = name
            if counts.get(name, 0) >= self.max_active_per_tenant:
                continue
            try:
                t = self.spool.admit(qpath)
            except SpoolError as e:
                self.metrics.log("tenant_reject", error=str(e))
                continue
            except OSError as e:
                # persistent I/O failure mid-admission: the queue file
                # (or a half-built tenant dir) survives on disk, so the
                # next loop iteration retries — one sick write must not
                # kill the server every other tenant is riding on
                self.metrics.log("tenant_reject", error=f"admission I/O: {e}")
                continue
            counts[name] = counts.get(name, 0) + 1
            self._tenants_memo = None  # a new tenant dir exists now
            self.metrics.log("tenant_admit", job=t.job_id, tenant=name)

    def _apply_queued_cancels(self) -> None:
        for t in self._tenants():
            s = self._tenant_status(t)
            # state first: the memo/terminal-cache lookup is a dict hit,
            # cancel_requested() is a stat — keep per-iteration syscalls
            # proportional to LIVE tenants, not all-time spool history
            if s.get("state") in tstates.RUNNABLE and t.cancel_requested():
                # the terminal write is lease-guarded: a peer that just
                # picked this tenant (parked -> about to run) holds the
                # lease, and our CANCELLED write would race its RUNNING
                # one — it will honor the cancel flag at its own first
                # boundary instead
                try:
                    lease = leases.acquire(t.lease, self.ident, self.lease_ttl)
                except OSError:
                    continue  # sick lease I/O: retry next iteration
                if lease is None:
                    continue
                # re-read under OUR lease: a peer may have run (or even
                # finished) a slice between our status snapshot and the
                # acquisition — writing the stale snapshot would erase
                # its slice accounting
                s = t.status
                if s.get("state") in tstates.RUNNABLE:
                    t.write_status(dict(s, state=tstates.CANCELLED))
                    self._wrote_status(t)
                    self._retire_usage(s)  # a parked job may have slices
                    self.metrics.log("tenant_cancelled", job=t.job_id, at="queue")
                leases.release(t.lease, lease)

    def _retire_usage(self, status: dict) -> None:
        """Remove a newly-terminal job's slice count from the in-session
        fair-share tally (every one of its slices was added here +1 at a
        time, or seeded at restart while the job was still live).
        Idempotent per job — a client-cancelled parked job reaches this
        both from _tenant_status's terminal-cache insertion and, for
        scheduler-produced terminals, from the transition site itself."""
        job_id = status.get("id")
        if job_id in self._retired:
            return
        self._retired.add(job_id)
        # the job's incremental idle tracker dies with it — EVERY
        # terminal transition funnels through here (slice end, queue
        # cancel, terminal-cache insertion), so a parked job cancelled
        # at the queue cannot leak its interval lists for the server's
        # lifetime
        self._idle_trackers.pop(job_id, None)
        name = status.get("tenant", "default")
        self._usage[name] = max(
            0, self._usage.get(name, 0) - int(status.get("slices") or 0)
        )

    def _takeover_candidate(self, t: TenantDir, s: dict) -> Optional[dict]:
        """Is this RUNNING tenant orphaned? Orphaned when its lease is
        absent (a pre-lease spool, or a crash in the claim window — the
        durable state is whatever the last boundary flushed) or expired
        / held by a provably dead process (the SIGKILLed-server shape).
        A RUNNING tenant with a live lease belongs to a working peer.
        Returns the dead holder's lease record as evidence (``{}`` for
        a lease-less orphan), or None when not a candidate — the
        record is captured HERE because by acquisition time a racing
        peer's steal may have the file mid-tomb (absent)."""
        if s.get("state") != tstates.RUNNING:
            return None
        lease = leases.read_lease(t.lease)
        if lease is None:
            return {}
        return lease if leases.expired(lease) else None

    def _pick_next(self) -> Optional[tuple]:
        """Priority class first, earliest deadline within it, then fair
        share (fewest-slices tenant name, FIFO within) — then ACQUIRE
        the pick's lease. Returns ``(tenant, lease, takeover_from)`` or
        None.

        The priority key is EFFECTIVE priority: the submitted class
        plus one class per ``starvation_floor_s`` the job has waited
        since submission — the starvation floor. A saturating stream of
        high-priority work therefore delays low-priority tenants by a
        bounded number of floors, never forever (a prio-0 job outranks
        a fresh prio-2 one after 2 floors of waiting). Deadlines order
        WITHIN a class (earliest first, deadline-less last), so urgency
        expressed as "finish by T" and importance expressed as a class
        stay independent axes.

        Acquisition is the fleet arbiter: a candidate whose lease a
        peer wins is skipped (never blocked on), so N servers sharing
        the spool settle every conflict at the lease file, not in
        scheduler logic. ``takeover_from`` is the dead holder's server
        id when the pick was an orphaned RUNNING tenant (the takeover
        shape), else None."""
        candidates = []
        for t in self._tenants():
            s = self._tenant_status(t)
            if s.get("state") in tstates.RUNNABLE:
                # resource-park cooldown: a tenant parked on rc 74
                # (disk full / device OOM) carries retry_after_ts —
                # skip it until the clock passes, so the fleet probes
                # the still-exhausted resource on a bounded cadence
                # instead of spinning slices against it
                try:
                    if float(s.get("retry_after_ts") or 0.0) > time.time():
                        continue
                except (TypeError, ValueError):
                    pass
                candidates.append((t, s, None))
            else:
                prior = self._takeover_candidate(t, s)
                if prior is not None:
                    candidates.append((t, s, prior))
        now = time.time()

        def _rank(tsk):
            t, s, _prior = tsk
            try:
                prio = int(s.get("priority") or 0)
            except (TypeError, ValueError):
                prio = 0
            try:
                waited = max(0.0, now - float(s.get("submitted_ts") or now))
            except (TypeError, ValueError):
                waited = 0.0
            eff_prio = prio + int(waited // self.starvation_floor_s)
            try:
                deadline = float(s["deadline_ts"])
            except (KeyError, TypeError, ValueError):
                deadline = float("inf")
            return (
                -eff_prio,
                deadline,
                self._usage.get(s.get("tenant", "default"), 0),
                t.job_id,
            )

        candidates.sort(key=_rank)
        for t, _s0, prior in candidates:
            try:
                lease = leases.acquire(t.lease, self.ident, self.lease_ttl)
            except OSError:
                # persistently sick I/O on ONE lease file must not kill
                # the server: skip the job this round, the next loop
                # iteration (or a healthier peer) retries
                continue
            if lease is None:
                continue  # a live peer holds (or just won) this job
            # re-read under OUR lease — for EVERY pick, not just the
            # takeover shape: a peer may have run the job to terminal
            # (or applied a cancel) between our candidacy snapshot and
            # the acquisition, and scheduling from the stale snapshot
            # would resurrect a settled tenant
            s = t.status
            state = s.get("state")
            if state in tstates.RUNNABLE:
                return t, lease, None
            if state == tstates.RUNNING:
                # still the orphan shape (we hold its lease: no live
                # peer does) — take it over
                from_server = (
                    (prior or {}).get("server_id")
                    or s.get("server")
                    or "unknown"
                )
                return t, lease, from_server
            leases.release(t.lease, lease)  # settled while we raced
        return None

    # -- the slice ---------------------------------------------------

    def _slice_argv(self, t: TenantDir, status: dict) -> list:
        # --resume UNCONDITIONALLY: empty ledger/checkpoint dirs start
        # fresh under it, and a server killed mid-FIRST-slice leaves
        # slices=0 with durable state already on disk — a fresh (non
        # -resume) retry would trip the CLI's stale-state refusal
        # (exit 2) and terminally fail a perfectly recoverable tenant
        argv = list(t.job["argv"]) + [
            "--ledger",
            t.ledger,
            "--checkpoint-dir",
            t.ckpt,
            "--resume",
            # per-tenant heartbeat (server-owned, like --ledger): beat
            # records carry the rank's active span phase, which is what
            # the status/report clients surface as an ACTIVE tenant's
            # live phase (spool.live_phase)
            "--heartbeat-file",
            t.heartbeat,
        ]
        if self.trace:
            argv += ["--metrics-file", t.metrics, "--trace"]
        return argv

    def _run_slice(
        self, t: TenantDir, lease: dict, takeover_from: Optional[str] = None
    ) -> Optional[str]:
        """One scheduling quantum on the device, under a HELD lease
        (the caller acquired it in ``_pick_next``). Returns the REAL
        signal name if one was delivered mid-slice (the server must
        drain), else None. Every tenant-metadata write below is fenced
        on the lease token, and the lease is released on every exit
        path we still own it on."""
        from mpi_opt_tpu.cli import main as cli_main
        from mpi_opt_tpu.health import heartbeat, shutdown
        from mpi_opt_tpu.service.spool import SpoolError

        # a real signal may land between the serve loop's shutdown check
        # and here (spool scans, the argparse probe): the SERVER guard
        # absorbed it, and the clear_delivered() below would erase the
        # evidence — so the tenant would burn a full quantum before the
        # drain. Re-check now, before any tenant state changes.
        if shutdown.requested() or shutdown.delivered_signal():
            leases.release(t.lease, lease)
            return shutdown.delivered_signal() or shutdown.active_signal()

        status = t.status
        try:
            argv = self._slice_argv(t, status)
        except SpoolError as e:
            # one tenant's unreadable job.json must not take down the
            # server (and every other tenant with it): terminal-fail
            # just this tenant and keep scheduling
            t.write_status(dict(status, state=tstates.FAILED, note=str(e)))
            leases.release(t.lease, lease)
            self._wrote_status(t)
            self._retire_usage(status)
            self.metrics.log("tenant_reject", job=t.job_id, error=str(e))
            return None
        from mpi_opt_tpu.obs import trace

        try:
            # acquire builds the shared workload instance on first use
            # (get_workload -> cls(): dataset caches, disk, arbitrary
            # user code) and the log open touches the tenant's own dir —
            # either failing must terminal-fail THIS tenant, same as the
            # unreadable-job.json case above: the tenant is still
            # RUNNABLE at this point, so letting the exception out would
            # crash-loop every restarted server on the same pick
            with trace.span("slice_setup", job=t.job_id):
                key, cache_hit, workload = self.programs.acquire(argv)
                log_start = os.path.getsize(t.log) if os.path.exists(t.log) else 0
                logf = open(t.log, "a")
        except Exception as e:
            t.write_status(
                dict(status, state=tstates.FAILED, note=f"slice setup failed: {e}")
            )
            leases.release(t.lease, lease)
            self._wrote_status(t)
            self._retire_usage(status)
            self.metrics.log("tenant_reject", job=t.job_id, error=str(e))
            return None
        if takeover_from is not None:
            # the takeover IS the existing resume machinery — all that
            # is new is the bookkeeping: the tenant's durable state is
            # whatever the dead server's last boundary flushed, and the
            # --resume in _slice_argv picks it up via verified-snapshot
            # + journal-prefix exactly like a restart would
            self._takeovers += 1
            status = dict(
                status,
                takeovers=int(status.get("takeovers") or 0) + 1,
                note=f"lease takeover from {takeover_from}",
            )
            self.metrics.count_takeovers()
            self.metrics.log(
                "tenant_takeover",
                job=t.job_id,
                from_server=takeover_from,
                to_server=self.server_id,
            )
        # slice_started_ts: the live-phase surface's elapsed anchor
        # (spool.live_phase reads it back while the slice runs);
        # server: which fleet member holds the device for this slice
        t.write_status(
            dict(
                status,
                state=tstates.RUNNING,
                server=self.server_id,
                slice_started_ts=round(time.time(), 4),
            )
        )
        self._wrote_status(t)
        self.metrics.log(
            "slice_start",
            job=t.job_id,
            tenant=status.get("tenant", "default"),
            server=self.server_id,
            slice=int(status.get("slices") or 0) + 1,
            program_cache_hit=cache_hit,
        )
        boundaries = 0
        t0 = time.perf_counter()
        # the lease keeper: rides every heartbeat beat (driver batch,
        # fused launch, wave sub-segment, staging transfer), refreshing
        # the deadline at ttl/3 cadence; on fencing (we were presumed
        # dead and taken over) it requests the SAME drain a slice
        # expiry does, so the zombie slice parks at its next boundary
        # instead of running on against a tenant it no longer owns
        refresher = leases.Refresher(
            t.lease, lease, self.lease_ttl, on_fenced=shutdown.request
        )

        def hook(stage: str) -> None:
            nonlocal boundaries
            boundaries += 1
            refresher()  # boundary-granular refresh floor (beat-less sweeps)
            if self.on_boundary is not None:
                self.on_boundary(t, stage, boundaries)
            # delivered_signal: a real signal that landed in the sliver
            # between the pre-slice check and the tenant guard's install
            # went to the SERVER guard, which the tenant's own handler
            # can't see — treat it like drain so the park still happens
            # at the FIRST boundary, not after a full quantum
            if (
                refresher.fenced
                or t.cancel_requested()
                or self.spool.drain_requested()
                or shutdown.delivered_signal()
            ):
                shutdown.request()
                return
            if boundaries >= self.slice_boundaries or (
                self.slice_seconds is not None
                and time.perf_counter() - t0 >= self.slice_seconds
            ):
                shutdown.request()

        # NO clear_delivered() here: the serve loop clears the window at
        # bring-up and breaks on any truthy delivery, so _DELIVERED is
        # None when a slice starts — a truthy value at any point from
        # here on IS this slice's signal, and erasing it would burn a
        # full quantum before the server notices (the hook above and the
        # post-slice read both depend on it surviving)
        def on_beat(rec) -> None:
            # two refreshes ride every unit of tenant progress: the
            # job's lease (the Refresher) and OUR fleet registration —
            # the serve loop is blocked inside this very slice, and a
            # registration left unrefreshed for a long slice would let
            # a remote peer judge a live server dead
            refresher(rec)
            self._refresh_registration()

        shutdown.set_slice_hook(hook)
        heartbeat.set_beat_listener(on_beat)
        # tenant tag for the slice's span records: cli.main's trace
        # wiring reads it, so a merged state-dir trace attributes phases
        # per tenant. Env (not a flag) because the spool's job argv must
        # stay exactly what the client submitted. Only touched under
        # serve --trace, and the operator's own pre-existing value is
        # restored afterwards — the slice must be env-side-effect-free.
        prev_tag = os.environ.get("MPI_OPT_TPU_TRACE_TAG")
        if self.trace:
            os.environ["MPI_OPT_TPU_TRACE_TAG"] = status.get("tenant", "default")
        # per-slice watermark window: the live-array fallback's running
        # peak resets here, so the post-slice reading below is THIS
        # slice's footprint, not a previous (possibly larger) tenant's
        obs_memory.reset_peak()
        # the slice span emits AFTER cli.main restores the server's own
        # sink (trace nesting contract), so it lands in the SERVER
        # stream with the tenant's in-slice spans as its children
        _slice_span = trace.span("slice", job=t.job_id)
        try:
            with _slice_span, logf:
                logf.write(f"--- slice {int(status.get('slices') or 0) + 1} ---\n")
                with contextlib.redirect_stdout(logf), contextlib.redirect_stderr(
                    logf
                ):
                    try:
                        rc = cli_main(argv, _workload=workload)
                    except SystemExit as e:
                        # parser.error and friends (in-process argparse).
                        # Match what the same argv would do as a
                        # subprocess: None exits 0, a string message
                        # prints and exits 1 — and the message must land
                        # in run.log (we ARE its stderr right now), not
                        # vanish with the exception
                        if e.code is None:
                            rc = 0
                        elif isinstance(e.code, int):
                            rc = e.code
                        else:
                            logf.write(f"{e.code}\n")
                            rc = 1
                    except KeyboardInterrupt:
                        raise
                    # sweeplint: disable=drain-swallow -- tenant-slice containment: one tenant's escaped error terminal-fails the slice (rc=1 in run.log), it must not kill the resident server; cli.main maps SweepInterrupted to exit 75 before it could reach here
                    except BaseException:
                        logf.write(traceback.format_exc())
                        rc = 1
        finally:
            heartbeat.clear_beat_listener()
            shutdown.clear_slice_hook()
            if self.trace:
                if prev_tag is None:
                    os.environ.pop("MPI_OPT_TPU_TRACE_TAG", None)
                else:
                    os.environ["MPI_OPT_TPU_TRACE_TAG"] = prev_tag
        wall = time.perf_counter() - t0
        delivered = shutdown.delivered_signal()
        # settle the refresher BEFORE judging the fence: an in-flight
        # refresh (a straggler beat from a staging thread that outlived
        # the listener clear) holds the lease file mid-rename, and
        # judging held()/release() through that absence window would
        # falsely fence a healthy slice — and then strand the refreshed
        # lease unreleased until the TTL
        final_lease = refresher.stop()

        cancel = t.cancel_requested()
        state = tstates.after_slice(rc, cancel)
        if state in (tstates.DONE, tstates.PARKED, tstates.CANCELLED):
            # the sweep completed or drained at a boundary — both are
            # past compile, so the key's programs really exist now
            self.programs.commit(key)
        # the fence: if our lease stopped carrying our token, this job
        # was taken over while we were presumed dead — the new owner's
        # status/ledger records are authoritative and EVERY write we
        # intended for this tenant is abandoned (no status, no usage,
        # no release: the lease is not ours to give up). The program
        # commit above stays — it records compiles in THIS process.
        if refresher.fenced or not leases.held(t.lease, final_lease):
            self.metrics.log(
                "slice_fenced",
                job=t.job_id,
                rc=rc,
                boundaries=boundaries,
                wall_s=round(wall, 3),
            )
            return delivered
        status = t.status  # re-read: cancel client may have raced a write
        status["state"] = state
        status["slices"] = int(status.get("slices") or 0) + 1
        status["boundaries"] = int(status.get("boundaries") or 0) + boundaries
        # capped tail: state classification uses rc directly and the
        # full per-slice record lives in the metrics stream — an
        # unbounded array would make every slice end rewrite (and every
        # status call re-parse) O(total slices) on a long-lived server
        status["rc_history"] = ((status.get("rc_history") or []) + [rc])[-32:]
        # resource-exhaustion park (rc 74): stamp the cooldown + reason
        # so _pick_next holds the tenant out of rotation until the
        # resource had a chance to be freed; any OTHER slice outcome
        # clears the stamp (the resource answer is stale once a slice
        # ran again)
        if state == tstates.PARKED and classify(rc) == "io_error":
            status["park_reason"] = "io_error"
            status["retry_after_ts"] = round(
                time.time() + self.IO_PARK_COOLDOWN_S, 4
            )
        else:
            status.pop("park_reason", None)
            status.pop("retry_after_ts", None)
        if state == tstates.PARKED and not delivered and classify(rc) != "io_error":
            # resource parks are not slice preemptions: the tenant did
            # not drain at its budget, the RESOURCE refused the write
            status["preemptions"] = int(status.get("preemptions") or 0) + 1
        pc = status.setdefault("program_cache", {"hits": 0, "misses": 0})
        pc["hits" if cache_hit else "misses"] += 1
        if status.get("first_slice_wall_s") is None:
            # time-to-first-trial proxy: the first slice carries all of
            # the tenant's setup (compile on a miss, dispatch on a hit)
            status["first_slice_wall_s"] = round(wall, 3)
            status["first_slice_program_cache_hit"] = cache_hit
        summary = _read_summary(t.log, log_start)
        if summary is not None:
            status["summary"] = summary
            if summary.get("best_score") is not None:
                status["best_score"] = summary["best_score"]
        # post-slice device-memory watermark (obs/memory.py): what this
        # tenant's residency costs the shared device — the number the
        # admission layer will need the day co-residency is attempted,
        # surfaced today by `status`/`report DIR`. The `scope` field
        # keeps it honest, per accounting: memory_stats' allocator peak
        # cannot be reset and spans the SERVER's lifetime (a tiny tenant
        # after a huge one would otherwise wear the big footprint); the
        # live-array fallback's peak was reset at slice start, but it
        # only observes when sampled — in-slice samples happen via the
        # traced spans' memory.note, so without --trace the one sample
        # below sees the post-slice residual, not the tenant's working
        # set, and the label must say so
        mem = obs_memory.watermark()
        if mem is not None:
            if mem["source"] != "live_arrays":
                scope = "server"
            elif self.trace:
                scope = "slice"
            else:
                scope = "post_slice"
            status["device_memory"] = dict(mem, scope=scope)
        # per-tenant device-idle fraction (ISSUE 11): how much of the
        # tenant's traced wall the device sat in bubbles — computed
        # from the tenant's own span stream, cumulative across its
        # slices so far, so it exists only under serve --trace. The
        # admission/packing layer's other half beside device_memory:
        # a high-idle tenant is the co-residency candidate. The
        # tracker is incremental (only bytes appended since its last
        # poll are parsed) so a resident tenant's status refresh stays
        # O(slice), not O(stream); dropped when the job goes terminal.
        if self.trace:
            from mpi_opt_tpu.obs.bubbles import StreamIdleTracker

            tracker = self._idle_trackers.get(t.job_id)
            if tracker is None:
                tracker = self._idle_trackers[t.job_id] = StreamIdleTracker(t.metrics)
            idle = tracker.poll()
            if idle is not None:
                status["idle_frac"] = idle
            # terminal cleanup happens in _retire_usage (the one funnel
            # every terminal transition passes through, including the
            # queue-cancel path that never reaches this slice-end code)
        t.write_status(status)
        # the lease outlived every write it fenced; give it up so any
        # fleet peer can pick the tenant for its next slice (fair share
        # stays per-server, the lease only arbitrates "who, right now")
        leases.release(t.lease, final_lease)
        self._wrote_status(t)
        name = status.get("tenant", "default")
        self._usage[name] = self._usage.get(name, 0) + 1
        if state in tstates.TERMINAL:
            # retire the finished job's whole slice history from the
            # fair-share ledger: usage is meant to balance LIVE work,
            # and on a long-lived server a tenant whose 50-slice job
            # just completed must not have its NEXT submission starved
            # for 50 slices (the restart seeding skips terminal jobs
            # for the same reason)
            self._retire_usage(status)
        self.metrics.count_slices()
        if cache_hit:
            self.metrics.count_program_cache(hits=1)
        else:
            self.metrics.count_program_cache(misses=1)
        if state == tstates.DONE:
            self.metrics.count_tenants_done()
        self.metrics.log(
            "slice_end",
            job=t.job_id,
            rc=rc,
            server=self.server_id,
            state=state,
            boundaries=boundaries,
            wall_s=round(wall, 3),
            signal=delivered,
            mem_peak_bytes=None if mem is None else mem.get("peak_bytes"),
        )
        if self.on_slice_end is not None:
            self.on_slice_end(t)
        return delivered

    # -- the loop ----------------------------------------------------

    def _all_quiet(self) -> bool:
        if self.spool.pending_jobs():
            return False
        return all(
            self._tenant_status(t).get("state") in tstates.TERMINAL
            for t in self._tenants()
        )

    def serve(self) -> int:
        from mpi_opt_tpu.health import shutdown

        try:
            # absl's stderr handler binds sys.stderr AT FIRST IMPORT; if
            # that first import happened inside a slice (orbax pulls it
            # in), it would latch the tenant's redirected log file and
            # spew "Logging error" noise once that file closes. Import
            # it now, while stderr is the server's real stream.
            import absl.logging  # noqa: F401
        except ImportError:
            pass
        if not self.spool.register_server(
            self.server_id,
            slice_boundaries=self.slice_boundaries,
            lease_ttl=self.lease_ttl,
            takeovers=0,
        ):
            from mpi_opt_tpu.service.spool import ServerClaimError, _read_json

            info = _read_json(self.spool.server_file(self.server_id)) or {}
            raise ServerClaimError(
                f"a live server (pid {info.get('pid')}) already owns "
                f"server-id {self.server_id!r} on {self.spool.state_dir}; "
                "one identity, one process — federate with a distinct "
                "--server-id"
            )
        self.spool.clear_drain()
        # open THIS server's signal-observation window: a signal a
        # previous in-process server (or sweep) absorbed is not ours
        shutdown.clear_delivered()
        trace_prior = None
        if self.trace:
            # server-side spans (slice/slice_setup) go to the server's
            # own stream; each tenant slice re-configures to its tenant
            # stream and cli.main restores this sink on the way out
            from mpi_opt_tpu.obs import trace

            trace_prior = trace.configure(self.metrics)
        self.metrics.log(
            "serve_start",
            state_dir=self.spool.state_dir,
            server_id=self.server_id,
            lease_ttl=self.lease_ttl,
            slice_boundaries=self.slice_boundaries,
            max_active_per_tenant=self.max_active_per_tenant,
        )
        reason = "drain"
        rc = 0
        try:
            with shutdown.ShutdownGuard() as guard:
                while True:
                    self._status_memo.clear()
                    self._tenants_memo = None
                    if not self._heartbeat_server():
                        # zombie fencing, server edition: another
                        # process registered OUR id while we were
                        # presumed dead. Its leases fence our tenant
                        # writes; stepping down (not fighting) is the
                        # only move that cannot split-brain the spool.
                        reason = "usurped"
                        rc = EX_UNAVAILABLE
                        self.metrics.log(
                            "server_usurped", server_id=self.server_id
                        )
                        break
                    self._admit_pending()
                    self._apply_queued_cancels()
                    if guard.requested or shutdown.delivered_signal():
                        reason = f"signal {guard.signal_name or shutdown.delivered_signal()}"
                        break
                    if self.spool.drain_requested():
                        break
                    pick = self._pick_next()
                    if pick is None:
                        if self.drain_on_empty and self._all_quiet():
                            reason = "empty"
                            break
                        time.sleep(self.poll_seconds)
                        continue
                    t, lease, takeover_from = pick
                    delivered = self._run_slice(t, lease, takeover_from)
                    if delivered:
                        # the platform told the PROCESS to die; the
                        # active tenant already drained + parked through
                        # its own guard — park the server too
                        reason = f"signal {delivered}"
                        break
        finally:
            if self.trace:
                from mpi_opt_tpu.obs import trace

                trace.deconfigure(trace_prior)
            # deregister ONLY if the file still records us: a stepped-
            # down zombie unlinking the usurper's live registration
            # would re-orphan the spool it just conceded
            self.spool.clear_server_if_mine(self.server_id)
            self.metrics.summary(final=True, reason=reason)
            self.metrics.close()
        return rc

    def _refresh_registration(self) -> None:
        """Refresh our fleet registration (throttled, monotonic): the
        ``ts`` stamp is what remote-host peers and the status client
        judge liveness by, and the takeover counter rides along.
        Called from the serve loop between slices and from the beat
        listener DURING one, so the longest unrefreshed gap is a beat
        gap, not a slice. Usurpation latches ``_usurped``; transient
        I/O failure rewinds the throttle so the next call retries —
        neither ever raises into a beating thread."""
        # non-blocking: beats arrive from more than one thread (main
        # loop, staging transfer) — the loser skips, it must not stall
        # the sweep behind the winner's registration write
        if not self._reg_lock.acquire(blocking=False):
            return
        try:
            now = time.monotonic()
            if self._usurped or now < self._server_refresh_next:
                return
            self._server_refresh_next = now + self._server_refresh_every
            try:
                mine = self.spool.refresh_server(
                    self.server_id,
                    takeovers=self._takeovers,
                    slices=self.metrics.slices,
                )
            except OSError:
                self._server_refresh_next = 0.0  # sick fs: retry next call
                return
            if mine is None:
                # unreadable != usurped: one torn read must not make a
                # healthy server abandon its fleet slot — retry soon
                self._server_refresh_next = 0.0
            elif mine is False:
                self._usurped = True
        finally:
            self._reg_lock.release()

    def _heartbeat_server(self) -> bool:
        """The serve loop's registration check: refresh, then report
        whether we still own our identity (False = step down)."""
        self._refresh_registration()
        return not self._usurped
