"""FLOPs accounting + MFU (model-flops-utilization) for benchmark runs.

BASELINE.json's metric of record is throughput (trials/sec/chip); MFU is
the companion number that says how much of the chip that throughput
actually uses — without it, "fast" can mean "faster than one CPU" while
leaving most of the MXU idle (the round-1 failure mode).

FLOPs come from XLA's own cost model (``Compiled.cost_analysis()``) on
the exact executable being measured, not from a hand-derived per-layer
formula — so rematerialization, eval passes, and the PBT/ASHA decision
kernels are all counted as compiled, and the number stays correct when
the model changes. Peak numbers are the published dense bf16 ratings
per TPU generation (MXU path; the models package computes in bf16).
"""

from __future__ import annotations

from typing import Optional

# (substring of jax Device.device_kind, dense bf16 peak FLOP/s per chip)
# Published per-chip numbers: v4 275 TF, v5e 394 TF, v5p 459 TF,
# v6e/Trillium 918 TF. Matching is substring-based because device_kind
# strings vary across libtpu versions ("TPU v5 lite", "TPU v5e", ...).
_PEAKS = (
    ("v6e", 918e12),
    ("trillium", 918e12),
    ("v5 lite", 394e12),
    ("v5e", 394e12),
    ("v5p", 459e12),
    ("v5", 459e12),  # bare "TPU v5" reports as v5p-class
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_chip(device=None) -> Optional[float]:
    """Dense bf16 peak FLOP/s for ``device`` (default: first device).

    Returns None off-TPU (CPU has no meaningful single peak for MFU).
    """
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and getattr(device, "platform", "") != "tpu":
        return None
    for tag, peak in _PEAKS:
        if tag in kind:
            return peak
    return None


def compiled_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """Total FLOPs of one execution of ``jitted_fn(*args, **kwargs)``,
    from XLA's cost analysis of the compiled executable.

    Uses the AOT path (``lower().compile()``); with the persistent
    compilation cache enabled (bench.py sets it) this re-hits the cache
    of the measured run rather than recompiling. Returns None when the
    backend's cost analysis is unavailable (some plugin backends).

    CAVEAT (measured on this container, 2026-07-30): XLA counts a
    While-loop body ONCE, not per trip — a whole-sweep program with
    ``lax.scan`` loops reports ~10x under truth. Only trust this on
    programs whose scans have trip count 1; for sweeps, compose with
    ``population_sweep_flops`` below.
    """
    try:
        if isinstance(jitted_fn, __import__("functools").partial):
            args = (*jitted_fn.args, *args)
            kwargs = {**jitted_fn.keywords, **kwargs}
            jitted_fn = jitted_fn.func
        cost = jitted_fn.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returned [dict]
            cost = cost[0]
        return float(cost["flops"])
    except Exception:
        return None


def population_sweep_flops(
    workload, population: int, generations: int, steps_per_gen: int,
    n_evals: Optional[int] = None, eval_chunk: int = 1024,
) -> Optional[float]:
    """FLOPs of a fused population sweep, composed from XLA-counted
    single-trip pieces scaled by their true trip counts.

    Lowers a ONE-member, ONE-step train segment and a one-member,
    one-chunk eval (every scan inside has trip count 1, where XLA's
    count is exact — verified against hand math for the SmallCNN:
    36.6 GFLOP/member-step vs ~38 by hand) and scales linearly:
    flops are exactly linear in members/steps/chunks; the only
    approximation is the shared per-step batch gather being charged
    per member, and gathers contribute bytes, not flops.

    ``n_evals`` defaults to generations — fused PBT evaluates once per
    generation and its final scores are a gather of the last
    generation's eval, not a re-eval (train/fused_pbt.py).
    """
    import jax
    import jax.numpy as jnp

    try:
        trainer = workload.make_trainer(donate=False)  # no member_chunk:
        # lax.map would add an inner loop and re-trigger the While caveat
        from mpi_opt_tpu.train.population import OptHParams

        d = workload.data()
        tx = jnp.asarray(d["train_x"])
        ty = jnp.asarray(d["train_y"])
        vx = jnp.asarray(d["val_x"])[:eval_chunk]
        vy = jnp.asarray(d["val_y"])[:eval_chunk]
        key = jax.random.key(0)
        state = trainer.init_population(key, tx[:2], 1)
        hp = OptHParams.defaults(1)
        jf = trainer.train_segment  # functools.partial(jit(...), self)
        f_step = compiled_flops(jf, state, hp, tx, ty, key, steps=1)
        # the unbound jitted function: 'self' is a static argname, and a
        # bound PjitFunction does not expose .lower
        f_eval = compiled_flops(
            type(trainer).eval_population, trainer, state, vx, vy, eval_chunk=eval_chunk
        )
        if f_step is None or f_eval is None:
            raise RuntimeError(
                f"cost analysis returned no flops (step={f_step}, eval={f_eval})"
            )
        n_val = int(jnp.shape(jnp.asarray(d["val_y"]))[0])
        n_chunks = -(-n_val // eval_chunk)
        if n_evals is None:
            n_evals = generations
        return population * (
            generations * steps_per_gen * f_step + n_evals * n_chunks * f_eval
        )
    except Exception as e:
        # None (not a crash) keeps benches running without flops, but a
        # silent None turns MFU into a mystery — say why on stderr
        import sys

        print(
            f"[flops] population_sweep_flops unavailable: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return None


def mfu(total_flops: Optional[float], seconds: float, device=None) -> Optional[float]:
    """Achieved FLOP/s as a fraction of the chip's dense bf16 peak."""
    peak = peak_flops_per_chip(device)
    if not total_flops or not peak or seconds <= 0:
        return None
    return total_flops / seconds / peak
