"""Results/reporting (SURVEY.md §2 row 12): JSONL metrics + throughput.

Emits one JSON object per event to a stream and/or file, and accounts
the metric of record (BASELINE.json): trials/sec/chip and wall-clock.
"""

from __future__ import annotations

import json
import sys
import time
from typing import IO, Optional


class MetricsLogger:
    def __init__(self, path: Optional[str] = None, stream: Optional[IO] = None, n_chips: int = 1):
        import threading

        self._file = open(path, "a") if path else None
        self._stream = stream
        # records arrive from more than one thread once span tracing is
        # wired (obs/trace.py: StagingEngine's transfer thread emits
        # stage_out spans concurrently with the main loop) — serialize
        # the sink writes so two records can never interleave mid-line
        self._sink_lock = threading.Lock()
        self.n_chips = max(1, n_chips)
        self.t_start = time.perf_counter()
        self.trials_done = 0
        # failure-lifecycle counters (driver.FailurePolicy feeds these):
        # trials_failed/trials_timeout count FINAL non-ok results (after
        # retries, disjoint by status); trials_retried counts retry
        # ATTEMPTS, so retried-then-recovered trials stay visible
        self.trials_failed = 0
        self.trials_timeout = 0
        self.trials_retried = 0
        # ledger-layer counters: evaluations SKIPPED (served from the
        # journal on resume / from the exact-match cache), disjoint from
        # trials_done so throughput never counts un-run work
        self.cache_hits = 0
        self.replayed = 0
        # health-layer counters (health/): preempted counts graceful-
        # shutdown drains this process honored (0 or 1 per run — summed
        # across restarts by log aggregation); stalls_detected counts
        # wedged evaluations this process detected and killed (the
        # driver feeds every reaped trial deadline into it — the
        # trial-level twin of launch.py's rank watchdog, whose own
        # kills appear in the supervisor's stall/done/failed events)
        self.preempted = 0
        self.stalls_detected = 0
        # integrity-layer counter (utils/integrity.py): snapshot steps
        # that failed digest/decode verification on restore and were
        # quarantined (renamed <step>.corrupt) before last-good fallback
        self.snapshots_quarantined = 0
        # staging-layer counters (train/staging.py, wave-scheduled fused
        # sweeps): staged_bytes counts host<->device bytes moved by the
        # background transfer engine; stage_overlap_s is how much of the
        # transfer time was hidden behind wave compute (transfer busy
        # time minus the main thread's barrier waits — the double
        # buffer's whole point, so it must be observable)
        self.staged_bytes = 0
        self.stage_overlap_s = 0.0
        # fused-ledger counter (ledger/fused.py): member records this
        # process appended to the boundary-granular journal (verified
        # re-computations on resume deliberately excluded — they are the
        # fused twin of `replayed`, carried in the summary's journal dict)
        self.members_journaled = 0
        # service-layer counters (service/scheduler.py, the resident
        # multi-tenant server): slices is scheduling quanta executed;
        # program_cache_hits/misses is the compiled-program reuse layer's
        # accounting — hits are slices whose (workload, pop-shape,
        # chunking) programs were already compiled in this process, the
        # observable form of "tenant N+1's cost is dispatch, not compile"
        self.slices = 0
        self.tenants_done = 0
        self.program_cache_hits = 0
        self.program_cache_misses = 0
        # fleet-federation counter (service/leases.py): orphaned jobs
        # this server claimed from a dead/expired peer's lease and
        # resumed — the observable form of "a dead host strands nothing"
        self.takeovers = 0
        # resource-exhaustion counters (utils/resources.py):
        # oom_backoffs = device-OOM wave halvings the fused scheduler
        # absorbed (each one re-ran a generation at half the wave and
        # kept the result bit-identical); wave_resized = pre-launch
        # headroom clamps of --wave-size against the measured budget;
        # snapshots_pruned = superseded retained steps deleted by the
        # ENOSPC retention-prune retry (never the newest verified step)
        self.oom_backoffs = 0
        self.wave_resized = 0
        self.snapshots_pruned = 0

    def log(self, event: str, **fields) -> dict:
        # `t` is relative (this process's clock, for intra-run deltas);
        # `ts` is absolute unix epoch so multi-process/multi-host streams
        # can be correlated after the fact
        rec = {
            "event": event,
            "t": round(time.perf_counter() - self.t_start, 4),
            "ts": round(time.time(), 4),
            **fields,
        }
        if self._file or self._stream:  # null_logger: no sink, no json cost
            line = json.dumps(rec)
            with self._sink_lock:
                if self._file:
                    self._file.write(line + "\n")
                    self._file.flush()
                if self._stream:
                    print(line, file=self._stream, flush=True)
        return rec

    def count_trials(self, n: int):
        self.trials_done += n

    def count_failure(self, status: str = "failed"):
        """One FINAL non-ok trial result (post-retry)."""
        if status == "timeout":
            self.trials_timeout += 1
        else:
            self.trials_failed += 1

    def count_retries(self, n: int = 1):
        self.trials_retried += n

    def count_cache_hits(self, n: int = 1):
        """Evaluations skipped by the exact-match ledger cache."""
        self.cache_hits += n

    def count_replayed(self, n: int = 1):
        """FINAL results served from the journal on replay-resume."""
        self.replayed += n

    def count_preempted(self, n: int = 1):
        """Graceful-shutdown drains honored (exit EX_TEMPFAIL follows)."""
        self.preempted += n

    def count_stalls(self, n: int = 1):
        """Stalled (hung-but-alive) executions detected and killed."""
        self.stalls_detected += n

    def count_quarantined(self, n: int = 1):
        """Corrupt snapshot steps quarantined during restore."""
        self.snapshots_quarantined += n

    def count_staging(self, staged_bytes: int = 0, overlap_s: float = 0.0):
        """Host-staging traffic from a wave-scheduled fused sweep."""
        self.staged_bytes += int(staged_bytes)
        self.stage_overlap_s += float(overlap_s)

    def count_journaled(self, n: int = 1):
        """Fused member records appended to the sweep ledger."""
        self.members_journaled += int(n)

    def count_slices(self, n: int = 1):
        """Service scheduling quanta (tenant slices) executed."""
        self.slices += int(n)

    def count_tenants_done(self, n: int = 1):
        """Service tenants that reached the done state."""
        self.tenants_done += int(n)

    def count_program_cache(self, hits: int = 0, misses: int = 0):
        """Compiled-program reuse accounting (service/programs.py)."""
        self.program_cache_hits += int(hits)
        self.program_cache_misses += int(misses)

    def count_takeovers(self, n: int = 1):
        """Expired-lease tenant takeovers this server performed."""
        self.takeovers += int(n)

    def count_oom_backoffs(self, n: int = 1):
        """Device-OOM wave halvings absorbed by the fused scheduler."""
        self.oom_backoffs += int(n)

    def count_wave_resized(self, n: int = 1):
        """Pre-launch wave-size headroom clamps (estimate vs budget)."""
        self.wave_resized += int(n)

    def count_pruned(self, n: int = 1):
        """Superseded snapshot steps pruned by the ENOSPC retry."""
        self.snapshots_pruned += int(n)

    @property
    def wall(self) -> float:
        return time.perf_counter() - self.t_start

    def trials_per_sec_per_chip(self) -> float:
        return self.trials_done / max(self.wall, 1e-9) / self.n_chips

    def summary(self, **extra) -> dict:
        return self.log(
            "summary",
            trials=self.trials_done,
            trials_failed=self.trials_failed,
            trials_retried=self.trials_retried,
            trials_timeout=self.trials_timeout,
            cache_hits=self.cache_hits,
            replayed=self.replayed,
            preempted=self.preempted,
            stalls_detected=self.stalls_detected,
            snapshots_quarantined=self.snapshots_quarantined,
            staged_bytes=self.staged_bytes,
            stage_overlap_s=round(self.stage_overlap_s, 3),
            members_journaled=self.members_journaled,
            slices=self.slices,
            tenants_done=self.tenants_done,
            program_cache_hits=self.program_cache_hits,
            program_cache_misses=self.program_cache_misses,
            takeovers=self.takeovers,
            oom_backoffs=self.oom_backoffs,
            wave_resized=self.wave_resized,
            snapshots_pruned=self.snapshots_pruned,
            wall_s=round(self.wall, 3),
            trials_per_sec_per_chip=round(self.trials_per_sec_per_chip(), 4),
            **extra,
        )

    def close(self):
        if self._file:
            self._file.close()
            self._file = None


def null_logger() -> MetricsLogger:
    return MetricsLogger()


def stdout_logger(path: Optional[str] = None, n_chips: int = 1) -> MetricsLogger:
    return MetricsLogger(path=path, stream=sys.stdout, n_chips=n_chips)


def wall_to_target(curve, wall_s: float, target: float):
    """Prorated wall-clock (seconds) until a per-generation best-score
    curve first reaches ``target``; None if it never does.

    The metric-of-record definition (BASELINE.json: "wall-clock to
    target validation accuracy"): generations are uniform work, so
    reaching the target at generation g costs (g+1)/G of the sweep's
    wall. Single-sourced here so every bench compares raw float curve
    values against the target identically.
    """
    curve = [float(v) for v in curve]
    for g, v in enumerate(curve):
        if v >= target:
            return wall_s * (g + 1) / len(curve)
    return None


def wall_to_target_launchwise(curve, launch_gens, launch_walls, target: float):
    """``wall_to_target`` with MEASURED per-launch wall times.

    A gen-chunked fused sweep runs as N launches of ``launch_gens[i]``
    generations taking ``launch_walls[i]`` seconds each (fused_pbt
    returns both). Whole-sweep prorating assumes every generation costs
    the same; here only generations *within* one launch are prorated
    (the scan's iterations really are identical programs), and launch
    boundaries use their measured times — tightening the granularity
    error from one sweep-fraction to at most one launch's interior.
    None if the curve never reaches target.
    """
    if len(launch_gens) != len(launch_walls):
        raise ValueError(
            f"launch_gens ({len(launch_gens)}) and launch_walls "
            f"({len(launch_walls)}) must align"
        )
    if sum(launch_gens) != len(curve):
        raise ValueError(
            f"launch_gens sums to {sum(launch_gens)} but curve has "
            f"{len(curve)} generations"
        )
    curve = [float(v) for v in curve]
    g0 = 0  # first generation index of the current launch
    done = 0.0  # wall of all completed launches before it
    for n_g, w in zip(launch_gens, launch_walls):
        for j in range(n_g):
            if curve[g0 + j] >= target:
                return done + w * (j + 1) / n_g
        g0 += n_g
        done += w
    return None


def sweep_wall_to_target(result: dict, wall_s: float, target: float):
    """Launch-granular when the sweep result carries measured launch
    durations (fused_pbt always does for fresh sweeps), whole-sweep
    prorating otherwise (``launch_walls`` is None when a resume from a
    pre-upgrade snapshot left early durations unknown).

    Semantics note: ``launch_walls`` deliberately excludes checkpoint-
    save time (the metric measures the sweep's compute-to-target; this
    container's tunnel makes snapshot fetches pathologically slow —
    PERF_NOTES.md), while the fallback's ``wall_s`` is the caller's
    clock and usually includes it. Records should carry the total wall
    alongside (benches record both) so the difference is visible."""
    if result.get("launch_walls") is not None:
        return wall_to_target_launchwise(
            result["best_curve"], result["launch_gens"], result["launch_walls"], target
        )
    return wall_to_target(result["best_curve"], wall_s, target)
