"""Results/reporting (SURVEY.md §2 row 12): JSONL metrics + throughput.

Emits one JSON object per event to a stream and/or file, and accounts
the metric of record (BASELINE.json): trials/sec/chip and wall-clock.
"""

from __future__ import annotations

import json
import sys
import time
from typing import IO, Optional


class MetricsLogger:
    def __init__(self, path: Optional[str] = None, stream: Optional[IO] = None, n_chips: int = 1):
        self._file = open(path, "a") if path else None
        self._stream = stream
        self.n_chips = max(1, n_chips)
        self.t_start = time.perf_counter()
        self.trials_done = 0

    def log(self, event: str, **fields) -> dict:
        rec = {"event": event, "t": round(time.perf_counter() - self.t_start, 4), **fields}
        line = json.dumps(rec)
        if self._file:
            self._file.write(line + "\n")
            self._file.flush()
        if self._stream:
            print(line, file=self._stream, flush=True)
        return rec

    def count_trials(self, n: int):
        self.trials_done += n

    @property
    def wall(self) -> float:
        return time.perf_counter() - self.t_start

    def trials_per_sec_per_chip(self) -> float:
        return self.trials_done / max(self.wall, 1e-9) / self.n_chips

    def summary(self, **extra) -> dict:
        return self.log(
            "summary",
            trials=self.trials_done,
            wall_s=round(self.wall, 3),
            trials_per_sec_per_chip=round(self.trials_per_sec_per_chip(), 4),
            **extra,
        )

    def close(self):
        if self._file:
            self._file.close()
            self._file = None


def null_logger() -> MetricsLogger:
    return MetricsLogger()


def stdout_logger(path: Optional[str] = None, n_chips: int = 1) -> MetricsLogger:
    return MetricsLogger(path=path, stream=sys.stdout, n_chips=n_chips)


def wall_to_target(curve, wall_s: float, target: float):
    """Prorated wall-clock (seconds) until a per-generation best-score
    curve first reaches ``target``; None if it never does.

    The metric-of-record definition (BASELINE.json: "wall-clock to
    target validation accuracy"): generations are uniform work, so
    reaching the target at generation g costs (g+1)/G of the sweep's
    wall. Single-sourced here so every bench compares raw float curve
    values against the target identically.
    """
    curve = [float(v) for v in curve]
    for g, v in enumerate(curve):
        if v >= target:
            return wall_s * (g + 1) / len(curve)
    return None
