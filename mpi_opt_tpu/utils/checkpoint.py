"""Durable checkpoint/resume of search state (SURVEY.md §2 row 13, §5).

The reference's failure model is MPI's: one rank dies, the gang dies,
the sweep restarts from zero. The TPU-native recovery path is
checkpoint-restart: the host-side search state (tiny JSON — trial
ledger, algorithm bookkeeping, RNG counters) and the device-resident
population state (params + momentum, the expensive thing to lose) are
written together through orbax, and a restarted process resumes
mid-sweep. In-flight trials at save time are re-dispatched on load by
each algorithm's ``_requeue_running`` recovery (see algorithms/base.py).

Layout: one orbax ``CheckpointManager`` step per completed driver batch,
``max_to_keep`` most recent retained. Items:
- ``search``: JSON — ``algorithm.state_dict()`` + backend host ledger.
- ``pool``: pytree — the backend's device state (present only for
  backends that carry one, i.e. the TPU population backend's slot pool).

Saves are asynchronous (orbax's background thread) so the driver loop
is never blocked on serialization of a multi-GB pool; ``close()`` (or
the context manager) drains pending writes.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp


class SearchCheckpointer:
    """Periodic durable snapshots of (algorithm, backend) state."""

    def __init__(self, directory: str, every: int = 1, keep: int = 2):
        if every < 1:
            raise ValueError(f"checkpoint every must be >= 1, got {every}")
        self.directory = os.path.abspath(directory)
        self.every = every
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )

    # -- save --------------------------------------------------------------

    def maybe_save(self, step: int, algorithm, backend) -> bool:
        """Save if ``step`` is on the cadence; returns whether it saved."""
        if step % self.every:
            return False
        self.save(step, algorithm, backend)
        return True

    def save(self, step: int, algorithm, backend) -> None:
        search = {
            "algorithm": algorithm.state_dict(),
            "backend": backend.host_state_dict(),
        }
        items = {"search": ocp.args.JsonSave(search)}
        pool = backend.device_state()
        if pool is not None:
            items["pool"] = ocp.args.StandardSave(pool)
        self._mgr.save(step, args=ocp.args.Composite(**items))

    # -- restore -----------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_into(self, algorithm, backend) -> Optional[int]:
        """Load the latest snapshot into a fresh algorithm/backend pair.

        Returns the restored step, or None if the directory holds no
        checkpoint (caller starts fresh).
        """
        step = self._mgr.latest_step()
        if step is None:
            return None
        items: dict[str, Any] = {"search": ocp.args.JsonRestore()}
        has_pool = "pool" in self._item_names(step)
        if has_pool:
            items["pool"] = ocp.args.StandardRestore()
        r = self._mgr.restore(step, args=ocp.args.Composite(**items))
        algorithm.load_state_dict(r.search["algorithm"])
        backend.load_host_state_dict(r.search["backend"])
        if has_pool:
            backend.load_device_state(r.pool)
        return step

    def _item_names(self, step: int) -> set:
        try:
            meta = self._mgr.item_metadata(step)
            return set(meta.keys()) if hasattr(meta, "keys") else set()
        except Exception:
            # metadata probe is best-effort; fall back to directory list
            step_dir = os.path.join(self.directory, str(step))
            return set(os.listdir(step_dir)) if os.path.isdir(step_dir) else set()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
