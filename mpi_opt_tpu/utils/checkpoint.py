"""Durable checkpoint/resume of search state (SURVEY.md §2 row 13, §5).

The reference's failure model is MPI's: one rank dies, the gang dies,
the sweep restarts from zero. The TPU-native recovery path is
checkpoint-restart: the host-side search state (tiny JSON — trial
ledger, algorithm bookkeeping, RNG counters) and the device-resident
population state (params + momentum, the expensive thing to lose) are
written together through orbax, and a restarted process resumes
mid-sweep. In-flight trials at save time are re-dispatched on load by
each algorithm's ``_requeue_running`` recovery (see algorithms/base.py).

Layout: one orbax ``CheckpointManager`` step per completed driver batch,
``max_to_keep`` most recent retained. Items:
- ``search``: JSON — ``algorithm.state_dict()`` + backend host ledger.
- ``pool``: pytree — the backend's device state (present only for
  backends that carry one, i.e. the TPU population backend's slot pool).

Saves are asynchronous (orbax's background thread) so the driver loop
is never blocked on serialization of a multi-GB pool; ``close()`` (or
the context manager) drains pending writes.

Integrity (utils/integrity.py): every save writes a ``manifest`` item
with per-item content digests; restore verifies digests BEFORE any
state is applied, quarantines a failing step (rename to
``<step>.corrupt``) and walks back to the newest older retained step —
``keep`` is therefore the fallback budget (default 3: the latest may be
torn by a SIGKILL mid-async-save, leaving two verified fallbacks). Only
when no verified step remains does restore raise
``NoVerifiedSnapshotError`` (the CLI exits EX_DATAERR=65, which the
launch supervisor treats as non-retryable).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp

from mpi_opt_tpu.obs import memory, trace
from mpi_opt_tpu.utils import integrity, resources


def _prune_superseded(mgr, directory: str) -> Optional[int]:
    """Delete the OLDEST retained step (the retention-prune half of the
    ENOSPC recovery): a superseded verified step is exactly the bytes
    retention policy was already going to discard — reclaiming it to
    land the CURRENT save trades fallback depth for forward progress.
    The newest step is NEVER touched (it is the resume point a parked
    run recovers through); with fewer than two steps there is nothing
    prunable and the caller parks instead. Returns the pruned step."""
    import shutil

    steps = sorted(mgr.all_steps())
    if len(steps) < 2:
        return None
    victim = int(steps[0])
    shutil.rmtree(os.path.join(directory, str(victim)), ignore_errors=True)
    mgr.reload()  # forget the deleted step
    return victim


def _wait_classified(mgr, directory: str) -> None:
    """Drain pending async saves with the storage classification: orbax
    saves are asynchronous, so a REAL disk-full often surfaces not at
    the enqueue (_save_storage_guard's territory) but in the background
    writer — re-raised here at close()'s ``wait_until_finished``. An
    unclassified ENOSPC escaping close() would exit as a generic rc 1
    traceback and launch.py would burn its whole retry budget on it —
    the exact failure mode the classifier exists to end. The failed
    write never committed its step, so durable state is the last
    committed step and the free-disk + --resume recovery holds."""
    try:
        mgr.wait_until_finished()
    except Exception as e:
        if not resources.is_storage_full(e):
            raise
        raise resources.StorageFull(
            "async snapshot write hit a full disk; durable state is the "
            "last committed step — free disk space and relaunch with "
            "--resume",
            path=directory,
        ) from e


def _save_storage_guard(mgr, directory: str, enqueue) -> None:
    """Run ``enqueue()`` (the orbax save) with the storage-exhaustion
    lifecycle (ISSUE 13): a classified ENOSPC/EDQUOT gets ONE
    retention-prune retry — delete the oldest superseded retained step,
    never the newest — then parks by raising typed ``StorageFull`` (the
    CLI maps it to ``EX_IOERR``=74, which launch.py treats as
    non-retryable-with-diagnostics and the service as parked). The
    chaos seam (``resources.disk_fault``) sits INSIDE each attempt so
    ``inject_enospc`` schedules are re-consulted on the retry, exactly
    like the spool injector. Non-storage failures propagate raw."""

    def attempt():
        resources.disk_fault("snapshot_save", directory)
        enqueue()

    try:
        attempt()
        return
    except Exception as e:
        if not resources.is_storage_full(e):
            raise
        first = e
    victim = _prune_superseded(mgr, directory)
    if victim is None:
        # nothing prunable without touching the newest verified step:
        # park now, state intact (the failed save never landed)
        raise resources.StorageFull(
            "snapshot save hit a full disk and no superseded retained "
            "step remains to prune (the newest verified step is never "
            "touched); free disk space and relaunch with --resume",
            path=directory,
        ) from first
    resources.notify("snapshot_pruned", step=victim, directory=directory)
    try:
        attempt()
    except Exception as e:
        if not resources.is_storage_full(e):
            raise
        raise resources.StorageFull(
            "snapshot save still hit a full disk after pruning one "
            f"superseded step ({victim}); free disk space and relaunch "
            "with --resume",
            path=directory,
        ) from e


def _step_item_names(mgr, directory: str, step: int) -> set:
    """Item names present in a snapshot step, via the manager's
    metadata probe with a directory-listing fallback (see the warning
    rationale in SearchCheckpointer._item_names)."""
    try:
        meta = mgr.item_metadata(step)
        names = set(meta.keys()) if hasattr(meta, "keys") else set()
        if names:
            return names
    except Exception as e:
        import warnings

        warnings.warn(
            f"checkpoint metadata probe failed at step {step} "
            f"({type(e).__name__}: {e}); falling back to directory "
            "listing to detect snapshot items",
            RuntimeWarning,
            stacklevel=2,
        )
    step_dir = os.path.join(directory, str(step))
    return set(os.listdir(step_dir)) if os.path.isdir(step_dir) else set()


def _restore_walk(mgr, directory: str, attempt):
    """Last-good-fallback restore: try retained steps newest-first via
    ``attempt(step)``; a step that fails decode or digest verification
    is QUARANTINED (renamed, never deleted) and the walk continues on
    the next older step. Returns ``(step, attempt_result)``, or None
    when the directory holds no steps at all (caller starts fresh).
    Raises NoVerifiedSnapshotError when steps existed but every one was
    quarantined — restarting cannot help, the caller must abort loudly.

    OSError is NOT corruption evidence: an I/O blip (EIO, NFS timeout,
    permission) says the *filesystem* is sick, not the bytes — it gets
    one retry, and a persistent OSError re-raises RAW so an intact
    checkpoint tree is never renamed away for a transient outage.
    (A SIGKILL-torn step surfaces as a decode/digest failure, not an
    OSError: its files are short or mangled, not unreadable; an
    UNcommitted torn step is invisible to orbax here and handled by
    ``fsck``.)"""
    quarantined: list = []
    had_any = False
    while True:
        # a fresh manager reflects disk; after each quarantine rename
        # the reload() below refreshes the step list
        steps = sorted(mgr.all_steps(), reverse=True)
        if not steps:
            break
        had_any = True
        step = steps[0]
        retried_io = False
        while True:
            try:
                return step, attempt(step)
            except OSError as e:
                if retried_io:
                    raise  # persistent I/O failure: not corruption
                retried_io = True
                integrity.notify(
                    "snapshot_io_retry",
                    step=step,
                    directory=directory,
                    error=f"{type(e).__name__}: {e}"[:500],
                )
                continue
            except Exception as e:
                q = integrity.quarantine_step(directory, step)
                quarantined.append(q or os.path.join(directory, str(step)))
                integrity.notify(
                    "snapshot_corrupt",
                    step=step,
                    directory=directory,
                    error=f"{type(e).__name__}: {e}"[:500],
                    quarantined_to=None if q is None else os.path.basename(q),
                )
                mgr.reload()  # forget the renamed step
                break
    if had_any or quarantined:
        raise integrity.NoVerifiedSnapshotError(directory, quarantined)
    return None


class SearchCheckpointer:
    """Periodic durable snapshots of (algorithm, backend) state."""

    def __init__(self, directory: str, every: int = 1, keep: int = 3):
        if every < 1:
            raise ValueError(f"checkpoint every must be >= 1, got {every}")
        self.directory = os.path.abspath(directory)
        self.every = every
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )

    # -- save --------------------------------------------------------------

    def maybe_save(self, step: int, algorithm, backend) -> bool:
        """Save if ``step`` is on the cadence; returns whether it saved."""
        if step % self.every:
            return False
        self.save(step, algorithm, backend)
        return True

    def save(self, step: int, algorithm, backend) -> None:
        # the save span bounds the HOST-side cost (state collection +
        # digest + async enqueue); orbax's background write time shows
        # up in close()'s save_wait span instead
        with trace.span("save", step=step) as sp:
            memory.note(sp)  # pre-fetch watermark: device pool still resident
            search = {
                "algorithm": algorithm.state_dict(),
                "backend": backend.host_state_dict(),
            }
            items = {"search": ocp.args.JsonSave(search)}
            tree_items = {}
            pool = backend.device_state()
            if pool is not None:
                items["pool"] = ocp.args.StandardSave(pool)
                tree_items["pool"] = pool
            # verified save: per-item content digests ride inside the step
            # (digesting a device pool costs one sync host fetch — the price
            # of restore being able to prove the bytes survived)
            manifest = integrity.build_manifest({"search": search}, tree_items)
            items[integrity.MANIFEST_ITEM] = ocp.args.JsonSave(manifest)
            _save_storage_guard(
                self._mgr,
                self.directory,
                lambda: self._mgr.save(step, args=ocp.args.Composite(**items)),
            )

    # -- restore -----------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_into(self, algorithm, backend) -> Optional[int]:
        """Load the newest VERIFIED snapshot into a fresh algorithm/
        backend pair, quarantining corrupt steps and walking back (see
        ``_restore_walk``). Restore and digest-verify complete before
        the first mutation, so a corrupt ``pool`` item can never leave
        a half-loaded algorithm behind.

        Returns the restored step, or None if the directory holds no
        checkpoint (caller starts fresh). Raises NoVerifiedSnapshotError
        when steps exist but none verifies.
        """

        def attempt(step):
            items: dict[str, Any] = {"search": ocp.args.JsonRestore()}
            names = self._item_names(step)
            has_pool = "pool" in names
            if has_pool:
                items["pool"] = ocp.args.StandardRestore()
            has_manifest = integrity.MANIFEST_ITEM in names
            if has_manifest:
                items[integrity.MANIFEST_ITEM] = ocp.args.JsonRestore()
            with trace.span("restore", step=step):
                r = self._mgr.restore(step, args=ocp.args.Composite(**items))
            if has_manifest:
                problems = integrity.verify_restored(
                    getattr(r, integrity.MANIFEST_ITEM),
                    {"search": r.search},
                    {"pool": r.pool} if has_pool else {},
                )
                if problems:
                    raise integrity.SnapshotCorruptError("; ".join(problems))
            else:
                # pre-manifest step: resumable (same rule as config keys
                # added after a snapshot format existed) but announced
                integrity.notify(
                    "snapshot_unverified", step=step, directory=self.directory
                )
            return r, has_pool

        res = _restore_walk(self._mgr, self.directory, attempt)
        if res is None:
            return None
        step, (r, has_pool) = res
        # apply phase: everything above is decoded temporaries — a
        # failure from here is schema/config drift in live code, not
        # snapshot corruption, and must surface raw (quarantining a
        # good snapshot for a program bug would destroy the evidence)
        algorithm.load_state_dict(r.search["algorithm"])
        backend.load_host_state_dict(r.search["backend"])
        if has_pool:
            backend.load_device_state(r.pool)
        return step

    def _item_names(self, step: int) -> set:
        # the metadata probe is best-effort, but a silent blanket
        # swallow would hide an orbax API break indefinitely:
        # _step_item_names surfaces what failed (type + step) before
        # falling back to the weaker directory-listing heuristic
        return _step_item_names(self._mgr, self.directory, step)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        # save_wait: where the async saves' background write time
        # surfaces on the host (the drain before the manager closes) —
        # and where a background writer's ENOSPC re-raises, classified
        with trace.span("save_wait"):
            _wait_classified(self._mgr, self.directory)
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SweepCheckpointer:
    """Durable snapshots of a fused on-device sweep, at the sweep's own
    granularity (PBT: launches; SHA: rungs; Hyperband: brackets via
    per-bracket directories).

    Items per orbax step:
    - ``sweep`` (StandardSave): host copies of the carried arrays
      (population state, unit hparams, RNG key data, scores...).
      Callers host-fetch BEFORE saving: the next launch may donate the
      device buffers out from under orbax's async writer.
    - ``meta`` (JsonSave): ``{"config": ..., **extra}`` — the sweep
      config is validated on restore, so a checkpoint from a different
      sweep shape raises instead of silently loading.
    """

    def __init__(self, directory: str, config: dict, keep: int = 3):
        self.config = config
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )

    def save(self, step: int, sweep: dict, meta_extra: dict) -> None:
        with trace.span("save", step=step) as sp:
            memory.note(sp)  # snapshot-time watermark: sweep state resident
            meta = {"config": self.config, **meta_extra}
            # verified save: both items' content digests ride with the step
            # (sweep arrays are host-fetched by every caller, so digesting
            # costs hashing only, no extra device fetch)
            manifest = integrity.build_manifest({"meta": meta}, {"sweep": sweep})
            _save_storage_guard(
                self._mgr,
                self.directory,
                lambda: self._mgr.save(
                    step,
                    args=ocp.args.Composite(
                        sweep=ocp.args.StandardSave(sweep),
                        meta=ocp.args.JsonSave(meta),
                        **{integrity.MANIFEST_ITEM: ocp.args.JsonSave(manifest)},
                    ),
                ),
            )

    def restore(self):
        """(sweep_arrays, meta) from the newest VERIFIED snapshot, or
        None when the directory holds no steps. A step failing digest
        verification or decode is quarantined (``<step>.corrupt``) and
        restore walks back to the next older retained step; when no
        verified step remains, NoVerifiedSnapshotError. Raises
        ValueError on a config mismatch."""

        def attempt(step):
            items = {
                "sweep": ocp.args.StandardRestore(),
                "meta": ocp.args.JsonRestore(),
            }
            names = _step_item_names(self._mgr, self.directory, step)
            has_manifest = integrity.MANIFEST_ITEM in names
            if has_manifest:
                items[integrity.MANIFEST_ITEM] = ocp.args.JsonRestore()
            with trace.span("restore", step=step):
                r = self._mgr.restore(step, args=ocp.args.Composite(**items))
            if has_manifest:
                problems = integrity.verify_restored(
                    getattr(r, integrity.MANIFEST_ITEM),
                    {"meta": r.meta},
                    {"sweep": r.sweep},
                )
                if problems:
                    raise integrity.SnapshotCorruptError("; ".join(problems))
            else:
                integrity.notify(
                    "snapshot_unverified", step=step, directory=self.directory
                )
            return r

        try:
            res = _restore_walk(self._mgr, self.directory, attempt)
        except integrity.NoVerifiedSnapshotError:
            # same contract as the config-mismatch raise below: callers
            # only reach their own close() via try/finally blocks
            # entered AFTER a successful restore
            self.close()
            raise
        if res is None:
            return None
        _step, r = res
        saved = dict(r.meta["config"])
        # config keys added AFTER a snapshot format existed compare
        # against their historical default, so genuine pre-upgrade
        # snapshots stay resumable instead of being refused for a key
        # their writer couldn't have known about. momentum_dtype and
        # init_unit_digest were added round 3; every earlier snapshot
        # was written under f32 momentum and a self-sampled cohort.
        saved.setdefault("momentum_dtype", "float32")
        if "init_unit_digest" in self.config:
            saved.setdefault("init_unit_digest", None)
        if "step_chunk" in self.config:
            saved.setdefault("step_chunk", 0)  # pre-upgrade sweeps were unchunked
        if "wave_size" in self.config:
            saved.setdefault("wave_size", 0)  # pre-upgrade sweeps were resident
        if "n_warm" in self.config:
            saved.setdefault("n_warm", 0)  # pre-upgrade TPE sweeps had no priors
        if saved != self.config:
            # name ONLY the mismatched keys: dumping two full config
            # dicts buries the one line that matters (wave_size vs
            # resident cross-resume is the common case and should read
            # as exactly that)
            diffs = [
                f"{k}: snapshot={saved.get(k, '<absent>')!r} vs "
                f"run={self.config.get(k, '<absent>')!r}"
                for k in sorted(set(saved) | set(self.config), key=str)
                if saved.get(k, "<absent>") != self.config.get(k, "<absent>")
            ]
            # close before raising: callers only reach their own close()
            # via try/finally blocks entered AFTER a successful restore
            self.close()
            raise ValueError(
                "checkpoint directory holds a different sweep "
                f"(mismatched {'; '.join(diffs)})"
            )
        return r.sweep, r.meta

    def close(self) -> None:
        with trace.span("save_wait"):
            _wait_classified(self._mgr, self.directory)
        self._mgr.close()


    # -- population-sweep payload (shared by fused PBT / SHA) -------------

    def save_population_sweep(self, step, state, unit, key, scores, meta_extra):
        """Snapshot the standard fused-sweep payload. Host-fetches the
        population state BEFORE the async save (the caller's next launch
        donates those device buffers). Fetches via ``fetch_global`` so a
        sweep sharded over a process-spanning mesh can snapshot: every
        process fetches the same global value (a collective for sharded
        leaves) and orbax's own multihost coordination handles the write.
        """
        import jax
        import numpy as np

        from mpi_opt_tpu.parallel.mesh import fetch_global

        tree = {"params": state.params, "momentum": state.momentum, "step": state.step}
        if all(
            not isinstance(l, jax.Array) or l.is_fully_addressable
            for l in jax.tree.leaves(tree)
        ):
            # single-process: one batched fetch (a ResNet pool is dozens
            # of leaves; per-leaf synchronous fetches would lengthen the
            # pause before the async save)
            host = jax.device_get(tree)
        else:
            host = jax.tree.map(fetch_global, tree)
        self.save(
            step,
            sweep={
                "state": host,
                "unit": fetch_global(unit),
                "key_data": np.asarray(jax.random.key_data(key)),
                # fetch_global, not np.asarray: both current callers pass
                # host arrays (no-op), but the docstring invites device
                # arrays and a process-spanning scores shard would crash
                # at its first snapshot otherwise
                "scores": fetch_global(scores),
            },
            meta_extra=meta_extra,
        )

    # -- wave-scheduled sweep payload (host-staged populations) -----------

    def restore_wave_sweep(self):
        """(sweep_payload, meta) for a wave-scheduled fused sweep, or
        None; ValueError on config mismatch (restore() closes on that
        path). The payload's arrays are host numpy by construction — a
        beyond-residency population LIVES on host, so wave snapshots
        save the staging pools directly, no device fetch involved.
        Two shapes, discriminated by ``meta['waves_done']``:

        - generation boundary (``waves_done == 0``): ``front`` (the
          post-training pool), ``perm`` (the exploit source map the next
          generation's stage-in applies), ``unit``, ``key_data`` (the
          next carried key), ``scores`` (post-exploit).
        - between waves (``waves_done == k``): both pools (``front``
          read / ``back`` written-through-wave-k), ``perm``, ``unit``,
          ``key_data`` (the PRE-generation carried key — train/exploit
          keys re-derive from it on resume), ``scores`` (pre-exploit,
          NaN past the completed prefix).

        Key wrapping and pool writability (orbax may restore read-only
        arrays) are the caller's job — see train/fused_pbt.py and the
        shared wave engine's ``writable`` helper (train/engine.py).
        """
        return self.restore()

    def restore_population_sweep(self):
        """(PopState, unit, key, scores, meta) from the latest snapshot,
        or None. Raises ValueError on config mismatch (restore() closes
        the manager on that path)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from mpi_opt_tpu.train.population import PopState

        r = self.restore()
        if r is None:
            return None
        sweep, meta = r
        state = PopState(
            params=sweep["state"]["params"],
            momentum=sweep["state"]["momentum"],
            step=sweep["state"]["step"],
        )
        key = jax.random.wrap_key_data(jnp.asarray(sweep["key_data"]))
        return state, sweep["unit"], key, np.asarray(sweep["scores"]), meta
