"""Snapshot integrity: content digests, manifests, quarantine, fsck.

The restart loop (launch.py supervisor, CLI ``--retries``, graceful
preemption) trusts that the latest orbax snapshot is intact — but saves
are ASYNC and restarts are triggered by SIGKILL-class events (stall
watchdog, chaos ``crash``, OOM, hard preemption deadlines), so a step
directory can be torn mid-write, and long-lived sweep state can bit-rot.
A poisoned latest step turns "free restart" into a crash loop that burns
the whole retry/preemption budget re-reading the same bad bytes.

This module is the bounding layer:

- **Verified saves**: ``build_manifest`` computes per-item content
  digests at save time; both checkpointers write the manifest as an
  extra JSON item inside the same orbax step. ``verify_restored``
  recomputes digests from the restored values before any state is
  applied.
- **Quarantine**: a step that fails restore or digest verification is
  renamed ``<step>.corrupt`` (never deleted — it is evidence), an
  observer event ``snapshot_corrupt`` fires (the CLI wires it into the
  metrics stream + ``snapshots_quarantined`` counter), and restore walks
  back to the newest older retained step (``keep`` is the fallback
  budget). Only when NO verified step remains does restore raise
  ``NoVerifiedSnapshotError`` — which the CLI maps to exit
  ``EX_DATAERR`` (65), the one failure class a supervisor must NOT
  retry: every restart would re-read the same dead state.
- **fsck**: ``mpi_opt_tpu fsck <dir>`` audits a sweep's durable state
  offline — enumerates steps, verifies manifests, cross-checks a
  co-located ledger journal against the newest verified snapshot
  (trial-granular for driver ledgers, boundary-granular for fused
  ones: every boundary a snapshot records complete must be fully
  journaled), ``--repair`` quarantines bad steps, ``--deep``
  additionally reads back every ocdbt key so tensorstore's CRC-32C
  checksums audit bytes a restore never touches; ``--json`` +
  exit-code contract for CI, mirroring ``report --validate``.

Digest notes: leaves are hashed as (path, dtype, shape, bytes) via
SHA-256, path-sorted so the flax-dataclass-vs-plain-dict structure
difference orbax's round trip introduces cannot flip the order. JSON
items are canonicalized through one json round trip (tuples become
lists, int keys become strings) so the save-side digest matches the
restored side byte-for-byte. Digesting a device-resident pool costs one
synchronous host fetch at save time — the price of knowing the bytes
you wrote are the bytes you'll read. Non-fully-addressable (multi-host
sharded) leaves are recorded as unverifiable and skipped on verify.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, Optional

# EX_DATAERR re-export (utils/exitcodes.py is the one home for the
# values; the historical `utils.integrity.EX_DATAERR` surface stays):
# the exit code for "resume found snapshots but none verified" — the
# one failure a launch supervisor must classify as NON-retryable (a
# restart re-reads the same poisoned state; see launch.py).
from mpi_opt_tpu.utils.exitcodes import EX_DATAERR  # noqa: F401

MANIFEST_ITEM = "manifest"
MANIFEST_VERSION = 1

# item names both checkpointers save as JSON (everything else is an
# array tree); fsck uses this to pick restore handlers for legacy steps
# that predate the manifest
_JSON_ITEMS = ("search", "meta", MANIFEST_ITEM)


class SnapshotCorruptError(RuntimeError):
    """One snapshot step failed restore/decode or digest verification
    (internal to the walk-back; callers see quarantine + fallback)."""


class NoVerifiedSnapshotError(RuntimeError):
    """Resume found snapshot steps but NONE verified: every retained
    step was quarantined. Restarting cannot help — the CLI exits
    ``EX_DATAERR`` and the launch supervisor aborts with diagnostics
    instead of consuming its retry/preemption budget."""

    def __init__(self, directory: str, quarantined: list):
        self.directory = directory
        self.quarantined = list(quarantined)
        super().__init__(
            f"no verified snapshot remains under {directory}: "
            f"{len(self.quarantined)} step(s) failed verification and were "
            f"quarantined ({', '.join(os.path.basename(q) for q in self.quarantined)}). "
            "Inspect the *.corrupt directories (mpi_opt_tpu fsck), then "
            "restart WITHOUT --resume to start fresh, or point at a "
            "different --checkpoint-dir. (Every retained step failing at "
            "once can also mean software drift — an orbax/schema upgrade "
            "— rather than bit-rot; the renames are reversible, so after "
            "fixing the environment the steps can be renamed back)"
        )


# -- digests ----------------------------------------------------------------


def _path_names(path) -> tuple:
    """A key path as bare name strings, normalized across node kinds:
    GetAttrKey('params') (flax dataclass) and DictKey('params') (the
    plain dict orbax restores it as) both become 'params', so save-side
    and restore-side digests see the same ordering."""
    out = []
    for p in path:
        for attr in ("name", "key", "idx"):
            v = getattr(p, attr, None)
            if v is not None:
                out.append(str(v))
                break
        else:
            out.append(str(p))
    return tuple(out)


def _leaf_digest(leaf) -> Optional[str]:
    """SHA-256 over (dtype, shape, bytes) of one array leaf; None when
    the leaf's bytes aren't reachable from this process (a non-fully-
    addressable multi-host shard) — recorded as unverifiable."""
    import numpy as np

    try:
        import jax

        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return None
    except Exception:
        pass
    arr = np.asarray(leaf)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


# total tree bytes above which leaf (= shard) hashing fans out across a
# thread pool: hashlib releases the GIL for buffers >= 2048 bytes, so a
# multi-GB pool's per-shard digests run genuinely parallel on multi-core
# hosts instead of serially on the save hot path. Workers clamp to the
# core count — on this 1-core container the path measures cost-neutral
# at 0.62 GB/s (PERF_NOTES round 6); the win scales with cores. Small
# trees stay serial — pool spin-up would cost more than it saves.
_PARALLEL_DIGEST_BYTES = int(
    os.environ.get("MPI_OPT_TPU_DIGEST_PARALLEL_BYTES", 64 << 20)
)


def _leaf_nbytes(leaf) -> int:
    try:
        import numpy as np

        return int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
    except Exception:
        return 0


def tree_digest(tree) -> Optional[str]:
    """Content digest of an array pytree, stable across the
    dataclass->dict structure change orbax's round trip introduces
    (leaves are path-sorted by normalized key names). None when any
    leaf is unverifiable from this process.

    Large trees (>= ``MPI_OPT_TPU_DIGEST_PARALLEL_BYTES``, default
    64 MiB) hash their leaves on a thread pool — per-shard, off the
    caller's hot thread — so a multi-GB pool's save-side digest costs
    roughly one shard's wall, not the sum. The combined digest is
    order-identical to the serial path (per-leaf digests are combined
    in sorted path order), so snapshots written either way verify
    against each other."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    entries = sorted((( _path_names(p), l) for p, l in flat), key=lambda e: e[0])
    leaves = [l for _, l in entries]
    if (
        len(leaves) > 1
        and sum(_leaf_nbytes(l) for l in leaves) >= _PARALLEL_DIGEST_BYTES
    ):
        from concurrent.futures import ThreadPoolExecutor

        workers = min(8, os.cpu_count() or 1, len(leaves))
        with ThreadPoolExecutor(max_workers=workers) as ex:
            digests = list(ex.map(_leaf_digest, leaves))
    else:
        digests = [_leaf_digest(l) for l in leaves]
    h = hashlib.sha256()
    for (path, _leaf), d in zip(entries, digests):
        if d is None:
            return None
        h.update("/".join(path).encode())
        h.update(d.encode())
    return h.hexdigest()


def json_digest(obj) -> str:
    """Digest of a JSON-item value, canonicalized through one json
    round trip so pre-serialization quirks (tuples, int keys) hash the
    same as the restored value."""
    canonical = json.loads(json.dumps(obj))
    return hashlib.sha256(
        json.dumps(canonical, sort_keys=True).encode()
    ).hexdigest()


def build_manifest(json_items: dict, tree_items: dict) -> dict:
    """The manifest record saved alongside a step's items:
    ``{"version", "items": {name: {"kind": "json"|"tree", "digest"}}}``.
    A ``digest`` of None marks an item unverifiable at save time
    (multi-host shards); verify skips it rather than failing."""
    from mpi_opt_tpu.obs import trace

    with trace.span("digest", op="build", items=len(json_items) + len(tree_items)):
        items = {}
        for name, val in json_items.items():
            items[name] = {"kind": "json", "digest": json_digest(val)}
        for name, val in tree_items.items():
            items[name] = {"kind": "tree", "digest": tree_digest(val)}
        return {"version": MANIFEST_VERSION, "items": items}


def verify_restored(manifest: dict, json_items: dict, tree_items: dict) -> list:
    """Recompute digests of restored values against ``manifest``;
    returns human-readable problems (empty = verified). Items the
    manifest lists but the caller didn't restore are problems too — a
    vanished item is exactly the torn-save shape."""
    from mpi_opt_tpu.obs import trace

    problems = []
    recorded = manifest.get("items", {})
    restored = {**json_items, **tree_items}
    with trace.span("digest", op="verify", items=len(recorded)):
        for name, entry in recorded.items():
            want = entry.get("digest")
            if want is None:
                continue  # unverifiable at save time (multi-host shard)
            if name not in restored:
                problems.append(
                    f"item {name!r}: recorded in manifest but not restored"
                )
                continue
            got = (
                json_digest(restored[name])
                if entry.get("kind") == "json"
                else tree_digest(restored[name])
            )
            if got != want:
                problems.append(
                    f"item {name!r}: content digest mismatch "
                    f"(saved {want[:12]}..., restored {(got or 'unverifiable')[:12]}...)"
                )
        for name in restored:
            if name not in recorded:
                problems.append(f"item {name!r}: present but not in manifest")
    return problems


# -- quarantine -------------------------------------------------------------


def quarantine_step(directory: str, step: int) -> Optional[str]:
    """Rename ``<directory>/<step>`` to ``<step>.corrupt`` (never
    delete: the bytes are evidence). Returns the quarantine path, or
    None when the step dir no longer exists. A name collision from a
    previous quarantine gets a numeric suffix."""
    src = os.path.join(directory, str(step))
    if not os.path.isdir(src):
        return None
    dst = f"{src}.corrupt"
    n = 1
    while os.path.exists(dst):
        dst = f"{src}.corrupt.{n}"
        n += 1
    os.replace(src, dst)
    return dst


def list_quarantined(directory: str) -> list:
    """Quarantined step dirs under ``directory`` (recursive: hyperband
    brackets nest per-bracket checkpoint roots)."""
    out = []
    for root, dirs, _files in os.walk(directory):
        for d in dirs:
            base = d.split(".corrupt")[0]
            if d != base and base.isdigit() and d[len(base):].startswith(".corrupt"):
                out.append(os.path.join(root, d))
    return sorted(out)


# -- corruption observer ----------------------------------------------------
#
# checkpoint.py has no metrics handle (fused trainers build their own
# checkpointers deep inside the sweep), so corruption events flow
# through a process-wide observer the CLI wires to its MetricsLogger —
# the same module-global pattern as health.heartbeat.

_OBSERVER: Optional[Callable] = None


def set_observer(cb: Optional[Callable]) -> None:
    """Install ``cb(event, **fields)`` as the corruption-event sink
    (the CLI points this at metrics.log + the quarantine counter)."""
    global _OBSERVER
    _OBSERVER = cb


def clear_observer() -> None:
    set_observer(None)


def notify(event: str, **fields) -> None:
    """Report a corruption-layer event; falls back to a warning so a
    library caller (tests, embedders) still sees quarantines happen."""
    if _OBSERVER is not None:
        _OBSERVER(event, **fields)
        return
    import warnings

    warnings.warn(f"{event}: {fields}", RuntimeWarning, stacklevel=2)


# -- fsck -------------------------------------------------------------------


def _committed_steps(root: str) -> list:
    """Numeric step dirs under ``root`` that carry the orbax commit
    marker, sorted ascending."""
    out = []
    for d in os.listdir(root):
        if d.isdigit() and os.path.exists(
            os.path.join(root, d, "_CHECKPOINT_METADATA")
        ):
            out.append(int(d))
    return sorted(out)


def _torn_steps(root: str) -> list:
    """Numeric step dirs WITHOUT the commit marker: a save that never
    committed (killed mid-async-write). orbax itself ignores them; fsck
    surfaces them so --repair can quarantine the debris."""
    out = []
    for d in os.listdir(root):
        if d.isdigit() and not os.path.exists(
            os.path.join(root, d, "_CHECKPOINT_METADATA")
        ):
            out.append(int(d))
    return sorted(out)


def find_checkpoint_roots(directory: str) -> list:
    """Directories under ``directory`` (inclusive) that directly hold
    step dirs — one root for flat sweeps, one per bracket dir for
    hyperband."""
    roots = []
    for root, dirs, _files in os.walk(directory):
        if any(d.isdigit() for d in dirs) or any(".corrupt" in d for d in dirs):
            roots.append(root)
            # don't descend into the step dirs themselves
            dirs[:] = [d for d in dirs if not (d.split(".")[0].isdigit())]
    return sorted(roots)


def verify_step(root: str, step: int, mgr=None) -> tuple:
    """(status, problems) for one committed step: ``"verified"`` (every
    manifest digest matches), ``"legacy"`` (pre-manifest step — decodes
    but can't be content-verified), or ``"corrupt"``. Pass ``mgr`` (an
    open CheckpointManager on ``root``) to amortize the per-root scan
    over many steps — fsck does."""
    import orbax.checkpoint as ocp

    step_dir = os.path.join(root, str(step))
    names = sorted(
        d for d in os.listdir(step_dir)
        if os.path.isdir(os.path.join(step_dir, d))
    )
    own_mgr = mgr is None
    if own_mgr:
        mgr = ocp.CheckpointManager(root)
    try:
        if MANIFEST_ITEM in names:
            try:
                manifest = mgr.restore(
                    step,
                    args=ocp.args.Composite(
                        **{MANIFEST_ITEM: ocp.args.JsonRestore()}
                    ),
                )[MANIFEST_ITEM]
            except Exception as e:
                return "corrupt", [f"manifest unreadable: {type(e).__name__}: {e}"]
            kinds = {
                n: e.get("kind", "tree")
                for n, e in manifest.get("items", {}).items()
            }
        else:
            manifest = None
            kinds = {
                n: ("json" if n in _JSON_ITEMS else "tree")
                for n in names
            }
        args = {}
        for n in names:
            if n == MANIFEST_ITEM:
                continue
            args[n] = (
                ocp.args.JsonRestore()
                if kinds.get(n, "tree") == "json"
                else ocp.args.StandardRestore()
            )
        try:
            r = mgr.restore(step, args=ocp.args.Composite(**args))
        except Exception as e:
            return "corrupt", [f"restore failed: {type(e).__name__}: {e}"]
        if manifest is None:
            return "legacy", ["no integrity manifest (pre-upgrade step)"]
        json_items = {n: r[n] for n in args if kinds.get(n) == "json"}
        tree_items = {n: r[n] for n in args if kinds.get(n) != "json"}
        problems = verify_restored(manifest, json_items, tree_items)
        return ("verified", []) if not problems else ("corrupt", problems)
    finally:
        if own_mgr:
            mgr.close()


def deep_verify_step(root: str, step: int) -> list:
    """``fsck --deep``: ocdbt-internal checksum audit of one committed
    step. Opens every ocdbt database under the step dir (orbax writes a
    top-level store per item PLUS nested ``ocdbt.process_*`` stores)
    and reads EVERY key back — tensorstore validates its CRC-32C
    checksums on read, so rot inside b-tree nodes or data files
    surfaces here even when it hides from a normal restore: measured in
    this container, a bit-flip in a nested process store's data file
    reads back clean through the top-level database (the manifest
    digest layer verifies what a restore RETURNS, not every byte on
    disk). Returns problems (empty = every stored byte decoded clean).
    """
    problems: list = []
    try:
        import tensorstore as ts
    except Exception as e:  # the orbax dep should always carry it
        return [f"--deep unavailable: tensorstore import failed ({e})"]
    step_dir = os.path.join(root, str(step))
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        if "manifest.ocdbt" not in filenames:
            continue
        rel = os.path.relpath(dirpath, step_dir)
        try:
            kv = ts.KvStore.open(
                {"driver": "ocdbt", "base": {"driver": "file", "path": dirpath}}
            ).result()
            for key in kv.list().result():
                kv.read(key).result()
        except Exception as e:
            problems.append(
                f"ocdbt {rel}: {type(e).__name__}: {str(e)[:300]}"
            )
    return problems


def load_sweep_meta(root: str, step: int, mgr=None) -> Optional[dict]:
    """The ``meta`` JSON item of a FUSED sweep's step (None when the
    step holds none — driver-path steps save ``search``/``pool``).
    fsck's fused ledger cross-check reads ``boundaries_done`` from it."""
    import orbax.checkpoint as ocp

    step_dir = os.path.join(root, str(step))
    if not os.path.isdir(os.path.join(step_dir, "meta")):
        return None
    own_mgr = mgr is None
    if own_mgr:
        mgr = ocp.CheckpointManager(root)
    try:
        return mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )["meta"]
    finally:
        if own_mgr:
            mgr.close()


def load_search_state(root: str, step: int, mgr=None) -> Optional[dict]:
    """The ``search`` JSON item of a step, or None when the step holds
    no driver-path search state (fused sweeps save ``sweep``/``meta``)."""
    import orbax.checkpoint as ocp

    step_dir = os.path.join(root, str(step))
    if not os.path.isdir(os.path.join(step_dir, "search")):
        return None
    own_mgr = mgr is None
    if own_mgr:
        mgr = ocp.CheckpointManager(root)
    try:
        return mgr.restore(
            step, args=ocp.args.Composite(search=ocp.args.JsonRestore())
        )["search"]
    finally:
        if own_mgr:
            mgr.close()


def _sniffs_as_ledger(path: str) -> bool:
    """Does line 1 look like a ledger header? (fsck's auto-detect gate;
    the sniff itself has one home, ``ledger.store.sniff_header``)"""
    from mpi_opt_tpu.ledger.store import sniff_header

    return sniff_header(path) is not None


def _sniffs_as_fused_ledger(path: str) -> bool:
    """Was this ledger written by a fused sweep? (picks which replay
    cross-check fsck runs: boundary-granular vs trial-granular)"""
    from mpi_opt_tpu.ledger.store import sniff_header

    header = sniff_header(path)
    return header is not None and header.get("config", {}).get("mode") == "fused"


def fsck_main(argv=None) -> int:
    """The ``mpi_opt_tpu fsck`` subcommand (see cli.main dispatch).

    Exit 0: every committed step verified (or legacy). Exit 1: any
    corrupt or torn step found this run (with ``--repair`` they are
    quarantined, but the run still reports the corruption it found —
    CI distinguishes "clean" from "repaired"). Usage errors exit 2.
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu fsck",
        description="audit a sweep's durable checkpoint state: verify "
        "snapshot manifests, surface torn saves, cross-check a ledger "
        "journal (see README: snapshot integrity)",
    )
    p.add_argument("directory", metavar="DIR", help="checkpoint directory")
    p.add_argument(
        "--repair",
        action="store_true",
        help="quarantine corrupt/torn steps (rename to <step>.corrupt) "
        "so a subsequent --resume restores the newest verified step",
    )
    p.add_argument(
        "--deep",
        action="store_true",
        help="additionally read back every key of every ocdbt database "
        "inside each committed step (tensorstore validates its CRC-32C "
        "checksums on read) — catches rot in ocdbt-internal structures "
        "a normal restore never touches; slower (full re-read)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--ledger",
        default=None,
        metavar="PATH",
        help="cross-check this ledger journal against the newest "
        "verified snapshot (default: any single co-located *.jsonl "
        "next to DIR's steps)",
    )
    args = p.parse_args(argv)
    directory = os.path.abspath(args.directory)
    if not os.path.isdir(directory):
        p.error(f"{args.directory!r} is not a directory")

    import orbax.checkpoint as ocp

    steps_out = []
    repaired = []
    newest_verified = None  # (root, step, mgr is closed by then — path only)
    newest_by_root: dict = {}  # root -> newest verified step (fused x-check)
    rc = 0
    for root in find_checkpoint_roots(directory):
        rel = os.path.relpath(root, directory)
        for step in _torn_steps(root):
            rc = 1
            entry = {
                "root": rel,
                "step": step,
                "status": "torn",
                "problems": ["uncommitted save (no _CHECKPOINT_METADATA)"],
            }
            if args.repair:
                q = quarantine_step(root, step)
                if q:
                    repaired.append(q)
                    entry["quarantined_to"] = os.path.basename(q)
            steps_out.append(entry)
        mgr = ocp.CheckpointManager(root)  # one scan amortized over steps
        try:
            for step in _committed_steps(root):
                status, problems = verify_step(root, step, mgr=mgr)
                if args.deep and status != "corrupt":
                    # ocdbt-internal audit on top of the manifest layer:
                    # a step whose restore verifies can still hold
                    # rotten bytes in stores a restore never reads
                    deep_problems = deep_verify_step(root, step)
                    if deep_problems:
                        status = "corrupt"
                        problems = problems + deep_problems
                entry = {
                    "root": rel, "step": step, "status": status, "problems": problems,
                }
                if status == "corrupt":
                    rc = 1
                    if args.repair:
                        q = quarantine_step(root, step)
                        if q:
                            repaired.append(q)
                            entry["quarantined_to"] = os.path.basename(q)
                elif status == "verified":
                    if newest_verified is None or step > newest_verified[1]:
                        newest_verified = (root, step)
                    if step > newest_by_root.get(root, -1):
                        newest_by_root[root] = step
                steps_out.append(entry)
        finally:
            mgr.close()

    # ledger audit: an explicit --ledger gets the full treatment (schema
    # + replay cross-check against the newest verified snapshot). With
    # no flag, exactly one co-located sibling jsonl that sniffs as a
    # ledger (header on line 1 — a metrics file also ends .jsonl) gets
    # the SCHEMA check only: auto-detection cannot prove the sibling
    # belongs to THIS sweep, and cross-checking a neighbor sweep's
    # journal would fail CI on a perfectly healthy tree.
    ledger_path = args.ledger
    explicit = ledger_path is not None
    if ledger_path is None:
        parent = os.path.dirname(directory) or "."
        sibling = [
            os.path.join(parent, f)
            for f in sorted(os.listdir(parent))
            if f.endswith(".jsonl")
            and _sniffs_as_ledger(os.path.join(parent, f))
        ]
        if len(sibling) == 1:
            ledger_path = sibling[0]
    ledger_out = None
    if ledger_path is not None:
        from mpi_opt_tpu.ledger.report import replay_consistency
        from mpi_opt_tpu.ledger.store import (
            LedgerError,
            SweepLedger,
            read_ledger,
            validate_ledger,
        )

        problems = validate_ledger(ledger_path)
        torn_tail = False
        torn_boundary = None
        if problems:
            # the two recoverable damage shapes a kill can leave: a torn
            # FINAL line (died mid-append) and, for fused journals, a
            # torn FINAL boundary (died between a boundary's member
            # records). The resume path self-heals both (SweepLedger
            # truncates on load); --repair does the same here so the
            # documented flag -> repair -> resume -> clean cycle also
            # goes green for ledgers, not just snapshot steps.
            try:
                _h, recs, n_torn = read_ledger(ledger_path, strict=False)
                torn_tail = n_torn > 0
                from mpi_opt_tpu.ledger.store import scan_boundaries

                _by, _sz, _bp, torn_boundary = scan_boundaries(recs)
            except Exception:
                torn_tail, torn_boundary = False, None
            if (torn_tail or torn_boundary is not None) and args.repair:
                try:
                    # sweeplint: disable=ledger-gate -- fsck --repair is a single-process operator tool; the load-time truncation IS the repair, no SPMD rank can race it
                    SweepLedger(ledger_path).close()  # load truncates in place
                except LedgerError:
                    pass  # damage beyond the append-kill shapes: report only
                else:
                    what = []
                    if torn_tail:
                        what.append("torn tail")
                    if torn_boundary is not None:
                        what.append(f"torn boundary {torn_boundary}")
                    repaired.append(f"{ledger_path} ({' + '.join(what)} truncated)")
                    problems = validate_ledger(ledger_path)
        if explicit and not problems:
            if _sniffs_as_fused_ledger(ledger_path):
                # boundary-granular invariant: every boundary any root's
                # newest verified snapshot records complete must be
                # fully journaled. MAX across roots — hyperband brackets
                # snapshot independently but share one global boundary
                # sequence, and the furthest-ahead bracket binds
                from mpi_opt_tpu.ledger.report import fused_replay_consistency

                done = [
                    int(meta["boundaries_done"])
                    for root, step in newest_by_root.items()
                    for meta in [load_sweep_meta(root, step)]
                    if meta is not None and "boundaries_done" in meta
                ]
                if done:
                    problems += fused_replay_consistency(ledger_path, max(done))
            else:
                search = (
                    load_search_state(*newest_verified) if newest_verified else None
                )
                if search is not None:
                    problems += replay_consistency(ledger_path, search)
        ledger_out = {
            "path": ledger_path,
            "problems": problems,
            "torn_tail": torn_tail,
            "torn_boundary": torn_boundary,
            "cross_checked": explicit,
        }
        # an auto-detected sibling can't be PROVEN to belong to this
        # sweep: its problems are reported but only an explicit --ledger
        # fails the audit (a neighbor sweep's torn journal must not turn
        # this tree's CI red). A repaired torn tail/boundary still
        # counts as damage FOUND this run, matching the step contract.
        if (problems or torn_tail or torn_boundary is not None) and explicit:
            rc = 1

    report = {
        "dir": directory,
        "ok": rc == 0,
        "steps": steps_out,
        "newest_verified": None if newest_verified is None else {
            "root": os.path.relpath(newest_verified[0], directory),
            "step": newest_verified[1],
        },
        "repaired": [os.path.basename(q) for q in repaired],
        "quarantined": [
            os.path.relpath(q, directory) for q in list_quarantined(directory)
        ],
        "ledger": ledger_out,
    }
    if args.json:
        print(json.dumps(report))
        return rc
    print(f"fsck {directory}: {'ok' if rc == 0 else 'CORRUPTION FOUND'}")
    for e in steps_out:
        loc = f"{e['root']}/{e['step']}" if e["root"] != "." else str(e["step"])
        line = f"  step {loc}: {e['status']}"
        if e["problems"]:
            line += f" ({'; '.join(e['problems'])})"
        if e.get("quarantined_to"):
            line += f" -> quarantined as {e['quarantined_to']}"
        print(line)
    if report["quarantined"]:
        print(f"  quarantined: {', '.join(report['quarantined'])}")
    if ledger_out is not None:
        status = "ok" if not ledger_out["problems"] else "; ".join(ledger_out["problems"])
        print(f"  ledger {ledger_out['path']}: {status}")
    if rc and not args.repair:
        print("  (re-run with --repair to quarantine bad steps, then --resume)")
    return rc
