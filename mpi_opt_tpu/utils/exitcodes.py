"""The sweep processes' exit-code contract, in ONE place.

Three layers classify these codes — the CLI (producing them), the
launch supervisor (restart policy), and the service's tenant state
machine (scheduling) — which is two places too many to keep literal
75s and 65s in sync by hand. Everything that maps an exit code to a
recovery decision imports from here.

The codes (sysexits.h where one exists):

- ``EX_OK`` (0): the sweep completed; the summary JSON line is final.
- ``EX_FAILURE`` (1): a RETRYABLE failure — a crashed rank, an aborted
  sweep (circuit breaker), an unclassified exception. Supervisors may
  bill a retry and relaunch.
- ``EX_USAGE`` (2): argparse's usage-error code. The invocation itself
  is wrong; no retry can help and a supervisor "recovering" it would
  loop forever on the same refusal.
- ``EX_DATAERR`` (65): durable state is poisoned (no verified snapshot
  remains, a journal diverges from the sweep it claims to record). The
  one failure class a supervisor must NOT retry: a restart re-reads the
  same poisoned state. Abort with diagnostics.
- ``EX_TEMPFAIL`` (75): the graceful-shutdown protocol's code — the
  sweep drained at a boundary with durable state flushed. "Restart me
  with ``--resume``, and don't bill the retry budget." The service's
  time-slice preemption exits through the same drain path, so 75 is
  also the code a parked tenant leaves behind.
- ``EX_UNAVAILABLE`` (69): the fleet's zombie-fencing code — a server
  discovered its own identity was usurped (another process registered
  its ``--server-id`` while it was presumed dead) and STEPPED DOWN
  rather than fight over the spool. The work is fine; this process's
  claim to it is not. A supervisor may restart it under a fresh id;
  retrying the same identity re-refuses while the usurper lives.
- ``EX_IOERR`` (74): resource exhaustion as a classified ANSWER
  (utils/resources.py) — the disk filled mid-snapshot/journal after
  the one retention-prune retry, or the device OOM'd with no wave left
  to halve. Durable state is INTACT (unlike 65: the failed write never
  landed, the newest verified step was never touched) but retrying
  changes nothing until an operator frees the resource — launch.py
  aborts with diagnostics, budget untouched; the service PARKS the
  tenant (not terminal) so freeing disk + ``--resume`` recovers.
- ``EX_PROTOCOL`` (76): the HTTP front door's typed protocol refusal,
  seen from the CLIENT — the server ANSWERED, and the answer is "your
  request is wrong" (idempotency-key reuse with a different body, a
  malformed envelope). Retrying the same bytes re-refuses, so scripts
  must treat it like ``EX_USAGE``, not like ``EX_UNAVAILABLE``.
  ``suggest-client --url`` additionally maps exhausted-transport
  retries to ``EX_UNAVAILABLE`` (69): no server answered at all.
"""

from __future__ import annotations

EX_OK = 0
EX_FAILURE = 1
EX_USAGE = 2
# sysexits.h EX_DATAERR: "input data was incorrect in some way"
EX_DATAERR = 65
# sysexits.h EX_UNAVAILABLE: "service unavailable" — the fenced-zombie
# step-down (fleet federation; see service/leases.py)
EX_UNAVAILABLE = 69
# sysexits.h EX_IOERR: "an error occurred while doing I/O" — the
# resource-exhaustion park (device OOM / disk full; utils/resources.py)
EX_IOERR = 74
# sysexits.h EX_TEMPFAIL: "temporary failure, user is invited to retry"
EX_TEMPFAIL = 75
# sysexits.h EX_PROTOCOL: "remote system returned something invalid" —
# repurposed client-side for the front door's typed refusals (409/400):
# the conversation worked, the REQUEST is wrong, retries re-refuse
EX_PROTOCOL = 76

_OUTCOMES = {
    EX_OK: "ok",
    EX_USAGE: "usage",
    EX_DATAERR: "data_error",
    EX_UNAVAILABLE: "unavailable",
    EX_IOERR: "io_error",
    EX_TEMPFAIL: "preempted",
    EX_PROTOCOL: "protocol",
}


def classify(rc: int) -> str:
    """Exit code -> outcome class: ``ok`` / ``usage`` / ``data_error``
    / ``unavailable`` / ``io_error`` / ``preempted`` / ``protocol`` /
    ``failure`` (the
    catch-all for every other nonzero code, including 1). ``preempted``
    is the only outcome that means "resumable, for free"; ``usage`` and
    ``data_error`` are terminal-without-retry; ``unavailable`` is the
    fleet's step-down (the PROCESS lost its identity, the work did
    not); ``io_error`` is resumable-after-operator-action (state is
    intact, the RESOURCE is exhausted — a retry without freeing it
    re-fails identically, so supervisors abort but services only
    park); ``failure`` is terminal-or-retry at the caller's budget."""
    return _OUTCOMES.get(int(rc), "failure")
