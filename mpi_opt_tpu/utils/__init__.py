"""Utilities: metrics, checkpointing, profiling."""
