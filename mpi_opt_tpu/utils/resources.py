"""Resource-exhaustion classification: ONE funnel for the two failure
classes that scale-out guarantees — device OOM and storage exhaustion.

The blind spot this closes (ISSUE 13): `train/staging.py`'s own
docstring names the device wall ("4.5 GB of params+momentum ... dies
RESOURCE_EXHAUSTED at warmup"), and until now that death was an
unclassified XlaRuntimeError traceback that launch.py burned its whole
retry budget on — while an ENOSPC during a snapshot save or ledger
fsync either spun a jittered backoff loop (a full disk does not heal on
retry) or tore state. Both become typed ANSWERS here:

- :class:`DeviceOOM` — an XLA ``RESOURCE_EXHAUSTED`` launch failure.
  Deterministic for a given program + population: retrying the same
  shape re-OOMs, so supervisors must not fund restarts. The shared wave
  engine's adaptive backoff (train/engine.py ``--oom-backoff``, every
  fused algorithm) is the one productive response: halve the wave and
  re-run the boundary — wave mode is bit-identical at any wave size,
  so backoff preserves the result.
- :class:`StorageFull` — ENOSPC/EDQUOT from a durable-state write.
  Also an answer, not weather: the snapshot layer gets ONE
  retention-prune retry (utils/checkpoint.py), then the run parks with
  ``EX_IOERR`` (74) so freeing disk + ``--resume`` recovers.

Funnel contract (machine-checked by the ``resource-funnel`` sweeplint
checker): RESOURCE_EXHAUSTED / XlaRuntimeError handling and ENOSPC
errno literals live in THIS module only. Everything else asks
:func:`is_device_oom` / :func:`is_storage_full` — ad-hoc swallows of
either class cannot regress the classification silently.

Observer + seams mirror utils/integrity.py: backoff/prune events flow
through a process-wide observer the CLI wires to its MetricsLogger, and
the two chaos injectors (workloads/chaos.py ``inject_enospc`` /
``inject_oom``) install schedules on the module-level fault seams.
"""

from __future__ import annotations

import errno
from typing import Callable, Optional

#: the storage-exhaustion errnos: "no space" and "quota exceeded" are
#: the same operational event (the tenant's writes stop landing until
#: an operator frees bytes) and classify identically everywhere
_STORAGE_ERRNOS = (errno.ENOSPC, errno.EDQUOT)

#: message markers an XLA allocation failure arrives with. Checked only
#: AFTER the type gate (XlaRuntimeError) — a user exception merely
#: QUOTING "out of memory" must not classify as a device OOM
_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory")


class DeviceOOM(RuntimeError):
    """Typed device-memory exhaustion: the program's resident state
    (population + activations) exceeded the device budget. Carries the
    original XLA error text; ``wave_size`` is the wave cap in force
    when the launch died (None for resident mode) so diagnostics can
    say what to halve."""

    def __init__(self, message: str, wave_size: Optional[int] = None):
        super().__init__(message)
        self.wave_size = wave_size


class StorageFull(OSError):
    """Typed storage exhaustion (ENOSPC semantics preserved: this IS an
    OSError with the original errno, so ``is_storage_full`` classifies
    it and errno-aware callers keep working)."""

    def __init__(self, message: str, path: Optional[str] = None, err: int = errno.ENOSPC):
        super().__init__(err, message, path)


def is_storage_full(e: BaseException) -> bool:
    """Is this exception a storage-exhaustion ANSWER (ENOSPC/EDQUOT)?
    The one predicate every retry loop and save path consults — a full
    disk must never spin a backoff schedule. Walks the EXPLICIT cause
    chain (``raise X from e``): orbax/tensorstore surface a background
    write's ENOSPC wrapped in their own error types, and the wrapper
    must classify like the root cause."""
    depth = 0
    while isinstance(e, BaseException) and depth < 8:
        if isinstance(e, OSError) and e.errno in _STORAGE_ERRNOS:
            return True
        e = e.__cause__
        depth += 1
    return False


def storage_full_error(path: str, op: str = "write") -> StorageFull:
    """Constructor for injectors and wrappers: a classified
    ``StorageFull`` naming the operation and path."""
    return StorageFull(f"no space left on device during {op}", path=path)


def is_device_oom(e: BaseException) -> bool:
    """Is this exception an XLA device-memory exhaustion? Type-first
    (same discipline as cli._is_transient): only the runtime's own
    error class (``XlaRuntimeError``) is eligible, then the message
    must carry a RESOURCE_EXHAUSTED marker."""
    if isinstance(e, DeviceOOM):
        return True
    try:
        import jax.errors
    except Exception:  # pragma: no cover - jax-less environment
        return False
    if not isinstance(e, jax.errors.JaxRuntimeError):
        return False
    return any(m in str(e).lower() for m in _OOM_MARKERS)


def as_device_oom(e: BaseException, wave_size: Optional[int] = None) -> Optional[DeviceOOM]:
    """``DeviceOOM`` wrapping ``e`` when it classifies, else None."""
    if isinstance(e, DeviceOOM):
        return e
    if not is_device_oom(e):
        return None
    return DeviceOOM(f"{type(e).__name__}: {e}"[:2000], wave_size=wave_size)


def synthetic_resource_exhausted(detail: str = "chaos-injected"):
    """A constructed ``XlaRuntimeError`` with the RESOURCE_EXHAUSTED
    shape — what the chaos ``oom`` fault raises so drills exercise the
    REAL classification path (type gate included), not a stand-in."""
    import jax.errors

    return jax.errors.JaxRuntimeError(
        f"RESOURCE_EXHAUSTED: Out of memory ({detail})"
    )


class oom_funnel:
    """Context manager: XLA RESOURCE_EXHAUSTED escaping the guarded
    region re-raises as typed :class:`DeviceOOM` (everything else
    propagates raw). The fused launch paths wrap their dispatches in
    this so the CLI and the wave scheduler's backoff catch ONE type."""

    def __init__(self, wave_size: Optional[int] = None):
        self.wave_size = wave_size

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is None:
            return False
        oom = None if isinstance(exc, DeviceOOM) else as_device_oom(exc, self.wave_size)
        if oom is not None:
            raise oom from exc
        return False


# -- observer (utils/integrity.py pattern) ----------------------------------
#
# The wave scheduler and checkpoint layer have no metrics handle (they
# run deep inside fused sweeps); backoff/prune events flow through this
# process-wide observer, which the CLI points at metrics.log + the
# oom_backoffs / wave_resized / snapshots_pruned counters.

_OBSERVER: Optional[Callable] = None


def set_observer(cb: Optional[Callable]) -> None:
    global _OBSERVER
    _OBSERVER = cb


def clear_observer() -> None:
    set_observer(None)


def notify(event: str, **fields) -> None:
    """Report a resource-lifecycle event (``oom_backoff`` /
    ``wave_resized`` / ``snapshot_pruned``); falls back to a warning so
    library callers still see backoffs happen."""
    if _OBSERVER is not None:
        _OBSERVER(event, **fields)
        return
    import warnings

    warnings.warn(f"{event}: {fields}", RuntimeWarning, stacklevel=2)


# -- chaos seams ------------------------------------------------------------
#
# Direct-call injector hooks, like workloads/chaos.py's snapshot
# injectors: deterministic schedules installed for a drill, uninstalled
# in a finally. ``disk_fault(op, path)`` sits inside the atomic-write/
# fsync paths (snapshot save enqueue, ledger fsync) and may raise a
# classified StorageFull; ``launch_fault(kind)`` sits at the top of
# every guarded fused launch (resident launch / one wave) and may raise
# a synthetic RESOURCE_EXHAUSTED at a chosen ordinal.

_DISK_FAULTS: Optional[Callable[[str, str], None]] = None
_LAUNCH_FAULTS: Optional[Callable[[str], None]] = None


def set_disk_fault_injector(fn: Optional[Callable[[str, str], None]]) -> None:
    global _DISK_FAULTS
    _DISK_FAULTS = fn


def disk_fault(op: str, path: str) -> None:
    if _DISK_FAULTS is not None:
        _DISK_FAULTS(op, path)


def set_launch_fault_injector(fn: Optional[Callable[[str], None]]) -> None:
    global _LAUNCH_FAULTS
    _LAUNCH_FAULTS = fn


def launch_fault(kind: str) -> None:
    if _LAUNCH_FAULTS is not None:
        _LAUNCH_FAULTS(kind)


# ``boundary_fault(stage)`` sits at the top of every launch/rung/
# generation boundary (train.common.launch_boundary) — the seam the
# ``rank_kill`` chaos injector hangs off to SIGKILL a chosen rank at a
# chosen 1-based boundary ordinal, the one fault shape that wedges an
# SPMD cohort mid-collective.

_BOUNDARY_FAULTS: Optional[Callable[[str], None]] = None


def set_boundary_fault_injector(fn: Optional[Callable[[str], None]]) -> None:
    global _BOUNDARY_FAULTS
    _BOUNDARY_FAULTS = fn


def boundary_fault(stage: str) -> None:
    if _BOUNDARY_FAULTS is not None:
        _BOUNDARY_FAULTS(stage)
