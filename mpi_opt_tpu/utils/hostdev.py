"""Pinning tiny host-side jax ops to the host CPU backend.

The host search layer (algorithms' sampling, the space's typed-value
materialization) runs scalar-to-few-KB jax ops between device
evaluations. On a tunneled accelerator each such op on the DEFAULT
device pays a full round trip, and that dominates end-to-end walls:
round 4 measured config-2's driver ASHA spending 56.7 s of a 57.8 s
search in one-row ``sample_unit`` programs, and config-4's driver TPE
spending ~100 s in per-dimension ``materialize_row`` ops — against
1.3 s of actual backend evaluation (probes/probe_driver_asha2.py,
probe_driver_tpe.py). jax.random is platform-invariant (threefry), so
CPU-pinning changes no sampled value — only where the op runs.
"""

from __future__ import annotations

import contextlib

import jax

_CPU = None
_CHECKED = False


def host_ops():
    """Context manager: run enclosed jax ops on the host CPU device.

    No-op where no CPU backend exists (pure-CPU test processes already
    default there; exotic platform sets without a cpu backend fall
    through to the default device).
    """
    global _CPU, _CHECKED
    if not _CHECKED:
        _CHECKED = True
        try:
            # local_devices, not devices: in a multi-process world
            # jax.devices() spans every process, and devices("cpu")[0]
            # is PROCESS 0's device — pinning another process's host
            # ops to it commits tiny arrays to a remote device and
            # kills that process (found by the 2-process fused-SHA
            # test: rank 1 died exactly there)
            _CPU = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            _CPU = None
    if _CPU is None:
        return contextlib.nullcontext()
    return jax.default_device(_CPU)


def request_cpu_devices(n: int) -> None:
    """Ask for ``n`` virtual CPU devices — must run BEFORE the first
    backend initialization (the same pre-init contract as platform
    pinning).

    Newer jax exposes this as the ``jax_num_cpu_devices`` config; pre-0.5
    jax (this container ships 0.4.x) only honors the XLA flag, which is
    read at backend init. Any device-count flag already present in
    XLA_FLAGS is REPLACED, not appended to: SPMD test workers inherit
    the parent pytest process's 8-device flag and must be able to
    override it with their own count.
    """
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
        return
    except AttributeError:
        pass
    # the config knob raises RuntimeError when the backend is already
    # up; the env-var route would just be silently ignored — keep the
    # loud post-init failure on both paths
    try:
        from jax._src import xla_bridge

        initialized = xla_bridge.backends_are_initialized()
    except Exception:
        initialized = False  # private-API probe: fall through quietly
    if initialized:
        raise RuntimeError(
            f"request_cpu_devices({n}) after the JAX backend initialized: "
            "XLA_FLAGS is only read at backend init, so the request would "
            "be silently ignored"
        )
    import os
    import re

    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags.strip() + f" --xla_force_host_platform_device_count={n}"
    ).strip()
