"""Pinning tiny host-side jax ops to the host CPU backend.

The host search layer (algorithms' sampling, the space's typed-value
materialization) runs scalar-to-few-KB jax ops between device
evaluations. On a tunneled accelerator each such op on the DEFAULT
device pays a full round trip, and that dominates end-to-end walls:
round 4 measured config-2's driver ASHA spending 56.7 s of a 57.8 s
search in one-row ``sample_unit`` programs, and config-4's driver TPE
spending ~100 s in per-dimension ``materialize_row`` ops — against
1.3 s of actual backend evaluation (probes/probe_driver_asha2.py,
probe_driver_tpe.py). jax.random is platform-invariant (threefry), so
CPU-pinning changes no sampled value — only where the op runs.
"""

from __future__ import annotations

import contextlib

import jax

_CPU = None
_CHECKED = False


def host_ops():
    """Context manager: run enclosed jax ops on the host CPU device.

    No-op where no CPU backend exists (pure-CPU test processes already
    default there; exotic platform sets without a cpu backend fall
    through to the default device).
    """
    global _CPU, _CHECKED
    if not _CHECKED:
        _CHECKED = True
        try:
            # local_devices, not devices: in a multi-process world
            # jax.devices() spans every process, and devices("cpu")[0]
            # is PROCESS 0's device — pinning another process's host
            # ops to it commits tiny arrays to a remote device and
            # kills that process (found by the 2-process fused-SHA
            # test: rank 1 died exactly there)
            _CPU = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            _CPU = None
    if _CPU is None:
        return contextlib.nullcontext()
    return jax.default_device(_CPU)
