"""Tracing/profiling hooks (SURVEY.md §5).

``profile_window(dir)`` wraps a measured region in a ``jax.profiler``
trace when a directory is given and is a zero-cost no-op otherwise, so
callers sprinkle it unconditionally:

    with profile_window(args.profile_dir):
        run_search(...)

The dump is TensorBoard-loadable (``xplane.pb`` under
``<dir>/plugins/profile/<run>/``); on this container's tunneled TPU the
device-side trace may be unavailable, in which case the host-side trace
(dispatch gaps, transfer waits) still lands and a warning is printed
rather than failing the run being measured.
"""

from __future__ import annotations

import contextlib
import sys


@contextlib.contextmanager
def profile_window(directory=None):
    if not directory:
        yield
        return
    import jax

    # guard only the trace start/stop: profiling must never kill (or
    # mask an exception from) the run being measured
    trace = None
    try:
        trace = jax.profiler.trace(str(directory))
        trace.__enter__()
    except Exception as e:
        print(f"[profile] trace start failed ({type(e).__name__}: {e}); "
              "continuing unprofiled", file=sys.stderr)
        trace = None
    try:
        yield
    finally:
        if trace is not None:
            try:
                trace.__exit__(None, None, None)
            except Exception as e:
                print(f"[profile] trace stop failed ({type(e).__name__}: {e})",
                      file=sys.stderr)
