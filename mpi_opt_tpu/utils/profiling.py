"""Tracing/profiling hooks (SURVEY.md §5).

``profile_window(dir)`` wraps a measured region in a ``jax.profiler``
trace when a directory is given and is a zero-cost no-op otherwise, so
callers sprinkle it unconditionally:

    with profile_window(args.profile_dir):
        run_search(...)

``profile_window(dir, launches=(A, B))`` defers the trace to a LAUNCH
WINDOW: the profiler starts when launch A begins and stops after launch
B completes (1-based, inclusive — ``--profile-launches`` on the CLI).
The fused drivers (and the driver loop, per batch) call ``launch_tick``
at the top of every launch; profiling a steady-state launch without the
cold-compile wall is what makes an XLA trace of the hot path readable.

``active()`` reports whether a jax profiler trace is CURRENTLY
recording — obs/trace.py gates its ``jax.profiler.TraceAnnotation``
wrappers on it, so span names ("train", "stage_in") appear on the XLA
timeline exactly when a trace is being taken and cost nothing
otherwise.

The dump is TensorBoard-loadable (``xplane.pb`` under
``<dir>/plugins/profile/<run>/``); on this container's tunneled TPU the
device-side trace may be unavailable, in which case the host-side trace
(dispatch gaps, transfer waits) still lands and a warning is printed
rather than failing the run being measured.
"""

from __future__ import annotations

import contextlib
import sys

_ACTIVE = False  # a jax profiler trace is currently recording
_WINDOW = None  # the installed _LaunchWindow, if any


def active() -> bool:
    return _ACTIVE


def _start(directory) -> bool:
    global _ACTIVE
    import jax

    try:
        jax.profiler.start_trace(str(directory))
    except Exception as e:
        print(
            f"[profile] trace start failed ({type(e).__name__}: {e}); "
            "continuing unprofiled",
            file=sys.stderr,
        )
        return False
    _ACTIVE = True
    return True


def _stop() -> None:
    global _ACTIVE
    if not _ACTIVE:
        return
    _ACTIVE = False
    import jax

    try:
        jax.profiler.stop_trace()
    except Exception as e:
        print(
            f"[profile] trace stop failed ({type(e).__name__}: {e})",
            file=sys.stderr,
        )


class _LaunchWindow:
    """Deferred profiler start/stop driven by launch ticks."""

    def __init__(self, directory, start: int, stop: int):
        self.directory = directory
        self.start = int(start)  # first profiled launch (1-based)
        self.stop = int(stop)  # last profiled launch (inclusive)
        self.n = 0

    def tick(self) -> None:
        self.n += 1
        if self.n == self.start:
            _start(self.directory)
        elif self.n == self.stop + 1:
            _stop()


def launch_tick() -> None:
    """Called at the top of every launch/batch; no-op unless a launch
    window is installed (the common case — one branch on a global)."""
    if _WINDOW is not None:
        _WINDOW.tick()


def parse_launch_window(spec: str):
    """``"A"`` or ``"A:B"`` -> (A, B), 1-based inclusive; ValueError on
    malformed/inverted input (the CLI maps it to a usage error)."""
    parts = spec.split(":")
    if len(parts) == 1:
        a = b = int(parts[0])
    elif len(parts) == 2:
        a, b = int(parts[0]), int(parts[1])
    else:
        raise ValueError(f"expected N or A:B, got {spec!r}")
    if a < 1 or b < a:
        raise ValueError(
            f"launch window must be 1-based and non-inverted, got {spec!r}"
        )
    return a, b


@contextlib.contextmanager
def profile_window(directory=None, launches=None):
    global _WINDOW
    if not directory:
        yield
        return
    if launches is not None:
        # guard only the install/teardown bookkeeping: profiling must
        # never kill (or mask an exception from) the run being measured
        _WINDOW = _LaunchWindow(directory, *launches)
        try:
            yield
        finally:
            _WINDOW = None
            _stop()  # window still open (fewer launches than B): close it
        return
    started = _start(directory)
    try:
        yield
    finally:
        if started:
            _stop()
