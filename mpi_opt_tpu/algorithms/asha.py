"""Asynchronous Successive Halving (ASHA), host-side bookkeeping.

Reference behavior (SURVEY.md §2 row 4; reference unreadable): trials
start at the lowest budget rung; when a trial finishes a rung, it is
promoted to the next rung if it ranks in the top 1/eta of all scores
recorded at that rung so far, otherwise it is stopped — asynchronously,
without waiting for the rung to fill (the reference coordinates this
with MPI messages between coordinator and ranks).

Here the promotion rule is evaluated on the host over numpy arrays
(scores at a rung are tiny); the *synchronous* population-wide variant —
``mpi_opt_tpu.train.fused_asha.fused_sha`` — runs the rung cuts
on-device through ``mpi_opt_tpu.ops.asha_cut``. Budgets are cumulative: a promoted
trial's ``budget`` is the next rung's total step count, and stateful
backends resume from the trial's saved state rather than retraining.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from mpi_opt_tpu.algorithms.base import Algorithm
from mpi_opt_tpu.utils.hostdev import host_ops
from mpi_opt_tpu.ops.asha import asha_rungs
from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import TrialResult, TrialStatus


class ASHA(Algorithm):
    name = "asha"

    def __init__(
        self,
        space: SearchSpace,
        seed: int = 0,
        max_trials: int = 64,
        min_budget: int = 1,
        max_budget: int = 27,
        eta: int = 3,
        id_base: int = 0,
    ):
        super().__init__(space, seed, id_base=id_base)
        self.max_trials = max_trials
        self.eta = eta
        self.rungs = asha_rungs(min_budget, max_budget, eta)
        # scores recorded per rung: rung index -> {trial_id: score}
        self.rung_scores: list[dict[int, float]] = [dict() for _ in self.rungs]
        self._suggested = 0
        self._promotable: list[int] = []  # trial ids awaiting their next rung
        self._outstanding: set[int] = set()

    # -- contract ---------------------------------------------------------

    def next_batch(self, n):
        out = []
        # trials whose results were lost to a checkpoint/restore cycle
        # get re-dispatched before anything else
        self._drain_requeue(out, n)
        # continuing trials next: they free memory sooner and drive the
        # search deeper (same priority the async rule gives promotions)
        while self._promotable and len(out) < n:
            tid = self._promotable.pop(0)
            t = self.trials[tid]
            t.status = TrialStatus.RUNNING
            out.append(t)
        # CPU-pinned sampling (utils.hostdev: one-row samples
        # on a tunneled default device dominated the whole search wall);
        # also covers BOHB's model-sampling override of _sample_fresh
        with host_ops():
            while len(out) < n and self._suggested < self.max_trials:
                key = jax.random.fold_in(jax.random.key(self.seed), self._suggested)
                # warm-start points (ingest_observations) take the first
                # fresh slots; they enter the rung race as ordinary
                # lowest-rung trials and must earn their promotions
                seed_u = self._next_seed_unit()
                unit = seed_u if seed_u is not None else self._sample_fresh(key)
                t = self._new_trial(unit, budget=self.rungs[0])
                t.status = TrialStatus.RUNNING
                out.append(t)
                self._suggested += 1
        self._outstanding.update(t.trial_id for t in out)
        return out

    def report_batch(self, results: Sequence[TrialResult]):
        for r in results:
            if not r.ok:
                # the failed trial leaves the rung race entirely: it is
                # discarded from _outstanding (so finished() can close
                # without waiting on it forever), never enters
                # rung_scores (a NaN there would promote — NaN compares
                # false against everything, so it always looks top-k),
                # and is never promotable
                self._outstanding.discard(r.trial_id)
                self._mark_failed(r)
                continue
            t = self.trials[r.trial_id]
            self._outstanding.discard(r.trial_id)
            t.record(r.score, r.step)
            rung = t.rung
            self.rung_scores[rung][t.trial_id] = float(r.score)
            if rung == len(self.rungs) - 1:
                t.status = TrialStatus.DONE
                continue
            if self._promotes(rung, r.score):
                t.rung = rung + 1
                t.budget = self.rungs[t.rung]
                t.status = TrialStatus.PAUSED
                self._promotable.append(t.trial_id)
            else:
                t.status = TrialStatus.STOPPED

    def finished(self):
        no_new = self._suggested >= self.max_trials
        return (
            no_new and not self._promotable and not self._outstanding and not self._requeue
        )

    def ingest_observations(self, observations):
        # best() seeding: the prior's best point joins the first cohort
        # at the lowest rung (cheap to verify, promoted only on merit)
        return self._ingest_seed_points(observations)

    # -- fresh-trial sampling (overridable: BOHB swaps in a model) --------

    def _sample_fresh(self, key) -> np.ndarray:
        """Unit-cube row for a brand-new trial. ASHA itself samples
        uniformly; model-based variants (algorithms/bohb.py) override
        this single point to keep the halving logic one source of truth."""
        return np.asarray(self.space.sample_unit(key, 1))[0]

    # -- promotion rule ---------------------------------------------------

    def _promotes(self, rung: int, score: float) -> bool:
        """Async rule: in the top 1/eta of scores recorded at this rung."""
        scores = np.array(list(self.rung_scores[rung].values()))
        k = max(1, int(np.ceil(len(scores) / self.eta)))
        # count of strictly-better scores < k  =>  within top-k
        return int((scores > score).sum()) < k

    # -- checkpoint -------------------------------------------------------

    def state_dict(self):
        d = super().state_dict()
        d["asha"] = {
            "suggested": self._suggested,
            "promotable": list(self._promotable),
            "rung_scores": [dict(r) for r in self.rung_scores],
        }
        return d

    def load_state_dict(self, state):
        super().load_state_dict(state)
        a = state["asha"]
        self._suggested = a["suggested"]
        self._promotable = list(a["promotable"])
        self.rung_scores = [
            {int(k): v for k, v in r.items()} for r in a["rung_scores"]
        ]
        self._outstanding = set()
        # in-flight trials (still RUNNING in the restored ledger) lost
        # their results with the old process; re-dispatch them rather
        # than dropping them as RUNNING forever
        self._requeue_running()
