"""BOHB: model-based Hyperband (Falkner, Klein & Hutter, 2018).

Extension beyond the reference's algorithm set (SURVEY.md §2 rows 4/6
attest ASHA and TPE separately; BOHB is their standard composition):
Hyperband's bracket schedule decides WHEN to stop trials, while a TPE
model decides WHERE to sample new ones — replacing each bracket's
uniform sampling with draws from the acquisition kernel fit on
completed observations.

Composition design (one source of truth, same as Hyperband's):

- brackets are ``ASHA`` instances via ``Hyperband._make_bracket``; the
  ONLY override is ``_sample_fresh`` — promotion rules, requeue-on-
  resume, and checkpointing all come along unchanged;
- the surrogate is the existing vectorized TPE acquisition
  (``ops.tpe.tpe_suggest``) — no second KDE implementation. BOHB fits
  it on the observations of the HIGHEST budget that has at least
  ``n_min`` of them (the paper's rule: models at bigger budgets are
  more informative, smaller budgets fill in first), falling back to
  uniform until any budget qualifies;
- a ``random_fraction`` of fresh trials stays uniform regardless
  (the paper's ρ, default 1/3), preserving Hyperband's worst-case
  guarantees over a misleading model.

Per-budget observation stores are bounded ring buffers like TPE's own.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from mpi_opt_tpu.algorithms.asha import ASHA
from mpi_opt_tpu.algorithms.hyperband import Hyperband
from mpi_opt_tpu.ops.tpe import TPEConfig, tpe_suggest
from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import TrialResult


def default_n_min(dim: int) -> int:
    """The paper's model-fit gate (Falkner et al. 2018 §3.1): N_min =
    d+1, and a KDE is fit once N_min + 2 = d + 3 observations exist at
    a budget (both the good and bad KDEs need points). Single-sourced:
    the host algorithm and the fused sweep both call this, so the
    qualification rule cannot drift between them."""
    return dim + 3


class ObsStore:
    """Per-budget ring buffers of (unit, score) observations plus the
    highest-qualified-budget rule — BOHB's model bookkeeping, shared by
    the host algorithm and the fused sweeps so the qualification and
    ring-wrap arithmetic cannot drift between them."""

    def __init__(self, dim: int, buffer_size: int, n_min: int):
        self.dim = dim
        self.buffer_size = buffer_size
        self.n_min = n_min
        self.budgets: dict[int, dict] = {}

    def ring(self, budget: int) -> dict:
        if budget not in self.budgets:
            self.budgets[budget] = {
                "unit": np.zeros((self.buffer_size, self.dim), np.float32),
                "score": np.zeros(self.buffer_size, np.float32),
                "valid": np.zeros(self.buffer_size, bool),
                "n": 0,
            }
        return self.budgets[budget]

    def add(self, budget: int, unit: np.ndarray, score: float) -> None:
        # Non-finite scores (diverged trials: NaN, or +/-inf from an
        # exploded loss) never enter the model: they would count toward
        # n_min qualification and poison the KDE moments/bandwidths.
        # Filtered HERE so the host and fused paths cannot disagree.
        if not np.isfinite(score):
            return
        s = self.ring(int(budget))
        slot = s["n"] % self.buffer_size
        s["unit"][slot] = unit
        s["score"][slot] = score
        s["valid"][slot] = True
        s["n"] += 1

    def model_budget(self):
        """Highest budget whose live observation count reaches n_min."""
        good = [
            b
            for b, s in self.budgets.items()
            if min(s["n"], self.buffer_size) >= self.n_min
        ]
        return max(good) if good else None

    # -- (de)serialization for algorithm checkpoints ----------------------

    def to_jsonable(self) -> dict:
        return {
            str(b): {
                "unit": s["unit"].tolist(),
                "score": s["score"].tolist(),
                "valid": s["valid"].tolist(),
                "n": s["n"],
            }
            for b, s in self.budgets.items()
        }

    def load_jsonable(self, d: dict) -> None:
        self.budgets = {
            int(k): {
                "unit": np.asarray(s["unit"], np.float32),
                "score": np.asarray(s["score"], np.float32),
                "valid": np.asarray(s["valid"], bool),
                "n": int(s["n"]),
            }
            for k, s in d.items()
        }


class _ModelBracket(ASHA):
    """ASHA bracket whose fresh trials come from the owning BOHB's
    model (uniform until it qualifies / for the random fraction)."""

    def __init__(self, owner: "BOHB", **kw):
        super().__init__(owner.space, **kw)
        self._owner = owner

    def _sample_fresh(self, key) -> np.ndarray:
        return self._owner._model_sample(key)


class BOHB(Hyperband):
    name = "bohb"

    def __init__(
        self,
        space: SearchSpace,
        seed: int = 0,
        max_budget: int = 81,
        eta: int = 3,
        random_fraction: float = 1 / 3,
        n_min: int | None = None,
        buffer_size: int = 512,
        config: TPEConfig = TPEConfig(),
    ):
        # model state must exist before Hyperband.__init__ builds the
        # brackets (their construction calls back into _make_bracket)
        self.random_fraction = random_fraction
        self.config = config
        self.buffer_size = buffer_size
        self.n_min = n_min if n_min is not None else default_n_min(space.dim)
        self.obs = ObsStore(space.dim, buffer_size, self.n_min)
        self._samples = 0  # fold-in counter for model/uniform draws
        super().__init__(space, seed=seed, max_budget=max_budget, eta=eta)
        self._suggest_fn = jax.jit(tpe_suggest, static_argnames=("n_suggest", "cfg"))

    def _bracket(self, **kw) -> ASHA:
        # Hyperband._make_bracket computes the per-bracket seed/id_base
        # scheme; overriding only the construction point keeps that
        # scheme single-sourced
        return _ModelBracket(self, **kw)

    # -- warm start --------------------------------------------------------

    def ingest_observations(self, observations):
        """Prior observations file into the per-budget stores at their
        recorded budgets (ObsStore drops non-finite scores itself), so a
        budget that accumulates ``n_min`` priors puts the KDE in charge
        of cohort sampling from bracket 0. Returns the finite count —
        what actually informed the model."""
        n = 0
        for o in observations:
            if not np.isfinite(o.score):
                continue
            self.obs.add(int(o.budget), np.asarray(o.unit, np.float32), float(o.score))
            n += 1
        return n

    # -- model ------------------------------------------------------------

    def _model_budget(self) -> int | None:
        return self.obs.model_budget()

    def _model_sample(self, key) -> np.ndarray:
        self._samples += 1
        k_choice, k_draw = jax.random.split(jax.random.fold_in(key, self._samples))
        budget = self._model_budget()
        if budget is None or float(jax.random.uniform(k_choice)) < self.random_fraction:
            return np.asarray(self.space.sample_unit(k_draw, 1))[0]
        s = self.obs.budgets[budget]
        sugg, _ = self._suggest_fn(
            k_draw, s["unit"], s["score"], s["valid"], n_suggest=1, cfg=self.config
        )
        return np.asarray(sugg)[0]

    # -- result flow -------------------------------------------------------

    def report_batch(self, results: Sequence[TrialResult]):
        # feed the per-budget model stores BEFORE the bracket applies its
        # halving rule; r.step is the cumulative budget the trial reached
        bracket = self.brackets[self._cur]
        for r in results:
            t = bracket.trials[r.trial_id]
            self.obs.add(int(r.step), t.unit, float(r.score))
        super().report_batch(results)

    # -- checkpoint -------------------------------------------------------

    def state_dict(self):
        d = super().state_dict()
        d["bohb"] = {
            "samples": self._samples,
            "buffer_size": self.buffer_size,
            "n_min": self.n_min,
            "obs": self.obs.to_jsonable(),
        }
        return d

    def load_state_dict(self, state):
        # a checkpoint written by plain hyperband has no model state;
        # refuse it with the same clear ValueError the R/eta and
        # buffer-size mismatches raise, not a bare KeyError
        if "bohb" not in state:
            raise ValueError("checkpoint is for hyperband, not bohb")
        b = state["bohb"]
        # validate BEFORE any mutation (matching Hyperband's R/eta
        # check): ring slot arithmetic (n % buffer_size) silently
        # corrupts — or IndexErrors mid-search — under a changed buffer
        # size, and a refusal must not leave the instance half-loaded
        saved = int(b.get("buffer_size", self.buffer_size))
        if saved != self.buffer_size:
            raise ValueError(
                f"checkpoint is for bohb(buffer_size={saved}), "
                f"not buffer_size={self.buffer_size}"
            )
        # n_min is the model-qualification threshold: resuming under a
        # different value silently changes WHEN the model engages.
        # setdefault (like momentum_dtype) keeps pre-upgrade checkpoints
        # loadable under the instance's current value
        saved_n_min = int(b.get("n_min", self.n_min))
        if saved_n_min != self.n_min:
            raise ValueError(
                f"checkpoint is for bohb(n_min={saved_n_min}), "
                f"not n_min={self.n_min}"
            )
        super().load_state_dict(state)
        self._samples = int(b["samples"])
        self.obs.load_jsonable(b["obs"])
