"""Algorithm registry (SURVEY.md §2 rows 3-6).

The registry mirrors the reference's named-algorithm selection on its
CLI (SURVEY.md §1 CLI layer; reference unreadable).
"""

from mpi_opt_tpu.algorithms.asha import ASHA
from mpi_opt_tpu.algorithms.base import Algorithm
from mpi_opt_tpu.algorithms.bohb import BOHB
from mpi_opt_tpu.algorithms.hyperband import Hyperband
from mpi_opt_tpu.algorithms.pbt import PBT
from mpi_opt_tpu.algorithms.random_search import RandomSearch
from mpi_opt_tpu.algorithms.tpe import TPE

ALGORITHMS: dict[str, type[Algorithm]] = {
    RandomSearch.name: RandomSearch,
    ASHA.name: ASHA,
    PBT.name: PBT,
    TPE.name: TPE,
    Hyperband.name: Hyperband,
    BOHB.name: BOHB,
}


def get_algorithm(name: str) -> type[Algorithm]:
    try:
        return ALGORITHMS[name]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; available: {sorted(ALGORITHMS)}"
        ) from None


__all__ = [
    "Algorithm",
    "RandomSearch",
    "ASHA",
    "Hyperband",
    "BOHB",
    "PBT",
    "TPE",
    "ALGORITHMS",
    "get_algorithm",
]
