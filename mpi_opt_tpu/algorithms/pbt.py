"""Population Based Training, host-side generational bookkeeping.

Reference behavior (SURVEY.md §2 row 5; reference unreadable): a fixed
population trains in parallel; each generation, losers copy winners'
weights + hyperparameters (exploit) and perturb them (explore). The
reference synchronizes this with ``MPI_Allgather`` of scores and
point-to-point weight transfers between ranks.

Host-side role here: this class drives PBT *through the generic backend
interface* — it emits one generation of member-trials at a time, and on
a full generation's results calls the same ``ops.pbt_exploit_explore``
kernel the TPU backend fuses on-device. Weight copies are communicated
to the backend as ``inherit_from`` metadata (trial_id of the source
member); a stateful backend maps that to a state copy — the TPU backend
instead realises it as a pure gather along the population axis without
any host involvement (see backends/tpu.py), which is the fast path.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mpi_opt_tpu.algorithms.base import Algorithm
from mpi_opt_tpu.utils.hostdev import host_ops
from mpi_opt_tpu.ops.pbt import PBTConfig, pbt_exploit_explore
from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import TrialResult, TrialStatus


class PBT(Algorithm):
    name = "pbt"

    def __init__(
        self,
        space: SearchSpace,
        seed: int = 0,
        population: int = 32,
        generations: int = 10,
        steps_per_generation: int = 200,
        config: PBTConfig = PBTConfig(),
    ):
        super().__init__(space, seed)
        self.population = population
        self.generations = generations
        self.steps_per_generation = steps_per_generation
        self.config = config
        self.generation = 0
        # slot -> current trial occupying it; a "trial" here is one
        # member-generation (fresh id per generation, as each may carry
        # new hparams/weights lineage)
        self._slots: list[int] = []
        self._pending: set[int] = set()  # spawned but unreported
        self._dispatch: list[int] = []  # spawned but not yet handed to a backend
        self._gen_scores = np.zeros(population, dtype=np.float32)
        self._unit = None  # float32[population, d] current hparams

    def _spawn_generation(self, unit: np.ndarray, inherit: np.ndarray | None):
        """Create this generation's member trials and queue them."""
        prev_slots = list(self._slots)
        self._slots = []
        for slot in range(self.population):
            t = self._new_trial(unit[slot], budget=self.steps_per_generation * (self.generation + 1))
            t.history = []
            if inherit is not None:
                src_slot = int(inherit[slot])
                t.params["__inherit_from__"] = prev_slots[src_slot]
                t.params["__slot__"] = slot
            else:
                t.params["__inherit_from__"] = None
                t.params["__slot__"] = slot
            self._slots.append(t.trial_id)
            self._pending.add(t.trial_id)
            self._dispatch.append(t.trial_id)

    def _pop_dispatch(self, n):
        out = []
        while self._dispatch and len(out) < n:
            t = self.trials[self._dispatch.pop(0)]
            t.status = TrialStatus.RUNNING
            out.append(t)
        return out

    def next_batch(self, n):
        if self.finished():
            return []
        if self._dispatch:
            return self._pop_dispatch(n)
        if self._pending:
            # fully dispatched, awaiting reports for this generation
            return []
        if self._unit is None:  # first generation
            with host_ops():  # tiny draw: no tunnel round trip
                key = jax.random.key(self.seed)
                self._unit = np.asarray(self.space.sample_unit(key, self.population))
            self._spawn_generation(self._unit, None)
            return self._pop_dispatch(n)
        # close the generation: exploit/explore via the shared kernel —
        # [P]-sized decision math, CPU-pinned for the same reason as
        # sampling (utils.hostdev rationale); the FUSED path runs the
        # same kernel on-device where it composes with the state gather
        with host_ops():
            key = jax.random.fold_in(jax.random.key(self.seed), 1000 + self.generation)
            new_unit, src_idx, _ = pbt_exploit_explore(
                key,
                jnp.asarray(self._unit),
                jnp.asarray(self._gen_scores),
                jnp.asarray(self.space.discrete_mask()),
                self.config,
            )
            self._unit = np.asarray(new_unit)
            src_idx = np.asarray(src_idx)
        self.generation += 1
        if self.finished():
            return []
        self._spawn_generation(self._unit, np.asarray(src_idx))
        return self._pop_dispatch(n)

    def report_batch(self, results: Sequence[TrialResult]):
        for r in results:
            if not r.ok:
                # a failed member scores -inf for the generation: it
                # ranks at the bottom of the exploit cut (rank_descending
                # sorts -inf last), so the next generation REPLACES it —
                # hparams and state copied from a surviving winner. NaN
                # would be wrong here: it also sorts last under argsort,
                # but any downstream arithmetic on the score vector
                # would propagate it
                t = self._mark_failed(r)
                self._pending.discard(r.trial_id)
                self._gen_scores[t.params["__slot__"]] = -np.inf
                continue
            t = self.trials[r.trial_id]
            t.record(r.score, r.step)
            t.status = TrialStatus.DONE
            self._pending.discard(r.trial_id)
            self._gen_scores[t.params["__slot__"]] = r.score

    def finished(self):
        return self.generation >= self.generations and not self._pending

    # -- checkpoint -------------------------------------------------------

    def state_dict(self):
        d = super().state_dict()
        d["pbt"] = {
            "generation": self.generation,
            "slots": list(self._slots),
            "gen_scores": self._gen_scores.tolist(),
            "unit": None if self._unit is None else self._unit.tolist(),
            # everything unreported, in slot order, for re-dispatch on resume
            "pending": [t for t in self._slots if t in self._pending],
            # per-member metadata, which base-class trial reconstruction
            # (unit -> params re-materialization) does not preserve
            "inherit": {
                str(tid): self.trials[tid].params.get("__inherit_from__")
                for tid in self._slots
                if tid in self.trials
            },
        }
        return d

    def load_state_dict(self, state):
        super().load_state_dict(state)
        p = state["pbt"]
        self.generation = p["generation"]
        self._slots = list(p["slots"])
        self._gen_scores = np.asarray(p["gen_scores"], dtype=np.float32)
        self._unit = None if p["unit"] is None else np.asarray(p["unit"], dtype=np.float32)
        # restore current-generation member metadata
        inherit = p.get("inherit", {})
        for slot, tid in enumerate(self._slots):
            if tid in self.trials:
                self.trials[tid].params["__slot__"] = slot
                self.trials[tid].params["__inherit_from__"] = inherit.get(str(tid))
        # in-flight results died with the old process: re-dispatch them
        pending = [int(t) for t in p.get("pending", [])]
        self._pending = set(pending)
        self._dispatch = list(pending)
