"""Host-side algorithm interface: the suggest→evaluate→report contract.

Reference parity (SURVEY.md §1, §3; reference unreadable — contract from
BASELINE.json): the reference's search driver runs a suggest→evaluate→
report loop over pluggable algorithms; its Coordinator dispatches
suggested trials to MPIWorker ranks and feeds results back.

Design difference: our API is *pull-based* — the driver asks the
algorithm for the next batch of trials sized to the backend's capacity
(`next_batch(n)`), instead of the coordinator pushing one trial per idle
rank. This shape serves the TPU backend, whose natural unit of work is a
whole vmapped population, while degrading gracefully to n=1 for serial
CPU evaluation. The decision *math* for ASHA/PBT/TPE lives in
``mpi_opt_tpu.ops`` as jittable kernels; these classes own bookkeeping
only, so the same kernels serve both the host loop and the fully
on-device loop.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np

from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import Trial, TrialResult, TrialStatus


@dataclasses.dataclass(frozen=True)
class Observation:
    """One prior (point, score) fact offered to an algorithm as warm
    start — NOT a trial of the current search. ``unit`` is the canonical
    unit-cube row; ``budget`` is the step count the score was measured
    at (budget-aware consumers like BOHB file it per-budget)."""

    unit: np.ndarray
    score: float
    budget: int = 0
    #: optional raw objective vector (ISSUE 17): present when the prior
    #: record journaled multi-objective ``scores``; ``score`` stays the
    #: scalarized authoritative value every scalar consumer ranks by
    scores: tuple = None


def best_finite(items, key):
    """The item with the highest FINITE key, else the first item.

    The one best-pick rule, shared by Algorithm.best, Hyperband.best and
    the fused bracket loop so host and fused paths cannot drift: a
    diverged trial's score (NaN, or +/-inf from an exploded loss) never
    wins — Python's max never displaces a NaN front-runner (`x > nan`
    is False) and +inf would beat every real score — matching the
    isfinite gate BOHB's ObsStore applies to model inputs. Only an
    all-diverged item set returns a diverged item (the first), so
    callers still see that *something* ran, with the non-finite key
    left visible as the flag. Returns None for an empty item list.
    """
    items = list(items)
    finite = [it for it in items if np.isfinite(key(it))]
    if finite:
        return max(finite, key=key)
    return items[0] if items else None


class Algorithm(abc.ABC):
    """Base class for search algorithms.

    Score convention: HIGHER IS BETTER. Drivers translate minimization
    problems by negating the objective before reporting.
    """

    name: str = "base"

    def __init__(self, space: SearchSpace, seed: int = 0, id_base: int = 0):
        self.space = space
        self.seed = seed
        self.trials: dict[int, Trial] = {}
        # id_base partitions the trial-id space when several Algorithm
        # instances share one search/backend (Hyperband/BOHB brackets):
        # stateful backends key their ledgers on trial_id, so two
        # brackets both starting at 0 would silently alias — bracket 2's
        # trial 0 warm-resumes bracket 1's trained state instead of
        # training fresh (see Backend.reset for the one-search form of
        # the same hazard)
        self._next_id = id_base
        self._requeue: list[int] = []  # in-flight trials recovered from a checkpoint
        self._seed_units: list[np.ndarray] = []  # warm-start points to try first

    # -- core contract ----------------------------------------------------

    @abc.abstractmethod
    def next_batch(self, n: int) -> list[Trial]:
        """Up to ``n`` trials to evaluate next (new or continuing).

        May return fewer (e.g. budget exhausted, or a generational
        algorithm mid-generation). Empty list + ``not finished()`` means
        "waiting on outstanding results".
        """

    @abc.abstractmethod
    def report_batch(self, results: Sequence[TrialResult]) -> None:
        """Record completed evaluations and update search state."""

    @abc.abstractmethod
    def finished(self) -> bool:
        """True when the search has no more work to hand out."""

    # -- warm start (ledger/warmstart.py): the ingestion contract ---------

    def ingest_observations(self, observations: Sequence[Observation]) -> int:
        """Absorb prior-sweep observations BEFORE the search starts.

        Contract: called at most once, before the first ``next_batch``;
        observations are facts about THIS space (the caller has already
        verified space compatibility via the space hash) but are NOT
        trials of this search — they must not consume trial ids, budget
        slots, or appear in ``best()``. Returns how many observations
        actually informed the search, so callers can log an honest
        count. The base default accepts none (0); model-based
        algorithms override to build priors (TPE ring, BOHB per-budget
        stores), samplers override to seed their first suggestions with
        the prior's best points (``_ingest_seed_points``).
        """
        return 0

    def _ingest_seed_points(self, observations: Sequence[Observation], k: int = 1) -> int:
        """Shared best()-seeding: queue the top-``k`` finite-scored prior
        points to be suggested before any fresh sampling. Non-finite
        scores never seed (a diverged prior point is exactly what a new
        sweep must not start from)."""
        finite = [o for o in observations if np.isfinite(o.score)]
        finite.sort(key=lambda o: o.score, reverse=True)
        self._seed_units = [
            np.asarray(o.unit, dtype=np.float32) for o in finite[:k]
        ]
        return len(self._seed_units)

    def _next_seed_unit(self) -> Optional[np.ndarray]:
        """Pop the next queued warm-start point (None when drained)."""
        return self._seed_units.pop(0) if self._seed_units else None

    # -- shared bookkeeping ----------------------------------------------

    def _new_trial(self, unit_row: np.ndarray, budget: int = 0) -> Trial:
        t = Trial(
            trial_id=self._next_id,
            params=self.space.materialize_row(np.asarray(unit_row)),
            unit=np.asarray(unit_row, dtype=np.float32),
            budget=budget,
        )
        self._next_id += 1
        self.trials[t.trial_id] = t
        return t

    def _drain_requeue(self, out: list, n: int) -> None:
        """Re-dispatch checkpoint-recovered in-flight trials before any
        new work (their results died with the old process)."""
        while self._requeue and len(out) < n:
            t = self.trials[self._requeue.pop(0)]
            t.status = TrialStatus.RUNNING
            out.append(t)

    def _requeue_running(self) -> None:
        """Recover trials left RUNNING by a checkpoint/restore cycle.

        Without this, a state captured between next_batch and
        report_batch resumes with suggested > done: next_batch returns
        [] while finished() is False and the driver deadlocks.
        """
        self._requeue = [
            t.trial_id for t in self.trials.values() if t.status == TrialStatus.RUNNING
        ]

    def _mark_failed(self, r: TrialResult) -> Trial:
        """Shared failed-report bookkeeping: flag the trial FAILED and
        keep the error visible on the ledger. The trial's score is NOT
        recorded (a failed result's score is NaN-family by contract), so
        ``best()`` can never surface it."""
        t = self.trials[r.trial_id]
        t.status = TrialStatus.FAILED
        t.error = r.error
        return t

    def best(self) -> Optional[Trial]:
        # FAILED trials are excluded even when an earlier rung left a
        # finite score behind: a trial whose latest evaluation failed is
        # not a result an operator can act on
        scored = [
            t
            for t in self.trials.values()
            if t.score is not None and t.status != TrialStatus.FAILED
        ]
        return best_finite(scored, key=lambda t: t.score)

    @property
    def n_trials(self) -> int:
        return len(self.trials)

    # -- checkpoint/resume (SURVEY.md §2 row 13) -------------------------

    def state_dict(self) -> dict:
        return {
            "next_id": self._next_id,
            "seed": self.seed,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "unit": t.unit.tolist(),
                    "budget": t.budget,
                    "rung": t.rung,
                    "status": t.status.value,
                    "score": t.score,
                    "history": t.history,
                    "error": t.error,
                }
                for t in self.trials.values()
            ],
        }

    def load_state_dict(self, state: dict) -> None:
        self._next_id = state["next_id"]
        self.seed = state["seed"]
        self.trials = {}
        for rec in state["trials"]:
            unit = np.asarray(rec["unit"], dtype=np.float32)
            t = Trial(
                trial_id=rec["trial_id"],
                params=self.space.materialize_row(unit),
                unit=unit,
                budget=rec["budget"],
                rung=rec["rung"],
                status=TrialStatus(rec["status"]),
            )
            t.score = rec["score"]
            t.history = [tuple(h) for h in rec["history"]]
            t.error = rec.get("error")  # pre-upgrade checkpoints: None
            self.trials[t.trial_id] = t
