"""Random search (SURVEY.md §2 row 3): i.i.d. sampling over the space."""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from mpi_opt_tpu.algorithms.base import Algorithm
from mpi_opt_tpu.utils.hostdev import host_ops
from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import TrialResult, TrialStatus


class RandomSearch(Algorithm):
    name = "random"

    def __init__(self, space: SearchSpace, seed: int = 0, max_trials: int = 16, budget: int = 1):
        super().__init__(space, seed)
        self.max_trials = max_trials
        self.budget = budget  # steps/epochs per trial, passed to the backend
        self._suggested = 0
        self._done = 0

    def ingest_observations(self, observations):
        # warm start = try the prior sweep's best point before any
        # random draw; the stream of random suggestions is unchanged
        # (seeded points REPLACE draws positionally, and the fold-in
        # counter keeps advancing per suggestion either way)
        return self._ingest_seed_points(observations)

    def next_batch(self, n):
        out = []
        self._drain_requeue(out, n)
        take = min(n - len(out), self.max_trials - self._suggested)
        if take <= 0:
            return out
        with host_ops():  # tiny draw: never pay a tunnel round trip
            key = jax.random.fold_in(jax.random.key(self.seed), self._suggested)
            unit = np.asarray(self.space.sample_unit(key, take))
        for i in range(take):
            seed_u = self._next_seed_unit()
            t = self._new_trial(seed_u if seed_u is not None else unit[i], budget=self.budget)
            t.status = TrialStatus.RUNNING
            out.append(t)
        self._suggested += take
        return out

    def report_batch(self, results: Sequence[TrialResult]):
        for r in results:
            if not r.ok:
                # a failed trial still consumed its suggestion slot: it
                # counts toward completion so the search terminates, it
                # just never scores (best() skips FAILED)
                self._mark_failed(r)
                self._done += 1
                continue
            t = self.trials[r.trial_id]
            t.record(r.score, r.step)
            t.status = TrialStatus.DONE
            self._done += 1

    def finished(self):
        return self._done >= self.max_trials

    def state_dict(self):
        d = super().state_dict()
        d["random"] = {"suggested": self._suggested, "done": self._done}
        return d

    def load_state_dict(self, state):
        super().load_state_dict(state)
        self._suggested = state["random"]["suggested"]
        self._done = state["random"]["done"]
        self._requeue_running()
