"""Hyperband: brackets of successive halving over a budget grid.

Li et al. 2018. ASHA (this package's `algorithms.asha`) is the
asynchronous core of one bracket; Hyperband hedges ASHA's single
aggressiveness setting by running `s_max+1` brackets that trade number
of configurations against starting budget — bracket s starts
`ceil((s_max+1)/(s+1) * eta^s)` trials at budget `R * eta^-s`.

Composition design: each bracket IS an `ASHA` instance (same promotion
rule, same checkpoint recovery); Hyperband runs them sequentially and
aggregates. This keeps one source of truth for the halving logic — the
driver contract, requeue-on-resume behavior, and the on-device
`ops.asha_cut` path all come along for free. With R=81, eta=3 the
bracket plan is the paper's Table 1: (81@1, 34@3, 15@9, 8@27, 5@81).

The fused on-device variant is `train.fused_asha.fused_hyperband`.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from mpi_opt_tpu.algorithms.asha import ASHA
from mpi_opt_tpu.algorithms.base import Algorithm, best_finite
from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import TrialResult


def bracket_plan(max_budget: int, eta: int) -> list[tuple[int, int]]:
    """[(n_trials, start_budget)] per bracket, most-exploratory first."""
    # s_max = floor(log_eta(R)) by integer division: float log loses a
    # whole bracket when R is an exact eta power (log3(243) computes as
    # 4.999...), silently dropping the most-exploratory bracket
    s_max, b = 0, max_budget
    while b >= eta:
        b //= eta
        s_max += 1
    plan = []
    for s in range(s_max, -1, -1):
        n = int(np.ceil((s_max + 1) / (s + 1) * eta**s))
        r = max(1, round(max_budget / eta**s))
        plan.append((n, r))
    return plan


class Hyperband(Algorithm):
    name = "hyperband"

    def __init__(
        self,
        space: SearchSpace,
        seed: int = 0,
        max_budget: int = 81,
        eta: int = 3,
    ):
        super().__init__(space, seed)
        self.eta = eta
        self.max_budget = max_budget
        self.brackets = [
            self._make_bracket(b, n, r)
            for b, (n, r) in enumerate(bracket_plan(max_budget, eta))
        ]
        self._cur = 0

    def _make_bracket(self, b: int, n: int, r: int) -> ASHA:
        """The per-bracket scheme, single-sourced for every subclass:
        seeds are decorrelated per bracket (deterministic), and id_base
        partitions the trial-id space so brackets sharing one stateful
        backend can never alias each other's ledger entries. Subclasses
        override ``_bracket`` (the construction point), not this."""
        return self._bracket(
            seed=self.seed + 7919 * b,
            max_trials=n,
            min_budget=r,
            max_budget=self.max_budget,
            eta=self.eta,
            id_base=b * 1_000_000,
        )

    def _bracket(self, **kw) -> ASHA:
        return ASHA(self.space, **kw)

    # -- contract ---------------------------------------------------------

    def _current(self) -> ASHA | None:
        while self._cur < len(self.brackets) and self.brackets[self._cur].finished():
            self._cur += 1
        return self.brackets[self._cur] if self._cur < len(self.brackets) else None

    def next_batch(self, n):
        b = self._current()
        return [] if b is None else b.next_batch(n)

    def report_batch(self, results: Sequence[TrialResult]):
        # brackets run sequentially, so outstanding results always
        # belong to the bracket that is current right now
        self.brackets[self._cur].report_batch(results)

    def finished(self):
        return self._current() is None

    # -- aggregation across brackets --------------------------------------

    def best(self):
        # a bracket whose trials ALL diverged reports a non-finite best;
        # the cross-bracket pick applies the same rule as within brackets
        bests = [b.best() for b in self.brackets]
        return best_finite([t for t in bests if t is not None], key=lambda t: t.score)

    @property
    def n_trials(self) -> int:
        return sum(b.n_trials for b in self.brackets)

    # -- checkpoint -------------------------------------------------------

    def state_dict(self):
        return {
            "hyperband": {
                "cur": self._cur,
                "max_budget": self.max_budget,
                "eta": self.eta,
                "brackets": [b.state_dict() for b in self.brackets],
            }
        }

    def load_state_dict(self, state):
        h = state["hyperband"]
        if h["max_budget"] != self.max_budget or h["eta"] != self.eta:
            raise ValueError(
                f"checkpoint is for hyperband(R={h['max_budget']}, eta={h['eta']}), "
                f"not (R={self.max_budget}, eta={self.eta})"
            )
        self._cur = h["cur"]
        for b, s in zip(self.brackets, h["brackets"]):
            b.load_state_dict(s)
