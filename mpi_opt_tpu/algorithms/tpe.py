"""TPE host wrapper around the vectorized acquisition kernel.

Reference behavior (SURVEY.md §2 row 6; reference unreadable): suggest
points maximizing l(x)/g(x) over Parzen estimators of good/bad trials.

The math lives in ``mpi_opt_tpu.ops.tpe.tpe_suggest`` (fixed-shape ring
buffer, batched candidate scoring). This class owns the buffer and the
trial ledger; the kernel is jitted once and reused for the whole search
regardless of how much history accumulates.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from mpi_opt_tpu.algorithms.base import Algorithm
from mpi_opt_tpu.utils.hostdev import host_ops
from mpi_opt_tpu.ops.tpe import TPEConfig, tpe_suggest
from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import TrialResult, TrialStatus


class TPE(Algorithm):
    name = "tpe"

    def __init__(
        self,
        space: SearchSpace,
        seed: int = 0,
        max_trials: int = 64,
        budget: int = 1,
        n_startup: int = 10,  # pure-random warmup before the surrogate kicks in
        buffer_size: int = 512,
        config: TPEConfig = TPEConfig(),
    ):
        super().__init__(space, seed)
        self.max_trials = max_trials
        self.budget = budget
        self.n_startup = n_startup
        self.config = config
        self.buffer_size = buffer_size
        self._obs_unit = np.zeros((buffer_size, space.dim), dtype=np.float32)
        self._obs_score = np.zeros(buffer_size, dtype=np.float32)
        self._valid = np.zeros(buffer_size, dtype=bool)
        self._n_obs = 0
        self._suggested = 0
        self._done = 0
        self._suggest_fn = jax.jit(tpe_suggest, static_argnames=("n_suggest", "cfg"))

    def ingest_observations(self, observations):
        """Prior-sweep observations become surrogate priors: they fill
        the observation ring exactly as live reports do, count toward
        ``n_startup`` (enough priors engage the surrogate from the very
        first suggestion), and never touch the trial ledger — they are
        observations, not trials, so ``best()``/``n_trials``/budget
        accounting are unaffected. Ascending score order: if the prior
        overflows the ring, the wrap evicts the WORST observations."""
        finite = [o for o in observations if np.isfinite(o.score)]
        finite.sort(key=lambda o: o.score)
        for o in finite:
            slot = self._n_obs % self.buffer_size
            self._obs_unit[slot] = np.asarray(o.unit, dtype=np.float32)
            self._obs_score[slot] = o.score
            self._valid[slot] = True
            self._n_obs += 1
        return len(finite)

    def next_batch(self, n):
        out = []
        self._drain_requeue(out, n)
        # the surrogate can only ever score n_candidates points, so a
        # backend capacity above that is clamped (not an IndexError)
        take = min(n - len(out), self.max_trials - self._suggested, self.config.n_candidates)
        if take <= 0:
            return out
        # CPU-pinned: the acquisition over a 512-row buffer is trivial
        # compute, and running it tunnel-side costs a round trip per
        # suggest batch (utils.hostdev rationale)
        with host_ops():
            key = jax.random.fold_in(jax.random.key(self.seed), self._suggested)
            if self._n_obs < self.n_startup:
                unit = np.asarray(self.space.sample_unit(key, take))
            else:
                # round n_suggest up to a power of two so varying batch
                # remainders hit at most log2(capacity) compiled variants
                block = 1 << (take - 1).bit_length()
                sugg, _ = self._suggest_fn(
                    key,
                    self._obs_unit,
                    self._obs_score,
                    self._valid,
                    n_suggest=min(block, self.config.n_candidates),
                    cfg=self.config,
                )
                unit = np.asarray(sugg[:take])
        for i in range(take):
            t = self._new_trial(unit[i], budget=self.budget)
            t.status = TrialStatus.RUNNING
            out.append(t)
        self._suggested += take
        return out

    def report_batch(self, results: Sequence[TrialResult]):
        for r in results:
            if not r.ok:
                # failed trials never enter the observation ring: a NaN
                # score would poison the Parzen moments, and counting it
                # toward n_startup would engage the surrogate on garbage
                self._mark_failed(r)
                self._done += 1
                continue
            t = self.trials[r.trial_id]
            t.record(r.score, r.step)
            t.status = TrialStatus.DONE
            slot = self._n_obs % self.buffer_size
            self._obs_unit[slot] = t.unit
            self._obs_score[slot] = r.score
            self._valid[slot] = True
            self._n_obs += 1
            self._done += 1

    def finished(self):
        return self._done >= self.max_trials

    # -- checkpoint -------------------------------------------------------

    def state_dict(self):
        d = super().state_dict()
        d["tpe"] = {
            "obs_unit": self._obs_unit.tolist(),
            "obs_score": self._obs_score.tolist(),
            "valid": self._valid.tolist(),
            "n_obs": self._n_obs,
            "suggested": self._suggested,
            "done": self._done,
        }
        return d

    def load_state_dict(self, state):
        super().load_state_dict(state)
        t = state["tpe"]
        self._obs_unit = np.asarray(t["obs_unit"], dtype=np.float32)
        self._obs_score = np.asarray(t["obs_score"], dtype=np.float32)
        self._valid = np.asarray(t["valid"], dtype=bool)
        self._n_obs = t["n_obs"]
        self._suggested = t["suggested"]
        self._done = t["done"]
        self._requeue_running()
