"""Search-space definition, array-first.

Every domain maps to/from the unit cube so that whole populations of
hyperparameters are plain ``float32[n, d]`` arrays on device:

- algorithms (TPE acquisition, PBT explore perturbations) operate on the
  unit-cube representation with pure ``jax.numpy`` ops and therefore
  ``vmap``/``jit`` cleanly;
- the typed value view (log-scaled floats, ints, categorical choices) is
  materialised only at the edge, either host-side (``materialize``) or
  on-device (``from_unit`` is itself jittable).

Reference parity: mpi_opt's search-space (uniform / log-uniform /
choice parameters fed to its optimizer; reference unreadable, surface per
SURVEY.md §2 row 3) — re-designed so sampling is a single vectorized op
instead of per-trial Python objects.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _plain(v):
    """One value -> a plain JSON scalar (bool/int/float/str/None), repr
    for anything exotic. Canonicalization rule shared by ``spec`` and
    ``canonical_params``: a live value and its JSON round trip must
    produce identical bytes (json floats round-trip exactly), so ledger
    replay can verify params by key equality. bool first: it IS an int."""
    if isinstance(v, (bool, str)) or v is None:
        return v
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        return float(v)
    return repr(v)


class Domain:
    """Base class for one hyperparameter's domain.

    Subclasses define a bijection (up to quantization) between the unit
    interval [0, 1] and the typed value space.
    """

    def from_unit(self, u: jax.Array) -> jax.Array:
        """Map unit-interval array -> value array (jittable)."""
        raise NotImplementedError

    def to_unit(self, v: jax.Array) -> jax.Array:
        """Map value array -> unit interval (jittable)."""
        raise NotImplementedError

    def materialize(self, v: Any):
        """Convert a scalar array element to the Python-typed value."""
        return float(v)

    @property
    def discrete(self) -> bool:
        return False


@dataclasses.dataclass(frozen=True)
class Uniform(Domain):
    low: float
    high: float

    def __post_init__(self):
        if not self.low < self.high:
            raise ValueError(f"Uniform requires low < high, got [{self.low}, {self.high}]")

    def from_unit(self, u):
        return self.low + (self.high - self.low) * u

    def to_unit(self, v):
        return (v - self.low) / (self.high - self.low)


@dataclasses.dataclass(frozen=True)
class LogUniform(Domain):
    low: float
    high: float

    def __post_init__(self):
        if self.low <= 0 or self.high <= 0:
            raise ValueError("LogUniform bounds must be positive")
        if not self.low < self.high:
            raise ValueError(f"LogUniform requires low < high, got [{self.low}, {self.high}]")

    def from_unit(self, u):
        lo, hi = np.log(self.low), np.log(self.high)
        return jnp.exp(lo + (hi - lo) * u)

    def to_unit(self, v):
        lo, hi = np.log(self.low), np.log(self.high)
        return (jnp.log(v) - lo) / (hi - lo)


@dataclasses.dataclass(frozen=True)
class IntUniform(Domain):
    low: int
    high: int  # inclusive

    def __post_init__(self):
        if not self.low <= self.high:
            raise ValueError(f"IntUniform requires low <= high, got [{self.low}, {self.high}]")

    def from_unit(self, u):
        n = self.high - self.low + 1
        idx = jnp.clip(jnp.floor(u * n), 0, n - 1)
        return self.low + idx

    def to_unit(self, v):
        n = self.high - self.low + 1
        # centre of the bucket, so from_unit(to_unit(v)) == v
        return ((v - self.low) + 0.5) / n

    def materialize(self, v):
        return int(v)

    @property
    def discrete(self):
        return True


@dataclasses.dataclass(frozen=True)
class Choice(Domain):
    options: tuple

    def __init__(self, options: Sequence[Any]):
        object.__setattr__(self, "options", tuple(options))

    def from_unit(self, u):
        n = len(self.options)
        return jnp.clip(jnp.floor(u * n), 0, n - 1)

    def to_unit(self, v):
        # v is the DEVICE representation: the option index, not the
        # option value (use SearchSpace.params_to_unit for typed values —
        # e.g. for Choice([True, False]) the value True is index 0, but
        # numerically True == 1 and would silently encode index 1 here)
        return (v + 0.5) / len(self.options)

    def value_to_index(self, value) -> int:
        for i, opt in enumerate(self.options):
            if opt is value or (type(opt) is type(value) and opt == value):
                return i
        raise ValueError(f"{value!r} is not one of {self.options}")

    def materialize(self, v):
        return self.options[int(v)]

    @property
    def discrete(self):
        return True


class SearchSpace:
    """An ordered mapping name -> Domain with vectorized sampling.

    The canonical array layout is ``float32[..., d]`` in unit-cube
    coordinates, with dimension order = insertion order of ``domains``.
    """

    def __init__(self, domains: Mapping[str, Domain]):
        self.domains = dict(domains)
        self.names = list(self.domains.keys())

    @property
    def dim(self) -> int:
        return len(self.names)

    # -- sampling ---------------------------------------------------------

    def sample_unit(self, key: jax.Array, n: int) -> jax.Array:
        """Uniform sample in the unit cube: ``float32[n, d]``."""
        return jax.random.uniform(key, (n, self.dim), dtype=jnp.float32)

    def from_unit(self, u: jax.Array) -> dict[str, jax.Array]:
        """Unit-cube array ``[..., d]`` -> dict of typed value arrays.

        Jittable; used on-device to turn a population matrix into the
        per-member hyperparameter arrays fed to the train step.

        The input is coerced to a jax array FIRST: domain maps mix
        float64 numpy scalars into their arithmetic (e.g. LogUniform's
        ``np.log`` bounds), and on a plain numpy ``u`` (a
        snapshot-restored cohort) NumPy would run the intermediate math
        in float64 and round to float32 only at the final jnp op —
        double rounding that flips the last ulp of values like the
        learning rate versus the all-float32 on-device path. A resumed
        sweep must map bit-identical hparams to the run it resumes.
        """
        u = jnp.asarray(u)
        return {
            name: dom.from_unit(u[..., i])
            for i, (name, dom) in enumerate(self.domains.items())
        }

    def to_unit(self, values: Mapping[str, jax.Array]) -> jax.Array:
        """Dict of *device-representation* arrays -> unit cube ``[..., d]``.

        Jittable inverse of ``from_unit``. For Choice domains the device
        representation is the option index; to encode typed Python
        values (option objects, bools) use ``params_to_unit``.
        """
        cols = [
            self.domains[name].to_unit(jnp.asarray(values[name], jnp.float32))
            for name in self.names
        ]
        return jnp.stack(cols, axis=-1)

    def params_to_unit(self, params: Mapping[str, Any]) -> np.ndarray:
        """Typed-value params dict (one point) -> unit-cube row (host side)."""
        from mpi_opt_tpu.utils.hostdev import host_ops

        row = np.zeros(self.dim, dtype=np.float32)
        with host_ops():  # scalar ops: never pay an accelerator round trip
            for i, (name, dom) in enumerate(self.domains.items()):
                v = params[name]
                if isinstance(dom, Choice):
                    v = dom.value_to_index(v)
                row[i] = float(np.asarray(dom.to_unit(jnp.asarray(float(v)))))
        return row

    def sample(self, key: jax.Array, n: int) -> dict[str, jax.Array]:
        """Sample n points, returned as typed value arrays."""
        return self.from_unit(self.sample_unit(key, n))

    # -- host-side edges --------------------------------------------------

    def materialize_row(self, u_row: np.ndarray) -> dict[str, Any]:
        """One unit-cube row -> a plain-Python hparam dict (host side).

        CPU-pinned: this runs one tiny ``from_unit`` op per dimension
        per trial — on a tunneled accelerator's default device that is
        a round trip each, which round 4 measured as ~100 s of a 256-
        trial driver TPE search (utils.hostdev).
        """
        from mpi_opt_tpu.utils.hostdev import host_ops

        out = {}
        with host_ops():
            for i, (name, dom) in enumerate(self.domains.items()):
                v = np.asarray(dom.from_unit(jnp.asarray(u_row[i])))
                out[name] = dom.materialize(v)
        return out

    def discrete_mask(self) -> np.ndarray:
        """bool[d]: which dims are discrete (used by TPE/PBT perturbation)."""
        return np.array([d.discrete for d in self.domains.values()])

    # -- durable identity (ledger/warm-start; SURVEY.md §5) ---------------

    def spec(self) -> list[dict]:
        """JSON-able description of the space, in dimension order.

        This is the space's DURABLE identity: the ledger header records
        its hash so a resume or warm-start against a ledger written for
        a different space is refused instead of silently misdecoding
        unit rows. Dataclass fields capture each domain's full bounds;
        Choice options go through ``_plain`` so non-JSON option objects
        degrade to their repr deterministically.

        Multi-objective sweeps (ISSUE 17) journal a sibling
        ``objective_spec`` (objectives.ObjectiveSpec.spec — names,
        directions, constraint bounds) in the same header, top-level
        beside ``space_spec``: the space says WHERE the sweep searched,
        the objective spec says WHAT it optimized. Both ride outside
        the hashed config identity; objective identity enters identity
        through the config's ``objectives`` string instead.
        """
        out = []
        for name, dom in self.domains.items():
            d: dict[str, Any] = {"name": name, "kind": type(dom).__name__}
            for f in dataclasses.fields(dom):
                v = getattr(dom, f.name)
                d[f.name] = [_plain(o) for o in v] if isinstance(v, tuple) else _plain(v)
            out.append(d)
        return out

    def space_hash(self) -> str:
        """Stable short digest of ``spec()`` (order- and value-exact)."""
        payload = json.dumps(self.spec(), sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    def canonical_params(self, params: Mapping[str, Any]) -> dict[str, Any]:
        """One hparam dict -> its canonical JSON-able form.

        Internal keys (``__``-prefixed driver plumbing like
        ``__inherit_from__``) are dropped, keys are restricted to this
        space's dimensions in insertion order, and values normalize to
        plain JSON scalars — so the SAME point always serializes to the
        SAME bytes whether it arrives live from ``materialize_row`` or
        back from a ledger JSON round trip.
        """
        missing = [n for n in self.names if n not in params]
        if missing:
            raise KeyError(f"params missing dimensions {missing} of {self.names}")
        return {name: _plain(params[name]) for name in self.names}

    def params_key(self, params: Mapping[str, Any]) -> str:
        """Canonical exact-match key for one point (ledger dedup cache)."""
        return json.dumps(self.canonical_params(params), sort_keys=True)

    def __repr__(self):
        inner = ", ".join(f"{k}={v}" for k, v in self.domains.items())
        return f"SearchSpace({inner})"
