"""CPU backend: pool fan-out, stateful inheritance, param hygiene."""

import numpy as np
import pytest

from mpi_opt_tpu.backends import available_backends, get_backend
from mpi_opt_tpu.backends.cpu import CPUBackend, _clean
from mpi_opt_tpu.trial import Trial
from mpi_opt_tpu.workloads import get_workload


def _trial(tid, params, budget, space):
    unit = space.params_to_unit({k: v for k, v in params.items() if not k.startswith("__")})
    return Trial(trial_id=tid, params=params, unit=unit, budget=budget)


def test_backend_registry():
    assert "cpu" in available_backends()
    with pytest.raises(ValueError, match="unknown backend"):
        get_backend("gpu", get_workload("quadratic"))


def test_clean_strips_internal_keys():
    assert _clean({"lr": 1.0, "__slot__": 3, "__inherit_from__": None}) == {"lr": 1.0}


def test_stateless_pool_evaluation():
    wl = get_workload("digits")
    space = wl.default_space()
    b = CPUBackend(wl, n_workers=2)
    trials = [
        _trial(0, {"C": 1.0, "tol": 1e-4, "fit_intercept": True}, 60, space),
        _trial(1, {"C": 0.01, "tol": 1e-4, "fit_intercept": True}, 60, space),
    ]
    try:
        results = b.evaluate(trials)
    finally:
        b.close()
    assert len(results) == 2
    assert results[0].trial_id == 0 and results[1].trial_id == 1
    assert 0.5 < results[0].score <= 1.0


def test_stateful_warm_resume_matches_budget():
    """Training 10 then resuming to 30 == training 30 from scratch."""
    wl = get_workload("quadratic")
    space = wl.default_space()
    b = CPUBackend(wl, n_workers=1)
    params = {"lr": 0.5, "reg": 0.3}
    t = _trial(0, dict(params), 10, space)
    r10 = b.evaluate([t])[0]
    t.budget = 30
    r30_resumed = b.evaluate([t])[0]
    b2 = CPUBackend(wl, n_workers=1)
    t2 = _trial(1, dict(params), 30, space)
    r30_scratch = b2.evaluate([t2])[0]
    assert r30_resumed.score == pytest.approx(r30_scratch.score, rel=1e-9)
    assert r30_resumed.score > r10.score  # more budget, better score (lr<1)


def test_stateful_inheritance_copies_source_state():
    wl = get_workload("quadratic")
    space = wl.default_space()
    b = CPUBackend(wl, n_workers=1)
    good = {"lr": 1.0, "reg": 0.3, "__inherit_from__": None, "__slot__": 0}
    t0 = _trial(0, good, 5, space)
    b.evaluate([t0])
    # child inherits t0's (converged) weights but trains 0 extra steps
    child_params = {"lr": 1e-3, "reg": 0.3, "__inherit_from__": 0, "__slot__": 1}
    t1 = _trial(1, child_params, 5, space)
    r1 = b.evaluate([t1])[0]
    # inherited w is already ~0 (lr=1 converges in one step), so even with
    # tiny lr the child's score reflects the inherited optimum
    assert r1.score > -0.05


def test_inline_exception_becomes_failed_result():
    """The inline (in-parent) stateless path catches per-trial
    exceptions into failed results, same contract as the pool path."""
    from mpi_opt_tpu.workloads import get_workload as gw

    wl = gw("chaos", inner="digits", exc=1.0)
    space = wl.default_space()
    b = CPUBackend(wl, n_workers=1)
    t = _trial(0, {"C": 1.0, "tol": 1e-4, "fit_intercept": True}, 10, space)
    (r,) = b.evaluate([t])
    b.close()
    assert r.status == "failed"
    assert "ChaosInjectedError" in r.error
    assert np.isnan(r.score)


def test_stateful_exception_becomes_failed_and_stores_no_state():
    """The stateful path reports a raising trial as failed WITHOUT
    storing its state: a PBT successor inheriting from it must retrain
    fresh, not resume a half-trained wreck."""
    from mpi_opt_tpu.workloads import get_workload as gw

    wl = gw("chaos", inner="quadratic", exc=1.0)
    space = wl.default_space()
    b = CPUBackend(wl, n_workers=1)
    t = _trial(7, {"lr": 0.5, "reg": 0.3}, 10, space)
    (r,) = b.evaluate([t])
    b.close()
    assert r.status == "failed" and not r.ok
    assert 7 not in b._states


def test_stateful_nan_score_becomes_failed():
    from mpi_opt_tpu.workloads import get_workload as gw

    wl = gw("chaos", inner="quadratic", nan=1.0)
    space = wl.default_space()
    b = CPUBackend(wl, n_workers=1)
    t = _trial(0, {"lr": 0.5, "reg": 0.3}, 10, space)
    (r,) = b.evaluate([t])
    b.close()
    assert r.status == "failed"
    assert "non-finite" in r.error
    assert np.isnan(r.score)


def test_trial_timeout_validation():
    wl = get_workload("quadratic")
    with pytest.raises(ValueError, match="trial_timeout"):
        CPUBackend(wl, n_workers=1, trial_timeout=0.0)


def test_stateful_trial_timeout_warns_unenforceable():
    """--trial-timeout cannot interrupt in-parent stateful evaluation;
    the backend must say so rather than silently ignore the deadline."""
    wl = get_workload("quadratic")
    space = wl.default_space()
    b = CPUBackend(wl, n_workers=1, trial_timeout=5.0)
    t = _trial(0, {"lr": 0.5, "reg": 0.3}, 10, space)
    with pytest.warns(UserWarning, match="cannot be enforced for stateful"):
        (r,) = b.evaluate([t])
    b.close()
    assert r.ok


# -- process-isolated stateful evaluation (--isolate-stateful) -------------


def test_isolated_stateful_matches_in_parent_exactly():
    """The isolated worker runs the SAME _stateful_eval over the same
    store semantics: warm resume and PBT inheritance produce bit-equal
    scores to the in-parent path (quadratic training is deterministic),
    and no unenforceable-timeout warning fires (the deadline IS
    enforceable now)."""
    import warnings

    wl = get_workload("quadratic")
    space = wl.default_space()
    params = {"lr": 0.5, "reg": 0.3}

    def run(backend):
        t = _trial(0, dict(params), 10, space)
        r10 = backend.evaluate([t])[0]
        t.budget = 30
        r30 = backend.evaluate([t])[0]  # warm resume to 30
        child = _trial(1, {**params, "__inherit_from__": 0}, 30, space)
        rc = backend.evaluate([child])[0]  # PBT-style inheritance
        return (r10.score, r30.score, rc.score)

    b_in = CPUBackend(wl, n_workers=1)
    b_iso = CPUBackend(wl, n_workers=1, isolate_stateful=True, trial_timeout=60.0)
    try:
        ref = run(b_in)
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # no "unenforceable" warning
            iso = run(b_iso)
    finally:
        b_in.close()
        b_iso.close()
    assert iso == ref


def test_isolated_stateful_worker_death_fails_trial_and_respawns():
    """A worker dying HARD mid-trial (chaos crash: os._exit) yields a
    failed result immediately — no timeout needed, the pipe EOF is the
    signal — and the NEXT trial transparently respawns a fresh worker
    (state store reset: the documented cost of losing the process)."""
    kw = {"inner": "quadratic", "crash": 0.5, "seed": 1}
    wl = get_workload("chaos", **kw)
    space = wl.default_space()
    crash_p = clean_p = None
    for i in range(200):
        p = {"lr": 0.1 + i * 0.007, "reg": 0.3}
        f = wl.fault_for(p)
        if f == "crash" and crash_p is None:
            crash_p = p
        elif f is None and clean_p is None:
            clean_p = p
        if crash_p and clean_p:
            break
    assert crash_p and clean_p
    b = CPUBackend(
        wl, n_workers=1, isolate_stateful=True, trial_timeout=60.0,
        workload_kwargs=kw,
    )
    try:
        (r,) = b.evaluate([_trial(0, dict(crash_p), 10, space)])
        assert not r.ok and r.status == "failed"
        assert "died" in r.error
        (r2,) = b.evaluate([_trial(1, dict(clean_p), 10, space)])
        assert r2.ok  # fresh worker, clean trial
    finally:
        b.close()
