"""Fused generational TPE: on-device ring buffer, suggest, train, report."""

import numpy as np
import pytest

import mpi_opt_tpu.train.fused_tpe as ft
from mpi_opt_tpu.workloads import get_workload


def _wl():
    return get_workload("fashion_mlp", n_train=256, n_val=128)


def test_fused_tpe_structure_and_determinism():
    wl = _wl()
    kw = dict(n_trials=10, batch=4, budget=5, seed=0)
    r1 = ft.fused_tpe(wl, **kw)
    # ceil(10/4) = 3 generations: 4 + 4 + 2
    assert r1["best_curve"].shape == (3,)
    assert r1["n_trials"] == 10
    assert 0.0 <= r1["best_score"] <= 1.0
    assert set(r1["best_params"]) == set(wl.default_space().domains)
    # cumulative best is monotone nondecreasing by construction
    assert all(b >= a - 1e-7 for a, b in zip(r1["best_curve"], r1["best_curve"][1:]))
    # deterministic per seed
    r2 = ft.fused_tpe(wl, **kw)
    assert r2["best_score"] == r1["best_score"]
    np.testing.assert_array_equal(r2["obs_scores"], r1["obs_scores"])


def test_fused_tpe_crash_resume_bit_identical(tmp_path, monkeypatch):
    wl = _wl()
    kw = dict(n_trials=8, batch=4, budget=5, seed=3)
    whole = ft.fused_tpe(wl, **kw)

    real = ft.tpe_generation
    calls = {"n": 0}

    def crashing(*a, **k):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated crash")
        return real(*a, **k)

    ckpt = str(tmp_path / "tpe")
    monkeypatch.setattr(ft, "tpe_generation", crashing)
    with pytest.raises(RuntimeError, match="simulated"):
        ft.fused_tpe(wl, checkpoint_dir=ckpt, **kw)
    monkeypatch.setattr(ft, "tpe_generation", real)

    resumed = ft.fused_tpe(wl, checkpoint_dir=ckpt, **kw)
    assert resumed["best_score"] == whole["best_score"]
    np.testing.assert_array_equal(resumed["obs_scores"], whole["obs_scores"])
    np.testing.assert_array_equal(resumed["best_curve"], whole["best_curve"])
    assert resumed["best_params"] == whole["best_params"]


def test_fused_tpe_rejects_zero_trials():
    with pytest.raises(ValueError, match="n_trials"):
        ft.fused_tpe(_wl(), n_trials=0)


def test_fused_tpe_checkpoint_cfg_mismatch_raises(tmp_path):
    from mpi_opt_tpu.ops.tpe import TPEConfig

    wl = _wl()
    ckpt = str(tmp_path / "tpe")
    ft.fused_tpe(wl, n_trials=4, batch=4, budget=3, seed=1, checkpoint_dir=ckpt)
    with pytest.raises(ValueError, match="different sweep"):
        ft.fused_tpe(wl, n_trials=4, batch=4, budget=3, seed=1,
                     cfg=TPEConfig(gamma=0.5), checkpoint_dir=ckpt)
