"""ResNet-18 (config 5): structure, dtype conventions, population path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_opt_tpu.models import ResNet18
from mpi_opt_tpu.workloads import get_workload

# ResNet XLA:CPU compiles cost minutes of wall in one process — out
# of the tier-1 870s single-process window; run explicitly or with
# ``-m slow``
pytestmark = pytest.mark.slow


def _n_params(params):
    return sum(p.size for p in jax.tree.leaves(params))


def test_resnet18_param_count_and_dtypes():
    """Full-width model is the real ResNet-18 (~11.2M params)."""
    m = ResNet18(n_classes=100)
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    params = m.init(jax.random.key(0), x)["params"]
    n = _n_params(params)
    assert 11.0e6 < n < 11.5e6, n
    # f32 params (models package convention)
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(params))
    out = m.apply({"params": params}, x)
    assert out.shape == (1, 100)
    assert out.dtype == jnp.float32


def test_resnet_remat_matches_no_remat():
    """remat changes the memory schedule, never the function."""
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    a = ResNet18(n_classes=10, width=8, remat=False)
    b = ResNet18(n_classes=10, width=8, remat=True)
    params = a.init(jax.random.key(2), x)["params"]
    ya = a.apply({"params": params}, x)
    yb = b.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(ya), np.asarray(yb), atol=1e-6)


@pytest.fixture(scope="module")
def tiny_workload():
    # tiny width keeps the CPU test fast; identical program structure
    return get_workload("cifar100_resnet18", n_train=256, n_val=128, width=8)


def test_resnet_population_trains_and_gathers(tiny_workload):
    """The config-5 model runs the full population protocol: vmapped
    init/train/eval plus the exploit gather over a deep pytree."""
    wl = tiny_workload
    d = wl.data()
    assert d["n_classes"] == 100
    trainer = wl.make_trainer(member_chunk=2)
    tx, ty = jnp.asarray(d["train_x"]), jnp.asarray(d["train_y"])
    vx, vy = jnp.asarray(d["val_x"]), jnp.asarray(d["val_y"])
    state = trainer.init_population(jax.random.key(0), tx[:2], 4)
    space = wl.default_space()
    unit = space.sample_unit(jax.random.key(1), 4)
    hp = wl.make_hparams(space.from_unit(unit))
    state, losses = trainer.train_segment(state, hp, tx, ty, jax.random.key(2), 3)
    assert losses.shape == (3,)
    assert np.isfinite(np.asarray(losses)).all()
    scores = trainer.eval_population(state, vx, vy)
    assert scores.shape == (4,)
    assert np.isfinite(np.asarray(scores)).all()
    # exploit: everyone continues from member 2
    gathered = trainer.gather_members(state, jnp.array([2, 2, 2, 2]))
    k0 = jax.tree.leaves(gathered.params)[0]
    np.testing.assert_array_equal(np.asarray(k0[0]), np.asarray(k0[3]))


def test_resnet_fused_pbt_generation(tiny_workload):
    """One fused PBT generation end-to-end on the config-5 model."""
    from mpi_opt_tpu.train.fused_pbt import fused_pbt

    result = fused_pbt(
        tiny_workload, population=4, generations=2, steps_per_gen=2, seed=0
    )
    assert result["best_curve"].shape == (2,)
    assert 0.0 <= result["best_score"] <= 1.0
