"""Multi-objective subsystem (ISSUE 17): spec parsing, Pareto kernels
vs brute-force oracles, constraint-aware selection tiers, hypervolume,
jit-compilability, and the warm-start vector-score finiteness guard.
"""

import dataclasses

import jax
import numpy as np
import pytest

from mpi_opt_tpu.algorithms.base import Observation
from mpi_opt_tpu.ledger.warmstart import best_observation, observation_fully_finite
from mpi_opt_tpu.objectives import (
    Objective,
    ObjectiveSpec,
    crowding_distance,
    hypervolume,
    parse_constraint,
    pareto_front_mask,
    pareto_rank,
    pareto_score,
    select_best,
)

# -- spec / syntax --------------------------------------------------------


def test_parse_full_syntax():
    spec = ObjectiveSpec.parse("accuracy:max,params:min<=2e4,latency:min")
    assert spec.names == ("accuracy", "params", "latency")
    assert spec.m == 3
    assert [o.direction for o in spec.objectives] == ["max", "min", "min"]
    assert spec.objectives[0].bound is None
    assert spec.objectives[1].bound == 2e4
    assert spec.has_bounds


def test_parse_default_direction_is_max():
    spec = ObjectiveSpec.parse("accuracy")
    assert spec.objectives[0].direction == "max"
    assert not spec.has_bounds


def test_parse_operator_must_agree_with_direction():
    # a bound means "at least this good": >= for max, <= for min
    with pytest.raises(ValueError, match="contradicts direction"):
        ObjectiveSpec.parse("params:min>=5")
    with pytest.raises(ValueError, match="contradicts direction"):
        ObjectiveSpec.parse("accuracy:max<=0.5")
    # the agreeing forms parse
    assert ObjectiveSpec.parse("accuracy:max>=0.5").objectives[0].bound == 0.5
    assert ObjectiveSpec.parse("params:min<=5").objectives[0].bound == 5.0


def test_parse_rejects_malformed():
    with pytest.raises(ValueError):
        ObjectiveSpec.parse("accuracy,,params")  # empty item
    with pytest.raises(ValueError):
        ObjectiveSpec.parse("accuracy:sideways")  # bad direction
    with pytest.raises(ValueError):
        ObjectiveSpec.parse("params:min<=not_a_number")
    with pytest.raises(ValueError, match="duplicate"):
        ObjectiveSpec.parse("accuracy,accuracy")
    with pytest.raises(ValueError):
        Objective(name="x", bound=float("nan"))


def test_spec_round_trips_through_durable_form():
    spec = ObjectiveSpec.parse("accuracy:max>=0.9,params:min<=2e4,latency:min")
    again = ObjectiveSpec.from_spec(spec.spec())
    assert again == spec
    # frozen + tuple-backed: usable as a static jit argument
    assert hash(again) == hash(spec)
    with pytest.raises(dataclasses.FrozenInstanceError):
        spec.objectives[0].name = "x"


def test_normalize_bounds_and_scalarize():
    spec = ObjectiveSpec.parse("accuracy:max,params:min<=100")
    assert list(spec.signs()) == [1.0, -1.0]
    raw = np.array([[0.5, 40.0], [0.8, 250.0]])
    norm = spec.normalize(raw)
    np.testing.assert_allclose(norm, [[0.5, -40.0], [0.8, -250.0]])
    nb = spec.norm_bounds()
    assert nb[0] == -np.inf  # unconstrained
    assert nb[1] == -100.0  # min<=100 in maximize form
    np.testing.assert_allclose(spec.scalarize(raw), [0.5, 0.8])
    # minimized primary scalarizes negated (higher is better)
    spec2 = ObjectiveSpec.parse("loss:min,params:min")
    np.testing.assert_allclose(spec2.scalarize(raw), [-0.5, -0.8])


def test_parse_constraint_clause():
    assert parse_constraint("params<=2e4") == ("params", "<=", 20000.0)
    assert parse_constraint(" accuracy >= 0.9 ") == ("accuracy", ">=", 0.9)
    with pytest.raises(ValueError):
        parse_constraint("params=5")
    with pytest.raises(ValueError):
        parse_constraint("params<=banana")


# -- device kernels vs brute-force oracles --------------------------------


def _brute_front_ranks(s: np.ndarray) -> np.ndarray:
    """Oracle: literal NSGA-II front peeling (front k = non-dominated
    after removing fronts < k). Non-finite rows get rank n."""
    n = s.shape[0]
    ok = np.all(np.isfinite(s), axis=-1)
    rank = np.full(n, n, dtype=np.int32)
    remaining = set(np.where(ok)[0])
    r = 0
    while remaining:
        front = [
            i
            for i in remaining
            if not any(
                np.all(s[j] >= s[i]) and np.any(s[j] > s[i])
                for j in remaining
                if j != i
            )
        ]
        for i in front:
            rank[i] = r
        remaining -= set(front)
        r += 1
    return rank


@pytest.mark.parametrize("n,m", [(1, 2), (7, 2), (16, 3), (9, 4)])
def test_pareto_rank_matches_peeling_oracle(n, m):
    rng = np.random.default_rng(n * 100 + m)
    s = rng.normal(size=(n, m))
    got = np.asarray(pareto_rank(s))
    np.testing.assert_array_equal(got, _brute_front_ranks(s))


def test_pareto_rank_nonfinite_and_masked_rows_rank_last():
    s = np.array([[1.0, 1.0], [np.nan, 2.0], [0.5, 0.5], [2.0, np.inf]])
    got = np.asarray(pareto_rank(s))
    assert got[1] == 4 and got[3] == 4  # n, strictly after every front
    assert got[0] == 0 and got[2] == 1
    # valid mask composes with finiteness
    masked = np.asarray(pareto_rank(s, valid=np.array([False, True, True, True])))
    assert masked[0] == 4 and masked[2] == 0


def test_pareto_rank_duplicates_share_a_front():
    s = np.array([[1.0, 1.0], [1.0, 1.0], [0.0, 0.0]])
    got = np.asarray(pareto_rank(s))
    assert got[0] == got[1] == 0 and got[2] == 1


@pytest.mark.parametrize("n,m", [(8, 2), (12, 3)])
def test_front_mask_matches_rank_zero(n, m):
    rng = np.random.default_rng(n + m)
    s = rng.normal(size=(n, m))
    mask = pareto_front_mask(s)
    np.testing.assert_array_equal(mask, np.asarray(pareto_rank(s)) == 0)


def test_crowding_boundaries_are_infinite_middle_is_finite():
    # one front, sorted along a line: the two extremes are boundary
    s = np.array([[0.0, 3.0], [1.0, 2.0], [2.0, 1.0], [3.0, 0.0]])
    rank = pareto_rank(s)
    d = np.asarray(crowding_distance(s, rank))
    assert np.isinf(d[0]) and np.isinf(d[3])
    assert np.isfinite(d[1]) and np.isfinite(d[2])
    # the lonelier middle point is crowd-preferred
    s2 = np.array([[0.0, 3.0], [0.1, 2.9], [2.0, 1.0], [3.0, 0.0]])
    d2 = np.asarray(crowding_distance(s2, pareto_rank(s2)))
    assert d2[2] > d2[1]


def test_pareto_score_tier_ordering():
    spec = ObjectiveSpec.parse("accuracy:max,params:min<=100")
    raw = np.array(
        [
            [0.90, 50.0],  # feasible, front 0
            [0.50, 40.0],  # feasible, dominated (worse acc, similar params)
            [0.99, 250.0],  # infeasible (params over bound)
            [0.95, 150.0],  # infeasible, smaller violation
            [np.nan, 10.0],  # diverged
        ]
    )
    eff = np.asarray(
        pareto_score(spec.normalize(raw), norm_bounds=spec.norm_bounds())
    )
    order = list(np.argsort(-eff))
    # feasible first (front order), then infeasible by least violation,
    # then -inf for the diverged row
    assert order[:2] == [0, 1]
    assert order[2] == 3 and order[3] == 2
    assert eff[4] == -np.inf
    # every feasible strictly above every infeasible
    assert eff[[0, 1]].min() > eff[[2, 3]].max()


def test_pareto_score_unbounded_spec_has_no_infeasible_tier():
    s = np.array([[1.0, 0.0], [0.0, 1.0], [-5.0, -5.0]])
    eff = np.asarray(pareto_score(s))
    assert np.isfinite(eff).all()
    assert eff[2] < min(eff[0], eff[1])  # dominated ranks below the front


def test_kernels_compile_under_jit():
    s = np.random.default_rng(3).normal(size=(6, 2)).astype(np.float32)
    nb = np.array([-np.inf, -1.0], np.float32)
    r_jit = jax.jit(pareto_rank)(s)
    np.testing.assert_array_equal(np.asarray(r_jit), _brute_front_ranks(s))
    eff_jit = jax.jit(pareto_score)(s, norm_bounds=nb)
    eff = pareto_score(s, norm_bounds=nb)
    np.testing.assert_allclose(np.asarray(eff_jit), np.asarray(eff), rtol=1e-6)


# -- hypervolume ----------------------------------------------------------


def test_hypervolume_known_values():
    # two rectangles 2x1 and 1x2 overlapping in the unit square: 3.0
    assert hypervolume([[2.0, 1.0], [1.0, 2.0]], ref=[0.0, 0.0]) == pytest.approx(3.0)
    # 1D degenerates to max - ref
    assert hypervolume([[3.0], [5.0]], ref=[1.0]) == pytest.approx(4.0)
    # self-referenced ref = per-objective front minimum: boundary points
    # anchor zero, the interior point contributes its box
    assert hypervolume([[3.0, 1.0], [2.0, 2.0], [1.0, 3.0]]) == pytest.approx(1.0)
    # ... so a 2-point self-referenced front is 0 by convention
    assert hypervolume([[2.0, 1.0], [1.0, 2.0]]) == 0.0


def test_hypervolume_edge_cases():
    assert hypervolume([]) == 0.0
    assert hypervolume([[np.nan, 1.0]]) == 0.0  # non-finite rows drop
    # dominated/below-ref points never add volume
    assert hypervolume(
        [[2.0, 2.0], [1.0, 1.0]], ref=[0.0, 0.0]
    ) == pytest.approx(4.0)
    with pytest.raises(ValueError):
        hypervolume([1.0, 2.0])  # not [n, m]


def test_hypervolume_deterministic_under_row_order():
    rng = np.random.default_rng(11)
    pts = rng.uniform(size=(6, 3))
    perm = rng.permutation(6)
    assert hypervolume(pts) == pytest.approx(hypervolume(pts[perm]))


# -- constraint-aware winner pick (typed degradation) ---------------------


def test_select_best_feasible():
    spec = ObjectiveSpec.parse("accuracy:max,params:min<=100")
    raw = [[0.90, 50.0], [0.95, 200.0], [0.80, 80.0]]
    got = select_best(raw, spec)
    assert got == {"index": 0, "kind": "feasible", "violation": 0.0}
    assert isinstance(got["index"], int)  # host values, not np scalars


def test_select_best_degrades_to_least_violation():
    spec = ObjectiveSpec.parse("accuracy:max,params:min<=100")
    raw = [[0.90, 300.0], [0.95, 150.0]]
    got = select_best(raw, spec)
    assert got["kind"] == "least_violation"
    assert got["index"] == 1
    assert got["violation"] == pytest.approx(0.5)  # (150-100)/100


def test_select_best_diverged_and_nan_disqualifies_row():
    spec = ObjectiveSpec.parse("accuracy:max,params:min<=100")
    assert select_best([[np.nan, 5.0], [np.inf, 1.0]], spec) == {
        "index": None,
        "kind": "diverged",
        "violation": None,
    }
    # a NaN in ANY objective knocks the row out even if primary looks fine
    got = select_best([[0.99, np.nan], [0.5, 50.0]], spec)
    assert got["index"] == 1 and got["kind"] == "feasible"


def test_select_best_unconstrained_spec_picks_primary():
    spec = ObjectiveSpec.parse("accuracy:max,params:min")
    got = select_best([[0.7, 10.0], [0.9, 99.0]], spec)
    assert got["index"] == 1 and got["kind"] == "feasible"


# -- warm-start vector-score guard (satellite 2) --------------------------


def _obs(score, scores=None):
    return Observation(unit=np.zeros(2, np.float32), score=score, scores=scores)


def test_observation_fully_finite_scalar_and_vector():
    assert observation_fully_finite(_obs(0.5))
    assert not observation_fully_finite(_obs(float("nan")))
    assert observation_fully_finite(_obs(0.5, scores=(0.5, 100.0)))
    # NaN in ANY objective disqualifies, even with a healthy scalar
    assert not observation_fully_finite(_obs(0.5, scores=(0.5, float("nan"))))
    assert not observation_fully_finite(_obs(0.5, scores=(float("inf"), 1.0)))
    # a None entry (journaled null) is non-finite by definition
    assert not observation_fully_finite(_obs(0.5, scores=(0.5, None)))


def test_best_observation_skips_partially_diverged_vectors():
    healthy = _obs(0.6, scores=(0.6, 120.0))
    tainted = _obs(0.9, scores=(0.9, float("nan")))  # best scalar, bad vector
    diverged = _obs(float("nan"))
    assert best_observation([tainted, healthy, diverged]) is healthy
    assert best_observation([tainted, diverged]) is None
    assert best_observation([]) is None
