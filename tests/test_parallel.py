"""Mesh layer + fused on-device PBT over a virtual 8-device mesh."""

import jax
import numpy as np
import pytest

from mpi_opt_tpu.ops.pbt import PBTConfig
from mpi_opt_tpu.parallel import make_mesh, pop_sharding, shard_popstate
from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.workloads import get_workload


def test_make_mesh_shapes():
    m = make_mesh(n_pop=4, n_data=2)
    assert m.shape == {"pop": 4, "data": 2}
    m2 = make_mesh(n_data=2)  # n_pop inferred: 8 devices / 2
    assert m2.shape == {"pop": 4, "data": 2}
    with pytest.raises(ValueError, match="not divisible"):
        make_mesh(n_data=3)
    with pytest.raises(ValueError, match="needs"):
        make_mesh(n_pop=16, n_data=1)


@pytest.fixture(scope="module")
def workload():
    wl = get_workload("fashion_mlp", n_train=512, n_val=256)
    wl.batch_size = 32
    return wl


def test_fused_pbt_learns(workload):
    r = fused_pbt(workload, population=8, generations=4, steps_per_gen=30, seed=0)
    assert r["best_curve"].shape == (4,)
    # best-of-population must improve over generations and beat chance
    assert r["best_score"] > 0.25
    assert r["best_curve"][-1] >= r["best_curve"][0] - 0.02
    assert set(r["best_params"]) == {"lr", "momentum", "weight_decay", "flip_prob", "shift"}


def test_fused_pbt_sharded_matches_unsharded(workload):
    """The same fused sweep over a ('pop','data') mesh must produce the
    same result — sharding is a layout, not a semantics change.

    Tolerance: measured single- vs 4x2-mesh divergence is <0.01 (bf16
    reduction-order noise over 20 training steps); 0.02 leaves margin
    without hiding a real semantics change."""
    r1 = fused_pbt(workload, population=8, generations=2, steps_per_gen=10, seed=3)
    mesh = make_mesh(n_pop=4, n_data=2)
    r2 = fused_pbt(workload, population=8, generations=2, steps_per_gen=10, seed=3, mesh=mesh)
    assert r2["best_score"] == pytest.approx(r1["best_score"], abs=0.02)
    np.testing.assert_allclose(r2["mean_curve"], r1["mean_curve"], atol=0.02)


def _count_tensor_allreduces(workload, n_pop, n_data):
    """Compile one train segment over an (n_pop, n_data) mesh and count
    all-reduce ops over non-scalar tensors in the optimized HLO."""
    import re

    import jax.numpy as jnp

    from mpi_opt_tpu.parallel.mesh import replicate

    d = workload.data()
    tx, ty = jnp.asarray(d["train_x"]), jnp.asarray(d["train_y"])
    mesh = make_mesh(n_pop=n_pop, n_data=n_data)
    trainer = workload.make_trainer(mesh=mesh)
    st = shard_popstate(
        trainer.init_population(jax.random.key(0), tx[:2], 8), mesh
    )
    space = workload.default_space()
    hp = workload.make_hparams(space.from_unit(space.sample_unit(jax.random.key(1), 8)))
    txp, typ = jax.device_put(tx, replicate(mesh)), jax.device_put(ty, replicate(mesh))
    lowered = trainer.train_segment.func.lower(
        trainer, st, hp, txp, typ, jax.random.key(2), 3
    )
    txt = lowered.compile().as_text()
    return sum(
        1
        for line in txt.splitlines()
        if "all-reduce(" in line and re.search(r"(f32|bf16|i32|u32)\[\d", line)
    )


def test_data_axis_inserts_gradient_allreduce(workload):
    """The 'data' axis must be real: sharding the batch over it makes
    the SPMD partitioner emit a gradient all-reduce (the reference's
    data-parallel MPI allreduce). Pop-only meshes have only the scalar
    loss-mean all-reduce; if the batch constraint is dropped, the
    tensor all-reduce disappears and this test fails."""
    assert _count_tensor_allreduces(workload, n_pop=8, n_data=1) == 0
    assert _count_tensor_allreduces(workload, n_pop=2, n_data=4) > 0


def test_shard_popstate_places_on_mesh(workload):
    mesh = make_mesh(n_pop=8, n_data=1)
    trainer = workload.make_trainer()
    d = workload.data()
    import jax.numpy as jnp

    st = trainer.init_population(jax.random.key(0), jnp.asarray(d["train_x"][:2]), 8)
    sharded = shard_popstate(st, mesh)
    leaf = jax.tree.leaves(sharded.params)[0]
    assert leaf.sharding == pop_sharding(mesh)
    assert len(leaf.devices()) == 8


class TestInitializeMultihost:
    """initialize_multihost is the config-5 bring-up shim; its contract:
    single-process requests degrade gracefully, explicit multi-host
    requests must never silently shrink to one process. In this test
    process the XLA backend is already up, so every inner
    jax.distributed.initialize raises — which is exactly the failure
    path being pinned down."""

    def test_single_process_swallows_late_init(self):
        from mpi_opt_tpu.parallel.mesh import initialize_multihost

        # no explicit world: failure to bring up distributed is fine,
        # and the current process index comes back
        assert initialize_multihost() == 0
        assert initialize_multihost(num_processes=1) == 0

    def test_explicit_coordinator_failure_raises(self):
        from mpi_opt_tpu.parallel.mesh import initialize_multihost

        with pytest.raises(RuntimeError):
            initialize_multihost(
                coordinator_address="127.0.0.1:1", num_processes=2, process_id=0
            )

    def test_explicit_world_size_failure_raises(self):
        from mpi_opt_tpu.parallel.mesh import initialize_multihost

        # num_processes>1 without a coordinator address is still an
        # explicit multi-process request: must raise, not shrink
        with pytest.raises(RuntimeError):
            initialize_multihost(num_processes=2)


def test_fused_pbt_final_state_sharded(workload):
    """The fused sweep's carried population must END sharded over 'pop'
    — if any launch-boundary op (exploit gather, snapshot round-trip)
    dropped the placement, multi-chip sweeps would silently degrade to
    replicated execution."""
    mesh = make_mesh(n_pop=8, n_data=1)
    r = fused_pbt(workload, population=8, generations=2, steps_per_gen=5, seed=1, mesh=mesh)
    leaves = jax.tree.leaves(r["state"].params)
    assert leaves, "fused_pbt result carries no state"
    for leaf in leaves:
        assert len(leaf.devices()) == 8, leaf.sharding
        assert not leaf.sharding.is_fully_replicated


def test_fused_tpe_sharded_matches_unsharded(workload):
    """Fused TPE over a mesh (incl. a tail generation that does not
    divide the 'pop' axis) must match the single-device trajectory."""
    from mpi_opt_tpu.train.fused_tpe import fused_tpe

    kw = dict(n_trials=12, batch=8, budget=5, seed=4)
    r1 = fused_tpe(workload, **kw)
    mesh = make_mesh(n_pop=8, n_data=1)
    r2 = fused_tpe(workload, mesh=mesh, **kw)
    assert r2["best_score"] == pytest.approx(r1["best_score"], abs=0.02)
    np.testing.assert_allclose(r2["best_curve"], r1["best_curve"], atol=0.02)


def test_fused_sha_sharded_rounds_survivors_to_pop_axis(workload):
    """On a mesh, rung survivor counts round UP to the 'pop' axis so
    cohorts stay shardable; a 16-trial eta-4 sweep on an 8-way mesh
    keeps 8 (not 4) survivors."""
    from mpi_opt_tpu.train.fused_asha import fused_sha

    mesh = make_mesh(n_pop=8, n_data=1)
    r = fused_sha(
        workload, n_trials=16, min_budget=5, max_budget=20, eta=4, seed=2, mesh=mesh
    )
    assert r["rung_sizes"] == [16, 8]
    assert 0.0 <= r["best_score"] <= 1.0


def test_replication_fallback_warns(workload):
    """A leading axis that doesn't divide the 'pop' axis replicates —
    correct but effectively single-device, so it must WARN instead of
    silently serializing the sweep (VERDICT r3 #7)."""
    import warnings as _w

    import jax.numpy as jnp

    from mpi_opt_tpu.parallel.mesh import place_pop

    mesh = make_mesh(n_pop=8, n_data=1)
    state = {"w": jnp.zeros((10, 3)), "b": jnp.zeros((10,))}
    with pytest.warns(RuntimeWarning, match="does not divide the mesh 'pop' axis"):
        shard_popstate(state, mesh)
    with pytest.warns(RuntimeWarning, match="multiple of 8"):
        place_pop(jnp.zeros((9, 2)), mesh)
    # dividing axes stay silent
    with _w.catch_warnings():
        _w.simplefilter("error")
        shard_popstate({"w": jnp.zeros((16, 3))}, mesh)
        place_pop(jnp.zeros((8, 2)), mesh)
