"""Cross-sweep knowledge corpus (ISSUE 14): index, fuzzy matching,
auto warm-start resolution, the corpus-backed cache, and the
suggestion service.

The headline is the acceptance drill in miniature: a corpus holding
one exact-hash and one fuzzy-match ledger resolves into BOTH kinds of
prior (exact as full observations, fuzzy down-weighted at budget 0),
the `warm_start` event names the chosen sources, a stale index entry
degrades to a `corpus_skip` event, and `--warm-start auto:` produces a
sweep ledger record-identical to a manually-pointed warm start.
"""

import contextlib
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from mpi_opt_tpu.algorithms.base import Observation
from mpi_opt_tpu.cli import main as cli_main
from mpi_opt_tpu.corpus import index as cindex
from mpi_opt_tpu.corpus.match import (
    compat_score,
    encode_record,
    fingerprint_from_records,
    fingerprint_from_spec,
    fuzzy_observations,
)
from mpi_opt_tpu.corpus.resolve import resolve
from mpi_opt_tpu.ledger import CorpusCache, SweepLedger
from mpi_opt_tpu.space import LogUniform, SearchSpace, Uniform
from mpi_opt_tpu.trial import TrialResult
from mpi_opt_tpu.workloads import get_workload


def run_cli(args):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(args)
    return rc, buf.getvalue()


def live_space():
    return get_workload("quadratic").default_space()


def sweep(ledger_path, seed=0, trials=6, warm=None, metrics=None):
    args = [
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", str(trials), "--budget", "3", "--workers", "1",
        "--seed", str(seed), "--ledger", str(ledger_path),
    ]
    if warm:
        args += ["--warm-start", str(warm)]
    if metrics:
        args += ["--metrics-file", str(metrics)]
    return run_cli(args)


def fabricate_ledger(path, space, points, config=None, spec=True):
    """A hand-built prior ledger over ``space``: points = [(params,
    score, step)]."""
    led = SweepLedger(str(path))
    led.ensure_header(
        dict(
            {
                "algorithm": "tpe",
                "workload": "quadratic",
                "backend": "cpu",
                "seed": 1,
                "space_hash": space.space_hash(),
            },
            **(config or {}),
        ),
        space_spec=space.spec() if spec else None,
    )
    for i, (params, score, step) in enumerate(points):
        led.record_trial(
            TrialResult(trial_id=i, score=score, step=step, wall_time=0.1),
            space.canonical_params(params),
        )
    led.close()
    return led.path


def fuzzy_space():
    """Same dim names/kinds as quadratic's space, different bounds —
    a different hash that still structurally overlaps."""
    return SearchSpace({"lr": LogUniform(0.0005, 8.0), "reg": Uniform(0.0, 2.0)})


@pytest.fixture
def corpus(tmp_path):
    """One exact-hash sweep ledger + one fabricated fuzzy ledger whose
    scores are all BELOW the exact best (so auto-vs-manual stays
    record-identical for seed-point consumers)."""
    c = tmp_path / "corpus"
    c.mkdir()
    rc, _ = sweep(c / "exact.jsonl", seed=0)
    assert rc == 0
    fabricate_ledger(
        c / "fuzzy.jsonl",
        fuzzy_space(),
        [
            ({"lr": 0.01, "reg": 0.2}, -5.0, 3),
            ({"lr": 0.1, "reg": 0.4}, -4.0, 3),
            ({"lr": 1.0, "reg": 0.6}, -6.0, 3),
            ({"lr": 5.0, "reg": 1.5}, -3.0, 3),  # out of the live domain
        ],
    )
    return c


# -- fingerprints / fuzzy matching ----------------------------------------


def test_fingerprint_spec_and_inference_agree_on_structure():
    space = live_space()
    from_spec = fingerprint_from_spec(space.spec())
    recs = [
        {"params": {"lr": 0.01, "reg": 0.2}},
        {"params": {"lr": 2.0, "reg": 0.9}},
    ]
    inferred = fingerprint_from_records(recs)
    assert [r["name"] for r in from_spec] == [r["name"] for r in inferred]
    assert all(r["kind"] == "numeric" for r in from_spec)
    assert all(r.get("inferred") for r in inferred)
    # either form scores full compatibility against the live spec
    assert compat_score(space.spec(), from_spec) == 1.0
    assert compat_score(space.spec(), inferred) == 1.0


def test_compat_score_judges_name_and_kind():
    space = live_space()
    disjoint = fingerprint_from_spec(
        SearchSpace({"alpha": Uniform(0, 1)}).spec()
    )
    assert compat_score(space.spec(), disjoint) == 0.0
    half = fingerprint_from_spec(
        SearchSpace({"lr": LogUniform(0.01, 1.0)}).spec()
    )
    assert compat_score(space.spec(), half) == pytest.approx(0.5)


def test_encode_record_skips_out_of_domain_never_clips():
    space = live_space()  # lr in [0.001, 4.0], reg in [0, 1]
    ok = encode_record(space, {"params": {"lr": 0.1, "reg": 0.5}})
    assert ok is not None and ok.shape == (2,)
    assert encode_record(space, {"params": {"lr": 5.0, "reg": 0.5}}) is None
    assert encode_record(space, {"params": {"lr": 0.1}}) is None  # missing dim


def test_fuzzy_observations_down_weight_and_budget_zero():
    space = live_space()
    recs = [
        {"params": {"lr": 0.01, "reg": 0.2}, "score": -5.0, "step": 9, "status": "ok"},
        {"params": {"lr": 0.1, "reg": 0.4}, "score": -4.0, "step": 9, "status": "ok"},
        {"params": {"lr": 1.0, "reg": 0.6}, "score": -6.0, "step": 9, "status": "ok"},
        {"params": {"lr": 1.0, "reg": 0.7}, "score": None, "step": 9, "status": "failed"},
    ]
    obs, skipped = fuzzy_observations(space, recs)
    # top half of the 3 encodable survive (ceil(3*0.5)=2), best-first
    assert [o.score for o in obs] == [-4.0, -5.0]
    assert all(o.budget == 0 for o in obs)  # lowest fidelity, by contract
    assert skipped == 2  # the failed record + the dropped worst


# -- index -----------------------------------------------------------------


def test_index_build_persist_and_incremental_reuse(corpus):
    doc = cindex.index_corpus(str(corpus))
    assert os.path.exists(cindex.index_path(str(corpus)))
    assert len(doc["entries"]) == 2
    by_name = {os.path.basename(e["path"]): e for e in doc["entries"]}
    exact = by_name["exact.jsonl"]
    assert exact["workload"] == "quadratic" and exact["ok"] == 6
    assert exact["space_hash"] == live_space().space_hash()
    assert exact["best_score"] is not None
    assert {r["name"] for r in exact["fingerprint"]} == {"lr", "reg"}
    # incremental: unchanged ledgers carry over the SAME entry objects
    doc2 = cindex.build_index(str(corpus), prior=doc)
    assert [e is o for e, o in zip(doc2["entries"], doc["entries"])] == [True, True]


def test_index_records_unreadable_ledger_as_error_entry(corpus):
    bad = corpus / "bad.jsonl"
    bad.write_text(
        '{"kind": "header", "version": 1, "config": {}}\nnot json\nalso not\n'
    )
    doc = cindex.index_corpus(str(corpus))
    errored = [e for e in doc["entries"] if e.get("error")]
    assert len(errored) == 1 and errored[0]["path"].endswith("bad.jsonl")
    rc, _out = run_cli(["corpus", "index", str(corpus)])
    assert rc == 1  # the indexing operator sees red; resolution skips


def test_read_index_tolerates_garbage(tmp_path):
    assert cindex.read_index(str(tmp_path)) is None
    (tmp_path / cindex.INDEX_NAME).write_text("{torn")
    assert cindex.read_index(str(tmp_path)) is None
    # valid JSON with a non-coercible version: same rebuild-don't-crash
    (tmp_path / cindex.INDEX_NAME).write_text('{"entries": [], "version": null}')
    assert cindex.read_index(str(tmp_path)) is None


# -- resolution ------------------------------------------------------------


def test_resolve_exact_plus_fuzzy_with_down_weighting(corpus):
    res = resolve(live_space(), str(corpus), workload="quadratic")
    kinds = {s["match"] for s in res.sources}
    assert kinds == {"exact", "fuzzy"}
    exact_n = sum(s["records"] for s in res.sources if s["match"] == "exact")
    assert exact_n == 6
    fuzzy_obs = [o for o in res.observations if o.budget == 0]
    exact_obs = [o for o in res.observations if o.budget != 0]
    assert len(exact_obs) == 6 and len(fuzzy_obs) == 2
    assert res.skips.get("fuzzy_dropped") == 2


def test_resolve_dedups_exact_duplicates_newest_wins(tmp_path):
    c = tmp_path / "corpus"
    c.mkdir()
    space = live_space()
    p = {"lr": 0.1, "reg": 0.3}
    fabricate_ledger(c / "old.jsonl", space, [(p, 0.1, 3)])
    fabricate_ledger(c / "new.jsonl", space, [(p, 0.9, 3)])
    res = resolve(space, str(c))
    assert len(res.observations) == 1  # one point, not two
    assert res.observations[0].score == pytest.approx(0.9)  # newest ts won
    assert res.skips.get("duplicate_params") == 1


def test_resolve_keeps_same_point_at_different_budgets(tmp_path):
    """The budget is part of evaluation identity (EvalCache's
    both-keys-survive rule): one point journaled at two budgets merges
    as TWO observations, so multi-rung corpora lose no low-rung
    evidence to the dedup."""
    c = tmp_path / "corpus"
    c.mkdir()
    space = live_space()
    p = {"lr": 0.1, "reg": 0.3}
    fabricate_ledger(c / "asha.jsonl", space, [(p, 0.4, 10), (p, 0.9, 270)])
    res = resolve(space, str(c))
    assert sorted((o.budget, o.score) for o in res.observations) == [
        (10, 0.4),
        (270, 0.9),
    ]
    assert "duplicate_params" not in res.skips


def test_resolve_excludes_own_ledger(corpus):
    res = resolve(
        live_space(),
        str(corpus),
        workload="quadratic",
        exclude=str(corpus / "exact.jsonl"),
    )
    assert all(s["match"] == "fuzzy" for s in res.sources)


def test_resolve_stale_and_corrupt_entries_degrade_to_skips(corpus):
    cindex.index_corpus(str(corpus))
    os.unlink(corpus / "fuzzy.jsonl")  # deleted behind the index
    events = []

    class Spy:
        def log(self, event, **f):
            events.append((event, f))

    res = resolve(live_space(), str(corpus), workload="quadratic", metrics=Spy())
    assert [s["match"] for s in res.sources] == ["exact"]
    assert len(res.skipped) == 1 and "deleted" in res.skipped[0]["reason"]
    assert events and events[0][0] == "corpus_skip"
    # a CORRUPT index file degrades to a rebuild + skip, never a crash
    with open(cindex.index_path(str(corpus)), "w") as f:  # sweeplint: disable=corpus-index-write -- the test FORGES the torn-index failure shape the checker exists to prevent
        f.write("{half a docu")
    res2 = resolve(live_space(), str(corpus), workload="quadratic")
    assert [s["match"] for s in res2.sources] == ["exact"]
    assert any("index-unreadable" in sk["reason"] for sk in res2.skipped)


def test_resolve_changed_ledger_is_resummarized_live(corpus):
    space = live_space()
    cindex.index_corpus(str(corpus))
    # the exact ledger GROWS after indexing: resolution re-reads it
    led = SweepLedger(str(corpus / "exact.jsonl"))
    led.record_trial(
        TrialResult(trial_id=99, score=123.0, step=3, wall_time=0.0),
        space.canonical_params({"lr": 0.5, "reg": 0.5}),
    )
    led.close()
    res = resolve(space, str(corpus))
    assert max(o.score for o in res.observations) == pytest.approx(123.0)


# -- the acceptance drill: --warm-start auto: ------------------------------


def test_auto_warm_start_matches_manual_and_names_sources(corpus, tmp_path):
    rc, _ = sweep(
        tmp_path / "auto.jsonl",
        seed=7,
        trials=5,
        warm=f"auto:{corpus}",
        metrics=tmp_path / "m.jsonl",
    )
    assert rc == 0
    rc, _ = sweep(
        tmp_path / "manual.jsonl", seed=7, trials=5, warm=corpus / "exact.jsonl"
    )
    assert rc == 0
    keep = ("trial_id", "params", "status", "score", "step")

    def records(p):
        return [
            {k: r[k] for k in keep}
            for r in map(json.loads, open(p).read().splitlines()[1:])
        ]

    assert records(tmp_path / "auto.jsonl") == records(tmp_path / "manual.jsonl")
    ws = [
        json.loads(line)
        for line in open(tmp_path / "m.jsonl")
        if '"warm_start"' in line
    ]
    assert len(ws) == 1
    sources = {s["match"]: s for s in ws[0]["sources"]}
    assert sources["exact"]["path"].endswith("exact.jsonl")
    assert sources["fuzzy"]["path"].endswith("fuzzy.jsonl")


def test_auto_warm_start_usage_errors(tmp_path):
    with pytest.raises(SystemExit) as e:
        run_cli(
            ["--workload", "quadratic", "--trials", "2", "--workers", "1",
             "--warm-start", "auto"]
        )
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        run_cli(
            ["--workload", "quadratic", "--trials", "2", "--workers", "1",
             "--warm-start", f"auto:{tmp_path}/nope"]
        )
    assert e.value.code == 2


def test_self_warm_start_guard_covers_fused_path(tmp_path):
    """The realpath guard now lives in the SHARED resolver: the fused
    path refuses self-feeding too (ISSUE 14 satellite)."""
    led = tmp_path / "sweep.jsonl"
    with pytest.raises(SystemExit) as e:
        run_cli(
            ["--workload", "fashion_mlp", "--algorithm", "tpe", "--fused",
             "--no-mesh", "--trials", "2", "--population", "2",
             "--ledger", str(led), "--warm-start", str(tmp_path / "." / "sweep.jsonl")]
        )
    assert e.value.code == 2


def test_corpus_resolve_cli_dry_run(corpus):
    rc, out = run_cli(
        ["corpus", "resolve", str(corpus), "--workload", "quadratic", "--json"]
    )
    assert rc == 0
    rep = json.loads(out)
    assert rep["observations"] == 8
    assert {s["match"] for s in rep["sources"]} == {"exact", "fuzzy"}


# -- CorpusCache -----------------------------------------------------------


def test_corpus_cache_exact_semantics_unchanged_prior_separate():
    space = live_space()
    cache = CorpusCache(space)
    params = space.canonical_params({"lr": 0.1, "reg": 0.3})
    cache.seed_from([{"status": "ok", "score": 0.4, "step": 10, "params": params}])
    cache.seed_prior([{"status": "ok", "score": 0.4, "step": 10, "params": params}])
    # exact: byte-identical to EvalCache — budget is part of the key
    hit = cache.get(params, 10, trial_id=1)
    assert hit is not None and hit.extra["cache_hit"] is True
    assert cache.get(params, 270, trial_id=2) is None
    # prior: the SAME point at a different budget serves as evidence
    prior = cache.get_prior(params, trial_id=3)
    assert prior.extra == {"fidelity": "prior", "prior_kind": "budget"}
    assert prior.score == pytest.approx(0.4) and prior.step == 10
    assert cache.prior_hits == 1
    # unseen point: no prior
    other = space.canonical_params({"lr": 2.0, "reg": 0.9})
    assert cache.get_prior(other, trial_id=4) is None


def test_corpus_cache_prior_prefers_same_space_and_higher_budget():
    space = live_space()
    cache = CorpusCache(space)
    params = space.canonical_params({"lr": 0.1, "reg": 0.3})
    cache.seed_prior(
        [{"status": "ok", "score": 0.2, "step": 10, "params": params}], fuzzy=True
    )
    assert cache.get_prior(params, 0).extra["prior_kind"] == "fuzzy"
    # same-space evidence displaces fuzzy...
    cache.seed_prior([{"status": "ok", "score": 0.5, "step": 10, "params": params}])
    assert cache.get_prior(params, 0).extra["prior_kind"] == "budget"
    # ...fuzzy can never displace it back
    cache.seed_prior(
        [{"status": "ok", "score": 0.9, "step": 99, "params": params}], fuzzy=True
    )
    p = cache.get_prior(params, 0)
    assert p.extra["prior_kind"] == "budget" and p.score == pytest.approx(0.5)
    # higher-budget same-space evidence wins over lower
    cache.seed_prior([{"status": "ok", "score": 0.7, "step": 270, "params": params}])
    assert cache.get_prior(params, 0).step == 270


# -- suggestion service ----------------------------------------------------


def serve_in_thread(server, sdir, ledger=None, idle_timeout=10.0):
    from mpi_opt_tpu.utils.metrics import null_logger

    out = {}

    def run():
        from mpi_opt_tpu.corpus.serve import serve_loop

        out.update(
            serve_loop(
                server,
                str(sdir),
                null_logger(),
                ledger=ledger,
                poll_seconds=0.01,
                idle_timeout=idle_timeout,
            )
        )

    th = threading.Thread(target=run)
    th.start()
    return th, out


def test_suggest_server_round_trip_lookup_and_resume(tmp_path):
    from mpi_opt_tpu.corpus import client
    from mpi_opt_tpu.corpus.serve import SuggestServer

    space = live_space()
    led = SweepLedger(str(tmp_path / "suggest.jsonl"))
    led.ensure_header(
        {"mode": "suggest", "algorithm": "tpe", "workload": "quadratic",
         "backend": "suggest", "seed": 0, "space_hash": space.space_hash()},
        space_spec=space.spec(),
    )
    server = SuggestServer(space, seed=0)
    th, summary = serve_in_thread(server, tmp_path / "sugg", ledger=led)
    try:
        ans = client.round_trip(str(tmp_path / "sugg"), {"op": "suggest", "n": 3})
        assert len(ans["params"]) == 3 and len(ans["units"]) == 3
        for p in ans["params"]:
            r = client.round_trip(
                str(tmp_path / "sugg"),
                {"op": "report", "params": p, "score": 0.5, "budget": 1},
            )
            assert r["ok"] is True
        # lookup: exact at the reported budget, prior at any other
        lk = client.round_trip(
            str(tmp_path / "sugg"),
            {"op": "lookup", "params": ans["params"][0], "budget": 1},
        )
        assert lk["hit"] == "exact"
        lk2 = client.round_trip(
            str(tmp_path / "sugg"),
            {"op": "lookup", "params": ans["params"][0], "budget": 99},
        )
        assert lk2["hit"] == "prior" and lk2["fidelity"] == "prior"
        # malformed ops are answered, never crash the server
        bad = client.round_trip(str(tmp_path / "sugg"), {"op": "nope"})
        assert "error" in bad
    finally:
        client.request_stop(str(tmp_path / "sugg"))
        th.join(timeout=30)
    assert not th.is_alive()
    assert summary["stopped"] and summary["reports"] == 3
    led.close()
    # resume: the ring and the report serial rebuild from the journal
    led2 = SweepLedger(str(tmp_path / "suggest.jsonl"))
    from mpi_opt_tpu.corpus.serve import SuggestServer as S2

    fresh = S2(space, seed=0)
    assert fresh.seed_from_ledger(led2.records) == 3
    assert fresh._next_id == 3
    led2.close()


def test_suggest_stop_drains_pending_and_consumes_flag(tmp_path):
    """The stop flag means 'finish what is queued, then exit': a
    request already on the spool when stop lands is still answered,
    and the consumed flag cannot instantly stop the NEXT server."""
    from mpi_opt_tpu.corpus import client
    from mpi_opt_tpu.corpus.serve import (
        SuggestServer,
        ensure_spool,
        serve_loop,
        stop_path,
    )
    from mpi_opt_tpu.utils.metrics import null_logger

    sdir = str(tmp_path / "sugg")
    ensure_spool(sdir)
    rid = client.request(sdir, {"op": "suggest", "n": 2})  # queued first
    client.request_stop(sdir)  # ...then stop, before any server runs
    server = SuggestServer(live_space(), seed=0)
    summary = serve_loop(server, sdir, null_logger(), poll_seconds=0.01)
    assert summary["stopped"] and summary["served"] == 1
    ans = client.wait_response(sdir, rid, timeout=5)
    assert ans is not None and len(ans["params"]) == 2  # answered, not dropped
    assert not os.path.exists(stop_path(sdir))  # flag consumed


def test_sweep_responses_expires_only_stale_files(tmp_path):
    from mpi_opt_tpu.corpus.serve import _sweep_responses

    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text("{}")
    new.write_text("{}")
    past = time.time() - 3600
    os.utime(old, (past, past))
    _sweep_responses(str(tmp_path), ttl_s=600)
    assert not old.exists() and new.exists()


def test_suggest_reports_journal_as_corpus_material(tmp_path):
    """A suggestion tenant's ledger is itself corpus material: its
    journaled reports index and resolve like any sweep's."""
    from mpi_opt_tpu.corpus.serve import SuggestServer

    space = live_space()
    c = tmp_path / "corpus"
    c.mkdir()
    led = SweepLedger(str(c / "suggest.jsonl"))
    led.ensure_header(
        {"mode": "suggest", "algorithm": "tpe", "workload": "quadratic",
         "backend": "suggest", "seed": 0, "space_hash": space.space_hash()},
        space_spec=space.spec(),
    )
    server = SuggestServer(space, seed=0)
    got = server.suggest(2)
    for p in got["params"]:
        server.report({"params": p, "score": 0.25, "budget": 2}, ledger=led)
    led.close()
    doc = cindex.index_corpus(str(c))
    assert doc["entries"][0]["ok"] == 2
    res = resolve(space, str(c), workload="quadratic")
    assert len(res.observations) == 2


def test_suggest_acquisition_engages_after_startup(tmp_path):
    """Past n_startup reports the served suggestions come from the
    acquisition kernel (differ from the cold uniform stream)."""
    from mpi_opt_tpu.corpus.serve import SuggestServer

    space = live_space()
    cold = SuggestServer(space, seed=3, n_startup=4)
    warm = SuggestServer(space, seed=3, n_startup=4)
    warm.ingest(
        [
            Observation(unit=np.full(2, 0.3, np.float32), score=float(s), budget=1)
            for s in range(6)
        ]
    )
    cold_units = np.asarray(cold.suggest(4)["units"])
    warm_units = np.asarray(warm.suggest(4)["units"])
    assert not np.allclose(cold_units, warm_units)


def test_suggest_tenant_parks_and_resumes_across_slices(tmp_path):
    """A suggestion tenant outliving its slice budget PARKS (exit 75)
    and the next slice's --resume rebuilds the ring from its journal:
    every report lands exactly once, the serial never aliases across
    slices, and the tenant still completes via its idle timeout."""
    from mpi_opt_tpu.corpus import client
    from mpi_opt_tpu.service.scheduler import SweepService
    from mpi_opt_tpu.service.spool import Spool

    state = tmp_path / "state"
    sdir = str(tmp_path / "sugg")
    spool = Spool(str(state))
    job = spool.submit(
        ["--workload", "quadratic", "--suggest-serve", sdir,
         "--suggest-idle-timeout", "0.4"],
        tenant="ext",
    )
    svc = SweepService(
        str(state), slice_boundaries=3, poll_seconds=0.02, drain_on_empty=True
    )

    def traffic():
        for i in range(6):  # more round trips than one slice's budget
            ans = client.round_trip(sdir, {"op": "suggest", "n": 2}, timeout=60)
            client.round_trip(
                sdir,
                {"op": "report", "params": ans["params"][0],
                 "score": 0.1 * i, "budget": 1},
                timeout=60,
            )

    th = threading.Thread(target=traffic)
    th.start()
    rc = svc.serve()
    th.join(timeout=60)
    assert rc == 0 and not th.is_alive()
    st = spool.tenant(job).status
    assert st["state"] == "done" and st["slices"] >= 2, st
    recs = [
        json.loads(line)
        for line in open(spool.tenant(job).ledger).read().splitlines()[1:]
    ]
    ids = [r["trial_id"] for r in recs]
    assert len(ids) == len(set(ids)) == 6, ids


def test_suggest_tenant_completes_under_sweep_service(tmp_path):
    """The suggestion server IS a schedulable tenant: submitted through
    the spool, sliced by the resident scheduler, completing (done) via
    its idle timeout — with its per-tenant ledger journaled."""
    from mpi_opt_tpu.service.scheduler import SweepService
    from mpi_opt_tpu.service.spool import Spool

    state = tmp_path / "state"
    sdir = tmp_path / "sugg"
    spool = Spool(str(state))
    job = spool.submit(
        ["--workload", "quadratic", "--suggest-serve", str(sdir),
         "--suggest-idle-timeout", "0.2"],
        tenant="ext",
    )
    svc = SweepService(
        str(state), slice_boundaries=100, poll_seconds=0.02, drain_on_empty=True
    )
    rc = svc.serve()
    assert rc == 0
    t = spool.tenant(job)
    assert t.status["state"] == "done"
    header = json.loads(open(t.ledger).read().splitlines()[0])
    assert header["config"]["mode"] == "suggest"
