import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_opt_tpu import Choice, IntUniform, LogUniform, SearchSpace, Uniform


@pytest.fixture
def space():
    return SearchSpace(
        {
            "lr": LogUniform(1e-4, 1e-1),
            "momentum": Uniform(0.5, 0.99),
            "layers": IntUniform(1, 4),
            "act": Choice(["relu", "tanh", "gelu"]),
        }
    )


def test_sample_shapes_and_ranges(space):
    key = jax.random.key(0)
    u = space.sample_unit(key, 100)
    assert u.shape == (100, 4)
    vals = space.from_unit(u)
    assert vals["lr"].shape == (100,)
    assert jnp.all(vals["lr"] >= 1e-4) and jnp.all(vals["lr"] <= 1e-1)
    assert jnp.all(vals["momentum"] >= 0.5) and jnp.all(vals["momentum"] <= 0.99)
    assert jnp.all(vals["layers"] >= 1) and jnp.all(vals["layers"] <= 4)
    assert jnp.all(vals["act"] >= 0) and jnp.all(vals["act"] <= 2)


def test_unit_roundtrip_continuous(space):
    key = jax.random.key(1)
    u = space.sample_unit(key, 50)
    vals = space.from_unit(u)
    u2 = space.to_unit(vals)
    # continuous dims roundtrip exactly (within float tolerance)
    np.testing.assert_allclose(u[:, 0], u2[:, 0], atol=1e-5)
    np.testing.assert_allclose(u[:, 1], u2[:, 1], atol=1e-5)
    # discrete dims roundtrip to the same bucket
    vals2 = space.from_unit(u2)
    np.testing.assert_array_equal(np.asarray(vals["layers"]), np.asarray(vals2["layers"]))
    np.testing.assert_array_equal(np.asarray(vals["act"]), np.asarray(vals2["act"]))


def test_loguniform_is_log_spaced(space):
    key = jax.random.key(2)
    vals = space.sample(key, 4000)
    lr = np.asarray(vals["lr"])
    # median of a log-uniform over [1e-4, 1e-1] is 10^-2.5
    assert 10**-2.8 < np.median(lr) < 10**-2.2


def test_materialize_row(space):
    row = np.array([0.5, 0.5, 0.5, 0.9])
    h = space.materialize_row(row)
    assert isinstance(h["lr"], float)
    assert isinstance(h["layers"], int)
    assert h["act"] == "gelu"


def test_discrete_mask(space):
    np.testing.assert_array_equal(space.discrete_mask(), [False, False, True, True])


def test_from_unit_is_jittable(space):
    f = jax.jit(space.from_unit)
    out = f(space.sample_unit(jax.random.key(3), 8))
    assert out["lr"].shape == (8,)
