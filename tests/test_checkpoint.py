"""Durable checkpoint/resume: kill a sweep mid-flight, resume, match the
uninterrupted run (SURVEY.md §2 row 13, §5)."""

import numpy as np
import pytest

from mpi_opt_tpu.algorithms import PBT, RandomSearch
from mpi_opt_tpu.backends.cpu import CPUBackend
from mpi_opt_tpu.backends.tpu import TPUPopulationBackend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.utils.checkpoint import SearchCheckpointer
from mpi_opt_tpu.workloads import get_workload


@pytest.fixture(scope="module")
def quad():
    return get_workload("quadratic")


def _best_units(algo):
    return sorted(tuple(np.round(t.unit, 6)) for t in algo.trials.values())


def test_kill_and_resume_matches_uninterrupted(tmp_path, quad):
    """Random search through the CPU backend: interrupt after 2 batches,
    resume from disk in a FRESH process-equivalent (new algorithm/backend
    objects), finish; the trial set and best score must equal the
    uninterrupted run's exactly."""
    space = quad.default_space()

    # uninterrupted reference
    ref = RandomSearch(space, seed=11, max_trials=12, budget=5)
    b = CPUBackend(quad, n_workers=1)
    run_search(ref, b)
    b.close()

    # interrupted run: checkpoint every batch, stop after 2
    ckpt_dir = str(tmp_path / "ck")
    algo = RandomSearch(space, seed=11, max_trials=12, budget=5)
    b1 = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(ckpt_dir, every=1) as ck:
        run_search(algo, b1, max_batches=2, checkpointer=ck)
    b1.close()
    assert 0 < sum(t.score is not None for t in algo.trials.values()) < 12

    # fresh objects, resume from disk, run to completion
    algo2 = RandomSearch(space, seed=0, max_trials=12, budget=5)
    b2 = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(ckpt_dir, every=1) as ck2:
        step = ck2.restore_into(algo2, b2)
        assert step == 2
        run_search(algo2, b2, checkpointer=ck2)
    b2.close()

    assert algo2.finished()
    assert _best_units(algo2) == _best_units(ref)
    assert algo2.best().score == pytest.approx(ref.best().score, abs=1e-6)


def test_tpu_backend_pool_roundtrip(tmp_path):
    """PBT through the population backend: kill mid-sweep, resume with a
    fresh backend whose slot pool is restored from orbax; the finished
    search must match the uninterrupted run exactly (weights inherited
    across the kill boundary, not retrained)."""
    wl = get_workload("fashion_mlp", n_train=256, n_val=128)
    wl.batch_size = 16
    space = wl.default_space()

    def make_algo():
        return PBT(space, seed=21, population=4, generations=3, steps_per_generation=4)

    def make_backend():
        return TPUPopulationBackend(wl, population=4, seed=21)

    ref = make_algo()
    run_search(ref, make_backend())

    ckpt_dir = str(tmp_path / "ck")
    algo = make_algo()
    with SearchCheckpointer(ckpt_dir, every=1) as ck:
        run_search(algo, make_backend(), max_batches=2, checkpointer=ck)

    algo2 = make_algo()
    b2 = make_backend()
    with SearchCheckpointer(ckpt_dir, every=1) as ck2:
        assert ck2.restore_into(algo2, b2) == 2
        run_search(algo2, b2, checkpointer=ck2)

    assert algo2.finished()
    ref_scores = {t.trial_id: t.score for t in ref.trials.values()}
    got_scores = {t.trial_id: t.score for t in algo2.trials.values()}
    assert set(got_scores) == set(ref_scores)
    for tid, s in ref_scores.items():
        assert got_scores[tid] == pytest.approx(s, abs=1e-6), tid


def test_restore_into_empty_dir_is_none(tmp_path, quad):
    algo = RandomSearch(quad.default_space(), seed=1, max_trials=4, budget=2)
    b = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(str(tmp_path / "empty")) as ck:
        assert ck.restore_into(algo, b) is None
    b.close()


def test_cli_checkpoint_resume_flow(tmp_path):
    """End-to-end through the CLI flags: run, interrupt (via tiny trial
    budget split across invocations is not expressible — instead verify
    the flags wire up: a full run writes checkpoints, and --resume on a
    finished search exits cleanly without re-running trials)."""
    import json

    from mpi_opt_tpu.cli import main

    ckpt = str(tmp_path / "cli_ck")
    rc = main(
        [
            "--workload", "quadratic", "--algorithm", "random", "--trials", "6",
            "--budget", "3", "--backend", "cpu", "--workers", "1",
            "--checkpoint-dir", ckpt,
        ]
    )
    assert rc == 0
    ck = SearchCheckpointer(ckpt)
    assert ck.latest_step() is not None
    ck.close()


def test_metadata_probe_failure_warns_before_fallback(tmp_path, quad):
    """The item-metadata probe is best-effort, but its blanket except
    must not be SILENT: a probe that always fails (an orbax API break)
    should be visible as a warning naming the exception and step, while
    the directory-listing fallback still resolves the snapshot items."""
    space = quad.default_space()
    algo = RandomSearch(space, seed=13, max_trials=4, budget=2)
    b = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(str(tmp_path / "ck"), every=1) as ck:
        run_search(algo, b, max_batches=1, checkpointer=ck)
        # drain the async save: the directory-listing fallback can only
        # see a step whose write has committed
        ck._mgr.wait_until_finished()
        step = ck.latest_step()
        assert step is not None

        def broken_probe(_step):
            raise RuntimeError("orbax item_metadata API drifted")

        ck._mgr.item_metadata = broken_probe
        with pytest.warns(RuntimeWarning, match=r"metadata probe failed at step 1.*RuntimeError"):
            names = ck._item_names(step)
        assert "search" in names  # the fallback still found the items
    b.close()
