"""Durable checkpoint/resume: kill a sweep mid-flight, resume, match the
uninterrupted run (SURVEY.md §2 row 13, §5)."""

import numpy as np
import pytest

from mpi_opt_tpu.algorithms import PBT, RandomSearch
from mpi_opt_tpu.backends.cpu import CPUBackend
from mpi_opt_tpu.backends.tpu import TPUPopulationBackend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.utils.checkpoint import SearchCheckpointer
from mpi_opt_tpu.workloads import get_workload


@pytest.fixture(scope="module")
def quad():
    return get_workload("quadratic")


def _best_units(algo):
    return sorted(tuple(np.round(t.unit, 6)) for t in algo.trials.values())


def test_kill_and_resume_matches_uninterrupted(tmp_path, quad):
    """Random search through the CPU backend: interrupt after 2 batches,
    resume from disk in a FRESH process-equivalent (new algorithm/backend
    objects), finish; the trial set and best score must equal the
    uninterrupted run's exactly."""
    space = quad.default_space()

    # uninterrupted reference
    ref = RandomSearch(space, seed=11, max_trials=12, budget=5)
    b = CPUBackend(quad, n_workers=1)
    run_search(ref, b)
    b.close()

    # interrupted run: checkpoint every batch, stop after 2
    ckpt_dir = str(tmp_path / "ck")
    algo = RandomSearch(space, seed=11, max_trials=12, budget=5)
    b1 = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(ckpt_dir, every=1) as ck:
        run_search(algo, b1, max_batches=2, checkpointer=ck)
    b1.close()
    assert 0 < sum(t.score is not None for t in algo.trials.values()) < 12

    # fresh objects, resume from disk, run to completion
    algo2 = RandomSearch(space, seed=0, max_trials=12, budget=5)
    b2 = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(ckpt_dir, every=1) as ck2:
        step = ck2.restore_into(algo2, b2)
        assert step == 2
        run_search(algo2, b2, checkpointer=ck2)
    b2.close()

    assert algo2.finished()
    assert _best_units(algo2) == _best_units(ref)
    assert algo2.best().score == pytest.approx(ref.best().score, abs=1e-6)


def test_tpu_backend_pool_roundtrip(tmp_path):
    """PBT through the population backend: kill mid-sweep, resume with a
    fresh backend whose slot pool is restored from orbax; the finished
    search must match the uninterrupted run exactly (weights inherited
    across the kill boundary, not retrained)."""
    wl = get_workload("fashion_mlp", n_train=256, n_val=128)
    wl.batch_size = 16
    space = wl.default_space()

    def make_algo():
        return PBT(space, seed=21, population=4, generations=3, steps_per_generation=4)

    def make_backend():
        return TPUPopulationBackend(wl, population=4, seed=21)

    ref = make_algo()
    run_search(ref, make_backend())

    ckpt_dir = str(tmp_path / "ck")
    algo = make_algo()
    with SearchCheckpointer(ckpt_dir, every=1) as ck:
        run_search(algo, make_backend(), max_batches=2, checkpointer=ck)

    algo2 = make_algo()
    b2 = make_backend()
    with SearchCheckpointer(ckpt_dir, every=1) as ck2:
        assert ck2.restore_into(algo2, b2) == 2
        run_search(algo2, b2, checkpointer=ck2)

    assert algo2.finished()
    ref_scores = {t.trial_id: t.score for t in ref.trials.values()}
    got_scores = {t.trial_id: t.score for t in algo2.trials.values()}
    assert set(got_scores) == set(ref_scores)
    for tid, s in ref_scores.items():
        assert got_scores[tid] == pytest.approx(s, abs=1e-6), tid


def test_restore_into_empty_dir_is_none(tmp_path, quad):
    algo = RandomSearch(quad.default_space(), seed=1, max_trials=4, budget=2)
    b = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(str(tmp_path / "empty")) as ck:
        assert ck.restore_into(algo, b) is None
    b.close()


def test_cli_checkpoint_resume_flow(tmp_path):
    """End-to-end through the CLI flags: run, interrupt (via tiny trial
    budget split across invocations is not expressible — instead verify
    the flags wire up: a full run writes checkpoints, and --resume on a
    finished search exits cleanly without re-running trials)."""
    import json

    from mpi_opt_tpu.cli import main

    ckpt = str(tmp_path / "cli_ck")
    rc = main(
        [
            "--workload", "quadratic", "--algorithm", "random", "--trials", "6",
            "--budget", "3", "--backend", "cpu", "--workers", "1",
            "--checkpoint-dir", ckpt,
        ]
    )
    assert rc == 0
    ck = SearchCheckpointer(ckpt)
    assert ck.latest_step() is not None
    ck.close()


def test_restore_falls_back_to_last_good_snapshot(tmp_path, quad):
    """Corrupt the LATEST step (silent bit-rot): restore_into must
    quarantine it (rename, never delete), fall back to the next older
    verified step, and the resumed search must still finish with the
    uninterrupted run's exact trial set — the last-good-fallback
    guarantee that keeps a poisoned snapshot from crash-looping the
    restart budget."""
    from mpi_opt_tpu.utils import integrity
    from mpi_opt_tpu.workloads.chaos import inject_corrupt_save

    space = quad.default_space()
    ref = RandomSearch(space, seed=11, max_trials=12, budget=5)
    b = CPUBackend(quad, n_workers=1)
    run_search(ref, b)
    b.close()

    ckpt_dir = str(tmp_path / "ck")
    algo = RandomSearch(space, seed=11, max_trials=12, budget=5)
    b1 = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(ckpt_dir, every=1) as ck:
        run_search(algo, b1, max_batches=3, checkpointer=ck)
    b1.close()

    inject_corrupt_save(ckpt_dir)  # latest = step 3
    events = []
    integrity.set_observer(lambda event, **f: events.append((event, f)))
    try:
        algo2 = RandomSearch(space, seed=0, max_trials=12, budget=5)
        b2 = CPUBackend(quad, n_workers=1)
        with SearchCheckpointer(ckpt_dir, every=1) as ck2:
            step = ck2.restore_into(algo2, b2)
            assert step == 2  # walked back past the poisoned step 3
            run_search(algo2, b2, checkpointer=ck2)
        b2.close()
    finally:
        integrity.clear_observer()
    assert [e for e, _ in events] == ["snapshot_corrupt"]
    assert events[0][1]["step"] == 3
    import os

    assert os.path.isdir(os.path.join(ckpt_dir, "3.corrupt"))  # evidence kept
    assert algo2.finished()
    assert _best_units(algo2) == _best_units(ref)
    assert algo2.best().score == pytest.approx(ref.best().score, abs=1e-6)


def test_search_checkpointer_keep_depth_is_fallback_budget(tmp_path, quad):
    """keep defaults to 3: the latest step may be the torn one, leaving
    two verified fallbacks (README documents keep as the fallback
    budget)."""
    import os

    space = quad.default_space()
    algo = RandomSearch(space, seed=5, max_trials=6, budget=2)
    b = CPUBackend(quad, n_workers=1)
    ckpt_dir = str(tmp_path / "ck")
    with SearchCheckpointer(ckpt_dir, every=1) as ck:
        run_search(algo, b, checkpointer=ck)
    b.close()
    kept = sorted(int(d) for d in os.listdir(ckpt_dir) if d.isdigit())
    assert kept == [4, 5, 6]


@pytest.mark.slow
def test_sigkill_during_async_save_resumes_on_prior_verified_step(tmp_path):
    """The ISSUE-5 acceptance drill for the driver path, end to end
    through real processes: SIGKILL a journaled+checkpointed sweep while
    orbax's async writer may still be in flight; `fsck --repair`
    quarantines whatever the kill tore; `--resume` lands on the prior
    verified step with the journaled ledger still consistent, and the
    finished sweep matches a clean run's best."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time

    from mpi_opt_tpu.cli import main
    from mpi_opt_tpu.utils import integrity

    ck = str(tmp_path / "ck")
    led = str(tmp_path / "sweep.jsonl")
    # chaos slow=1.0: every trial sleeps 0.3 s (scores untouched), so
    # the sweep is mid-flight long enough for the kill to land between
    # a step's commit and the next async save
    args = [
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", "24", "--budget", "200", "--workers", "1",
        "--seed", "3", "--platform", "cpu", "--no-mesh",
        "--chaos", "slow=1.0,slow_s=0.3,seed=0",
        "--checkpoint-dir", ck, "--ledger", led,
    ]
    p = subprocess.Popen(
        [sys.executable, "-m", "mpi_opt_tpu", *args],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        cwd="/root/repo",
    )
    try:
        # kill as soon as a second step's commit marker lands — the
        # next async save (and the process) die mid-flight
        deadline = time.time() + 300
        while time.time() < deadline:
            steps = [
                d for d in (os.listdir(ck) if os.path.isdir(ck) else [])
                if d.isdigit()
                and os.path.exists(os.path.join(ck, d, "_CHECKPOINT_METADATA"))
            ]
            if len(steps) >= 2 or p.poll() is not None:
                break
            time.sleep(0.02)
        assert p.poll() is None, "sweep finished before the kill landed"
        os.kill(p.pid, signal.SIGKILL)
    finally:
        p.wait()

    # repair: quarantine anything the kill tore (rc 1 when it found
    # debris, 0 when the kill happened to land between writes)
    assert integrity.fsck_main([ck, "--repair", "--json"]) in (0, 1)
    # the journal survived append-fsync-consistent
    from mpi_opt_tpu.ledger.store import validate_ledger

    assert validate_ledger(led) == []
    # resume completes from the prior verified step
    rc = main(args + ["--resume"])
    assert rc == 0
    # post-resume audit: everything verified, journal consistent with
    # the newest snapshot
    assert integrity.fsck_main([ck, "--json", "--ledger", led]) == 0
    # and the recovered sweep found the clean run's best
    clean = str(tmp_path / "clean.jsonl")
    assert main([
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", "24", "--budget", "200", "--workers", "1",
        "--seed", "3", "--ledger", clean,
        "--chaos", "slow=1.0,slow_s=0.3,seed=0",
    ]) == 0
    from mpi_opt_tpu.ledger.report import summarize_ledger

    got = summarize_ledger(led)["best"]
    want = summarize_ledger(clean)["best"]
    assert got["score"] == pytest.approx(want["score"], abs=1e-9)
    assert got["trial_id"] == want["trial_id"]


def test_metadata_probe_failure_warns_before_fallback(tmp_path, quad):
    """The item-metadata probe is best-effort, but its blanket except
    must not be SILENT: a probe that always fails (an orbax API break)
    should be visible as a warning naming the exception and step, while
    the directory-listing fallback still resolves the snapshot items."""
    space = quad.default_space()
    algo = RandomSearch(space, seed=13, max_trials=4, budget=2)
    b = CPUBackend(quad, n_workers=1)
    with SearchCheckpointer(str(tmp_path / "ck"), every=1) as ck:
        run_search(algo, b, max_batches=1, checkpointer=ck)
        # drain the async save: the directory-listing fallback can only
        # see a step whose write has committed
        ck._mgr.wait_until_finished()
        step = ck.latest_step()
        assert step is not None

        def broken_probe(_step):
            raise RuntimeError("orbax item_metadata API drifted")

        ck._mgr.item_metadata = broken_probe
        with pytest.warns(RuntimeWarning, match=r"metadata probe failed at step 1.*RuntimeError"):
            names = ck._item_names(step)
        assert "search" in names  # the fallback still found the items
    b.close()
