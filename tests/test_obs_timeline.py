"""Timeline export, bubble attribution, and the roofline verdict
(ISSUE 11: obs/timeline.py + obs/bubbles.py).

Covers: the Chrome trace-event schema gate (what the tier-1
TIMELINE_DRILL asserts), bubble edge cases (single-span streams,
overlapping threads on one rank, clock-skewed multi-rank merges with
gaps clamped >= 0, legacy embeds without the new sections), the
staging-overlap promotion from StagingEngine counters to trace attrs,
roofline classification + platform-cap resolution, the new absolute
gate keys (idle_frac / min_overlap / min_mxu_frac), and the end-to-end
acceptance drill: a traced wave sweep whose bubble attribution
reproduces the engine's measured staging overlap within 5%.
"""

from __future__ import annotations

import json
import os

import pytest

from mpi_opt_tpu.obs import bubbles, events, timeline, trace
from mpi_opt_tpu.obs.report import attribute, trace_main


@pytest.fixture(autouse=True)
def _clean_trace_state():
    saved = trace.save()
    trace.deconfigure()
    yield
    trace.deconfigure(saved)


def _rec(span, ts, dur, **attrs):
    return {
        "event": "span",
        "span": span,
        "ts": ts,
        "dur_s": dur,
        "self_s": attrs.pop("self_s", dur),
        "tid": attrs.pop("tid", 0),
        **attrs,
    }


def _write_stream(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return str(path)


# -- bubble analysis edge cases ------------------------------------------


def test_single_busy_span_has_zero_idle():
    rep = bubbles.analyze([_rec("train", 101.0, 1.0)])
    assert rep["busy_s"] == pytest.approx(1.0)
    assert rep["idle_s"] == 0.0 and rep["gaps"] == 0
    assert rep["idle_frac"] == 0.0
    # the invariant the drill asserts: busy + idle == wall exactly
    assert rep["busy_s"] + rep["idle_s"] == pytest.approx(rep["wall_s"])


def test_single_nonbusy_span_is_all_idle_attributed():
    """A stream holding only a compile span: its whole window is one
    gap, fully attributed to compile."""
    rep = bubbles.analyze([_rec("compile", 102.0, 2.0, cache="cold")])
    assert rep["idle_s"] == pytest.approx(2.0)
    assert rep["by_cause"] == {"compile": 2.0}
    assert rep["idle_frac"] == pytest.approx(1.0)


def test_gap_attribution_by_cause_and_unattributed():
    recs = [
        _rec("train", 101.0, 1.0),  # busy [100, 101]
        _rec("compile", 102.0, 1.0, cache="cold"),  # covers gap [101, 102]
        _rec("save", 102.5, 0.5),  # checkpoint [102, 102.5]
        _rec("train", 104.0, 1.0),  # busy [103, 104]
    ]
    rep = bubbles.analyze(recs)
    # gaps: [101, 103] = 2s; compile covers 1s, save 0.5s, 0.5s uncovered
    assert rep["idle_s"] == pytest.approx(2.0)
    assert rep["by_cause"]["compile"] == pytest.approx(1.0)
    assert rep["by_cause"]["checkpoint"] == pytest.approx(0.5)
    assert rep["by_cause"]["unattributed"] == pytest.approx(0.5)
    assert rep["largest_gap_s"] == pytest.approx(2.0)


def test_overlapping_threads_on_one_rank_merge_busy():
    """The staging worker's stage_out overlapping the main thread's
    train is ONE continuous busy region — overlap is not idle."""
    recs = [
        _rec("train", 102.0, 2.0, tid=0),  # [100, 102]
        _rec("stage_out", 103.0, 2.0, tid=1),  # [101, 103] overlaps
    ]
    rep = bubbles.analyze(recs)
    assert rep["idle_s"] == 0.0
    assert rep["busy_s"] == pytest.approx(3.0)
    assert rep["wall_s"] == pytest.approx(3.0)


def test_clock_skewed_multi_rank_never_negative_idle():
    """Ranks are judged on their OWN clocks: a rank whose timestamps sit
    minutes away from another's cannot manufacture (negative) idle in
    the merge — per-rank windows, gaps clamped >= 0 by construction."""
    recs = [
        _rec("train", 101.0, 1.0, rank=0),
        _rec("train", 103.0, 1.0, rank=0),
        # rank 1's clock is ~10 minutes skewed; identical local shape
        _rec("train", 701.0, 1.0, rank=1),
        _rec("train", 703.0, 1.0, rank=1),
    ]
    rep = bubbles.analyze(recs)
    assert set(rep["per_rank"]) == {"rank0", "rank1"}
    for entry in rep["per_rank"].values():
        assert entry["idle_s"] >= 0.0
        assert entry["idle_s"] == pytest.approx(1.0)  # the local [end, begin] gap
        assert entry["wall_s"] == pytest.approx(3.0)
    # totals are per-rank sums, not a skew-spanning merged window
    assert rep["wall_s"] == pytest.approx(6.0)
    assert rep["idle_s"] == pytest.approx(2.0)
    assert rep["busy_s"] + rep["idle_s"] == pytest.approx(rep["wall_s"])


def test_tenant_groups_are_separate():
    recs = [
        _rec("train", 101.0, 1.0, tenant="alice"),
        _rec("train", 103.0, 1.0, tenant="bob"),
    ]
    rep = bubbles.analyze(recs)
    assert set(rep["per_rank"]) == {"alice:rank0", "bob:rank0"}
    assert rep["idle_s"] == 0.0  # each tenant's window is just its span


def test_analyze_empty_returns_none():
    assert bubbles.analyze([]) is None
    assert bubbles.stream_idle_frac("/nonexistent/path.jsonl") is None


def test_stream_idle_frac_reads_a_file(tmp_path):
    path = _write_stream(
        tmp_path / "m.jsonl",
        [_rec("train", 101.0, 1.0), _rec("train", 103.0, 1.0)],
    )
    assert bubbles.stream_idle_frac(path) == pytest.approx(1.0 / 3.0, abs=1e-3)


# -- staging-overlap promotion -------------------------------------------


def test_staging_summary_prefers_engine_attrs():
    """The newest stage span's cumulative overlap_s/wait_s attrs ARE the
    engine's accounting — exact, not re-derived from durations."""
    recs = [
        _rec("stage_out", 10.5, 0.4, tid=1, bytes=1000, overlap_s=0.3, wait_s=0.05),
        _rec("stage_wait", 11.0, 0.1, overlap_s=0.35, wait_s=0.15),
    ]
    s = bubbles.staging_summary(recs)
    assert s["overlap_s"] == pytest.approx(0.35)
    assert s["wait_s"] == pytest.approx(0.15)
    assert s["transfer_s"] == pytest.approx(0.4)
    assert s["overlap_frac"] == pytest.approx(0.875)
    assert s["staged_bytes"] == 1000 and s["drains"] == 1


def test_staging_summary_mid_generation_kill_evidence():
    """A wave run killed before any drain still carries overlap
    evidence: the last stage_out's cumulative attrs (the satellite fix
    — summary counters alone die with the process)."""
    recs = [
        _rec("stage_out", 10.5, 0.4, tid=1, bytes=500, overlap_s=0.2, wait_s=0.0),
        _rec("stage_out", 11.0, 0.4, tid=1, bytes=500, overlap_s=0.6, wait_s=0.0),
    ]
    s = bubbles.staging_summary(recs)
    assert s["overlap_s"] == pytest.approx(0.6)
    assert s["wait_s"] == 0.0 and s["drains"] == 0


def test_staging_summary_sums_per_rank_engines():
    """Each rank runs its OWN StagingEngine: a multi-rank merge must sum
    per-group cumulative counters, not divide one rank's overlap by
    every rank's transfer (which would under-report overlap ~Nx)."""
    recs = []
    for rank in (0, 1, 2, 3):
        recs += [
            _rec("stage_out", 10.5 + rank, 0.4, tid=1, rank=rank,
                 bytes=100, overlap_s=0.38, wait_s=0.02),
            _rec("stage_wait", 11.0 + rank, 0.02, rank=rank,
                 overlap_s=0.38, wait_s=0.02),
        ]
    s = bubbles.staging_summary(recs)
    assert s["transfer_s"] == pytest.approx(1.6)
    assert s["overlap_s"] == pytest.approx(4 * 0.38)
    assert s["wait_s"] == pytest.approx(4 * 0.02)
    # a fully-hiding schedule reads ~95% on EVERY rank, so merged too
    assert s["overlap_frac"] == pytest.approx(0.95)
    assert s["staged_bytes"] == 400 and s["drains"] == 4


def test_stream_idle_tracker_matches_one_shot(tmp_path):
    """The scheduler's incremental tracker (reads only appended bytes)
    agrees with the one-shot whole-file computation, across polls and
    with a torn trailing line left un-consumed until completed."""
    path = str(tmp_path / "m.jsonl")
    first = [_rec("train", 101.0, 1.0), _rec("compile", 102.0, 0.8)]
    more = [_rec("train", 104.0, 1.0), _rec("stage_out", 104.5, 0.3, tid=1)]
    tracker = bubbles.StreamIdleTracker(path)
    assert tracker.poll() is None  # stream does not exist yet: no crash
    _write_stream(path, first)
    assert tracker.poll() == bubbles.stream_idle_frac(path)
    # append more + a torn half-line: the tracker must stop at the last
    # complete line and pick the rest up once finished
    with open(path, "a") as f:
        for r in more:
            f.write(json.dumps(r) + "\n")
        f.write('{"event": "span", "span": "tr')  # torn mid-append
    torn_val = tracker.poll()
    with open(path, "a") as f:
        f.write('ain", "ts": 106.0, "dur_s": 0.5, "self_s": 0.5, "tid": 0}\n')
    assert tracker.poll() == bubbles.stream_idle_frac(path)
    assert torn_val is not None  # the torn poll still judged complete lines


def test_staging_summary_legacy_stream_falls_back_to_durations():
    recs = [
        _rec("stage_out", 10.5, 0.4, tid=1),
        _rec("stage_wait", 11.0, 0.1),
    ]
    s = bubbles.staging_summary(recs)
    assert s["transfer_s"] == pytest.approx(0.4)
    assert s["wait_s"] == pytest.approx(0.1)
    assert s["overlap_s"] == pytest.approx(0.3)


def test_staging_engine_emits_cumulative_attrs(tmp_path):
    """The real engine: stage_out and stage_wait spans carry the
    cumulative accounting, and the final drain's attrs equal the
    engine's own counters exactly."""
    import jax.numpy as jnp

    from mpi_opt_tpu.train.staging import StagingEngine
    from mpi_opt_tpu.utils.metrics import MetricsLogger

    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(path=path)
    prior = trace.configure(m)
    try:
        with StagingEngine() as engine:
            engine.stage_out({"x": jnp.arange(64.0)}, lambda h: None)
            engine.drain()
            engine.stage_out({"x": jnp.arange(64.0)}, lambda h: None)
            engine.drain()
            final_wait, final_overlap = engine.wait_s, engine.overlap_s
    finally:
        trace.deconfigure(prior)
        m.close()
    from mpi_opt_tpu.obs.report import load_stream

    spans = [r for r in load_stream(path) if r.get("event") == "span"]
    outs = [r for r in spans if r["span"] == "stage_out"]
    waits = [r for r in spans if r["span"] == "stage_wait"]
    assert len(outs) == 2 and len(waits) == 2
    for r in outs + waits:
        assert isinstance(r["overlap_s"], (int, float)), r
        assert isinstance(r["wait_s"], (int, float)), r
    last = max(waits, key=lambda r: r["ts"])
    assert last["wait_s"] == pytest.approx(final_wait, abs=1e-4)
    assert last["overlap_s"] == pytest.approx(final_overlap, abs=1e-4)


# -- the roofline verdict -------------------------------------------------


def test_resolve_peak_cli_beats_calibration():
    spans = [_rec("setup", 100.0, 0.1, device="TPU v5 lite")]
    assert bubbles.resolve_peak(spans, 200.0) == (200.0, "cli")
    peak, src = bubbles.resolve_peak(spans)
    assert peak == 157.0 and src == "calibration:TPU v5 lite"
    assert bubbles.resolve_peak([_rec("setup", 0.1, 0.1, device="martian")]) == (
        None,
        None,
    )


def test_roofline_per_launch_transfer_bound_on_stall():
    recs = [
        # launch 1: a third of its window is un-hidden stage_wait
        _rec("train", 103.0, 3.0, flops=10e12, launch=1, self_s=2.0),
        _rec("stage_wait", 102.5, 1.2),
        # launch 2: clean compute
        _rec("train", 105.0, 1.0, flops=10e12, launch=2),
    ]
    roof = bubbles.roofline(recs, bubbles.analyze(recs), bubbles.staging_summary(recs), 157.0, "cli")
    by_launch = {e["launch"]: e for e in roof["per_launch"]}
    assert by_launch[1]["bound"] == "transfer-bound"
    assert by_launch[1]["stall_frac"] > bubbles.TRANSFER_BOUND_FRAC
    assert by_launch[2]["bound"] == "compute-bound"
    assert by_launch[2]["mxu_frac"] == pytest.approx(10.0 / 157.0, abs=1e-3)


def test_roofline_run_verdict_precedence():
    # bubble-bound: half the wall is a bare gap
    idle = [_rec("train", 101.0, 1.0, flops=1e12), _rec("train", 104.0, 1.0, flops=1e12)]
    rep = attribute({"s": idle}, peak_tflops=157.0)
    assert rep["roofline"]["bound"] == "bubble-bound"
    # transfer-bound: low idle, but waits dominate the wall
    xfer = [
        _rec("train", 102.0, 2.0, flops=1e12),
        _rec("stage_wait", 103.5, 1.5, overlap_s=0.1, wait_s=1.5),
        _rec("stage_out", 103.4, 1.4, tid=1),
    ]
    rep = attribute({"s": xfer}, peak_tflops=157.0)
    assert rep["roofline"]["bound"] == "transfer-bound"
    # compute-bound: busy wall, no staging
    comp = [_rec("train", 101.0, 1.0, flops=1e12), _rec("train", 102.0, 1.0, flops=1e12)]
    rep = attribute({"s": comp}, peak_tflops=157.0)
    assert rep["roofline"]["bound"] == "compute-bound"
    assert rep["roofline"]["mxu_frac"] == pytest.approx(1.0 / 157.0, abs=1e-4)


def test_attribution_sections_none_without_spans():
    rep = attribute({"s": [{"event": "batch", "ts": 100.0}]})
    assert rep["bubbles"] is None
    assert rep["staging"] is None
    assert rep["roofline"] is None


# -- new attrs are registry-gated (satellite 1) ---------------------------


def test_new_span_attrs_registered():
    for attr in ("overlap_s", "wait_s", "idle_gap_s", "bound", "peak_tflops", "device"):
        assert events.is_span_attr(attr), attr


# -- the timeline export --------------------------------------------------


def _two_rank_streams():
    return {
        "rank0.out": [
            _rec("setup", 100.5, 0.5, rank=0, device="TPU v5 lite"),
            _rec("compile", 101.0, 0.5, rank=0, cache="cold"),
            _rec("train", 103.0, 2.0, rank=0, flops=4e12, launch=1),
            _rec("stage_out", 103.5, 0.4, rank=0, tid=1, bytes=1000),
            {"event": "preempt_drain", "ts": 103.6, "rank": 0},
        ],
        "rank1.out": [
            _rec("train", 104.0, 1.5, rank=1, flops=3e12, launch=1),
        ],
    }


def test_timeline_schema_and_structure(tmp_path):
    streams = _two_rank_streams()
    doc = timeline.build(streams, peak_tflops=157.0)
    assert timeline.validate_timeline(doc) == []
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X" and e["cat"] == "span"]
    spans = [r for recs in streams.values() for r in recs if r.get("event") == "span"]
    assert len(xs) == len(spans)
    # per-rank process rows with names, per-thread tracks
    names = {
        e["args"]["name"]
        for e in evs
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert names == {"rank 0", "rank 1"}
    tnames = {
        e["args"]["name"]
        for e in evs
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert any("staging" in n for n in tnames)
    # span attrs ride as args; roofline verdict lands on train events
    train_ev = next(e for e in xs if e["name"] == "train" and e["args"].get("flops") == 4e12)
    assert train_ev["args"]["peak_tflops"] == 157.0
    assert train_ev["args"]["bound"] == "compute-bound"
    assert train_ev["args"]["mxu_frac"] == pytest.approx(2.0 / 157.0, abs=1e-3)
    # non-span events become instants; the bubble analysis its own track
    assert any(e["ph"] == "i" and e["name"] == "preempt_drain" for e in evs)
    idle = [e for e in evs if e.get("cat") == "bubble"]
    assert idle and all(e["tid"] == timeline.IDLE_TID for e in idle)
    assert all("idle_gap_s" in e["args"] for e in idle)
    # ts are normalized to the earliest begin (no negative timestamps)
    assert min(e["ts"] for e in evs) >= 0
    # write path: atomic, loadable
    out = str(tmp_path / "tl.json")
    n = timeline.write_timeline(streams, out)
    with open(out) as f:
        assert len(json.load(f)["traceEvents"]) == n


def test_timeline_empty_and_validator_catches_damage():
    doc = timeline.build({})
    assert doc["traceEvents"] == [] and timeline.validate_timeline(doc) == []
    assert timeline.validate_timeline("nope")
    assert timeline.validate_timeline({"traceEvents": [{"ph": "X"}]})
    bad_dur = {"traceEvents": [{"name": "t", "ph": "X", "pid": 1, "tid": 0, "ts": 0, "dur": -1}]}
    assert any("dur" in p for p in timeline.validate_timeline(bad_dur))


def test_trace_cli_timeline_flag(tmp_path, capsys):
    path = _write_stream(tmp_path / "m.jsonl", [_rec("train", 101.0, 1.0, launch=1)])
    out = str(tmp_path / "tl.json")
    assert trace_main([path, "--timeline", out, "--json"]) == 0
    captured = capsys.readouterr()
    json.loads(captured.out)  # --json stdout stays one parseable object
    assert "timeline:" in captured.err
    with open(out) as f:
        assert timeline.validate_timeline(json.load(f)) == []
    # --timeline cannot combine with --diff (one run's streams only)
    with pytest.raises(SystemExit) as e:
        trace_main(["--diff", path, path, "--timeline", out])
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        trace_main([path, "--peak-tflops", "-3"])
    assert e.value.code == 2


# -- the gate: idle_frac / min_overlap / min_mxu_frac ---------------------


def _busy_stream(stall_s=0.0):
    """A synthetic traced run: 4 train launches back-to-back, with an
    optional seeded staging stall (a bare device-idle hole covered only
    by stage_wait) in the middle."""
    recs = [_rec("setup", 100.2, 0.2, device="TPU v5 lite")]
    t = 100.2
    for launch in range(1, 5):
        if launch == 3 and stall_s:
            recs.append(_rec("stage_wait", t + stall_s, stall_s, overlap_s=0.0, wait_s=stall_s))
            t += stall_s
        recs.append(_rec("train", t + 1.0, 1.0, flops=40e12, launch=launch))
        t += 1.0
    return recs


def test_gate_idle_frac_seeded_staging_stall(tmp_path, capsys):
    """The acceptance contract: a --gate with an idle_frac budget exits
    1 on a seeded staging-stall run and 0 on self-diff."""
    base = _write_stream(tmp_path / "base.jsonl", _busy_stream())
    stalled = _write_stream(tmp_path / "new.jsonl", _busy_stream(stall_s=4.0))
    tol = str(tmp_path / "tol.json")
    with open(tol, "w") as f:
        json.dump({"default": 10.0, "idle_frac": 0.3}, f)
    assert trace_main(["--diff", base, base, "--gate", tol, "--json"]) == 0
    capsys.readouterr()
    assert trace_main(["--diff", base, stalled, "--gate", tol, "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["gate"]["ok"] is False
    assert any("idle fraction" in v for v in rep["gate"]["violations"])
    # the stall is attributed, not just counted: staging_wait names it
    assert rep["bubbles"]["new_idle_frac"] > 0.3


def test_gate_min_overlap_and_min_mxu(tmp_path, capsys):
    good = [
        _rec("train", 101.0, 1.0, flops=100e12, launch=1),
        _rec("stage_out", 101.5, 0.4, tid=1, overlap_s=0.38, wait_s=0.02),
        _rec("stage_wait", 101.6, 0.02, overlap_s=0.38, wait_s=0.02),
    ]
    bad = [
        _rec("train", 101.0, 1.0, flops=5e12, launch=1),
        _rec("stage_out", 101.5, 0.4, tid=1, overlap_s=0.05, wait_s=0.35),
        _rec("stage_wait", 102.0, 0.35, overlap_s=0.05, wait_s=0.35),
    ]
    g = _write_stream(tmp_path / "good.jsonl", good)
    b = _write_stream(tmp_path / "bad.jsonl", bad)
    tol = str(tmp_path / "tol.json")
    with open(tol, "w") as f:
        json.dump({"default": 10.0, "min_overlap": 0.5, "min_mxu_frac": 0.15}, f)
    args = ["--diff", g, g, "--gate", tol, "--json", "--peak-tflops", "157"]
    assert trace_main(args) == 0
    capsys.readouterr()
    assert trace_main(["--diff", g, b, "--gate", tol, "--json", "--peak-tflops", "157"]) == 1
    rep = json.loads(capsys.readouterr().out)
    vs = rep["gate"]["violations"]
    assert any("overlap" in v for v in vs), vs
    assert any("MXU" in v for v in vs), vs


def test_gate_legacy_embed_without_sections(tmp_path):
    """Satellite: legacy embeds (no bubbles/staging/roofline) diff
    without crashing; an EXPLICIT idle_frac budget on one is a lost-
    coverage violation, min_overlap skips (nothing was staged)."""
    from mpi_opt_tpu.obs.diff import apply_gate, diff_attributions

    legacy = {
        "wall_s": 5.0,
        "phases": {
            "train": {"count": 2, "total_s": 4.0, "self_s": 4.0, "p50_s": 2.0, "p95_s": 2.0}
        },
        "compile": {"cold": {"count": 0, "total_s": 0}, "persistent": {"count": 0, "total_s": 0}},
        "train": None,
        "time_to_first_trial_s": None,
        "memory": None,
    }
    rep = diff_attributions(legacy, legacy)
    assert rep["bubbles"] is None and rep["staging"] is None and rep["roofline"] is None
    gate = apply_gate(rep, {"min_overlap": 0.5})
    assert gate["ok"], gate["violations"]
    gate = apply_gate(rep, {"idle_frac": 0.3})
    assert not gate["ok"]
    assert any("no bubble analysis" in v for v in gate["violations"])


# -- end to end: traced wave sweep ---------------------------------------


def test_traced_wave_sweep_overlap_and_timeline(tmp_path, capsys):
    """The acceptance drill: a traced wave-scheduled fused PBT sweep.
    The bubble/staging attribution must reproduce the engine's measured
    staging-overlap number (probe_wave's metric, now in the summary
    JSON) within 5%, busy+idle must sum to the wall exactly, and the
    timeline export must validate."""
    from mpi_opt_tpu.cli import main

    mf = str(tmp_path / "m.jsonl")
    rc = main(
        [
            "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
            "--no-mesh", "--population", "4", "--generations", "2",
            "--steps-per-generation", "1", "--wave-size", "2", "--seed", "0",
            "--metrics-file", mf, "--trace",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    summary = None
    for line in out.splitlines():
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and "event" not in doc:
            summary = doc
    assert summary is not None and summary.get("stage_overlap_s") is not None
    from mpi_opt_tpu.obs.report import load_stream

    rep = attribute({"m": load_stream(mf)})
    stg = rep["staging"]
    assert stg is not None and stg["drains"] >= 2
    # the engine's own number, reproduced from the trace (5% + the
    # summary's 1e-3 rounding quantum for near-zero CPU transfers)
    assert stg["overlap_s"] == pytest.approx(
        summary["stage_overlap_s"], rel=0.05, abs=2e-3
    )
    assert stg["wait_s"] == pytest.approx(
        summary["stage_wait_s"], rel=0.05, abs=2e-3
    )
    bub = rep["bubbles"]
    assert bub is not None
    assert bub["busy_s"] + bub["idle_s"] == pytest.approx(bub["wall_s"], abs=0.01)
    assert rep["roofline"] is not None and rep["roofline"]["bound"] in (
        "compute-bound",
        "transfer-bound",
        "bubble-bound",
    )
    # the timeline over the same stream validates (Perfetto-loadable)
    tl = str(tmp_path / "tl.json")
    assert trace_main([mf, "--timeline", tl]) == 0
    capsys.readouterr()
    with open(tl) as f:
        doc = json.load(f)
    assert timeline.validate_timeline(doc) == []
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"train", "stage_out", "stage_wait"} <= names
