"""Fused multi-objective sweeps end to end (ISSUE 17): journaled
objective vectors, scalar-ledger back-compat, crash→resume record
identity, resume verification of vectors, report ``--best-under``, and
the snapshot-config gate between scalar and MO resumes.

The headline invariants:
- an MO fused sweep journals one raw ``scores`` vector beside the
  scalarized ``score`` per member record, validating clean under the
  same schema v1;
- a SCALAR fused sweep's ledger carries NO ``scores``/``objective_spec``
  key anywhere — pre-17 consumers see byte-identical output;
- a sweep killed mid-run resumes to the record-identical journal of an
  unkilled run, vectors included;
- a resumed boundary whose recomputed vector diverges from the journal
  refuses (LedgerError), same as the scalar path;
- ``report --best-under`` answers typed (feasible / least_violation),
  and refuses unknown objectives, contradictory operators, and scalar
  ledgers.
"""

import json

import numpy as np
import pytest

import mpi_opt_tpu.train.fused_asha as fa
import mpi_opt_tpu.train.fused_pbt as fp
from mpi_opt_tpu.ledger import (
    FusedJournal,
    LedgerError,
    SweepLedger,
    validate_ledger,
)
from mpi_opt_tpu.ledger.report import summarize_ledger
from mpi_opt_tpu.objectives import ObjectiveSpec
from mpi_opt_tpu.workloads import get_workload

SPEC = ObjectiveSpec.parse("accuracy:max,params:min")
KW = dict(population=6, generations=3, steps_per_gen=4, seed=3, gen_chunk=1)


@pytest.fixture(scope="module")
def wl():
    return get_workload("digits_mlp")


def _mo_ledger(path, space, algorithm="pbt", spec=SPEC):
    led = SweepLedger(str(path))
    led.ensure_header(
        {
            "mode": "fused",
            "granularity": "generation",
            "algorithm": algorithm,
            "seed": KW["seed"],
            "space_hash": space.space_hash(),
            "objectives": "accuracy:max,params:min",
        },
        objective_spec=spec.spec(),
    )
    return led


def _records(path):
    return [json.loads(l) for l in open(path).read().splitlines()[1:]]


def test_mo_pbt_journals_vectors_and_scalarized_score(tmp_path, wl):
    space = wl.default_space()
    led = _mo_ledger(tmp_path / "mo.jsonl", space)
    res = fp.fused_pbt(wl, ledger=led, objectives=SPEC, **KW)
    led.close()

    assert validate_ledger(led.path) == []
    recs = _records(led.path)
    assert len(recs) == KW["population"] * KW["generations"]
    for r in recs:
        if r["status"] != "ok":
            continue
        assert isinstance(r["scores"], list) and len(r["scores"]) == SPEC.m
        # score IS the scalarized primary (accuracy:max → identity)
        assert r["score"] == pytest.approx(r["scores"][0])
        assert all(np.isfinite(v) for v in r["scores"])

    # the spec rides the header top-level beside space_spec, durable
    header = json.loads(open(led.path).readline())
    assert ObjectiveSpec.from_spec(header["objective_spec"]) == SPEC
    assert "space_spec" not in header["config"]  # both are metadata keys

    # the result carries the typed Pareto block
    assert res["objectives"] == ["accuracy", "params"]
    p = res["pareto"]
    assert p["front_size"] == len(p["front_members"]) >= 1
    assert p["selection"] == "feasible"  # unconstrained spec: always
    assert p["hypervolume"] >= 0.0
    assert len(p["front_scores"]) == p["front_size"]

    # report recomputes the same front from the journaled vectors
    rep = summarize_ledger(led.path)
    mo = rep["multi_objective"]
    assert [o["name"] for o in mo["objectives"]] == ["accuracy", "params"]
    assert mo["evaluated"] == KW["population"]  # end-state: one row/member
    assert mo["front_size"] >= 1
    assert mo["hypervolume"] == pytest.approx(p["hypervolume"])


def test_scalar_fused_ledger_carries_no_mo_keys(tmp_path, wl):
    """Back-compat floor: a scalar sweep's ledger must be EXACTLY what
    pre-17 binaries wrote — no ``scores`` key in any record, no
    ``objective_spec`` in the header, no MO block in the report."""
    space = wl.default_space()
    led = SweepLedger(str(tmp_path / "scalar.jsonl"))
    led.ensure_header(
        {
            "mode": "fused",
            "granularity": "generation",
            "algorithm": "pbt",
            "seed": KW["seed"],
            "space_hash": space.space_hash(),
        }
    )
    res = fp.fused_pbt(wl, ledger=led, **KW)
    led.close()

    header = json.loads(open(led.path).readline())
    assert "objective_spec" not in header
    assert "objectives" not in header["config"]
    for r in _records(led.path):
        assert "scores" not in r
    assert res["objectives"] is None and res["pareto"] is None
    assert summarize_ledger(led.path)["multi_objective"] is None
    with pytest.raises(LedgerError, match="multi-objective"):
        summarize_ledger(led.path, best_under="params<=100")


def test_mo_crash_resume_record_identical(tmp_path, wl):
    """Acceptance drill: kill an MO sweep mid-run, ``--resume`` it, and
    the journal — vectors included — is record-identical to an unkilled
    run's."""
    space = wl.default_space()
    clean = _mo_ledger(tmp_path / "clean.jsonl", space)
    fp.fused_pbt(wl, ledger=clean, objectives=SPEC, **KW)
    clean.close()

    real = fp.run_fused_pbt
    calls = {"n": 0}

    def crashing(*a, **k):
        calls["n"] += 1
        if calls["n"] == 3:  # die after 2 completed launches
            raise RuntimeError("simulated TPU worker crash")
        return real(*a, **k)

    ckpt = str(tmp_path / "ck")
    led = _mo_ledger(tmp_path / "killed.jsonl", space)
    import unittest.mock as mock

    with mock.patch.object(fp, "run_fused_pbt", crashing):
        with pytest.raises(RuntimeError, match="simulated"):
            fp.fused_pbt(wl, checkpoint_dir=ckpt, ledger=led, objectives=SPEC, **KW)
    led.close()

    led = _mo_ledger(tmp_path / "killed.jsonl", space)
    resumed = fp.fused_pbt(
        wl, checkpoint_dir=ckpt, ledger=led, objectives=SPEC, **KW
    )
    led.close()

    assert validate_ledger(led.path) == []

    def durable(path):
        # project away per-run identity (sweep_id, wall-clock): every
        # FACT of the sweep — vectors included — must be identical
        keys = ("trial_id", "member", "boundary", "boundary_size", "params",
                "status", "score", "scores", "step")
        return [
            {k: r.get(k) for k in keys} for r in _records(path)
        ]

    assert durable(led.path) == durable(clean.path)
    # the resumed result's front matches the clean run's
    whole = summarize_ledger(clean.path)["multi_objective"]
    again = summarize_ledger(led.path)["multi_objective"]
    assert again == whole
    assert resumed["pareto"]["selection"] == "feasible"


def test_resume_verify_catches_diverged_vector(tmp_path, wl):
    """A re-computed boundary whose scalar scores match but whose
    objective VECTOR diverges is a different trajectory — refused."""
    space = wl.default_space()
    led = _mo_ledger(tmp_path / "v.jsonl", space)
    j = FusedJournal(led, space)
    rng = np.random.default_rng(0)
    u = rng.random((3, space.dim), dtype=np.float32)
    scores = np.array([0.5, 0.6, 0.7])
    mo = np.array([[0.5, 100.0], [0.6, 200.0], [0.7, 300.0]])
    j.record_boundary(0, [0, 1, 2], u, scores, step=5, scores_mo=mo)
    led.close()

    led2 = SweepLedger(led.path)
    j2 = FusedJournal(led2, space)
    # identical recomputation verifies (no rewrite)
    j2.record_boundary(0, [0, 1, 2], u, scores, step=5, scores_mo=mo)
    assert j2.written == 0 and j2.verified == 3
    bad = mo.copy()
    bad[1, 1] = 999.0
    with pytest.raises(LedgerError, match="diverges"):
        j2.record_boundary(0, [0, 1, 2], u, scores, step=5, scores_mo=bad)
    led2.close()


def test_report_best_under_typed_answers(tmp_path, wl):
    space = wl.default_space()
    led = _mo_ledger(tmp_path / "bu.jsonl", space)
    fp.fused_pbt(wl, ledger=led, objectives=SPEC, **KW)
    led.close()

    # a satisfiable bound answers feasible with a concrete winner
    mo = summarize_ledger(led.path)["multi_objective"]
    loosest = max(r["scores"][1] for r in mo["front"])
    rep = summarize_ledger(led.path, best_under=f"params<={loosest * 10}")
    bu = rep["multi_objective"]["best_under"]
    assert bu["kind"] == "feasible" and bu["trial_id"] is not None
    assert bu["scores"][1] <= loosest * 10

    # an unsatisfiable bound DEGRADES (typed), never crashes
    rep = summarize_ledger(led.path, best_under="params<=0.5")
    bu = rep["multi_objective"]["best_under"]
    assert bu["kind"] == "least_violation"
    assert bu["violation"] > 0 and bu["trial_id"] is not None

    # unknown objective and contradictory operator are typed refusals
    with pytest.raises(LedgerError, match="names 'bogus'"):
        summarize_ledger(led.path, best_under="bogus<=1")
    with pytest.raises(LedgerError, match="must use '>='"):
        summarize_ledger(led.path, best_under="accuracy<=0.5")


def test_mo_snapshot_refuses_scalar_resume(tmp_path, wl):
    """The checkpoint config carries the objectives spec ONLY on MO
    sweeps, so an MO snapshot refuses a scalar resume (and vice versa)
    instead of silently continuing under a different selection rule."""
    ckpt = str(tmp_path / "ck")
    fp.fused_pbt(wl, checkpoint_dir=ckpt, objectives=SPEC, **KW)
    with pytest.raises(ValueError, match="mismatch"):
        fp.fused_pbt(wl, checkpoint_dir=ckpt, **KW)


def test_mo_sha_journals_vectors(tmp_path, wl):
    space = wl.default_space()
    led = _mo_ledger(tmp_path / "sha.jsonl", space, algorithm="asha")
    res = fa.fused_sha(
        wl,
        n_trials=6,
        min_budget=2,
        max_budget=8,
        eta=2,
        seed=3,
        ledger=led,
        objectives=SPEC,
    )
    led.close()

    assert validate_ledger(led.path) == []
    recs = _records(led.path)
    assert len(recs) == 6 + 3 + 2  # rung sizes under eta=2
    for r in recs:
        if r["status"] == "ok":
            assert len(r["scores"]) == SPEC.m
            assert r["score"] == pytest.approx(r["scores"][0])
    assert res["objectives"] == ["accuracy", "params"]
    assert res["pareto"]["front_size"] >= 1
    assert summarize_ledger(led.path)["multi_objective"]["front_size"] >= 1


# -- scores drift gates (satellite 3) -------------------------------------


def _write_ledger(path, header, records):
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for r in records:
            f.write(json.dumps(r) + "\n")


def _rec(space, **over):
    base = {
        "v": 1,
        "kind": "trial",
        "trial_id": 0,
        "status": "ok",
        "params": {"lr": 0.01, "momentum": 0.5, "weight_decay": 1e-4},
        "score": 0.5,
        "step": 5,
        "seed": 0,
    }
    base.update(over)
    return base


def test_validate_flags_mistyped_scores_and_accepts_absent(tmp_path, wl):
    """The drift gate for the OPTIONAL ``scores`` field: absent is valid
    forever (that is the whole scalar history); present-but-mistyped is
    flagged, and an ok record may not carry a null objective entry."""
    space = wl.default_space()
    header = {
        "v": 1,
        "kind": "header",
        "config": {"space_hash": space.space_hash()},
    }
    path = str(tmp_path / "drift.jsonl")

    # absent scores: valid forever
    _write_ledger(path, header, [_rec(space)])
    assert validate_ledger(path) == []

    # well-typed vector (null allowed on a failed record): valid
    _write_ledger(
        path,
        header,
        [
            _rec(space, scores=[0.5, 120.0]),
            _rec(space, trial_id=1, status="failed", score=None, scores=None),
        ],
    )
    assert validate_ledger(path) == []

    # mistyped shapes are each flagged
    for bad, match in [
        (_rec(space, scores=[]), "non-empty"),
        (_rec(space, scores="0.5"), "non-empty"),
        (_rec(space, scores=[0.5, "fast"]), "non-numeric"),
        (_rec(space, scores=[0.5, True]), "non-numeric"),
        (_rec(space, scores=[0.5, None]), "null objective"),
    ]:
        _write_ledger(path, header, [bad])
        problems = validate_ledger(path)
        assert problems and match in problems[0], (bad["scores"], problems)
