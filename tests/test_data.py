import numpy as np
import pytest

from mpi_opt_tpu.data import DATASETS, load_dataset
from mpi_opt_tpu.data.synthetic import make_image_classification


def test_registry_and_unknown():
    assert "cifar10" in DATASETS and "digits" in DATASETS
    with pytest.raises(ValueError, match="unknown dataset"):
        load_dataset("imagenet")


def test_synthetic_shapes_and_determinism():
    a = make_image_classification(256, 64, 32, 32, 3, 10, seed=7)
    b = make_image_classification(256, 64, 32, 32, 3, 10, seed=7)
    assert a["train_x"].shape == (256, 32, 32, 3)
    assert a["val_y"].shape == (64,)
    assert a["train_x"].dtype == np.float32 and a["train_y"].dtype == np.int32
    np.testing.assert_array_equal(a["train_x"], b["train_x"])  # fully deterministic
    c = make_image_classification(256, 64, 32, 32, 3, 10, seed=8)
    assert not np.array_equal(a["train_x"], c["train_x"])  # seed matters


def test_synthetic_train_val_disjoint_noise():
    d = make_image_classification(128, 128, 28, 28, 1, 10, seed=0)
    assert not np.array_equal(d["train_x"][:64], d["val_x"][:64])


def test_sklearn_offline_datasets():
    d = load_dataset("digits")
    assert d["train_x"].shape[1] == 64 and d["n_classes"] == 10
    di = load_dataset("digits_image")
    assert di["train_x"].shape[1:] == (8, 8, 1)
    w = load_dataset("wine")
    assert w["n_classes"] == 3
    r = load_dataset("diabetes")
    assert r["n_classes"] == 0  # regression
    assert r["train_y"].dtype == np.float32


def test_cache_returns_same_object():
    a = load_dataset("cifar10", n_train=128, n_val=32)
    b = load_dataset("cifar10", n_train=128, n_val=32)
    assert a is b


def test_difficulty_kwargs_passthrough():
    """The synthetic image sets expose their difficulty knobs."""
    from mpi_opt_tpu.data import load_dataset

    easy = load_dataset("cifar10", n_train=64, n_val=16, delta=0.5)
    hard = load_dataset("cifar10", n_train=64, n_val=16, delta=0.05)
    import numpy as np

    assert not np.allclose(easy["train_x"], hard["train_x"])


def test_label_noise_ceiling():
    """cifar100 carries a 0.35 label-noise fraction: an oracle that
    always predicts the TRUE class scores ~ 1 - p + p/K on the noisy
    labels — the irreducible ceiling that stops config-5's curve from
    memorizing to ~1.0 (round-3 verdict weak #3)."""
    import numpy as np

    from mpi_opt_tpu.data.synthetic import make_image_classification

    clean = make_image_classification(2048, 2048, 8, 8, 1, 100, seed=7)
    noisy = make_image_classification(2048, 2048, 8, 8, 1, 100, seed=7, label_noise=0.35)
    # identical images, labels re-drawn for ~p*(1-1/K) of samples
    np.testing.assert_array_equal(clean["train_x"], noisy["train_x"])
    frac = float((clean["val_y"] != noisy["val_y"]).mean())
    assert 0.30 < frac < 0.40, frac  # p*(1-1/K) = 0.3465
    # oracle accuracy on noisy labels = agreement with the true labels
    oracle = float((noisy["val_y"] == clean["val_y"]).mean())
    assert abs(oracle - 0.6535) < 0.03, oracle
