import jax
import jax.numpy as jnp
import numpy as np

from mpi_opt_tpu.ops import PBTConfig, pbt_exploit_explore


def _setup(n=16, d=3, seed=0):
    key = jax.random.key(seed)
    k1, k2, k3 = jax.random.split(key, 3)
    unit = jax.random.uniform(k1, (n, d))
    scores = jax.random.uniform(k2, (n,))
    disc = jnp.array([False, False, True])
    return k3, unit, scores, disc


def test_survivors_untouched():
    key, unit, scores, disc = _setup()
    cfg = PBTConfig(truncation_frac=0.25)
    new_unit, src_idx, exploited = pbt_exploit_explore(key, unit, scores, disc, cfg)
    n_cut = 4
    assert int(exploited.sum()) == n_cut
    keep = ~np.asarray(exploited)
    np.testing.assert_allclose(np.asarray(new_unit)[keep], np.asarray(unit)[keep])
    np.testing.assert_array_equal(np.asarray(src_idx)[keep], np.arange(16)[keep])


def test_losers_copy_from_top():
    key, unit, scores, disc = _setup(n=32)
    cfg = PBTConfig(truncation_frac=0.25)
    _, src_idx, exploited = pbt_exploit_explore(key, unit, scores, disc, cfg)
    order = np.argsort(-np.asarray(scores))
    top = set(order[:8].tolist())
    bottom = set(order[-8:].tolist())
    for i in np.where(np.asarray(exploited))[0]:
        assert i in bottom
        assert int(src_idx[i]) in top


def test_explored_values_near_source():
    key, unit, scores, disc = _setup(n=64, d=2, seed=1)
    disc = jnp.array([False, False])
    cfg = PBTConfig(truncation_frac=0.25, perturb_scale=0.05)
    new_unit, src_idx, exploited = pbt_exploit_explore(key, unit, scores, disc, cfg)
    src = np.asarray(unit)[np.asarray(src_idx)]
    diff = np.abs(np.asarray(new_unit) - src)[np.asarray(exploited)]
    # perturbation is small Gaussian, clipped; 5 sigma bound
    assert diff.max() < 0.25
    assert diff.max() > 0  # but nonzero: explore actually happened


def test_bounds_respected():
    key, unit, scores, disc = _setup(n=128, d=4, seed=2)
    disc = jnp.array([False, True, False, True])
    new_unit, _, _ = pbt_exploit_explore(key, unit, scores, disc, PBTConfig(perturb_scale=0.5))
    arr = np.asarray(new_unit)
    assert arr.min() >= 0.0 and arr.max() <= 1.0


def test_jittable_and_deterministic():
    key, unit, scores, disc = _setup()
    f = jax.jit(pbt_exploit_explore, static_argnames="cfg")
    a = f(key, unit, scores, disc, PBTConfig())
    b = f(key, unit, scores, disc, PBTConfig())
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
