"""CLI end-to-end: the config-1 minimum slice, in-process."""

import json

import pytest

from mpi_opt_tpu.cli import build_parser, main


def test_parser_defaults():
    args = build_parser().parse_args(["--workload", "digits"])
    assert args.backend == "cpu"  # CPU path stays default; tpu is opt-in
    assert args.algorithm == "random"


def test_parser_rejects_unknown_backend():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--workload", "digits", "--backend", "cuda"])


def test_config1_minimum_slice(capsys):
    rc = main(
        [
            "--workload", "digits",
            "--algorithm", "random",
            "--trials", "4",
            "--budget", "40",
            "--workers", "1",
            "--seed", "0",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["n_trials"] == 4
    assert summary["best_score"] > 0.8
    assert summary["trials_per_sec_per_chip"] > 0
    assert "C" in summary["best_params"]


def test_cli_quadratic_pbt(capsys):
    rc = main(
        [
            "--workload", "quadratic",
            "--algorithm", "pbt",
            "--population", "8",
            "--generations", "3",
            "--steps-per-generation", "5",
            "--workers", "1",
        ]
    )
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["n_trials"] == 24


def test_fused_pbt_cli(capsys, tmp_path):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "pbt",
            "--fused",
            "--population", "8",
            "--generations", "2",
            "--steps-per-generation", "5",
            "--seed", "0",
            "--checkpoint-dir", str(tmp_path / "ck"),
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 16
    assert len(summary["best_curve"]) == 2
    assert 0.0 <= summary["best_score"] <= 1.0
    assert "lr" in summary["best_params"]


def test_fused_asha_cli(capsys):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "asha",
            "--fused",
            "--trials", "9",
            "--min-budget", "5",
            "--max-budget", "45",
            "--eta", "3",
            "--seed", "0",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 9
    assert summary["rung_sizes"][0] == 9
    assert 0.0 <= summary["best_score"] <= 1.0


def test_fused_rejects_non_population_workload():
    with pytest.raises(SystemExit):
        main(["--workload", "digits", "--algorithm", "pbt", "--fused"])


def test_fused_rejects_random_algorithm():
    with pytest.raises(SystemExit):
        main(["--workload", "fashion_mlp", "--algorithm", "random", "--fused"])


def test_fused_tpe_cli(capsys):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "tpe",
            "--fused",
            "--trials", "8",
            "--population", "4",
            "--budget", "5",
            "--seed", "0",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 8
    assert len(summary["best_curve"]) == 2
    assert 0.0 <= summary["best_score"] <= 1.0
