"""CLI end-to-end: the config-1 minimum slice, in-process."""

import json
import os

import jax.errors

import pytest

from mpi_opt_tpu.cli import build_parser, main


def test_parser_defaults():
    args = build_parser().parse_args(["--workload", "digits"])
    assert args.backend == "cpu"  # CPU path stays default; tpu is opt-in
    assert args.algorithm == "random"


def test_parser_rejects_unknown_backend():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--workload", "digits", "--backend", "cuda"])


def test_config1_minimum_slice(capsys):
    rc = main(
        [
            "--workload", "digits",
            "--algorithm", "random",
            "--trials", "4",
            "--budget", "40",
            "--workers", "1",
            "--seed", "0",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["n_trials"] == 4
    assert summary["best_score"] > 0.8
    assert summary["trials_per_sec_per_chip"] > 0
    assert "C" in summary["best_params"]


def test_cli_quadratic_pbt(capsys):
    rc = main(
        [
            "--workload", "quadratic",
            "--algorithm", "pbt",
            "--population", "8",
            "--generations", "3",
            "--steps-per-generation", "5",
            "--workers", "1",
        ]
    )
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["n_trials"] == 24


def test_fused_pbt_cli(capsys, tmp_path):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "pbt",
            "--fused",
            "--population", "8",
            "--generations", "2",
            "--steps-per-generation", "5",
            "--seed", "0",
            "--checkpoint-dir", str(tmp_path / "ck"),
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 16
    assert len(summary["best_curve"]) == 2
    assert 0.0 <= summary["best_score"] <= 1.0
    assert "lr" in summary["best_params"]


def test_fused_asha_cli(capsys):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "asha",
            "--fused",
            "--trials", "9",
            "--min-budget", "5",
            "--max-budget", "45",
            "--eta", "3",
            "--seed", "0",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 9
    assert summary["rung_sizes"][0] == 9
    assert 0.0 <= summary["best_score"] <= 1.0


def test_fused_rejects_non_population_workload():
    with pytest.raises(SystemExit):
        main(["--workload", "digits", "--algorithm", "pbt", "--fused"])


def test_fused_pbt_step_chunk_cli(capsys, monkeypatch):
    """--step-chunk actually reaches fused_pbt (a dropped kwarg would
    run unchunked and every summary assertion would still pass, so the
    plumbing is asserted directly) and the sweep completes."""
    import mpi_opt_tpu.train.fused_pbt as fpbt

    seen = {}
    real = fpbt.fused_pbt

    def spying(workload, **kw):
        seen.update(kw)
        return real(workload, **kw)

    monkeypatch.setattr(fpbt, "fused_pbt", spying)
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "pbt",
            "--fused",
            "--population", "4",
            "--generations", "2",
            "--steps-per-generation", "4",
            "--step-chunk", "2",
            "--seed", "0",
        ]
    )
    assert rc == 0
    assert seen["step_chunk"] == 2
    summary = _summary(capsys)
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 8
    assert len(summary["best_curve"]) == 2
    assert 0.0 <= summary["best_score"] <= 1.0


def test_fused_random_cli(capsys):
    """Fused random search = the single-rung case of fused SHA: one
    cohort trains to --budget in lockstep, no cuts."""
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "random",
            "--fused",
            "--trials", "6",
            "--budget", "5",
            "--seed", "0",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 6
    assert summary["rung_budgets"] == [5]  # exactly one rung, no cuts
    assert summary["rung_sizes"] == [6]
    assert 0.0 <= summary["best_score"] <= 1.0


def test_fused_bohb_cli(capsys):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "bohb",
            "--fused",
            "--max-budget", "9",
            "--eta", "3",
            "--seed", "0",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 9 + 5 + 3
    assert len(summary["brackets"]) == 3
    assert "n_model_sampled" in summary["brackets"][0]
    assert 0.0 <= summary["best_score"] <= 1.0


def test_unknown_algorithm_rejected_at_parse():
    # argparse choices guard: unknown names never reach run_fused (its
    # own else-branch is a registry-drift guard for algorithms added
    # without fused support)
    with pytest.raises(SystemExit):
        main(["--workload", "fashion_mlp", "--algorithm", "nope", "--fused"])


def test_fused_tpe_cli(capsys):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "tpe",
            "--fused",
            "--trials", "8",
            "--population", "4",
            "--budget", "5",
            "--seed", "0",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.strip().splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["backend"] == "fused"
    assert summary["n_trials"] == 8
    assert len(summary["best_curve"]) == 2
    assert 0.0 <= summary["best_score"] <= 1.0


def _summary(capsys):
    return _summary_from(capsys.readouterr().out)


def test_fused_cli_auto_mesh(capsys):
    """On a multi-device host the fused CLI path must run sharded by
    default (VERDICT r2 item 1): the conftest's 8 virtual devices should
    yield an 8-way 'pop' mesh with per-chip accounting to match."""
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "pbt",
            "--fused",
            "--population", "8",
            "--generations", "2",
            "--steps-per-generation", "5",
            "--seed", "0",
        ]
    )
    assert rc == 0
    summary = _summary(capsys)
    assert summary["mesh"] == {"pop": 8, "data": 1}
    assert summary["n_chips"] == 8


def test_fused_cli_mesh_flags(capsys):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "pbt",
            "--fused",
            "--population", "8",
            "--generations", "2",
            "--steps-per-generation", "5",
            "--n-data", "2",
            "--seed", "0",
        ]
    )
    assert rc == 0
    summary = _summary(capsys)
    assert summary["mesh"] == {"pop": 4, "data": 2}
    assert summary["n_chips"] == 8


def test_fused_cli_no_mesh_runs_single_device(capsys):
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "pbt",
            "--fused",
            "--no-mesh",
            "--population", "8",
            "--generations", "2",
            "--steps-per-generation", "5",
            "--seed", "0",
        ]
    )
    assert rc == 0
    summary = _summary(capsys)
    assert summary["mesh"] is None
    # ADVICE r2: per-chip divisor = devices the sweep actually ran on (1)
    assert summary["n_chips"] == 1


def test_no_mesh_contradicts_mesh_flags():
    with pytest.raises(SystemExit):
        main(
            [
                "--workload", "fashion_mlp",
                "--algorithm", "pbt",
                "--fused",
                "--no-mesh",
                "--n-data", "2",
            ]
        )


def test_fused_checkpoint_requires_explicit_resume(capsys, tmp_path):
    """A checkpoint dir holding a previous sweep must not silently
    replay it: resuming is --resume opt-in, like the driver path
    (ADVICE r2)."""
    ck = str(tmp_path / "ck")
    argv = [
        "--workload", "fashion_mlp",
        "--algorithm", "pbt",
        "--fused",
        "--population", "8",
        "--generations", "2",
        "--steps-per-generation", "5",
        "--seed", "0",
        "--checkpoint-dir", ck,
    ]
    assert main(argv) == 0
    first = _summary(capsys)
    with pytest.raises(SystemExit):  # stale dir, no --resume: refuse
        main(argv)
    capsys.readouterr()
    assert main(argv + ["--resume"]) == 0  # explicit resume: replays fine
    resumed = _summary(capsys)
    assert resumed["best_score"] == pytest.approx(first["best_score"], abs=1e-6)


def test_has_snapshot_matches_orbax_layout_only(tmp_path):
    """Only committed orbax step dirs (digit name + _CHECKPOINT_METADATA
    marker) count as snapshots: unrelated numeric directories sharing
    the tree — e.g. profiler output dated dirs — must not block a fresh
    sweep with a 'pass --resume' error (VERDICT r3 weak #6)."""
    from mpi_opt_tpu.cli import _has_snapshot

    ck = tmp_path / "ck"
    (ck / "plugins" / "profile" / "20260730").mkdir(parents=True)
    (ck / "cohort_0.npz").parent.mkdir(exist_ok=True)
    assert not _has_snapshot(str(ck))
    # a real committed orbax step flips it
    step = ck / "bracket_0" / "2"
    step.mkdir(parents=True)
    (step / "_CHECKPOINT_METADATA").write_text("{}")
    assert _has_snapshot(str(ck))


def test_fused_population_must_divide_mesh(capsys):
    """--fused --population 100 on an 8-device mesh would replicate the
    standing cohort on every device (an effectively single-device
    sweep); the CLI refuses with the fix spelled out (VERDICT r3 #7)."""
    with pytest.raises(SystemExit):
        main(
            [
                "--workload", "fashion_mlp",
                "--algorithm", "pbt",
                "--fused",
                "--population", "100",
                "--generations", "2",
            ]
        )
    err = capsys.readouterr().err
    assert "does not divide the mesh 'pop' axis" in err
    assert "--population 96 or 104" in err


def test_fused_retries_transient_failure(capsys, monkeypatch):
    """--retries N: a transient runtime death (worker crash/restart)
    mid-sweep is retried — with --checkpoint-dir that retry is a resume,
    the automatic form of the kill-and-rerun recovery the snapshot tests
    prove by hand (SURVEY.md §5 failure recovery)."""
    import mpi_opt_tpu.train.fused_pbt as fpbt

    real = fpbt.fused_pbt
    calls = {"n": 0}

    def flaky(workload, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            # the class the tunneled runtime's crash errors arrive as —
            # _is_transient type-gates on it before the marker scan
            raise jax.errors.JaxRuntimeError(
                "TPU worker process crashed or restarted"
            )
        return real(workload, **kw)

    monkeypatch.setattr(fpbt, "fused_pbt", flaky)
    argv = [
        "--workload", "fashion_mlp",
        "--algorithm", "pbt",
        "--fused",
        "--population", "8",
        "--generations", "2",
        "--steps-per-generation", "4",
        "--no-mesh",
    ]
    # without --retries the failure propagates
    with pytest.raises(RuntimeError, match="crashed"):
        main(argv)
    capsys.readouterr()
    calls["n"] = 0
    assert main(argv + ["--retries", "1"]) == 0
    assert calls["n"] == 2
    out = capsys.readouterr().out
    assert '"event": "retry"' in out  # the retry is visible in metrics
    summary = _summary_from(out)
    assert 0.0 <= summary["best_score"] <= 1.0


def test_fused_retries_never_mask_program_errors(monkeypatch, capsys):
    """A non-transient error (the program being wrong) is NEVER retried:
    N retries of a shape error are N identical failures."""
    import mpi_opt_tpu.train.fused_pbt as fpbt

    calls = {"n": 0}

    def broken(workload, **kw):
        calls["n"] += 1
        raise ValueError("bad shapes")

    monkeypatch.setattr(fpbt, "fused_pbt", broken)
    with pytest.raises(ValueError, match="bad shapes"):
        main([
            "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
            "--population", "4", "--generations", "1", "--no-mesh",
            "--retries", "3",
        ])
    assert calls["n"] == 1
    capsys.readouterr()


def test_multihost_flags_must_be_complete(capsys):
    """Partial bring-up flags are a launch-script bug: refuse with the
    full recipe rather than auto-detecting half a cluster."""
    with pytest.raises(SystemExit):
        main([
            "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
            "--population", "4", "--generations", "1", "--no-mesh",
            "--coordinator", "127.0.0.1:1234",
        ])
    err = capsys.readouterr().err
    assert "--coordinator, --num-processes and --process-id" in err


def test_fused_retries_type_gate_beats_marker_text(monkeypatch, capsys):
    """A program error whose MESSAGE happens to quote a transient marker
    (a dataset path containing 'unavailable') must not be retried: the
    type gate runs before the substring scan (ADVICE r4 / VERDICT r4
    weak #4)."""
    import mpi_opt_tpu.train.fused_pbt as fpbt

    calls = {"n": 0}

    def broken(workload, **kw):
        calls["n"] += 1
        raise ValueError("dataset file '/data/unavailable/train.npz' deadline")

    monkeypatch.setattr(fpbt, "fused_pbt", broken)
    with pytest.raises(ValueError, match="unavailable"):
        main([
            "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
            "--population", "4", "--generations", "1", "--no-mesh",
            "--retries", "3",
        ])
    assert calls["n"] == 1
    capsys.readouterr()


def _summary_from(out):
    lines = [l for l in out.strip().splitlines() if l.startswith("{")]
    for l in reversed(lines):
        d = json.loads(l)
        if "best_score" in d:
            return d
    raise AssertionError(out)


@pytest.mark.chaos
def test_cli_chaos_drill_counts_failures_and_matches_clean_best(capsys):
    """--chaos end-to-end: the sweep completes, the summary carries the
    injected-failure counters, and the best trial matches the clean
    run's (constants shared with tests/test_chaos.py)."""
    base = [
        "--workload", "quadratic",
        "--algorithm", "random",
        "--trials", "30",
        "--budget", "20",
        "--workers", "2",
        "--seed", "0",
    ]
    assert main(base) == 0
    clean = _summary(capsys)
    assert clean["trials_failed"] == 0

    assert main(base + ["--chaos", "exc=0.12,nan=0.08,seed=10"]) == 0
    out = capsys.readouterr().out
    drill = _summary_from(out)
    assert drill["trials_failed"] == 9  # 5 exc + 4 nan, deterministic
    assert drill["trials_retried"] == 0 and drill["trials_timeout"] == 0
    assert drill["best_score"] == pytest.approx(clean["best_score"], abs=1e-9)
    assert drill["best_params"] == clean["best_params"]
    # per-trial failures are visible as metrics events, not just tallies
    assert '"event": "trial_failed"' in out
    # the summary EVENT carries the counters too (operators tail metrics)
    summary_events = [
        json.loads(l) for l in out.splitlines()
        if l.startswith("{") and '"event": "summary"' in l
    ]
    assert summary_events and summary_events[-1]["trials_failed"] == 9


@pytest.mark.chaos
def test_cli_trial_retries_reach_the_driver(capsys):
    """--trial-retries N: retry attempts show up in the summary counters
    (chaos faults are deterministic, so every retry re-fails — the knob
    exists for nondeterministic production failures)."""
    rc = main([
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", "30", "--budget", "20", "--workers", "2", "--seed", "0",
        "--chaos", "exc=0.12,nan=0.08,seed=10",
        "--trial-retries", "1",
    ])
    assert rc == 0
    s = _summary(capsys)
    assert s["trials_failed"] == 9
    assert s["trials_retried"] == 9


@pytest.mark.chaos
def test_cli_max_failure_rate_aborts_systemic_failure(capsys):
    """A sweep whose failure fraction crosses --max-failure-rate exits
    nonzero with an 'aborted' line instead of grinding to the end."""
    rc = main([
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", "60", "--budget", "20", "--workers", "1", "--seed", "0",
        "--chaos", "exc=0.9,seed=0",
        "--max-failure-rate", "0.5",
    ])
    assert rc == 1
    captured = capsys.readouterr()
    lines = [l for l in captured.out.strip().splitlines() if l.startswith("{")]
    aborted = json.loads(lines[-1])
    assert "aborted" in aborted and "max_failure_rate" in aborted["aborted"]
    assert "systemic" in captured.err


def test_cli_chaos_rejects_fused():
    with pytest.raises(SystemExit):
        main([
            "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
            "--population", "4", "--generations", "1",
            "--chaos", "exc=0.5",
        ])


def test_cli_chaos_rejects_bad_spec(capsys):
    with pytest.raises(SystemExit):
        main([
            "--workload", "quadratic", "--trials", "2",
            "--chaos", "explode=0.5",
        ])
    assert "unknown chaos key" in capsys.readouterr().err


def test_cli_chaos_rejects_tpu_backend(capsys):
    with pytest.raises(SystemExit):
        main([
            "--workload", "fashion_mlp", "--backend", "tpu",
            "--trials", "2", "--chaos", "exc=0.5",
        ])
    assert "cpu backend" in capsys.readouterr().err


def test_fused_summary_reports_member_failures(capsys):
    """Every fused sweep's summary carries the per-generation diverged-
    member tallies (ROADMAP open item) — zero for a healthy sweep, but
    PRESENT, so operators can alarm on it."""
    rc = main(
        [
            "--workload", "fashion_mlp",
            "--algorithm", "pbt",
            "--fused",
            "--population", "8",
            "--generations", "2",
            "--steps-per-generation", "5",
            "--seed", "0",
            "--no-mesh",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    summary = _summary_from(out)
    assert summary["member_failures"] == [0, 0]
    # ...and the metrics summary event carries the total
    events = [json.loads(l) for l in out.splitlines() if '"event": "summary"' in l]
    assert events[-1]["member_failures"] == 0


# -- durable sweep ledger (--ledger / --warm-start / report) ---------------


LEDGER_ARGS = [
    "--workload", "quadratic",
    "--algorithm", "random",
    "--trials", "10",
    "--budget", "20",
    "--workers", "1",
    "--seed", "0",
]


def test_cli_ledger_journals_and_resumes(capsys, tmp_path):
    """--ledger end-to-end: journal a sweep, refuse a stale ledger
    without --resume, replay it fully with --resume (zero evaluations),
    and report the same best."""
    led = str(tmp_path / "sweep.jsonl")
    assert main(LEDGER_ARGS + ["--ledger", led]) == 0
    first = _summary(capsys)
    lines = open(led).read().splitlines()
    assert len(lines) == 11  # header + one record per trial
    assert json.loads(lines[0])["kind"] == "header"

    with pytest.raises(SystemExit):  # stale ledger, no --resume: refuse
        main(LEDGER_ARGS + ["--ledger", led])
    assert "pass --resume" in capsys.readouterr().err

    assert main(LEDGER_ARGS + ["--ledger", led, "--resume"]) == 0
    resumed = _summary(capsys)
    assert resumed["replayed"] == 10
    assert resumed["best_score"] == pytest.approx(first["best_score"], abs=1e-12)
    # a full replay journals nothing new
    assert len(open(led).read().splitlines()) == 11


def test_cli_ledger_refuses_config_drift(capsys, tmp_path):
    led = str(tmp_path / "sweep.jsonl")
    assert main(LEDGER_ARGS + ["--ledger", led]) == 0
    capsys.readouterr()
    with pytest.raises(SystemExit):
        main(LEDGER_ARGS[:-1] + ["7", "--ledger", led, "--resume"])  # other seed
    assert "different sweep" in capsys.readouterr().err


def test_cli_warm_start_and_space_check(capsys, tmp_path):
    led = str(tmp_path / "prior.jsonl")
    assert main(LEDGER_ARGS + ["--ledger", led]) == 0
    prior = _summary(capsys)
    # a warm-started sweep over the same space runs fine and its first
    # suggestion is the prior best (seed 1 would otherwise sample fresh)
    rc = main(
        [
            "--workload", "quadratic", "--algorithm", "random",
            "--trials", "4", "--budget", "20", "--workers", "1",
            "--seed", "1", "--warm-start", led,
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    warm = _summary_from(out)
    assert '"event": "warm_start"' in out
    assert warm["best_score"] >= prior["best_score"] - 1e-9
    # a different workload = different space: refused via the space hash
    with pytest.raises(SystemExit):
        main(
            [
                "--workload", "digits", "--algorithm", "random",
                "--trials", "2", "--workers", "1", "--warm-start", led,
            ]
        )
    assert "space hash" in capsys.readouterr().err


def test_cli_ledger_flag_validation(capsys, tmp_path):
    led = str(tmp_path / "l.jsonl")
    for argv, msg in (
        (
            ["--workload", "quadratic", "--trials", "2",
             "--ledger", led, "--warm-start", led],
            "PRIOR sweep",
        ),
        (
            # a path ALIAS of the same file is still self-feeding
            ["--workload", "quadratic", "--trials", "2", "--ledger", led,
             "--warm-start", str(tmp_path / "." / "l.jsonl")],
            "PRIOR sweep",
        ),
        (
            # the self-feed guard is mode-independent (fused included)
            ["--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
             "--population", "4", "--generations", "1", "--ledger", led,
             "--warm-start", led],
            "PRIOR sweep",
        ),
    ):
        with pytest.raises(SystemExit) as exc:
            main(argv)
        assert exc.value.code == 2
        assert msg in capsys.readouterr().err


def test_cli_bad_warm_start_does_not_wedge_fresh_ledger(capsys, tmp_path):
    """--warm-start is validated BEFORE the new ledger's header commits:
    a typo'd prior path must not journal itself into the fresh ledger's
    identity (which would refuse the corrected re-run)."""
    led = str(tmp_path / "new.jsonl")
    with pytest.raises(SystemExit) as exc:
        main(LEDGER_ARGS + ["--ledger", led, "--warm-start", str(tmp_path / "typo.jsonl")])
    assert exc.value.code == 2
    assert "--warm-start" in capsys.readouterr().err
    assert not os.path.exists(led)  # nothing was committed
    # the corrected re-run works with the same --ledger path
    prior = str(tmp_path / "prior.jsonl")
    assert main(LEDGER_ARGS + ["--ledger", prior]) == 0
    capsys.readouterr()
    assert main(LEDGER_ARGS + ["--ledger", led, "--warm-start", prior]) == 0
    capsys.readouterr()


def test_cli_warm_start_not_reingested_on_checkpoint_resume(capsys, tmp_path):
    """Priors ingested before a checkpoint live inside the restored
    state (TPE's obs ring is checkpointed): a --resume re-run must skip
    re-ingestion instead of double-weighting them."""
    prior = str(tmp_path / "prior.jsonl")
    assert main(LEDGER_ARGS + ["--ledger", prior]) == 0
    capsys.readouterr()
    ck = str(tmp_path / "ck")
    base = [
        "--workload", "quadratic", "--algorithm", "tpe",
        "--trials", "6", "--budget", "20", "--workers", "1", "--seed", "3",
        "--warm-start", prior, "--checkpoint-dir", ck,
    ]
    assert main(base) == 0
    assert '"event": "warm_start"' in capsys.readouterr().out
    out2 = None
    assert main(base + ["--resume"]) == 0
    out2 = capsys.readouterr().out
    assert '"event": "warm_start_skipped"' in out2
    assert '"event": "warm_start"' not in out2.replace("warm_start_skipped", "X")


def test_report_subcommand_text_json_and_validate(capsys, tmp_path):
    """`mpi_opt_tpu report`: renders a ledger, --json machine mode, and
    --validate as the CI schema gate (exit 1 on malformed records) —
    this test IS the tier-1 wiring that catches ledger-format drift."""
    led = str(tmp_path / "sweep.jsonl")
    # chaos seed 4 injects 4 exc faults over this 10-trial capacity-1
    # stream (faults are a pure function of (seed, params), so the
    # count is stable across machines)
    assert main(LEDGER_ARGS + ["--ledger", led, "--chaos", "exc=0.2,seed=4"]) == 0
    sweep = _summary(capsys)

    assert main(["report", led]) == 0
    out = capsys.readouterr().out
    assert "best:" in out and "failed=" in out

    assert main(["report", led, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    one = rep["ledgers"][0]
    assert one["trials"] == 10
    assert one["by_status"]["failed"] > 0  # the chaos drill's injections
    assert one["by_status"]["ok"] + one["by_status"]["failed"] == 10
    # the sweep summary rounds to 6 decimals; the report keeps full precision
    assert rep["best"]["score"] == pytest.approx(sweep["best_score"], abs=1e-6)

    assert main(["report", led, "--validate"]) == 0

    # any malformed record (torn tail included) fails validation loudly
    with open(led, "a") as f:
        f.write('{"kind": "trial", "trial_id": 99, "trunc')
    assert main(["report", led, "--validate"]) == 1
    capsys.readouterr()


def test_cli_fsck_json_schema_repair_resume_cycle(capsys, tmp_path):
    """`mpi_opt_tpu fsck`: the CI contract mirroring report --validate —
    exit 0 + ok:true on a clean tree, exit 1 with the corrupt step named
    after bit-rot, --repair quarantines, --resume recovers via last-good
    fallback, and the final audit shows the quarantine. This test IS the
    tier-1 wiring that catches fsck schema drift (probes/tier1.sh runs
    the same cycle as a shell drill)."""
    from mpi_opt_tpu.workloads.chaos import inject_corrupt_save

    ck = str(tmp_path / "ck")
    base = [
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", "6", "--budget", "3", "--workers", "1",
        "--seed", "0", "--checkpoint-dir", ck,
    ]
    assert main(base) == 0
    capsys.readouterr()

    assert main(["fsck", ck, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    # the stable schema fsck's CI consumers key on
    assert set(rep) >= {
        "dir", "ok", "steps", "newest_verified", "repaired", "quarantined", "ledger",
    }
    assert rep["ok"] is True
    assert [s["status"] for s in rep["steps"]] == ["verified"] * 3  # keep=3
    assert rep["newest_verified"]["step"] == 6

    inject_corrupt_save(ck)
    assert main(["fsck", ck, "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] is False
    assert {s["step"]: s["status"] for s in rep["steps"]}[6] == "corrupt"

    assert main(["fsck", ck, "--json", "--repair"]) == 1  # found + repaired
    rep = json.loads(capsys.readouterr().out)
    assert rep["repaired"] == ["6.corrupt"]

    # --resume recovers from the prior verified step and completes
    assert main(base + ["--resume"]) == 0
    out = capsys.readouterr().out
    assert '"event": "resume"' in out and '"step": 5' in out
    s = _summary_from(out)
    assert s["n_trials"] == 6 and s["best_score"] is not None

    assert main(["fsck", ck, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] is True and rep["quarantined"] == ["6.corrupt"]


def test_cli_resume_with_no_verified_snapshot_exits_data_error(capsys, tmp_path):
    """Every retained step poisoned: --resume must exit the distinct
    EX_DATAERR (65) — the code launch.py refuses to retry — after
    quarantining the evidence, and say so on the single-JSON-line
    contract."""
    from mpi_opt_tpu.workloads.chaos import (
        _committed_step_dirs,
        inject_corrupt_save,
    )

    ck = str(tmp_path / "ck")
    base = [
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", "4", "--budget", "3", "--workers", "1",
        "--seed", "0", "--checkpoint-dir", ck,
    ]
    assert main(base) == 0
    capsys.readouterr()
    poisoned = [step for step, _path in _committed_step_dirs(ck)]
    for step in poisoned:
        inject_corrupt_save(ck, step=step)
    assert len(poisoned) == 3  # keep=3 retained steps, all now bad
    rc = main(base + ["--resume"])
    out = capsys.readouterr().out
    assert rc == 65
    lines = [json.loads(l) for l in out.splitlines() if l.startswith("{")]
    data_err = [l for l in lines if "data_error" in l]
    assert data_err and "no verified snapshot" in data_err[-1]["data_error"]
    # the corruption events reached the metrics stream with the counter
    summaries = [l for l in lines if l.get("event") == "summary"]
    assert summaries[-1]["snapshots_quarantined"] == 3
    assert sum(1 for l in lines if l.get("event") == "snapshot_corrupt") == 3
    # quarantines, not deletions
    assert sorted(d for d in os.listdir(ck) if d.endswith(".corrupt")) == [
        f"{s}.corrupt" for s in poisoned
    ]


def test_cli_validates_failure_policy_flags(capsys):
    """Bad policy values are usage errors (exit 2 + message), not raw
    ValueError tracebacks from deep inside the run."""
    for argv, msg in (
        (["--trial-retries", "-1"], "--trial-retries must be >= 0"),
        (["--max-failure-rate", "0"], "--max-failure-rate must be in (0, 1]"),
        (["--max-failure-rate", "1.5"], "--max-failure-rate must be in (0, 1]"),
        (["--trial-timeout", "0"], "--trial-timeout must be > 0"),
    ):
        with pytest.raises(SystemExit) as exc:
            main(["--workload", "quadratic", "--trials", "2", *argv])
        assert exc.value.code == 2
        assert msg in capsys.readouterr().err


# -- graceful shutdown (health/): exit 75, flushed state, free resume ------


def test_cli_isolate_stateful_rejected_off_the_cpu_path(capsys):
    with pytest.raises(SystemExit) as exc:
        main([
            "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
            "--population", "4", "--generations", "1",
            "--isolate-stateful",
        ])
    assert exc.value.code == 2
    assert "--isolate-stateful" in capsys.readouterr().err


@pytest.mark.chaos
def test_cli_preempt_drill_exits_75_with_flushed_ledger_then_resumes(capsys, tmp_path):
    """The acceptance drill, in-process: a chaos ``preempt`` SIGTERM
    mid-sweep yields a flushed ledger and exit code 75; the re-run with
    --resume replays the journaled trials and finishes with the clean
    run's best. Chaos seed 7 puts the single preempt draw at trial
    index 6 of this 12-trial seed-0 stream (so the drain journals 7
    trials)."""
    clean_args = [
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", "12", "--budget", "10", "--workers", "1", "--seed", "0",
    ]
    assert main(clean_args) == 0
    clean = _summary(capsys)

    led = str(tmp_path / "sweep.jsonl")
    drill = clean_args + ["--ledger", led, "--chaos", "preempt=0.15,seed=7"]
    rc = main(drill)
    out = capsys.readouterr().out
    assert rc == 75
    pre = [
        json.loads(l) for l in out.splitlines()
        if l.startswith("{") and '"preempted": true' in l and '"event"' not in l
    ][-1]
    assert pre["signal"] == "SIGTERM" and pre["trials_done"] == 7
    # the metrics summary event carries the preempted counter
    sev = [json.loads(l) for l in out.splitlines() if '"event": "summary"' in l][-1]
    assert sev["preempted"] == 1
    # the journal was fsync-flushed BEFORE exit: header + 7 trials
    lines = open(led).read().splitlines()
    assert len(lines) == 8
    assert json.loads(lines[0])["kind"] == "header"

    # resume: replay the 7, run the remaining 5, match the clean best
    assert main(drill + ["--resume"]) == 0
    resumed = _summary(capsys)
    assert resumed["replayed"] == 7
    assert resumed["n_trials"] == 12
    assert resumed["best_score"] == pytest.approx(clean["best_score"], abs=1e-12)


def test_fused_preempt_drains_snapshot_and_exits_75(capsys, tmp_path, monkeypatch):
    """Fused sweeps drain at launch boundaries too: with a shutdown
    pending, the first launch completes, its snapshot is flushed, and
    the CLI exits 75; the --resume re-run finishes the sweep from that
    snapshot. The drain flag is stubbed (not a real signal) so the test
    is deterministic about WHERE the preemption lands."""
    from mpi_opt_tpu.health import shutdown as shutdown_mod

    ck = str(tmp_path / "ck")
    argv = [
        "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
        "--population", "4", "--generations", "2",
        "--steps-per-generation", "2", "--gen-chunk", "1", "--no-mesh",
        "--seed", "0", "--checkpoint-dir", ck,
    ]
    monkeypatch.setattr(shutdown_mod, "requested", lambda: True)
    monkeypatch.setattr(shutdown_mod, "active_signal", lambda: "SIGTERM")
    rc = main(argv)
    out = capsys.readouterr().out
    assert rc == 75
    pre = [
        json.loads(l) for l in out.splitlines()
        if l.startswith("{") and '"preempted": true' in l
    ][-1]
    assert pre["backend"] == "fused" and "launch 1/2" in pre["at"]
    monkeypatch.undo()  # signals back to normal: the resume must finish
    assert main(argv + ["--resume"]) == 0
    resumed = _summary(capsys)
    assert len(resumed["best_curve"]) == 2  # both generations present
    assert 0.0 <= resumed["best_score"] <= 1.0


def test_cli_heartbeat_file_beats_per_batch(tmp_path, capsys):
    """--heartbeat-file: the driver writes one monotonic beat per
    completed batch — the liveness signal launch.py's stall watchdog
    consumes — and the configuration never leaks past main()."""
    from mpi_opt_tpu.health import heartbeat, read_beat

    hb = str(tmp_path / "rank0.hb")
    rc = main([
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", "4", "--budget", "10", "--workers", "1", "--seed", "0",
        "--heartbeat-file", hb,
    ])
    capsys.readouterr()
    assert rc == 0
    rec = read_beat(hb)
    assert rec is not None and rec["beats"] == 4  # one per batch
    assert rec["progress"]["stage"] == "driver"
    assert heartbeat.active() is None  # deconfigured on the way out


def test_cli_wave_size_validation(capsys):
    """--wave-size bad values / wrong context are usage errors (rc=2),
    not tracebacks from fused_pbt deep in the run."""
    base = ["--workload", "fashion_mlp", "--algorithm", "pbt"]
    for argv in (
        base + ["--wave-size", "4"],  # requires --fused
        base + ["--fused", "--wave-size", "nope"],
        base + ["--fused", "--wave-size", "-1"],
        base + ["--fused", "--wave-size", "4", "--step-chunk", "2"],
        base + ["--fused", "--wave-size", "4", "--gen-chunk", "2"],
        # any algorithm is wave-capable now, but only under --fused
        ["--workload", "fashion_mlp", "--algorithm", "tpe",
         "--wave-size", "4"],
        ["--workload", "fashion_mlp", "--algorithm", "asha",
         "--wave-size", "4"],
    ):
        with pytest.raises(SystemExit) as ei:
            main(argv)
        assert ei.value.code == 2
        capsys.readouterr()


def test_cli_fused_sha_wave_summary_surfaces_staging(capsys):
    """--wave-size is no longer PBT-only: a fused SHA sweep accepts it
    and its summary carries the same staging observability block."""
    rc = main([
        "--workload", "fashion_mlp", "--algorithm", "asha", "--fused",
        "--trials", "8", "--min-budget", "2", "--max-budget", "4",
        "--eta", "2", "--wave-size", "4", "--no-mesh", "--seed", "0",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["wave_size"] == 4
    assert summary["staged_bytes"] > 0
    assert summary["rung_sizes"][0] == 8


def test_cli_fused_wave_summary_surfaces_staging(capsys):
    """--fused --wave-size: the summary JSON and the metrics summary
    both carry the staging observability (staged_bytes + overlap)."""
    rc = main([
        "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
        "--population", "8", "--generations", "2",
        "--steps-per-generation", "3", "--wave-size", "4", "--no-mesh",
        "--seed", "0",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    summary = json.loads(lines[-1])
    assert summary["wave_size"] == 4 and summary["n_waves"] == 2
    assert summary["staged_bytes"] > 0
    assert summary["stage_overlap_s"] >= 0
    msum = [json.loads(l) for l in lines if '"event": "summary"' in l][-1]
    assert msum["staged_bytes"] == summary["staged_bytes"]
    assert msum["stage_overlap_s"] >= 0


def test_cli_fused_diverged_summary_is_strict_json(capsys, monkeypatch):
    """ADVICE r5: an all-diverged fused sweep's NaNs (best_score AND
    curve entries) must serialize as null — json.dumps' bare NaN token
    breaks the single-JSON-line contract for strict parsers."""
    import mpi_opt_tpu.train.fused_pbt as fp

    nan = float("nan")
    diverged = {
        "best_score": nan,
        "best_params": None,
        "diverged": True,
        "best_curve": [0.5, nan],
        "mean_curve": [0.4, nan],
        "member_failures": [0, 8],
        "state": None,
        "unit": None,
        "launch_gens": [1, 1],
        "launch_walls": [0.1, 0.1],
    }
    monkeypatch.setattr(fp, "fused_pbt", lambda *a, **k: diverged)
    rc = main([
        "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
        "--population", "8", "--generations", "2",
        "--steps-per-generation", "3", "--no-mesh",
    ])
    assert rc == 0
    line = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")][-1]

    def no_constants(s):  # NaN/Infinity tokens -> hard failure
        raise AssertionError(f"non-JSON constant emitted: {s}")

    summary = json.loads(line, parse_constant=no_constants)
    assert summary["best_score"] is None
    assert summary["best_params"] is None
    assert summary["best_curve"] == [0.5, None]


# -- fused-path ledger durability (ISSUE 6) --------------------------------


def test_cli_fused_ledger_preempt_resume_journal_identical(capsys, tmp_path, monkeypatch):
    """The fused acceptance drill end-to-end: a preempted --fused
    --ledger sweep exits 75 with the completed generation journaled;
    --resume re-trains only the incomplete generation; the final
    journal is record-identical to an unkilled run's and passes both
    `report --validate` and summary accounting."""
    from mpi_opt_tpu.health import shutdown as shutdown_mod
    from mpi_opt_tpu.ledger.report import report_main

    clean_led = str(tmp_path / "clean.jsonl")
    base = [
        "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
        "--population", "4", "--generations", "2",
        "--steps-per-generation", "2", "--gen-chunk", "1", "--no-mesh",
        "--seed", "0",
    ]
    assert main(base + ["--ledger", clean_led]) == 0
    clean = _summary(capsys)
    assert clean["journal"] == {"written": 8, "verified": 0}

    led = str(tmp_path / "sweep.jsonl")
    ck = str(tmp_path / "ck")
    drill = base + ["--ledger", led, "--checkpoint-dir", ck]
    # drain at the FIRST boundary (the final boundary suppresses the
    # poll, so a 2-generation sweep has exactly one drain point) — the
    # generation's members are journaled BEFORE the drain honors the flag
    monkeypatch.setattr(shutdown_mod, "requested", lambda: True)
    monkeypatch.setattr(shutdown_mod, "active_signal", lambda: "SIGTERM")
    assert main(drill) == 75
    out = capsys.readouterr().out
    assert '"preempted": true' in out
    # generation 0's members were journaled before the drain
    assert len(open(led).read().splitlines()) == 1 + 4
    monkeypatch.undo()

    assert main(drill + ["--resume"]) == 0
    resumed = _summary(capsys)
    # only the incomplete generation re-journals; nothing re-verifies
    # (the completed one was never re-computed — its snapshot replayed)
    assert resumed["journal"] == {"written": 4, "verified": 0}
    assert resumed["best_score"] == clean["best_score"]

    def records(path):
        keep = ("trial_id", "member", "boundary", "boundary_size", "params",
                "status", "score", "step")
        return [
            {k: r[k] for k in keep}
            for r in map(json.loads, open(path).read().splitlines()[1:])
        ]

    assert records(led) == records(clean_led)
    assert report_main(["--validate", led, clean_led]) == 0
    capsys.readouterr()


def test_cli_fused_ledger_kill_fsck_repair_resume_cycle(capsys, tmp_path):
    """The tier-1 drill's state machine, in-process: a mid-journal kill
    leaves a torn final boundary + a snapshot at the previous one; fsck
    --ledger flags it (exit 1), --resume self-heals and re-journals,
    and the post-recovery audit is clean (validate + fsck exit 0)."""
    import shutil

    from mpi_opt_tpu.ledger.report import report_main
    from mpi_opt_tpu.utils.integrity import fsck_main

    led = str(tmp_path / "sweep.jsonl")
    ck = str(tmp_path / "ck")
    argv = [
        "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
        "--population", "4", "--generations", "2",
        "--steps-per-generation", "2", "--gen-chunk", "1", "--no-mesh",
        "--seed", "0", "--ledger", led, "--checkpoint-dir", ck,
    ]
    assert main(argv) == 0
    clean_lines = open(led).read().splitlines()
    capsys.readouterr()

    # reconstruct the kill-mid-journal state: boundary 1 half-written
    # (2 of 4 records), and the snapshot that would have covered it
    # never committed — exactly what dying between record 6 and 7 leaves
    open(led, "w").write("\n".join(clean_lines[:7]) + "\n")
    shutil.rmtree(os.path.join(ck, "2"))

    assert fsck_main([ck, "--ledger", led]) == 1  # torn boundary FLAGGED
    out = capsys.readouterr().out
    assert "torn" in out
    assert main(argv + ["--resume"]) == 0  # heals + verifies + re-journals
    capsys.readouterr()

    # the healed + re-journaled ledger carries the clean run's exact
    # record content (only timestamps may differ)
    def strip_ts(lines):
        return [
            {k: v for k, v in json.loads(l).items() if k != "ts"}
            for l in lines
        ]

    assert strip_ts(open(led).read().splitlines()) == strip_ts(clean_lines)
    assert report_main(["--validate", led]) == 0
    assert fsck_main([ck, "--ledger", led]) == 0  # post-recovery audit clean
    capsys.readouterr()


def test_cli_fused_ledger_divergence_exits_data_error(capsys, tmp_path):
    """A journal whose scores belong to a DIFFERENT trajectory is a
    data dead-end: the resume's boundary verification raises and the
    CLI exits 65 (non-retryable), never silently re-writing history."""
    led = str(tmp_path / "sweep.jsonl")
    argv = [
        "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
        "--population", "4", "--generations", "1",
        "--steps-per-generation", "2", "--no-mesh", "--seed", "0",
        "--ledger", led,
    ]
    assert main(argv) == 0
    capsys.readouterr()
    lines = open(led).read().splitlines()
    rec = json.loads(lines[1])
    rec["score"] = 0.123456  # a score this seed never produced
    lines[1] = json.dumps(rec)
    open(led, "w").write("\n".join(lines) + "\n")
    assert main(argv + ["--resume"]) == 65
    out = capsys.readouterr().out
    assert '"data_error"' in out and "diverges" in out


def test_cli_fused_warm_start_cross_mode(capsys, tmp_path):
    """--warm-start with --fused: a prior ledger (either mode) seeds
    the fused sweep; refusal happens ONLY on space-hash mismatch."""
    prior = str(tmp_path / "prior.jsonl")
    assert main([
        "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
        "--population", "4", "--generations", "1",
        "--steps-per-generation", "2", "--no-mesh", "--seed", "0",
        "--ledger", prior,
    ]) == 0
    capsys.readouterr()
    fused_tpe = [
        "--workload", "fashion_mlp", "--algorithm", "tpe", "--fused",
        "--trials", "4", "--population", "2", "--budget", "2", "--no-mesh",
        "--seed", "1", "--warm-start", prior,
    ]
    assert main(fused_tpe) == 0
    out = capsys.readouterr().out
    assert '"event": "warm_start"' in out and '"observations": 4' in out

    # forge a foreign space hash: the SAME file now refuses — proving
    # the gate is the space, not the mode
    lines = open(prior).read().splitlines()
    hdr = json.loads(lines[0])
    hdr["config"]["space_hash"] = "feedfacefeedface"
    open(prior, "w").write("\n".join([json.dumps(hdr)] + lines[1:]) + "\n")
    with pytest.raises(SystemExit) as exc:
        main(fused_tpe)
    assert exc.value.code == 2
    assert "space hash" in capsys.readouterr().err
