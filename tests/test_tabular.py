"""Config 4: TPE sweep over the tabular surrogate workload."""

import pytest

from mpi_opt_tpu.algorithms import TPE, RandomSearch
from mpi_opt_tpu.backends import get_backend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.workloads import get_workload


def test_tabular_rejects_regression_set():
    with pytest.raises(ValueError, match="classification"):
        get_workload("tabular_mlp", dataset="diabetes")


def test_tpe_sweep_on_tabular_tpu_backend():
    wl = get_workload("tabular_mlp", dataset="breast_cancer")
    algo = TPE(wl.default_space(), seed=0, max_trials=24, budget=60, n_startup=8)
    be = get_backend("tpu", wl, population=8, seed=0)
    res = run_search(algo, be)
    assert res.n_trials == 24
    assert res.best.score > 0.85  # breast_cancer separates easily


def test_tabular_cpu_parity_path():
    wl = get_workload("tabular_mlp", dataset="wine")
    score = wl.evaluate({"lr": 0.05, "momentum": 0.9, "weight_decay": 1e-5}, budget=80, seed=0)
    assert 0.5 < score <= 1.0
