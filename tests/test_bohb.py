"""BOHB: model-based Hyperband — bracket composition, model gating,
id-space partitioning, checkpoint roundtrip, end-to-end search."""

import jax
import numpy as np
import pytest

from mpi_opt_tpu.algorithms import BOHB, Hyperband, get_algorithm
from mpi_opt_tpu.backends.cpu import CPUBackend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.workloads import get_workload


def _space():
    return get_workload("quadratic").default_space()


def test_registered():
    assert get_algorithm("bohb") is BOHB


def test_uniform_until_model_qualifies():
    """Before any budget accumulates n_min observations, every draw is
    uniform; after feeding one budget past n_min, non-random draws come
    from the acquisition kernel (deterministically, given the key)."""
    space = _space()
    algo = BOHB(space, seed=0, max_budget=9, eta=3, random_fraction=0.0)
    assert algo._model_budget() is None
    key = jax.random.key(1)
    u = algo._model_sample(key)
    assert u.shape == (space.dim,)

    # feed a discriminative history at budget 9: high scores cluster at
    # 0.2, low scores at 0.8 (every dim), well past n_min points
    s = algo._store(9)
    rng = np.random.default_rng(0)
    n = max(4 * algo.n_min, 24)
    for i in range(n):
        good = i % 2 == 0
        center = 0.2 if good else 0.8
        s["unit"][i] = np.clip(center + 0.03 * rng.standard_normal(algo.space.dim), 0, 1)
        s["score"][i] = (1.0 if good else 0.0) + 0.01 * rng.standard_normal()
        s["valid"][i] = True
        s["n"] += 1
    assert algo._model_budget() == 9
    draws = np.stack([algo._model_sample(jax.random.fold_in(key, i)) for i in range(16)])
    # the model concentrates samples toward the good cluster
    m = float(draws[:, 0].mean())
    assert abs(m - 0.2) < abs(m - 0.8), f"model samples not biased to the good cluster: {m}"


def test_model_prefers_highest_qualified_budget():
    algo = BOHB(_space(), seed=0, max_budget=27, eta=3)
    for b in (1, 3, 9):
        s = algo._store(b)
        s["n"] = algo.n_min + 1
    assert algo._model_budget() == 9


def test_bracket_ids_are_disjoint():
    """Brackets share one (possibly stateful) backend; their trial-id
    ranges must never overlap or bracket 2's fresh trials would warm-
    resume bracket 1's ledger entries (Backend.reset's hazard, in its
    multi-Algorithm form). Applies to Hyperband and BOHB alike."""
    for cls in (Hyperband, BOHB):
        algo = cls(_space(), seed=0, max_budget=27, eta=3)
        seen = set()
        for b in algo.brackets:
            batch = b.next_batch(1000)
            ids = {t.trial_id for t in batch}
            assert not (ids & seen), f"{cls.name}: overlapping trial ids"
            seen |= ids


def test_bohb_driver_loop_completes_and_uses_model():
    wl = get_workload("quadratic")
    algo = BOHB(wl.default_space(), seed=0, max_budget=27, eta=3)
    be = CPUBackend(wl, n_workers=1)
    try:
        res = run_search(algo, be)
    finally:
        be.close()
    assert algo.finished()
    assert res.n_trials == 27 + 12 + 6 + 4  # same plan as hyperband R=27
    assert res.best is not None and res.best.score is not None
    # the later brackets ran with a qualified model (enough budget-1
    # observations exist after bracket 0's first rung alone)
    assert algo._model_budget() is not None


def test_bohb_checkpoint_roundtrip():
    wl = get_workload("quadratic")
    space = wl.default_space()
    algo = BOHB(space, seed=3, max_budget=27, eta=3)
    be = CPUBackend(wl, n_workers=1)
    try:
        run_search(algo, be, max_batches=3)
        mid = algo.state_dict()
        resumed = BOHB(space, seed=3, max_budget=27, eta=3)
        resumed.load_state_dict(mid)
        assert resumed._samples == algo._samples
        for b in algo._obs:
            np.testing.assert_array_equal(resumed._obs[b]["unit"], algo._obs[b]["unit"])
            assert resumed._obs[b]["n"] == algo._obs[b]["n"]
        r1 = run_search(algo, be)
        be.reset()
        r2 = run_search(resumed, be)
    finally:
        be.close()
    assert r1.best is not None and r2.best is not None
    # both complete the full plan (arrival-order effects can differ, as
    # with hyperband's resume; completion and a sane best are the contract)
    assert algo.finished() and resumed.finished()
