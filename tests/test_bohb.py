"""BOHB: model-based Hyperband — bracket composition, model gating,
id-space partitioning, checkpoint roundtrip, end-to-end search."""

import jax
import numpy as np
import pytest

from mpi_opt_tpu.algorithms import BOHB, Hyperband, get_algorithm
from mpi_opt_tpu.backends.cpu import CPUBackend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.workloads import get_workload


def _space():
    return get_workload("quadratic").default_space()


def test_registered():
    assert get_algorithm("bohb") is BOHB


def test_uniform_until_model_qualifies():
    """Before any budget accumulates n_min observations, every draw is
    uniform; after feeding one budget past n_min, non-random draws come
    from the acquisition kernel (deterministically, given the key)."""
    space = _space()
    algo = BOHB(space, seed=0, max_budget=9, eta=3, random_fraction=0.0)
    assert algo._model_budget() is None
    key = jax.random.key(1)
    u = algo._model_sample(key)
    assert u.shape == (space.dim,)

    # feed a discriminative history at budget 9: high scores cluster at
    # 0.2, low scores at 0.8 (every dim), well past n_min points
    s = algo.obs.ring(9)
    rng = np.random.default_rng(0)
    n = max(4 * algo.n_min, 24)
    for i in range(n):
        good = i % 2 == 0
        center = 0.2 if good else 0.8
        s["unit"][i] = np.clip(center + 0.03 * rng.standard_normal(algo.space.dim), 0, 1)
        s["score"][i] = (1.0 if good else 0.0) + 0.01 * rng.standard_normal()
        s["valid"][i] = True
        s["n"] += 1
    assert algo._model_budget() == 9
    draws = np.stack([algo._model_sample(jax.random.fold_in(key, i)) for i in range(16)])
    # the model concentrates samples toward the good cluster
    m = float(draws[:, 0].mean())
    assert abs(m - 0.2) < abs(m - 0.8), f"model samples not biased to the good cluster: {m}"


def test_model_prefers_highest_qualified_budget():
    algo = BOHB(_space(), seed=0, max_budget=27, eta=3)
    for b in (1, 3, 9):
        s = algo.obs.ring(b)
        s["n"] = algo.n_min + 1
    assert algo._model_budget() == 9


def test_bracket_ids_are_disjoint():
    """Brackets share one (possibly stateful) backend; their trial-id
    ranges must never overlap or bracket 2's fresh trials would warm-
    resume bracket 1's ledger entries (Backend.reset's hazard, in its
    multi-Algorithm form). Applies to Hyperband and BOHB alike."""
    for cls in (Hyperband, BOHB):
        algo = cls(_space(), seed=0, max_budget=27, eta=3)
        seen = set()
        for b in algo.brackets:
            batch = b.next_batch(1000)
            ids = {t.trial_id for t in batch}
            assert not (ids & seen), f"{cls.name}: overlapping trial ids"
            seen |= ids


def test_bohb_driver_loop_completes_and_uses_model():
    wl = get_workload("quadratic")
    algo = BOHB(wl.default_space(), seed=0, max_budget=27, eta=3)
    be = CPUBackend(wl, n_workers=1)
    try:
        res = run_search(algo, be)
    finally:
        be.close()
    assert algo.finished()
    assert res.n_trials == 27 + 12 + 6 + 4  # same plan as hyperband R=27
    assert res.best is not None and res.best.score is not None
    # the later brackets ran with a qualified model (enough budget-1
    # observations exist after bracket 0's first rung alone)
    assert algo._model_budget() is not None


def test_obsstore_drops_nan_scores():
    """Diverged trials (NaN scores) must not enter the model or count
    toward n_min — filtered in ObsStore.add so the host and fused paths
    cannot disagree."""
    from mpi_opt_tpu.algorithms.bohb import ObsStore

    st = ObsStore(dim=2, buffer_size=4, n_min=2)
    st.add(1, np.array([0.1, 0.2], np.float32), float("nan"))
    assert 1 not in st.budgets  # nothing stored at all
    st.add(1, np.array([0.1, 0.2], np.float32), 0.5)
    st.add(1, np.array([0.3, 0.2], np.float32), 0.6)
    assert st.model_budget() == 1


def test_fused_hyperband_nan_bracket_never_sticks(monkeypatch):
    """A diverged bracket (best_score NaN) must not freeze as the
    overall winner — `x > nan` is False for every x, so the naive
    best-pick would return the NaN bracket forever."""
    import mpi_opt_tpu.train.fused_asha as fa

    def fake(best):
        return {
            "best_score": best,
            "best_params": {"marker": best},
            "rung_sizes": [1],
            "rung_budgets": [1],
            "stop_rung": np.zeros(1, np.int32),
            "last_score": np.array([best], np.float32),
            "rung_history": [],
            "n_trials": 1,
        }

    results = iter([fake(float("nan")), fake(0.9)])
    monkeypatch.setattr(fa, "fused_sha", lambda *a, **k: next(results))
    res = fa.fused_hyperband(None, max_budget=3, eta=3, seed=0)  # 2 brackets
    assert res["best_score"] == pytest.approx(0.9)


def test_fused_bohb_runs_and_uses_model():
    """Fused BOHB: every bracket executes as a fused on-device SHA; by
    the later brackets the model store has qualified, so cohorts carry
    model-sampled rows (random_fraction=0 makes the count exact)."""
    from mpi_opt_tpu.train.fused_bohb import fused_bohb

    wl = get_workload("fashion_mlp", n_train=512, n_val=256)
    # bracket 0's first rung alone contributes 9 observations at budget
    # 1 (the FULL cohort scores, not just stop-rung ones), clearing the
    # 5-dim space's default n_min = d+3 = 8 — so the model qualifies for
    # every later bracket, same as the host algorithm would
    res = fused_bohb(wl, max_budget=9, eta=3, seed=0, random_fraction=0.0)
    # R=9: brackets (9@1, 5@3, 3@9) from bracket_plan
    assert res["n_trials"] == 9 + 5 + 3
    assert 0.0 <= res["best_score"] <= 1.0
    assert res["brackets"][0]["n_model_sampled"] == 0  # nothing to fit yet
    assert res["brackets"][1]["n_model_sampled"] == 5
    assert res["brackets"][2]["n_model_sampled"] == 3


def test_fused_sha_init_unit_digest_guards_resume(tmp_path):
    """A fused SHA resumed under DIFFERENT initial configurations is a
    different search: the checkpoint's cohort digest must refuse it."""
    import jax

    from mpi_opt_tpu.train.fused_asha import fused_sha

    wl = get_workload("fashion_mlp", n_train=512, n_val=256)
    space = wl.default_space()
    ck = str(tmp_path / "ck")
    unit_a = np.asarray(space.sample_unit(jax.random.key(1), 6))
    fused_sha(wl, n_trials=6, min_budget=2, max_budget=6, eta=3,
              seed=0, checkpoint_dir=ck, init_unit=unit_a)
    unit_b = np.asarray(space.sample_unit(jax.random.key(2), 6))
    with pytest.raises(ValueError, match="different sweep"):
        fused_sha(wl, n_trials=6, min_budget=2, max_budget=6, eta=3,
                  seed=0, checkpoint_dir=ck, init_unit=unit_b)
    # the SAME cohort resumes fine (replays from the final snapshot)
    res = fused_sha(wl, n_trials=6, min_budget=2, max_budget=6, eta=3,
                    seed=0, checkpoint_dir=ck, init_unit=unit_a)
    assert 0.0 <= res["best_score"] <= 1.0


def test_bohb_checkpoint_roundtrip():
    wl = get_workload("quadratic")
    space = wl.default_space()
    algo = BOHB(space, seed=3, max_budget=27, eta=3)
    be = CPUBackend(wl, n_workers=1)
    try:
        run_search(algo, be, max_batches=3)
        mid = algo.state_dict()
        resumed = BOHB(space, seed=3, max_budget=27, eta=3)
        resumed.load_state_dict(mid)
        assert resumed._samples == algo._samples
        for b in algo.obs.budgets:
            np.testing.assert_array_equal(resumed.obs.budgets[b]["unit"], algo.obs.budgets[b]["unit"])
            assert resumed.obs.budgets[b]["n"] == algo.obs.budgets[b]["n"]
        r1 = run_search(algo, be)
        be.reset()
        r2 = run_search(resumed, be)
    finally:
        be.close()
    assert r1.best is not None and r2.best is not None
    # both complete the full plan (arrival-order effects can differ, as
    # with hyperband's resume; completion and a sane best are the contract)
    assert algo.finished() and resumed.finished()


def test_bohb_checkpoint_validates_n_min():
    """n_min is the model-qualification threshold: a checkpoint written
    under a different value must be refused (silently resuming under a
    changed threshold changes WHEN the model engages) — while a
    pre-upgrade checkpoint with no recorded n_min stays loadable
    (ADVICE r4)."""
    space = _space()
    st = BOHB(space, seed=0, max_budget=9, eta=3, n_min=5).state_dict()
    algo = BOHB(space, seed=0, max_budget=9, eta=3, n_min=7)
    with pytest.raises(ValueError, match=r"n_min=5.*not n_min=7"):
        algo.load_state_dict(st)
    # pre-upgrade checkpoints carry no n_min: setdefault to the
    # instance's value, matching the momentum_dtype pattern
    del st["bohb"]["n_min"]
    BOHB(space, seed=0, max_budget=9, eta=3, n_min=7).load_state_dict(st)


def test_obsstore_drops_inf_scores():
    """+/-inf scores (exploded losses) are as model-poisoning as NaN:
    they'd blow up the KDE moments/bandwidths. Same isfinite gate, same
    single filtering point (ADVICE r3)."""
    from mpi_opt_tpu.algorithms.bohb import ObsStore

    st = ObsStore(dim=2, buffer_size=4, n_min=2)
    st.add(1, np.array([0.1, 0.2], np.float32), float("inf"))
    st.add(1, np.array([0.3, 0.4], np.float32), float("-inf"))
    assert 1 not in st.budgets


def test_bohb_refuses_hyperband_checkpoint():
    """Restoring a plain-hyperband checkpoint into BOHB must be the
    clear ValueError refusal the R/eta and buffer-size mismatches give,
    not a bare KeyError (ADVICE r3)."""
    space = _space()
    hb_state = Hyperband(space, seed=0, max_budget=9, eta=3).state_dict()
    algo = BOHB(space, seed=0, max_budget=9, eta=3)
    with pytest.raises(ValueError, match="hyperband, not bohb"):
        algo.load_state_dict(hb_state)


def test_fused_hyperband_persists_cohorts_for_resume(tmp_path):
    """Resume correctness must not depend on the model regenerating
    bit-identical cohorts: each bracket's sampled cohort is persisted
    (cohort_b.npz) and reused, so a resumed sweep whose sampler would
    drift numerically still replays — the drifted sampler is never even
    consulted (ADVICE r3)."""
    import jax

    from mpi_opt_tpu.train.fused_asha import fused_hyperband

    wl = get_workload("fashion_mlp", n_train=512, n_val=256)
    space = wl.default_space()
    ck = str(tmp_path / "ck")

    def cohort_a(b, n):
        u = np.array(space.sample_unit(jax.random.fold_in(jax.random.key(7), b), n))
        return u, 0

    r1 = fused_hyperband(wl, max_budget=3, eta=3, seed=0,
                         checkpoint_dir=ck, cohort_fn=cohort_a)

    def cohort_drifted(b, n):
        raise AssertionError("resume must reuse the persisted cohort, "
                             "not regenerate it")

    r2 = fused_hyperband(wl, max_budget=3, eta=3, seed=0,
                         checkpoint_dir=ck, cohort_fn=cohort_drifted)
    assert r2["best_score"] == pytest.approx(r1["best_score"])
    assert r2["best_params"] == r1["best_params"]


def test_persisted_cohort_refuses_different_sweep(tmp_path):
    """A cohort file left by a crashed run of a DIFFERENT sweep (other
    seed/workload/plan) must be refused even when no bracket snapshot
    exists yet to trigger fused_sha's config check — the cohort npz
    carries its own sweep-identity tag."""
    from mpi_opt_tpu.train.fused_asha import _bracket_cohort

    ck = str(tmp_path / "ck")

    def cohort(b, n):
        return np.full((n, 2), 0.5, np.float32), 0

    tag_a = "fashion_mlp|R=9|eta=3|seed=0"
    _bracket_cohort(ck, 0, 3, tag_a, cohort)  # first run writes cohort_0.npz
    for other in ("fashion_mlp|R=9|eta=3|seed=1",   # different seed
                  "cifar_cnn|R=9|eta=3|seed=0",      # different workload
                  "fashion_mlp|R=27|eta=3|seed=0"):  # different plan
        with pytest.raises(ValueError, match="different sweep"):
            _bracket_cohort(ck, 0, 3, other, cohort)
    # the matching sweep still reuses it, without consulting the sampler
    c, m = _bracket_cohort(ck, 0, 3, tag_a,
                           lambda b, n: (_ for _ in ()).throw(AssertionError))
    assert c.shape == (3, 2) and m == 0
