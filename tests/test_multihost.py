"""Multi-process bring-up SUCCESS path (SURVEY.md §5 "multi-host").

``TestInitializeMultihost`` (test_parallel.py) pins the failure paths —
this file proves the success path this container CAN run: two real OS
processes (the stand-in for two TPU hosts), a localhost coordinator,
``initialize_multihost`` in each, a global ('pop','data') mesh spanning
both processes' devices, and a cross-process reduction whose result
agrees in both processes (gloo CPU collectives; on TPU hardware the
identical code rides ICI/DCN).

Subprocesses are unavoidable here: jax.distributed must initialize
before the XLA backend exists, and the pytest process's backend is
already up (and pinned to 8 virtual devices).
"""

import socket
import subprocess
import sys

_WORKER = r"""
import sys

import jax

# per-process platform pinning must happen BEFORE initialize_multihost
# (the axon sitecustomize pins JAX_PLATFORMS; config overrides it)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

from mpi_opt_tpu.parallel.mesh import make_mesh, initialize_multihost

pid, port = int(sys.argv[1]), sys.argv[2]
idx = initialize_multihost(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert idx == pid, (idx, pid)
assert jax.process_count() == 2, jax.process_count()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

# the global mesh spans BOTH processes' devices (4 = 2 procs x 2 local)
mesh = make_mesh(n_pop=2, n_data=2)
assert mesh.devices.size == 4
assert len(set(d.process_index for d in mesh.devices.flat)) == 2

x = jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P(("pop", "data"))))
total = jax.jit(lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P()))(x)
val = float(total.addressable_shards[0].data)
assert val == 28.0, val
print(f"RESULT {pid} {val}", flush=True)
"""


def test_two_process_bringup_and_global_psum():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd="/root/repo",
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        outs.append(out)
    for pid, out in enumerate(outs):
        assert f"RESULT {pid} 28.0" in out, out
