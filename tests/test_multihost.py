"""Multi-process bring-up SUCCESS path (SURVEY.md §5 "multi-host").

``TestInitializeMultihost`` (test_parallel.py) pins the failure paths —
this file proves the success path this container CAN run: two real OS
processes (the stand-in for two TPU hosts), a localhost coordinator,
``initialize_multihost`` in each, a global ('pop','data') mesh spanning
both processes' devices, and a cross-process reduction whose result
agrees in both processes (gloo CPU collectives; on TPU hardware the
identical code rides ICI/DCN).

Subprocesses are unavoidable here: jax.distributed must initialize
before the XLA backend exists, and the pytest process's backend is
already up (and pinned to 8 virtual devices).
"""

import pytest

import socket
import subprocess
import sys

# Subprocess SPMD bring-up (2 jax-importing worker processes per test):
# out of the tier-1 870s single-process window — run explicitly or with
# ``-m slow``
pytestmark = pytest.mark.slow

_WORKER = r"""
import sys

import jax

# per-process platform pinning must happen BEFORE initialize_multihost
# (the axon sitecustomize pins JAX_PLATFORMS; config overrides it)
jax.config.update("jax_platforms", "cpu")
from mpi_opt_tpu.utils.hostdev import request_cpu_devices
request_cpu_devices(2)  # compat: pre-0.5 jax has no jax_num_cpu_devices

from mpi_opt_tpu.parallel.mesh import make_mesh, initialize_multihost

pid, port = int(sys.argv[1]), sys.argv[2]
idx = initialize_multihost(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert idx == pid, (idx, pid)
assert jax.process_count() == 2, jax.process_count()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

# the global mesh spans BOTH processes' devices (4 = 2 procs x 2 local)
mesh = make_mesh(n_pop=2, n_data=2)
assert mesh.devices.size == 4
assert len(set(d.process_index for d in mesh.devices.flat)) == 2

x = jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P(("pop", "data"))))
total = jax.jit(lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P()))(x)
val = float(total.addressable_shards[0].data)
assert val == 28.0, val
print(f"RESULT {pid} {val}", flush=True)
"""


def _run_two_procs(worker_src: str, extra_args=(), timeout: int = 420) -> list[str]:
    """Spawn 2 SPMD worker ranks (argv: pid, coordinator port, *extra)
    and return their stdouts; kills stragglers on any failure so a hung
    rank can't outlive the test. Shared with test_multihost_families."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src, str(pid), str(port), *extra_args],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd="/root/repo",
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs


def test_two_process_bringup_and_global_psum():
    outs = _run_two_procs(_WORKER, timeout=240)
    for pid, out in enumerate(outs):
        assert f"RESULT {pid} 28.0" in out, out


# -- a REAL fused sweep across the process boundary ----------------------
#
# Bring-up + one psum is not a sweep (round-3 verdict item 1): config
# 5's v4-32 target is multi-HOST, where every process traces identical
# programs, the population shardings span processes, and the host-side
# ledger runs once per process. This worker runs a fused PBT sweep AND
# a fused SHA sweep (non-dividing first cohort -> replication fallback
# + rounded rungs) to completion on a global ('pop','data') mesh over
# 2 OS processes x 2 CPU devices, and prints the results; the test
# asserts both processes report the IDENTICAL best (the SPMD contract).

_SWEEP_WORKER = r"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")
from mpi_opt_tpu.utils.hostdev import request_cpu_devices
request_cpu_devices(2)  # compat: pre-0.5 jax has no jax_num_cpu_devices
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cpu")

from mpi_opt_tpu.parallel.mesh import make_mesh, initialize_multihost

pid, port = int(sys.argv[1]), sys.argv[2]
initialize_multihost(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)

import warnings

from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.train.fused_asha import fused_sha
from mpi_opt_tpu.workloads import get_workload

mesh = make_mesh(n_pop=2, n_data=2)
assert len(set(d.process_index for d in mesh.devices.flat)) == 2

wl = get_workload("fashion_mlp", n_train=256, n_val=128)
wl.batch_size = 32

res = fused_pbt(
    wl, population=4, generations=2, steps_per_gen=2, seed=0, mesh=mesh
)
curve = ",".join(f"{v:.6f}" for v in res["best_curve"])
print(f"PBT {pid} {res['best_score']:.6f} [{curve}]", flush=True)

with warnings.catch_warnings():
    warnings.simplefilter("ignore")  # 5-cohort on 2-way axis replicates (by design here)
    sres = fused_sha(
        wl, n_trials=5, min_budget=1, max_budget=4, eta=2, seed=0, mesh=mesh
    )
print(f"SHA {pid} {sres['best_score']:.6f} {sres['best_trial']} "
      f"{sres['rung_sizes']}", flush=True)
"""


def test_two_process_fused_sweeps_agree():
    outs = _run_two_procs(_SWEEP_WORKER)
    pbt = [next(l for l in out.splitlines() if l.startswith("PBT")) for out in outs]
    sha = [next(l for l in out.splitlines() if l.startswith("SHA")) for out in outs]
    # identical best score, curve, winner, and rung plan in BOTH processes
    assert pbt[0].split(" ", 2)[2] == pbt[1].split(" ", 2)[2], pbt
    assert sha[0].split(" ", 2)[2] == sha[1].split(" ", 2)[2], sha


# -- checkpoint/resume across the process boundary -----------------------
#
# The failure-recovery story must survive multi-host too: a sweep
# sharded over a process-spanning mesh snapshots via fetch_global'd
# host copies + orbax's own multihost coordination, and a re-run with
# the same arguments replays from the final snapshot bit-identically in
# EVERY process.

_CKPT_WORKER = r"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")
from mpi_opt_tpu.utils.hostdev import request_cpu_devices
request_cpu_devices(2)  # compat: pre-0.5 jax has no jax_num_cpu_devices
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cpu")

from mpi_opt_tpu.parallel.mesh import make_mesh, initialize_multihost

pid, port, ck = int(sys.argv[1]), sys.argv[2], sys.argv[3]
initialize_multihost(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)

from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.workloads import get_workload

mesh = make_mesh(n_pop=2, n_data=2)
wl = get_workload("fashion_mlp", n_train=256, n_val=128)
wl.batch_size = 32

kw = dict(population=4, generations=2, steps_per_gen=2, seed=0, mesh=mesh,
          gen_chunk=1, checkpoint_dir=ck)
res = fused_pbt(wl, **kw)
curve = ",".join(f"{v:.6f}" for v in res["best_curve"])
print(f"RUN1 {pid} {res['best_score']:.6f} [{curve}]", flush=True)
res2 = fused_pbt(wl, **kw)  # resumes from the final snapshot: pure replay
curve2 = ",".join(f"{v:.6f}" for v in res2["best_curve"])
print(f"RUN2 {pid} {res2['best_score']:.6f} [{curve2}]", flush=True)
"""


def test_two_process_checkpointed_sweep_replays(tmp_path):
    ck = str(tmp_path / "ck")
    outs = _run_two_procs(_CKPT_WORKER, extra_args=(ck,))
    lines = {}
    for out in outs:
        for l in out.splitlines():
            if l.startswith("RUN"):
                tag, pid, rest = l.split(" ", 2)
                lines[(tag, pid)] = rest
    # the checkpointed sweep and its replay agree, in BOTH processes
    assert lines[("RUN1", "0")] == lines[("RUN1", "1")], lines
    assert lines[("RUN2", "0")] == lines[("RUN2", "1")], lines
    assert lines[("RUN1", "0")] == lines[("RUN2", "0")], lines
