"""Fused GroupNorm(+ReLU) Pallas kernel vs the jnp reference.

Interpret mode on CPU (the kernel's Mosaic lowering runs on real TPU in
the config-5 probes/bench); correctness here covers fwd, the custom
VJP, the no-relu form, vmap batching (the population path), and the
flax module's param-tree compatibility with nn.GroupNorm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi_opt_tpu.ops.pallas_gn as pg


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setattr(pg, "_INTERPRET", True)


def _setup(c, groups, b=2, hw=4, seed=0):
    k = jax.random.fold_in(jax.random.key(seed), c)
    kx, kg, kb, kd = jax.random.split(k, 4)
    x = jax.random.normal(kx, (b, hw, hw, c), jnp.float32)
    gamma = jax.random.normal(kg, (c,)) * 0.5 + 1.0
    beta = jax.random.normal(kb, (c,)) * 0.1
    dy = jax.random.normal(kd, x.shape)
    return x, gamma, beta, dy


@pytest.mark.parametrize("c,groups", [(64, 32), (128, 32), (8, 4)])
@pytest.mark.parametrize("relu", [True, False])
def test_forward_and_grads_match_reference(c, groups, relu):
    x, gamma, beta, dy = _setup(c, groups)
    y = pg.group_norm_relu(x, gamma, beta, groups, 1e-6, relu)
    yr = pg.reference_group_norm_relu(x, gamma, beta, groups, 1e-6, relu)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=1e-4)

    f = lambda x, g, b: jnp.sum(pg.group_norm_relu(x, g, b, groups, 1e-6, relu) * dy)
    fr = lambda x, g, b: jnp.sum(
        pg.reference_group_norm_relu(x, g, b, groups, 1e-6, relu) * dy
    )
    got = jax.grad(f, argnums=(0, 1, 2))(x, gamma, beta)
    want = jax.grad(fr, argnums=(0, 1, 2))(x, gamma, beta)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-3)


def test_vmap_matches_per_member(interpret_mode):
    """The population trainer vmaps members over the kernel; pallas's
    batching rule must agree with a per-member loop."""
    x = jax.random.normal(jax.random.key(1), (3, 2, 4, 4, 64))
    gamma = jnp.ones((3, 64))
    beta = jnp.zeros((3, 64))
    yv = jax.vmap(lambda x, g, b: pg.group_norm_relu(x, g, b, 32, 1e-6, True))(
        x, gamma, beta
    )
    yr = jnp.stack(
        [pg.reference_group_norm_relu(x[i], gamma[i], beta[i], 32) for i in range(3)]
    )
    np.testing.assert_allclose(np.asarray(yv), np.asarray(yr), atol=1e-4)


def test_resnet_param_tree_identical_across_gn_variants():
    """PallasGN keeps nn.GroupNorm's param names/shapes, so population
    states (and checkpoints) swap between the two model variants."""
    from mpi_opt_tpu.models.resnet import ResNet

    x = jnp.zeros((2, 8, 8, 3))
    kw = dict(n_classes=10, stage_sizes=(1, 1), width=8)
    p_xla = ResNet(**kw, pallas_gn=False).init(jax.random.key(0), x)["params"]
    p_pal = ResNet(**kw, pallas_gn=True).init(jax.random.key(0), x)["params"]
    assert jax.tree.structure(p_xla) == jax.tree.structure(p_pal)
    assert [tuple(l.shape) for l in jax.tree.leaves(p_xla)] == [
        tuple(l.shape) for l in jax.tree.leaves(p_pal)
    ]


def test_bf16_activation_dtype_roundtrip():
    x, gamma, beta, _ = _setup(64, 32)
    y = pg.group_norm_relu(x.astype(jnp.bfloat16), gamma, beta, 32, 1e-6, True)
    assert y.dtype == jnp.bfloat16
    yr = pg.reference_group_norm_relu(x.astype(jnp.bfloat16), gamma, beta, 32)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32), atol=3e-2
    )


def test_non_dividing_group_count_raises():
    """C % num_groups != 0 must raise (flax parity): _group_matrices
    floor-divides, so a non-dividing count would silently normalize over
    a WRONG group membership instead of failing."""
    x, gamma, beta, _ = _setup(48, 32)
    with pytest.raises(ValueError, match="divisible"):
        pg.group_norm_relu(x, gamma, beta, groups=32)
    # the gradient path funnels through the same forward check
    with pytest.raises(ValueError, match="divisible"):
        jax.grad(lambda v: pg.group_norm_relu(v, gamma, beta, groups=5).sum())(x)
