"""Fused-path ledger durability (ledger/fused.py): member-granular
boundary journaling, torn-boundary recovery, resume verification, and
cross-mode warm-start.

The headline invariants under test:
- one journaled record per member per boundary, same schema v1 the
  driver path writes, validating clean;
- the only append-kill damage shape (a torn FINAL boundary) is flagged
  by strict validation and self-healed on load; every OTHER boundary
  damage refuses to load;
- a re-computed boundary VERIFIES against its records (divergence =
  LedgerError) and a journal lagging its snapshot is refused;
- fused records warm-start driver algorithms and vice versa — the only
  gate is the space hash.
"""

import json
import os

import numpy as np
import pytest

from mpi_opt_tpu.ledger import (
    FusedJournal,
    LedgerError,
    SweepLedger,
    scan_boundaries,
    validate_ledger,
)
from mpi_opt_tpu.ledger.report import (
    fused_replay_consistency,
    summarize_ledger,
)
from mpi_opt_tpu.ledger.warmstart import best_observation, load_observations
from mpi_opt_tpu.workloads import get_workload


@pytest.fixture(scope="module")
def space():
    return get_workload("fashion_mlp", n_train=64, n_val=32).default_space()


def _fused_ledger(tmp_path, space, name="fused.jsonl"):
    led = SweepLedger(str(tmp_path / name))
    led.ensure_header(
        {
            "mode": "fused",
            "granularity": "generation",
            "algorithm": "pbt",
            "seed": 0,
            "space_hash": space.space_hash(),
        }
    )
    return led


def _units(n, space, seed=0):
    rng = np.random.default_rng(seed)
    return rng.random((n, space.dim), dtype=np.float32)


def test_record_boundary_journals_one_record_per_member(tmp_path, space):
    led = _fused_ledger(tmp_path, space)
    j = FusedJournal(led, space)
    u = _units(3, space)
    j.record_boundary(0, [0, 1, 2], u, [0.5, float("nan"), 0.7], step=5)
    j.record_boundary(1, [0, 1, 2], u, [0.6, 0.8, 0.9], step=10)
    led.close()
    assert j.written == 6
    assert validate_ledger(led.path) == []
    recs = [json.loads(l) for l in open(led.path).read().splitlines()[1:]]
    assert [r["trial_id"] for r in recs] == list(range(6))
    assert [r["boundary"] for r in recs] == [0, 0, 0, 1, 1, 1]
    assert all(r["boundary_size"] == 3 for r in recs)
    # non-finite member score -> failed with null score (strict JSON)
    nan_rec = recs[1]
    assert nan_rec["status"] == "failed" and nan_rec["score"] is None
    # canonical params decode back through the space (cross-mode edge)
    assert set(recs[0]["params"]) == set(space.names)


def test_resume_verifies_instead_of_rewriting(tmp_path, space):
    led = _fused_ledger(tmp_path, space)
    j = FusedJournal(led, space)
    u = _units(3, space)
    scores = np.array([0.5, 0.6, 0.7])
    j.record_boundary(0, [0, 1, 2], u, scores, step=5)
    led.close()

    led2 = SweepLedger(led.path)
    j2 = FusedJournal(led2, space)
    assert j2.complete_prefix() == 1
    j2.record_boundary(0, [0, 1, 2], u, scores, step=5)
    assert j2.written == 0 and j2.verified == 3
    # the file did not grow: verification never re-appends
    assert len(led2.records) == 3
    with pytest.raises(LedgerError, match="diverges"):
        j2.record_boundary(0, [0, 1, 2], u, scores + 0.5, step=5)
    led2.close()


def test_status_divergence_is_refused(tmp_path, space):
    led = _fused_ledger(tmp_path, space)
    j = FusedJournal(led, space)
    u = _units(2, space)
    j.record_boundary(0, [0, 1], u, [0.5, 0.6], step=5)
    with pytest.raises(LedgerError, match="status"):
        j.record_boundary(0, [0, 1], u, [0.5, float("nan")], step=5)
    led.close()


def test_torn_final_boundary_flagged_then_healed(tmp_path, space):
    led = _fused_ledger(tmp_path, space)
    j = FusedJournal(led, space)
    u = _units(3, space)
    j.record_boundary(0, [0, 1, 2], u, [0.1, 0.2, 0.3], step=5)
    j.record_boundary(1, [0, 1, 2], u, [0.4, 0.5, 0.6], step=10)
    led.close()
    # the mid-journal-kill shape: drop the final boundary's last record
    lines = open(led.path).read().splitlines()
    open(led.path, "w").write("\n".join(lines[:-1]) + "\n")

    problems = validate_ledger(led.path)
    assert any("torn" in p and "boundary 1" in p for p in problems)

    led2 = SweepLedger(led.path)  # load self-heals: partial boundary dropped
    assert led2.n_torn_boundary == 2
    j2 = FusedJournal(led2, space)
    assert j2.complete_prefix() == 1
    j2.record_boundary(1, [0, 1, 2], u, [0.4, 0.5, 0.6], step=10)
    led2.close()
    assert validate_ledger(led.path) == []
    # the healed + re-journaled file is record-identical to the original
    recs = [json.loads(l) for l in open(led.path).read().splitlines()[1:]]
    assert [r["trial_id"] for r in recs] == list(range(6))


def test_midfile_partial_boundary_refuses_to_load(tmp_path, space):
    led = _fused_ledger(tmp_path, space)
    j = FusedJournal(led, space)
    u = _units(2, space)
    j.record_boundary(0, [0, 1], u, [0.1, 0.2], step=5)
    j.record_boundary(1, [0, 1], u, [0.3, 0.4], step=10)
    led.close()
    # delete a MID-FILE record (boundary 0's second member): not an
    # append-crash shape — must refuse, never silently truncate
    lines = open(led.path).read().splitlines()
    del lines[2]
    open(led.path, "w").write("\n".join(lines) + "\n")
    with pytest.raises(LedgerError, match="damaged beyond"):
        SweepLedger(led.path)
    assert validate_ledger(led.path)  # strict mode flags it too


def test_journal_lagging_snapshot_is_refused(tmp_path, space):
    led = _fused_ledger(tmp_path, space)
    j = FusedJournal(led, space)
    j.record_boundary(0, [0, 1], _units(2, space), [0.1, 0.2], step=5)
    # a snapshot claiming 2 boundaries complete is AHEAD of the journal
    with pytest.raises(LedgerError, match="lags the snapshot"):
        j.require_prefix(2)
    j.require_prefix(1)  # the journaled prefix passes
    led.close()
    assert fused_replay_consistency(led.path, 1) == []
    assert fused_replay_consistency(led.path, 2)


def test_scan_boundaries_structural_problems():
    def rec(b, m, size=2, tid=0):
        return {
            "kind": "trial", "trial_id": tid, "member": m, "boundary": b,
            "boundary_size": size, "params": {}, "status": "ok",
            "score": 0.5, "step": 1,
        }

    # duplicate member
    _by, _sz, probs, _t = scan_boundaries([rec(0, 0), rec(0, 0, tid=1)])
    assert any("twice" in p for p in probs)
    # inconsistent declared size
    _by, _sz, probs, _t = scan_boundaries([rec(0, 0), rec(0, 1, size=3, tid=1)])
    assert any("inconsistent" in p for p in probs)
    # non-contiguous boundary blocks
    _by, _sz, probs, _t = scan_boundaries(
        [rec(0, 0, size=1), rec(1, 0, size=2, tid=1), rec(0, 1, size=1, tid=2)]
    )
    assert any("out of order" in p or "non-contiguous" in p for p in probs)
    # index gap
    _by, _sz, probs, _t = scan_boundaries([rec(0, 0, size=1), rec(2, 0, size=1, tid=1)])
    assert any("contiguous range" in p for p in probs)
    # driver record mixed into a fused journal
    _by, _sz, probs, _t = scan_boundaries(
        [rec(0, 0, size=1), {"kind": "trial", "trial_id": 9, "params": {},
                             "status": "ok", "score": 1.0, "step": 1}]
    )
    assert any("mixed" in p for p in probs)


def test_bracket_offsets_compose_one_contiguous_journal(tmp_path, space):
    """Hyperband-style composite: two bracket views over ONE ledger,
    placed by boundary/trial/member offsets, read back as a single
    contiguous boundary sequence."""
    led = _fused_ledger(tmp_path, space)
    u = _units(4, space)
    j0 = FusedJournal(led, space)  # bracket 0: 2 rungs, 4->2 trials
    j0.record_boundary(0, [0, 1, 2, 3], u, [0.1, 0.2, 0.3, 0.4], step=3)
    j0.record_boundary(1, [2, 3], u[:2], [0.5, 0.6], step=9)
    j1 = FusedJournal(led, space, boundary_offset=2, trial_offset=6, member_offset=4)
    j1.record_boundary(0, [0, 1], u[:2], [0.7, 0.8], step=9)  # bracket 1
    led.close()
    assert validate_ledger(led.path) == []
    recs = [json.loads(l) for l in open(led.path).read().splitlines()[1:]]
    assert [r["boundary"] for r in recs] == [0, 0, 0, 0, 1, 1, 2, 2]
    assert [r["trial_id"] for r in recs] == list(range(8))
    assert [r["member"] for r in recs] == [0, 1, 2, 3, 2, 3, 4, 5]
    # a fresh composite view sees the whole prefix
    led2 = SweepLedger(led.path, read_only=True)
    assert FusedJournal(led2, space).complete_prefix() == 3


def test_fused_report_renders_boundary_view(tmp_path, space):
    led = _fused_ledger(tmp_path, space)
    j = FusedJournal(led, space)
    u = _units(3, space)
    j.record_boundary(0, [0, 1, 2], u, [0.5, float("nan"), 0.7], step=5)
    led.close()
    rep = summarize_ledger(led.path)
    assert rep["fused"]["granularity"] == "generation"
    assert rep["fused"]["boundaries"] == 1
    assert rep["fused"]["member_records"] == 3
    assert rep["fused"]["member_failures"] == [1]
    assert rep["by_status"]["ok"] == 2 and rep["by_status"]["failed"] == 1


def test_cross_mode_warm_start_fused_to_driver(tmp_path, space):
    """A fused ledger's member records load as driver observations: the
    acceptance direction (fused ledger seeds a driver TPE sweep)."""
    from mpi_opt_tpu.algorithms.tpe import TPE

    led = _fused_ledger(tmp_path, space)
    j = FusedJournal(led, space)
    u = _units(3, space)
    j.record_boundary(0, [0, 1, 2], u, [0.5, float("nan"), 0.7], step=5)
    led.close()
    obs, skips = load_observations(led.path, space)
    assert len(obs) == 2  # failed member never becomes an observation
    assert skips == {"not_ok": 1}  # ...and the loss is COUNTED, not silent
    assert best_observation(obs).score == pytest.approx(0.7)
    # params round-trip: the best observation's unit decodes back to
    # (approximately) the journaled member's unit row
    np.testing.assert_allclose(obs[-1].unit, u[2], atol=1e-5)
    algo = TPE(space, seed=0, max_trials=4, budget=5)
    assert algo.ingest_observations(obs) == 2


def test_cross_mode_warm_start_refused_only_on_space_hash(tmp_path, space):
    """The reverse direction's ONLY gate is the space hash — a forged
    hash refuses, a matching fused/driver header never does."""
    led = _fused_ledger(tmp_path, space)
    FusedJournal(led, space).record_boundary(
        0, [0], _units(1, space), [0.5], step=5
    )
    led.close()
    assert len(load_observations(led.path, space)[0]) == 1  # mode never refuses
    # forge a different space hash into the header
    lines = open(led.path).read().splitlines()
    hdr = json.loads(lines[0])
    hdr["config"]["space_hash"] = "deadbeefdeadbeef"
    open(led.path, "w").write("\n".join([json.dumps(hdr)] + lines[1:]) + "\n")
    with pytest.raises(LedgerError, match="space hash"):
        load_observations(led.path, space)


def test_driver_records_before_fused_also_flagged_as_mixed():
    driver = {"kind": "trial", "trial_id": 0, "params": {}, "status": "ok",
              "score": 1.0, "step": 1}
    fused = {"kind": "trial", "trial_id": 1, "member": 0, "boundary": 0,
             "boundary_size": 1, "params": {}, "status": "ok", "score": 0.5,
             "step": 1}
    # both interleavings of a mixed file are refused, not just one
    for order in ([driver, fused], [fused, driver]):
        _by, _sz, probs, _t = scan_boundaries(order)
        assert any("mixed" in p for p in probs), order


def test_open_ledger_reentry_heals_partial_boundary(tmp_path, space):
    """The in-process --retries shape: an error escapes mid-boundary
    (k of N member records appended), then a fused driver re-enters
    with the SAME open ledger object. The fresh FusedJournal must heal
    the partial boundary (memory AND file) and re-journal it — not
    misdiagnose a sweep-shape divergence."""
    led = _fused_ledger(tmp_path, space)
    u = _units(3, space)
    j = FusedJournal(led, space)
    j.record_boundary(0, [0, 1, 2], u, [0.1, 0.2, 0.3], step=5)
    # simulate the escaped-mid-boundary state: 1 of 3 records appended
    led.record_member(trial_id=3, member=0, boundary=1, boundary_size=3,
                      canonical_params={}, score=0.4, step=10)

    j2 = FusedJournal(led, space)  # the retry's fresh view, same object
    assert led.n_torn_boundary == 1
    assert j2.complete_prefix() == 1
    j2.record_boundary(1, [0, 1, 2], u, [0.4, 0.5, 0.6], step=10)
    led.close()
    assert validate_ledger(led.path) == []
    recs = [json.loads(l) for l in open(led.path).read().splitlines()[1:]]
    assert [r["trial_id"] for r in recs] == list(range(6))
