import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_opt_tpu.ops import asha_cut, asha_rungs


def test_rung_ladder():
    assert asha_rungs(1, 81, 3) == [1, 3, 9, 27, 81]
    assert asha_rungs(2, 20, 4) == [2, 8, 20]
    with pytest.raises(ValueError):
        asha_rungs(0, 10, 3)


def test_cut_promotes_top_fraction():
    scores = jnp.array([0.1, 0.9, 0.5, 0.8, 0.2, 0.7, 0.3, 0.6])
    promote, order = asha_cut(scores, eta=4)
    # ceil(8/4)=2 survivors: the 0.9 and 0.8 entries
    assert int(promote.sum()) == 2
    assert bool(promote[1]) and bool(promote[3])
    np.testing.assert_array_equal(np.asarray(order[:2]), [1, 3])


def test_cut_respects_valid_mask():
    scores = jnp.array([0.9, 0.8, 0.7, 0.1])
    valid = jnp.array([False, True, True, True])
    promote, _ = asha_cut(scores, eta=3, valid=valid)
    # ceil(3/3)=1 survivor among valid entries: index 1 (0.8)
    assert int(promote.sum()) == 1
    assert bool(promote[1])
    assert not bool(promote[0])


def test_cut_is_jittable():
    f = jax.jit(asha_cut, static_argnames="eta")
    promote, _ = f(jnp.arange(9.0), eta=3)
    assert int(promote.sum()) == 3
    # the top third are indices 6,7,8
    assert bool(promote[6]) and bool(promote[7]) and bool(promote[8])
