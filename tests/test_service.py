"""Sweep-as-a-service: the resident multi-tenant scheduler (ISSUE 7).

The headline invariants under test:

- admission is FIFO within a tenant and fair-share across tenants;
- a time-sliced tenant's ledger is record-identical to a solo CLI run
  (slicing preempts ONLY at natural boundaries through the existing
  graceful-drain path, so it cannot alter results);
- cancel drains at a boundary — nothing killed, nothing quarantined,
  the device freed for the next tenant;
- server SIGTERM parks the active tenant and a restarted server
  continues the queue; a SIGKILL-shaped death (stale ``running``
  status, dead server pid) recovers through the same resume machinery;
- a shape-matching second tenant hits the compiled-program cache
  (counter-based; the CPU-backend form of "tenant N+1 costs dispatch,
  not compile").
"""

import json
import os
import signal
import time

import pytest

from mpi_opt_tpu.cli import main
from mpi_opt_tpu.service import service_main
from mpi_opt_tpu.service import tenants as tstates
from mpi_opt_tpu.service.scheduler import SweepService
from mpi_opt_tpu.service.spool import Spool, SpoolError
from mpi_opt_tpu.utils.metrics import MetricsLogger


def _quad(seed=0, trials=6):
    return [
        "--workload", "quadratic", "--algorithm", "random",
        "--trials", str(trials), "--budget", "3",
        "--workers", "1", "--seed", str(seed),
    ]


FUSED = [
    "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
    "--population", "4", "--generations", "3",
    "--steps-per-generation", "2", "--gen-chunk", "1", "--no-mesh",
    "--seed", "0",
]


def _service(state_dir, **kw):
    kw.setdefault("drain_on_empty", True)
    kw.setdefault("poll_seconds", 0.02)
    kw.setdefault(
        "metrics", MetricsLogger(path=os.path.join(state_dir, "server-metrics.jsonl"))
    )
    return SweepService(str(state_dir), **kw)


def _records(path, fused=False):
    keep = ("trial_id", "params", "status", "score", "step")
    if fused:
        keep += ("member", "boundary", "boundary_size")
    return [
        {k: r[k] for k in keep}
        for r in map(json.loads, open(path).read().splitlines()[1:])
    ]


def _events(state_dir, name):
    path = os.path.join(str(state_dir), "server-metrics.jsonl")
    return [
        r
        for r in map(json.loads, open(path).read().splitlines())
        if r.get("event") == name
    ]


# -- exit codes: one home (satellite) --------------------------------------


def test_exitcodes_single_home():
    from mpi_opt_tpu.health import shutdown
    from mpi_opt_tpu.utils import exitcodes, integrity

    assert exitcodes.EX_TEMPFAIL == 75 and shutdown.EX_TEMPFAIL is exitcodes.EX_TEMPFAIL
    assert exitcodes.EX_DATAERR == 65 and integrity.EX_DATAERR is exitcodes.EX_DATAERR
    assert exitcodes.classify(0) == "ok"
    assert exitcodes.classify(2) == "usage"
    assert exitcodes.classify(65) == "data_error"
    assert exitcodes.classify(69) == "unavailable"
    assert exitcodes.classify(75) == "preempted"
    assert exitcodes.classify(1) == "failure"
    assert exitcodes.classify(137) == "failure"


def test_tenant_state_machine():
    assert tstates.after_slice(0, cancel_requested=False) == tstates.DONE
    assert tstates.after_slice(75, cancel_requested=False) == tstates.PARKED
    assert tstates.after_slice(75, cancel_requested=True) == tstates.CANCELLED
    assert tstates.after_slice(65, cancel_requested=False) == tstates.DATA_ERROR
    assert tstates.after_slice(2, cancel_requested=False) == tstates.FAILED
    assert tstates.after_slice(1, cancel_requested=False) == tstates.FAILED
    assert tstates.PARKED in tstates.RUNNABLE
    assert tstates.DATA_ERROR in tstates.TERMINAL


# -- slice-hook plumbing (health/shutdown.py) ------------------------------


def test_slice_request_is_guard_scoped():
    from mpi_opt_tpu.health import shutdown

    # no guard active: a slice request has nothing to drain
    assert shutdown.request() is False
    with shutdown.ShutdownGuard() as g:
        assert shutdown.request() is True
        assert g.requested and g.signal_name == shutdown.SLICE
        assert shutdown.requested()
    # the request died with its guard — nothing leaks to the next sweep
    assert not shutdown.requested()


def test_real_signal_outranks_slice_label():
    from mpi_opt_tpu.health import shutdown

    shutdown.clear_delivered()
    with shutdown.ShutdownGuard() as g:
        shutdown.request()
        g._handle(signal.SIGTERM, None)
        assert g.signal_name == "SIGTERM"  # platform signal wins the label
    assert shutdown.delivered_signal() == "SIGTERM"
    shutdown.clear_delivered()
    assert shutdown.delivered_signal() is None


def test_poll_slice_hook_lifecycle():
    from mpi_opt_tpu.health import shutdown

    seen = []
    shutdown.poll_slice("nobody listening")  # no hook: no-op
    shutdown.set_slice_hook(seen.append)
    try:
        shutdown.poll_slice("stage a")
    finally:
        shutdown.clear_slice_hook()
    shutdown.poll_slice("after clear")
    assert seen == ["stage a"]


# -- spool clients ---------------------------------------------------------


def test_submit_rejects_server_owned_flags(tmp_path):
    spool = Spool(str(tmp_path))
    with pytest.raises(SpoolError, match="server-owned"):
        spool.submit(["--workload", "quadratic", "--ledger", "x.jsonl"])
    with pytest.raises(SpoolError, match="server-owned"):
        spool.submit(["--workload", "quadratic", "--checkpoint-dir=/tmp/x"])
    # argparse resolves unambiguous abbreviations, so the gate must
    # match prefixes: `--platfor` would reach the slice as --platform
    with pytest.raises(SpoolError, match="server-owned"):
        spool.submit(["--workload", "quadratic", "--platfor", "tpu"])
    # the CLI surface maps it to a usage error
    with pytest.raises(SystemExit) as e:
        service_main(
            ["submit", "--state-dir", str(tmp_path), "--",
             "--workload", "quadratic", "--resume"]
        )
    assert e.value.code == 2


def test_submit_status_cancel_roundtrip(tmp_path, capsys):
    d = str(tmp_path)
    assert service_main(
        ["submit", "--state-dir", d, "--tenant", "alice", "--"] + _quad(0)
    ) == 0
    j1 = json.loads(capsys.readouterr().out)["job"]
    assert service_main(["submit", "--state-dir", d, "--"] + _quad(1)) == 0
    j2 = json.loads(capsys.readouterr().out)["job"]

    assert service_main(["status", "--state-dir", d, "--json"]) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["server"]["alive"] is False
    assert [j["job"] for j in st["jobs"]] == [j1, j2]
    # one label across every surface: submit printed "queued", status
    # must agree (no third "submitted" state outside the state machine)
    assert all(j["state"] == tstates.QUEUED for j in st["jobs"])

    # cancel while queued: terminal immediately, never ran
    assert service_main(["cancel", j2, "--state-dir", d]) == 0
    assert json.loads(capsys.readouterr().out)["state"] == tstates.CANCELLED
    assert service_main(["status", "--state-dir", d, "--json"]) == 0
    st = json.loads(capsys.readouterr().out)
    by_job = {j["job"]: j for j in st["jobs"]}
    assert by_job[j2]["state"] == tstates.CANCELLED
    assert by_job[j1]["state"] == tstates.QUEUED

    with pytest.raises(SystemExit):  # unknown job: usage error
        service_main(["cancel", "job-nope", "--state-dir", d])
    capsys.readouterr()


def test_serve_refuses_second_server_with_same_id(tmp_path):
    """The default server-id deliberately collides: two default-id
    servers refuse each other (preserving one-server-per-spool until
    the operator federates with distinct --server-id values)."""
    from mpi_opt_tpu.service.spool import ServerClaimError

    spool = Spool(str(tmp_path))
    spool.write_server()  # this live process "is" the default server
    with pytest.raises(ServerClaimError, match="federate with a distinct"):
        _service(tmp_path).serve()
    # a DISTINCT id registers fine beside the live default one
    assert spool.register_server("srv-b") is True
    assert {s["server_id"] for s in spool.read_servers()} == {"server", "srv-b"}
    spool.clear_server("srv-b")
    spool.clear_server()


def test_serve_main_masks_only_claim_refusals(tmp_path, monkeypatch, capsys):
    """Exit EX_USAGE is reserved for the one-server-per-spool refusal; a
    genuine server crash must propagate with its traceback, not come out
    usage-shaped."""
    from mpi_opt_tpu.service.scheduler import SweepService
    from mpi_opt_tpu.utils.exitcodes import EX_USAGE

    Spool(str(tmp_path)).write_server()  # live claim -> refusal path
    assert service_main(["serve", "--state-dir", str(tmp_path)]) == EX_USAGE
    assert "already owns server-id" in capsys.readouterr().err
    Spool(str(tmp_path)).clear_server()

    def crash(self):
        raise RuntimeError("scheduler bug")

    monkeypatch.setattr(SweepService, "serve", crash)
    with pytest.raises(RuntimeError, match="scheduler bug"):
        service_main(["serve", "--state-dir", str(tmp_path)])


# -- scheduling ------------------------------------------------------------


def test_fair_share_across_tenants_fifo_within(tmp_path):
    """alice submits two jobs, bob one: the schedule alternates tenant
    NAMES while both are runnable (fewest-slices-first) and keeps
    alice's jobs in submission order."""
    spool = Spool(str(tmp_path))
    a1 = spool.submit(_quad(0, trials=4), tenant="alice")
    a2 = spool.submit(_quad(1, trials=4), tenant="alice")
    b1 = spool.submit(_quad(2, trials=4), tenant="bob")
    assert _service(tmp_path, slice_boundaries=2).serve() == 0
    assert all(
        t.status["state"] == tstates.DONE for t in spool.tenants()
    )
    order = [e["job"] for e in _events(tmp_path, "slice_start")]
    # 4 trials / 2-boundary slices = 2 slices per job. Usage balances
    # LIVE work: names alternate while both tenants hold unfinished
    # jobs (a1,b1,a1), a1's completion retires alice's tally so a2
    # competes fresh (fewest-slices -> a2, then FIFO tiebreak -> a2),
    # and bob's remaining slice closes the schedule. FIFO keeps a1
    # before a2 throughout.
    assert order == [a1, b1, a1, a2, a2, b1]


def test_admission_cap_per_tenant(tmp_path):
    spool = Spool(str(tmp_path))
    jobs = [spool.submit(_quad(s, trials=2), tenant="alice") for s in range(3)]
    svc = _service(tmp_path, slice_boundaries=50, max_active_per_tenant=1)
    assert svc.serve() == 0
    # all complete (the cap throttles concurrency, not total work), and
    # admission order follows submission
    assert [e["job"] for e in _events(tmp_path, "tenant_admit")] == jobs
    assert all(t.status["state"] == tstates.DONE for t in spool.tenants())


# -- the acceptance drill: concurrent tenants, bit-identical ledgers -------


def test_three_tenants_slice_interleaved_ledgers_identical_to_solo(
    tmp_path, capsys
):
    """Three concurrent tenants — two driver sweeps and one fused PBT —
    time-sliced at every boundary (>= 2 preemptions each), finish with
    ledger record-sets identical to their solo CLI runs."""
    d = tmp_path / "svc"
    spool = Spool(str(d))
    specs = {
        spool.submit(_quad(0), tenant="alice"): (_quad(0), False),
        spool.submit(_quad(1), tenant="bob"): (_quad(1), False),
        spool.submit(FUSED, tenant="carol"): (FUSED, True),
    }
    assert _service(d, slice_boundaries=1).serve() == 0

    summary = json.loads(
        open(os.path.join(str(d), "server-metrics.jsonl")).read().splitlines()[-1]
    )
    assert summary["slices"] >= 9 and summary["tenants_done"] == 3

    for job_id, (argv, fused) in specs.items():
        t = spool.tenant(job_id)
        s = t.status
        assert s["state"] == tstates.DONE
        assert s["preemptions"] >= 2, (job_id, s)
        solo = str(tmp_path / f"solo-{job_id}.jsonl")
        assert main(argv + ["--ledger", solo]) == 0
        capsys.readouterr()
        assert _records(t.ledger, fused=fused) == _records(solo, fused=fused), job_id
        # and the journal passes the strict schema gate
        assert main(["report", "--validate", t.ledger]) == 0
        capsys.readouterr()


# -- compiled-program reuse ------------------------------------------------


def test_traced_slice_writes_idle_frac(tmp_path):
    """serve --trace (ISSUE 11): every slice end writes the tenant's
    cumulative device-idle fraction — computed from the tenant's own
    span stream by obs/bubbles.py — into status.json beside the memory
    watermark, so the admission layer can spot the co-residency
    candidates (high-idle tenants) without replaying traces."""
    spool = Spool(str(tmp_path))
    j = spool.submit(FUSED, tenant="alice")
    assert _service(tmp_path, slice_boundaries=2, trace=True).serve() == 0
    st = spool.tenant(j).status
    assert st["state"] == tstates.DONE
    assert isinstance(st.get("idle_frac"), float), st.get("idle_frac")
    assert 0.0 <= st["idle_frac"] <= 1.0
    # untraced server: the field never appears (no stream to judge)
    j2 = spool.submit(FUSED, tenant="bob")
    assert _service(tmp_path, slice_boundaries=2).serve() == 0
    assert "idle_frac" not in spool.tenant(j2).status


def test_program_cache_hit_for_shape_matching_second_tenant(tmp_path):
    """Tenant B submits the same (workload, pop-shape, chunking) as A:
    B's first slice reports a program-cache HIT (its trainers/programs
    were built for A and never rebuilt), and B's setup wall collapses
    to dispatch instead of compile."""
    spool = Spool(str(tmp_path))
    a = spool.submit(FUSED, tenant="alice")
    b = spool.submit(FUSED, tenant="bob")
    assert _service(tmp_path, slice_boundaries=1).serve() == 0
    sa, sb = spool.tenant(a).status, spool.tenant(b).status
    assert sa["state"] == sb["state"] == tstates.DONE
    assert sa["first_slice_program_cache_hit"] is False
    assert sb["first_slice_program_cache_hit"] is True
    assert sb["program_cache"]["hits"] == sb["slices"]
    assert sb["program_cache"]["misses"] == 0
    # the warm tenant's time-to-first-trial is dominated by dispatch,
    # not compile — orders of magnitude apart, so the comparison is
    # timing-safe even on a loaded machine
    assert sb["first_slice_wall_s"] < sa["first_slice_wall_s"]
    summary = json.loads(
        open(os.path.join(str(tmp_path), "server-metrics.jsonl")).read().splitlines()[-1]
    )
    assert summary["program_cache_hits"] > 0
    assert summary["program_cache_misses"] >= 1


# -- cancel ----------------------------------------------------------------


def test_cancel_running_tenant_drains_cleanly(tmp_path, capsys):
    """Cancelling a RUNNING tenant takes effect at its next natural
    boundary: the sweep drains (snapshot + ledger intact — nothing
    quarantined, fsck clean) and the device moves on to the next job."""
    from mpi_opt_tpu.utils.integrity import fsck_main

    spool = Spool(str(tmp_path))
    long_job = spool.submit(_quad(0, trials=40), tenant="alice")
    short_job = spool.submit(_quad(1, trials=4), tenant="bob")

    def cancel_mid_slice(t, stage, n):
        if t.job_id == long_job and n == 3:
            spool.tenant(long_job).request_cancel()

    svc = _service(tmp_path, slice_boundaries=100, on_boundary=cancel_mid_slice)
    assert svc.serve() == 0
    s_long = spool.tenant(long_job).status
    assert s_long["state"] == tstates.CANCELLED
    assert s_long["slices"] == 1
    assert spool.tenant(short_job).status["state"] == tstates.DONE
    # drained, not killed: 3 completed trials journaled, nothing torn
    assert len(_records(spool.tenant(long_job).ledger)) == 3
    assert main(["report", "--validate", spool.tenant(long_job).ledger]) == 0
    capsys.readouterr()
    assert fsck_main([spool.tenant(long_job).ckpt]) == 0
    out = capsys.readouterr().out
    assert "quarantined=0" in out.replace(" ", "") or "corrupt" not in out


# -- server death and recovery ---------------------------------------------


def test_sigterm_drains_active_tenant_and_restart_continues(tmp_path, capsys):
    """A real SIGTERM mid-slice: the ACTIVE tenant drains at its next
    boundary and parks, the server exits 0 and clears its liveness
    file; a restarted server resumes the tenant to completion with a
    ledger identical to a solo run."""
    spool = Spool(str(tmp_path))
    job = spool.submit(_quad(0, trials=8), tenant="alice")
    seen = {"n": 0}

    def kill_mid_slice(t, stage, n):
        seen["n"] += 1
        if seen["n"] == 3:
            os.kill(os.getpid(), signal.SIGTERM)

    svc = _service(tmp_path, slice_boundaries=100, on_boundary=kill_mid_slice)
    assert svc.serve() == 0
    st = spool.tenant(job).status
    assert st["state"] == tstates.PARKED
    assert st["slices"] == 1
    assert spool.read_server() is None  # liveness file cleared on exit
    ends = _events(tmp_path, "slice_end")
    assert ends[-1]["signal"] == "SIGTERM"

    assert _service(tmp_path, slice_boundaries=100).serve() == 0
    st = spool.tenant(job).status
    assert st["state"] == tstates.DONE
    solo = str(tmp_path / "solo.jsonl")
    assert main(_quad(0, trials=8) + ["--ledger", solo]) == 0
    capsys.readouterr()
    assert _records(spool.tenant(job).ledger) == _records(solo)


def test_sigkill_shaped_death_recovers_on_restart(tmp_path, capsys):
    """The SIGKILL shape: a tenant left marked ``running`` behind a
    dead server pid. Restart demotes it to parked and the existing
    verified-snapshot + journal machinery resumes it to the same
    record set a solo run produces."""
    spool = Spool(str(tmp_path))
    job = spool.submit(_quad(0, trials=6), tenant="alice")

    # park the tenant mid-sweep via a drain request at its 2nd boundary
    def drain_mid_slice(t, stage, n):
        if n == 2:
            spool.request_drain()

    assert _service(
        tmp_path, slice_boundaries=100, on_boundary=drain_mid_slice
    ).serve() == 0
    t = spool.tenant(job)
    assert t.status["state"] == tstates.PARKED
    # forge the kill shape: status says running, the registration names
    # a pid that no longer exists, and no (or a dead-holder) lease —
    # the restarted server claims the orphan's lease and resumes it
    t.write_status(dict(t.status, state=tstates.RUNNING))
    spool.write_server()
    srv = spool.read_server()
    srv["pid"] = 2**22 + 7919  # vanishingly unlikely to be alive
    import json as _json

    open(spool.server_path, "w").write(_json.dumps(srv))
    assert spool.server_alive() is False

    assert _service(tmp_path, slice_boundaries=100).serve() == 0
    st = spool.tenant(job).status
    assert st["state"] == tstates.DONE
    assert any(e["job"] == job for e in _events(tmp_path, "tenant_takeover"))
    assert st["takeovers"] == 1
    solo = str(tmp_path / "solo.jsonl")
    assert main(_quad(0, trials=6) + ["--ledger", solo]) == 0
    capsys.readouterr()
    assert _records(spool.tenant(job).ledger) == _records(solo)


def test_sigkill_during_first_slice_resumes_not_fails(tmp_path, capsys):
    """The widest kill window: the server dies during a tenant's FIRST
    slice (slices still 0) after the sweep already journaled records.
    The retry must pass --resume — a fresh invocation would trip the
    CLI's stale-ledger refusal (exit 2) and terminally fail a tenant
    whose durable state is perfectly recoverable."""
    spool = Spool(str(tmp_path))
    job = spool.submit(_quad(0, trials=6), tenant="alice")

    def drain_mid_slice(t, stage, n):
        if n == 2:
            spool.request_drain()

    assert _service(
        tmp_path, slice_boundaries=100, on_boundary=drain_mid_slice
    ).serve() == 0
    t = spool.tenant(job)
    assert t.status["state"] == tstates.PARKED
    assert os.path.getsize(t.ledger) > 0  # durable records exist
    # forge "killed before the first slice_end": running, zero slices
    t.write_status(dict(t.status, state=tstates.RUNNING, slices=0))

    assert _service(tmp_path, slice_boundaries=100).serve() == 0
    st = spool.tenant(job).status
    assert st["state"] == tstates.DONE, st
    solo = str(tmp_path / "solo.jsonl")
    assert main(_quad(0, trials=6) + ["--ledger", solo]) == 0
    capsys.readouterr()
    assert _records(spool.tenant(job).ledger) == _records(solo)


def test_program_cache_commits_only_after_a_real_run(tmp_path):
    """A slice that dies before compiling must not make the next
    same-shape slice report a warm start that never happened."""
    from mpi_opt_tpu.service.programs import ProgramCache

    cache = ProgramCache()
    argv = _quad(0, trials=6)
    key, hit, _ = cache.acquire(argv)
    assert key is not None and hit is False
    # no commit (the slice failed pre-compile): still a miss
    key2, hit2, _ = cache.acquire(argv)
    assert key2 == key and hit2 is False
    cache.commit(key)
    _, hit3, _ = cache.acquire(argv)
    assert hit3 is True
    # chaos programs are never warm (wrappers rebuilt per run): no key
    # to commit — so a chaos slice can't falsely warm-start the
    # fault-free tenant of the same shape, nor report hits itself
    ck, chit, cworkload = cache.acquire(argv + ["--chaos", "exc=0.1,seed=1"])
    assert ck is None and chit is False and cworkload is None


def test_unreadable_job_spec_fails_tenant_not_server(tmp_path):
    """One tenant's unreadable job.json terminal-fails that tenant and
    the server keeps scheduling everyone else."""
    spool = Spool(str(tmp_path))
    bad = spool.submit(_quad(0, trials=6), tenant="alice")
    good = spool.submit(_quad(1, trials=6), tenant="bob")
    svc = _service(tmp_path, slice_boundaries=100)
    svc._admit_pending()
    os.unlink(spool.tenant(bad).job_path)
    assert svc.serve() == 0
    assert spool.tenant(bad).status["state"] == tstates.FAILED
    assert spool.tenant(good).status["state"] == tstates.DONE


def test_workload_construction_failure_fails_tenant_not_server(
    tmp_path, monkeypatch
):
    """A workload whose constructor raises (dataset cache, disk,
    arbitrary user code in get_workload -> cls()) terminal-fails its
    tenant at slice setup. The tenant was still RUNNABLE at that point,
    so an uncontained raise would kill the server with the tenant
    re-picked first by every restarted server: a permanent crash loop
    that takes every other tenant's service down with it."""
    import mpi_opt_tpu.workloads as workloads_mod

    real = workloads_mod.get_workload

    def exploding(name):
        if name == "fashion_mlp":
            raise RuntimeError("dataset cache corrupt")
        return real(name)

    monkeypatch.setattr(workloads_mod, "get_workload", exploding)
    spool = Spool(str(tmp_path))
    bad = spool.submit(FUSED, tenant="alice")
    good = spool.submit(_quad(1, trials=6), tenant="bob")
    svc = _service(tmp_path, slice_boundaries=100)
    assert svc.serve() == 0
    bad_status = spool.tenant(bad).status
    assert bad_status["state"] == tstates.FAILED
    assert "dataset cache corrupt" in bad_status["note"]
    assert spool.tenant(good).status["state"] == tstates.DONE


def test_fair_share_usage_is_session_scoped(tmp_path):
    """Fair-share usage dies with the server: a tenant's long-finished
    history must not starve its NEW job on a restarted server for as
    many slices as the history ever consumed. Live (parked) jobs' slice
    counts DO seed the new session, so in-flight fairness resumes."""
    spool = Spool(str(tmp_path))
    svc = _service(tmp_path, slice_boundaries=100)
    a_new = spool.submit(_quad(0, trials=6), tenant="alice")
    b_new = spool.submit(_quad(1, trials=6), tenant="bob")
    # alice's heavy history: a DONE job with 100 lifetime slices, plus
    # bob's PARKED in-flight job holding 3
    hist = spool.submit(_quad(2, trials=6), tenant="alice")
    svc._admit_pending()
    done = spool.tenant(hist)
    done.write_status(dict(done.status, state=tstates.DONE, slices=100))
    parked = spool.tenant(b_new)
    parked.write_status(dict(parked.status, state=tstates.PARKED, slices=3))

    restarted = _service(tmp_path, slice_boundaries=100)
    # history gone (alice back to her live jobs' 0), live seed kept
    assert restarted._usage.get("alice", 0) == 0
    assert restarted._usage["bob"] == 3
    # alice (0) outranks bob (3): her new job is picked immediately
    # (_pick_next now also ACQUIRES the pick's lease — fleet arbitration)
    picked, lease, takeover_from = restarted._pick_next()
    assert picked.job_id == a_new and lease is not None and takeover_from is None


def test_server_alive_counts_eperm_as_alive(tmp_path, monkeypatch):
    """os.kill EPERM means a LIVE process owned by someone else — the
    one-server-per-spool refusal must still see it on a shared dir."""
    spool = Spool(str(tmp_path))
    spool.write_server()

    def kill_eperm(pid, sig):
        raise PermissionError("not your process")

    monkeypatch.setattr(os, "kill", kill_eperm)
    assert spool.server_alive() is True


def test_read_summary_scoped_to_this_slice(tmp_path):
    """A slice that crashed before printing its summary must not
    inherit the previous slice's from the append-only run.log."""
    from mpi_opt_tpu.service.scheduler import _read_summary

    log = tmp_path / "run.log"
    prior = json.dumps({"best_score": 0.5, "workload": "quadratic"})
    log.write_text(prior + "\n")
    start = os.path.getsize(log)
    with open(log, "a") as f:
        f.write("Traceback (most recent call last):\n  boom\n")
    assert _read_summary(str(log), 0) == json.loads(prior)
    assert _read_summary(str(log), start) is None


def test_register_server_is_atomic_and_breaks_stale_registrations(tmp_path):
    """One-process-per-server-id is an O_EXCL claim, not a
    check-then-write: a live registration refuses peers, a dead pid's
    registration is broken."""
    spool = Spool(str(tmp_path))
    assert spool.register_server() is True
    assert Spool(str(tmp_path)).register_server() is False  # we are alive
    spool.clear_server()
    # stale registration: dead pid
    spool.write_server()
    srv = json.loads(open(spool.server_path).read())
    srv["pid"] = 2**22 + 7919
    open(spool.server_path, "w").write(json.dumps(srv))
    assert spool.register_server() is True
    # refresh is token-checked against THIS process: ours refreshes,
    # and a file rewritten by someone else refuses (the step-down cue)
    assert spool.refresh_server(Spool.DEFAULT_SERVER_ID, takeovers=3) is True
    assert spool.read_server()["takeovers"] == 3
    open(spool.server_path, "w").write(json.dumps(dict(srv, pid_start="999")))
    assert spool.refresh_server(Spool.DEFAULT_SERVER_ID) is False


def test_stale_claim_with_recycled_pid_is_broken(tmp_path):
    """A SIGKILLed server's claim keeps its pid forever — and the
    kernel eventually hands that pid to an unrelated process. A
    pid-existence-only liveness check would then block the spool until
    an operator deleted server.json by hand; the recorded process
    start time tells the incarnations apart."""
    from mpi_opt_tpu.service.spool import _write_json_atomic

    spool = Spool(str(tmp_path))
    spool.write_server()
    info = spool.read_server()
    assert info["pid_start"] is not None  # Linux /proc is available here
    # pid reuse shape: the claim's pid is alive (it is OURS), but the
    # claim was written by a previous incarnation of that pid
    _write_json_atomic(spool.server_path, dict(info, pid_start="12345"))
    assert spool.server_alive() is False
    assert spool.register_server() is True
    spool.clear_server()


def test_serve_rejects_zero_local_devices(tmp_path):
    """serve validates --local-devices through the same pin helper the
    flat CLI uses: a zero count is an immediate usage error, not a
    deferred backend-init crash inside the first tenant's slice."""
    from mpi_opt_tpu.service.client import serve_main

    with pytest.raises(SystemExit) as e:
        serve_main(
            [
                "--state-dir", str(tmp_path),
                "--platform", "cpu",
                "--local-devices", "0",
            ]
        )
    assert e.value.code == 2


def test_admission_tolerates_racing_cancel(tmp_path):
    """A queue file claimed by a concurrent peer surfaces as SpoolError
    (handled by _admit_pending), never FileNotFoundError (which would
    crash the server loop)."""
    spool = Spool(str(tmp_path))
    job = spool.submit(_quad(0, trials=4), tenant="alice")
    qpath = spool.pending_jobs()[0]
    os.unlink(qpath)  # the racing peer took it
    with pytest.raises(SpoolError, match="claimed by a peer"):
        spool.admit(qpath)
    # and a cancel that loses the materialize race still cancels via
    # the tenant-dir fall-through
    job2 = spool.submit(_quad(1, trials=4), tenant="bob")
    q2 = spool.pending_jobs()[0]
    spool.admit(q2)  # "the server" admits first
    assert spool.cancel(job2) == tstates.CANCELLED
    assert spool.tenant(job2).cancel_requested()


def test_drain_subcommand_parks_and_preserves_queue(tmp_path, capsys):
    """`mpi_opt_tpu drain`: the server finishes the active slice,
    parks, and exits; queued jobs stay queued for the next server."""
    spool = Spool(str(tmp_path))
    j1 = spool.submit(_quad(0, trials=8), tenant="alice")
    j2 = spool.submit(_quad(1, trials=4), tenant="bob")

    def drain_early(t, stage, n):
        if n == 1:
            assert service_main(["drain", "--state-dir", str(tmp_path)]) == 0

    assert _service(
        tmp_path, slice_boundaries=100, on_boundary=drain_early
    ).serve() == 0
    capsys.readouterr()
    states = {t.job_id: t.status["state"] for t in spool.tenants()}
    assert states[j1] == tstates.PARKED
    # j2 was admitted-or-queued but never ran; either way it is not lost
    assert states.get(j2, tstates.QUEUED) in (tstates.QUEUED,)
    # restart finishes everything
    assert _service(tmp_path, slice_boundaries=100).serve() == 0
    assert all(t.status["state"] == tstates.DONE for t in spool.tenants())


# -- report over a directory (satellite) -----------------------------------


def test_report_over_service_state_dir(tmp_path, capsys):
    spool = Spool(str(tmp_path))
    spool.submit(_quad(0), tenant="alice")
    spool.submit(_quad(1), tenant="bob")
    assert _service(tmp_path, slice_boundaries=2).serve() == 0

    assert main(["report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert out.count("service:") == 2  # per-tenant status lines
    assert "state=done" in out
    assert "sweep identities: 1" in out  # same workload/algo/space
    assert "quadratic/random: 2 ledger(s), 12 trials" in out

    assert main(["report", str(tmp_path), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert len(rep["ledgers"]) == 2
    assert all(r["service"]["state"] == "done" for r in rep["ledgers"])
    assert rep["best"] is not None

    # validate mode expands directories the same way
    assert main(["report", str(tmp_path), "--validate"]) == 0
    capsys.readouterr()

    # an empty directory is a loud audit failure, not a green no-op —
    # and the diagnostic goes to stderr, so --json stdout stays a
    # single machine-parseable object even with a mistyped dir mixed in
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["report", str(empty)]) == 1
    captured = capsys.readouterr()
    assert "no ledgers found" in captured.err
    assert main(["report", str(tmp_path), str(empty), "--json"]) == 1
    captured = capsys.readouterr()
    assert "no ledgers found" in captured.err
    assert len(json.loads(captured.out)["ledgers"]) == 2


def test_report_groups_differing_only_by_space_stay_distinguishable(
    tmp_path, capsys
):
    """Identity is (workload, algorithm, space_hash) but the label shows
    workload/algorithm — two groups split ONLY by a changed search space
    (the exact split the grouping exists to make) must not render as two
    identical lines with no way to tell them apart."""
    import time as time_mod

    def write_ledger(name, space_hash, score):
        header = {
            "kind": "header", "version": 1, "sweep_id": name,
            "created_ts": time_mod.time(),
            "config": {
                "workload": "quadratic", "algorithm": "random",
                "backend": "cpu", "seed": 0, "space_hash": space_hash,
            },
        }
        trial = {
            "kind": "trial", "trial_id": 0, "params": {"x": 0.5},
            "status": "ok", "score": score, "step": 3,
            "ts": time_mod.time(),
        }
        path = tmp_path / f"{name}.jsonl"
        path.write_text(
            json.dumps(header) + "\n" + json.dumps(trial) + "\n"
        )

    write_ledger("old-space", "aaaa1111bbbb", 1.0)
    write_ledger("new-space", "cccc2222dddd", 2.0)
    assert main(["report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "sweep identities: 2" in out
    assert "quadratic/random (space aaaa1111):" in out
    assert "quadratic/random (space cccc2222):" in out


# -- slice exit-shape fidelity (review-round fixes) ------------------------


def test_program_key_splits_on_statically_baked_config():
    """--truncation sizes the jitted exploit's n_cut at trace time and
    --workers shapes the driver path's eval batches: same pop-shape with
    either differing must NOT report a program-cache hit."""
    from mpi_opt_tpu.cli import build_parser
    from mpi_opt_tpu.service.programs import program_key

    base = FUSED + ["--trials", "4"]
    k = program_key(build_parser().parse_args(base))
    assert k == program_key(build_parser().parse_args(list(base)))
    assert k != program_key(
        build_parser().parse_args(base + ["--truncation", "0.5"])
    )
    assert k != program_key(build_parser().parse_args(base + ["--workers", "2"]))


def test_program_key_splits_on_warm_start(tmp_path):
    """Fused TPE sizes its compiled obs ring as n_trials + n_warm: a
    warm-starting tenant recompiles relative to the cold shape-match,
    and priors of different length differ again — neither may report a
    program-cache hit against the other."""
    from mpi_opt_tpu.cli import build_parser
    from mpi_opt_tpu.service.programs import program_key

    prior = tmp_path / "prior.jsonl"
    prior.write_text("x\n")
    base = FUSED + ["--trials", "4"]
    warm = base + ["--warm-start", str(prior)]
    cold_key = program_key(build_parser().parse_args(base))
    warm_key = program_key(build_parser().parse_args(warm))
    assert cold_key != warm_key
    assert warm_key == program_key(build_parser().parse_args(list(warm)))
    prior.write_text("x\ny\n")  # a longer prior = a different obs ring
    assert warm_key != program_key(build_parser().parse_args(list(warm)))


def test_slice_systemexit_string_fails_with_message_in_log(tmp_path):
    """cli.py's bare `raise SystemExit("msg")` refusals must classify
    like the subprocess world (rc 1) and leave the message in run.log,
    not vanish with the exception."""
    spool = Spool(str(tmp_path))
    # --no-mesh + --n-data 2 trips build_mesh's SystemExit(str) refusal
    # (the fused path calls build_mesh; the cpu driver path does not)
    spool.submit(FUSED + ["--n-data", "2"], tenant="a")
    assert _service(tmp_path).serve() == 0
    (t,) = spool.tenants()
    assert t.status["state"] == tstates.FAILED
    assert t.status["rc_history"] == [1]
    assert "--no-mesh contradicts" in open(t.log).read()


def test_slice_systemexit_none_is_success(tmp_path, monkeypatch):
    """SystemExit(None) is Python's success convention — a sweep exiting
    that way completed, and the tenant must land `done`, not `failed`."""
    import mpi_opt_tpu.cli as cli_mod

    spool = Spool(str(tmp_path))
    spool.submit(_quad(), tenant="a")
    monkeypatch.setattr(
        cli_mod, "main", lambda argv, _workload=None: (_ for _ in ()).throw(
            SystemExit(None)
        )
    )
    assert _service(tmp_path).serve() == 0
    (t,) = spool.tenants()
    assert t.status["state"] == tstates.DONE
    assert t.status["rc_history"] == [0]


def test_malformed_argv_reports_in_tenant_log_not_server_console(
    tmp_path, capsys
):
    """The program cache's probe parse is silent; the slice's own parse
    re-fails under the log redirect, so the usage text is attributable
    to the tenant (run.log), not interleaved into the server console."""
    spool = Spool(str(tmp_path))
    spool.submit(["--workload", "quadratic", "--algorithm", "nosuch"], tenant="a")
    assert _service(tmp_path).serve() == 0
    (t,) = spool.tenants()
    assert t.status["state"] == tstates.FAILED
    assert t.status["rc_history"] == [2]
    assert "invalid choice" in open(t.log).read()
    captured = capsys.readouterr()
    assert "invalid choice" not in captured.err
    assert "invalid choice" not in captured.out


def test_signal_between_loop_check_and_slice_never_burns_a_quantum(tmp_path):
    """A real signal landing in the window between the serve loop's
    shutdown check and the slice (spool scans) hits the SERVER guard;
    the slice must notice BEFORE running the tenant — not burn a full
    quantum (potentially minutes) while the platform's SIGKILL grace
    window ticks down."""
    from mpi_opt_tpu.health import shutdown

    from mpi_opt_tpu.service import leases

    spool = Spool(str(tmp_path))
    spool.submit(_quad(), tenant="a")
    svc = _service(tmp_path)
    (qpath,) = spool.pending_jobs()
    t = spool.admit(qpath)
    lease = leases.acquire(t.lease, svc.ident, svc.lease_ttl)
    shutdown.clear_delivered()
    try:
        with shutdown.ShutdownGuard() as g:  # the server's guard
            g._handle(signal.SIGTERM, None)  # the race: signal pre-slice
            assert svc._run_slice(t, lease) == "SIGTERM"
        # the tenant never ran: no slice accounting, still runnable
        assert t.status["state"] == tstates.QUEUED
        assert int(t.status.get("slices") or 0) == 0
    finally:
        shutdown.clear_delivered()


def test_signal_during_slice_parks_at_first_boundary(tmp_path):
    """A real delivery the tenant's own guard never saw (it landed on
    the server guard in the install sliver) still parks the tenant at
    its FIRST boundary via the hook's delivered_signal() check."""
    from mpi_opt_tpu.health import shutdown

    spool = Spool(str(tmp_path))
    spool.submit(_quad(0, trials=8), tenant="a")

    def fake_delivery(t, stage, n):
        if n == 1:
            shutdown._DELIVERED = "SIGTERM"  # white-box: the sliver shape

    svc = _service(tmp_path, slice_boundaries=50, on_boundary=fake_delivery)
    try:
        assert svc.serve() == 0
        (t,) = spool.tenants()
        # parked after ONE boundary, nowhere near the 50-boundary budget
        assert t.status["state"] == tstates.PARKED
        assert t.status["boundaries"] <= 2
    finally:
        shutdown.clear_delivered()


def test_help_tenant_never_leaks_into_server_stdout(tmp_path, capsys):
    """A tenant argv containing --help must not print multi-KB help text
    to the server's stdout (its JSONL metrics stream) via the program
    cache's probe parse — the text belongs in the tenant's run.log."""
    spool = Spool(str(tmp_path))
    spool.submit(["--help"], tenant="a")
    assert _service(tmp_path).serve() == 0
    (t,) = spool.tenants()
    assert "--workload" in open(t.log).read()  # help text, attributed
    captured = capsys.readouterr()
    assert "usage:" not in captured.out and "usage:" not in captured.err


def test_fair_share_usage_retires_with_the_job(tmp_path):
    """On a long-lived server, a tenant whose 50-slice job just finished
    must not have its NEXT submission starved for 50 slices: terminal
    jobs retire their slice count from the in-session tally."""
    spool = Spool(str(tmp_path))
    spool.submit(_quad(0, trials=6), tenant="alice")
    svc = _service(tmp_path, slice_boundaries=2)
    assert svc.serve() == 0
    (t,) = spool.tenants()
    assert t.status["state"] == tstates.DONE
    assert int(t.status["slices"]) >= 2  # multi-slice history existed
    assert svc._usage.get("alice", 0) == 0  # ...and was retired


def test_readonly_clients_refuse_a_nonexistent_spool(tmp_path):
    """status/cancel/drain must not fabricate an empty spool at a
    mistyped --state-dir and report healthy-looking answers about it."""
    missing = str(tmp_path / "svc_prod_typo")
    for argv in (
        ["status", "--state-dir", missing],
        ["cancel", "some-job", "--state-dir", missing],
        ["drain", "--state-dir", missing],
    ):
        with pytest.raises(SystemExit) as e:
            service_main(argv)
        assert e.value.code == 2
        assert not os.path.exists(missing)  # nothing fabricated
    # submit still queue-aheads (documented): it CREATES the spool
    spool_dir = str(tmp_path / "fresh")
    assert service_main(
        ["submit", "--state-dir", spool_dir, "--tenant", "a", "--"] + _quad()
    ) == 0
    assert os.path.isdir(os.path.join(spool_dir, "queue"))


# -- persistent compile cache (satellite) ----------------------------------


def test_compile_cache_env_wiring(tmp_path, monkeypatch):
    """MPI_OPT_TPU_CACHE_DIR -> jax_compilation_cache_dir, wired the way
    backends/cpu.py already does for pool workers, but for the main
    process's default/TPU path (cli.wire_compile_cache, called before
    backend init and inherited by launch.py rank processes)."""
    import jax

    from mpi_opt_tpu.cli import wire_compile_cache

    prev = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.delenv("MPI_OPT_TPU_CACHE_DIR", raising=False)
        assert wire_compile_cache() is False  # unset: never touches config
        assert jax.config.jax_compilation_cache_dir == prev
        cache = str(tmp_path / "xla-cache")
        monkeypatch.setenv("MPI_OPT_TPU_CACHE_DIR", cache)
        assert wire_compile_cache() is True
        assert jax.config.jax_compilation_cache_dir == cache
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_spawn_ranks_propagate_cache_env(tmp_path, monkeypatch):
    """launch.py rank processes INHERIT the environment (Popen env=None),
    so MPI_OPT_TPU_CACHE_DIR set on the supervisor reaches every rank of
    every restart/resume attempt without an explicit copy."""
    import mpi_opt_tpu.launch as launch_mod

    cache = str(tmp_path / "xla-cache")
    monkeypatch.setenv("MPI_OPT_TPU_CACHE_DIR", cache)
    captured = {}

    class FakeProc:
        def poll(self):
            return None

        def kill(self):
            pass

        def wait(self):
            pass

    def fake_popen(argv, stdout=None, stderr=None, text=None, env=None):
        captured["env"] = env
        return FakeProc()

    monkeypatch.setattr(launch_mod.subprocess, "Popen", fake_popen)
    procs = launch_mod._spawn_ranks(1, ["--workload", "quadratic"], str(tmp_path))
    for _p, out, err in procs:
        out.close()
        err.close()
    # env=None IS the propagation mechanism: the child shares os.environ,
    # where the cache dir is already set
    assert captured["env"] is None
    assert os.environ["MPI_OPT_TPU_CACHE_DIR"] == cache


# -- priority / deadline scheduling (ISSUE 16) ----------------------------


def test_pick_next_priority_class_outranks_fair_share(tmp_path):
    """A higher --priority job is picked first even when fair share
    favors the other tenant (priority is a CLASS above the usage key,
    not a tiebreak inside it)."""
    spool = Spool(str(tmp_path))
    lo = spool.submit(_quad(0), tenant="cheap", priority=0)
    hi = spool.submit(_quad(1), tenant="busy", priority=5)
    svc = _service(tmp_path)
    svc._admit_pending()
    svc._usage["busy"] = 50  # fair share alone would pick "cheap"
    picked, lease, _ = svc._pick_next()
    assert picked.job_id == hi and lease is not None
    assert spool.tenant(lo).status["priority"] == 0
    assert spool.tenant(hi).status["priority"] == 5


def test_pick_next_earliest_deadline_orders_within_class(tmp_path):
    """Inside one priority class, earliest deadline wins and
    deadline-less jobs sort last — urgency and importance stay
    independent axes."""
    spool = Spool(str(tmp_path))
    nodl = spool.submit(_quad(0), tenant="a")
    late = spool.submit(_quad(1), tenant="b", deadline_ts=time.time() + 3600)
    soon = spool.submit(_quad(2), tenant="c", deadline_ts=time.time() + 60)
    svc = _service(tmp_path)
    svc._admit_pending()
    picked, _, _ = svc._pick_next()
    assert picked.job_id == soon
    st = spool.tenant(soon).status
    assert st["deadline_ts"] == pytest.approx(
        spool.tenant(soon).job["deadline_ts"]
    )
    assert spool.tenant(nodl).status["deadline_ts"] is None
    assert spool.tenant(late).status["deadline_ts"] > st["deadline_ts"]


def test_starvation_floor_promotes_a_waiting_job(tmp_path):
    """A prio-0 job that has waited N floors gains N effective classes,
    so a saturating high-priority stream delays it by a bounded number
    of floors, never forever."""
    spool = Spool(str(tmp_path))
    old = spool.submit(_quad(0), tenant="patient", priority=0)
    fresh = spool.submit(_quad(1), tenant="vip", priority=2)
    svc = _service(tmp_path, starvation_floor_s=0.1)
    svc._admit_pending()
    t = spool.tenant(old)
    t.write_status(dict(t.status, submitted_ts=time.time() - 1.0))
    # waited ~10 floors: effective priority ~10 > the fresh job's 2
    picked, _, _ = svc._pick_next()
    assert picked.job_id == old
    with pytest.raises(ValueError):
        _service(tmp_path, starvation_floor_s=0.0)


def test_submit_cli_priority_deadline_surfaced_in_status(tmp_path, capsys):
    d = str(tmp_path)
    assert service_main(
        ["submit", "--state-dir", d, "--priority", "3", "--deadline", "120",
         "--"] + _quad(0)
    ) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["priority"] == 3
    assert out["deadline_ts"] == pytest.approx(time.time() + 120, abs=30)
    assert service_main(["status", "--state-dir", d, "--json"]) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["jobs"][0]["priority"] == 3
    assert st["jobs"][0]["deadline_ts"] == pytest.approx(out["deadline_ts"])
    # the text rendering names both (the operator's at-a-glance view)
    assert service_main(["status", "--state-dir", d]) == 0
    text = capsys.readouterr().out
    assert "prio=3" in text and "deadline=" in text
