"""health/: graceful-shutdown protocol, heartbeats, stall detection.

The headline is the driver drain drill: a chaos-injected SIGTERM
(``preempt`` fault) mid-sweep lets the in-flight batch FINISH, forces an
off-cadence checkpoint save, and surfaces as ``SweepInterrupted`` — the
exception the CLI maps to exit 75 (EX_TEMPFAIL), which launch.py
classifies as a free (non-retry-consuming) coordinated restart.
"""

import os
import shutil
import signal
import warnings

import pytest

from mpi_opt_tpu.health import (
    EX_TEMPFAIL,
    Heartbeat,
    ShutdownGuard,
    StallDetector,
    SweepInterrupted,
    read_beat,
)
from mpi_opt_tpu.health import shutdown as shutdown_mod


# -- heartbeat -------------------------------------------------------------


def test_heartbeat_monotonic_and_atomic(tmp_path):
    path = str(tmp_path / "r0.hb")
    h = Heartbeat(path)
    r1 = h.beat(stage="driver", batches=1)
    r2 = h.beat(stage="driver", batches=2)
    assert (r1["beats"], r2["beats"]) == (1, 2)
    rec = read_beat(path)
    assert rec["beats"] == 2 and rec["pid"] == os.getpid()
    assert rec["progress"] == {"stage": "driver", "batches": 2}
    # write-tmp-then-rename leaves no litter a reader could mistake
    assert os.listdir(tmp_path) == ["r0.hb"]


def test_read_beat_missing_or_torn_returns_none(tmp_path):
    assert read_beat(str(tmp_path / "nope.hb")) is None
    torn = tmp_path / "torn.hb"
    torn.write_text('{"beats": ')
    assert read_beat(str(torn)) is None
    notdict = tmp_path / "list.hb"
    notdict.write_text("[1, 2]")
    assert read_beat(str(notdict)) is None


def test_heartbeat_write_failure_warns_once_never_raises(tmp_path):
    h = Heartbeat(str(tmp_path / "d" / "r.hb"))
    shutil.rmtree(tmp_path / "d")  # the directory vanishes under the rank
    with pytest.warns(UserWarning, match="heartbeat write"):
        assert h.beat() is None
    with warnings.catch_warnings():  # quiet (and still harmless) after
        warnings.simplefilter("error")
        assert h.beat() is None


# -- stall detection -------------------------------------------------------


def _write_beat(path, beats):
    import json

    with open(path, "w") as f:
        f.write(json.dumps({"pid": 1, "beats": beats, "ts": 0.0, "progress": {}}))


def test_stall_detector_watches_only_after_first_beat(tmp_path):
    p = str(tmp_path / "r0.hb")
    d = StallDetector([p], stall_timeout=10.0)
    # no heartbeat file yet = rank still compiling: NOT watched, no
    # matter how long (the engagement rule that keeps conservative
    # timeouts from killing legitimate cold starts)
    assert d.poll(now=0.0) == []
    assert d.poll(now=10_000.0) == []
    _write_beat(p, 1)
    assert d.poll(now=10_000.0) == []  # first beat: the clock starts here
    assert d.poll(now=10_009.0) == []  # within timeout
    assert d.poll(now=10_011.0) == [0]  # frozen past it: stalled
    _write_beat(p, 2)
    assert d.poll(now=10_012.0) == []  # advanced: watch resets
    assert d.poll(now=10_023.0) == [0]


def test_stall_detector_validates_timeout():
    with pytest.raises(ValueError, match="stall_timeout"):
        StallDetector([], 0.0)


# -- shutdown guard --------------------------------------------------------


def test_shutdown_guard_sets_flag_and_restores_handlers():
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    assert not shutdown_mod.requested()  # no active guard
    with ShutdownGuard() as g:
        assert not g.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert g.requested and g.signal_name == "SIGTERM"
        assert shutdown_mod.requested()
        assert shutdown_mod.active_signal() == "SIGTERM"
        # repeated SIGTERM stays graceful: a supervisor forwarding the
        # platform's signal must not turn the drain into an abort
        os.kill(os.getpid(), signal.SIGTERM)
        assert g.requested
    assert not shutdown_mod.requested()
    assert signal.getsignal(signal.SIGTERM) is prev_term
    assert signal.getsignal(signal.SIGINT) is prev_int


def test_second_sigint_escalates_to_keyboard_interrupt():
    with ShutdownGuard() as g:
        g._handle(signal.SIGINT, None)
        assert g.requested and g.signal_name == "SIGINT"
        with pytest.raises(KeyboardInterrupt):
            g._handle(signal.SIGINT, None)


def test_ex_tempfail_is_sysexits_value():
    assert EX_TEMPFAIL == 75  # launch.py's preemption classification key


# -- the driver drain drill ------------------------------------------------


@pytest.mark.chaos
def test_driver_drains_at_batch_boundary_and_forces_checkpoint():
    """chaos ``preempt`` delivers SIGTERM mid-evaluation: the guard
    absorbs it, the batch completes (its trial reports normally), and
    run_search drains — forcing an off-cadence checkpoint save so
    --resume loses nothing. Chaos seed 7 puts the one preempt draw at
    trial index 6 of this 12-trial seed-0 stream."""
    from mpi_opt_tpu.algorithms import RandomSearch
    from mpi_opt_tpu.backends.cpu import CPUBackend
    from mpi_opt_tpu.driver import run_search
    from mpi_opt_tpu.workloads import get_workload

    kw = {"inner": "quadratic", "preempt": 0.15, "seed": 7}
    wl = get_workload("chaos", **kw)
    algo = RandomSearch(wl.default_space(), seed=0, max_trials=12, budget=10)

    class SpyCheckpointer:
        def __init__(self):
            self.forced = []

        def maybe_save(self, step, algorithm, backend):
            return False  # never on cadence: any save below is the forced one

        def save(self, step, algorithm, backend):
            self.forced.append(step)

    ck = SpyCheckpointer()
    b = CPUBackend(wl, n_workers=1, workload_kwargs=kw)
    try:
        with ShutdownGuard():
            with pytest.raises(SweepInterrupted) as ei:
                run_search(algo, b, checkpointer=ck)
    finally:
        b.close()
    # trial 6 (0-based) preempted -> its batch still COMPLETED: 7 trials
    assert algo.n_trials == 7
    assert ck.forced == [7]  # the off-cadence flush
    assert ei.value.signal == "SIGTERM"
    assert "batch 7" in ei.value.at


@pytest.mark.chaos
def test_driver_completes_when_preempted_on_the_final_batch():
    """A SIGTERM landing during the batch that FINISHES the sweep must
    not turn success into exit 75: finishing strictly dominates
    preempting a finished sweep (same rule as the fused paths'
    final=True boundary)."""
    from mpi_opt_tpu.algorithms import RandomSearch
    from mpi_opt_tpu.backends.cpu import CPUBackend
    from mpi_opt_tpu.driver import run_search
    from mpi_opt_tpu.workloads import get_workload

    kw = {"inner": "quadratic", "preempt": 1.0}
    wl = get_workload("chaos", **kw)
    algo = RandomSearch(wl.default_space(), seed=0, max_trials=1, budget=10)
    b = CPUBackend(wl, n_workers=1, workload_kwargs=kw)
    try:
        with ShutdownGuard() as g:
            res = run_search(algo, b)  # must return, not raise
            assert g.requested  # the signal really was delivered
    finally:
        b.close()
    assert res.n_trials == 1 and res.best is not None


def test_fused_step_chunk_sub_launches_beat(tmp_path):
    """Sub-launch heartbeat granularity (ROADMAP follow-up): a
    step-chunked fused PBT generation beats once per train sub-segment,
    so --stall-timeout can be sized to one step_chunk instead of a
    whole generation's train_segment scan."""
    from mpi_opt_tpu.health import heartbeat
    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("fashion_mlp", n_train=256, n_val=128)
    hb_path = str(tmp_path / "rank.hb")
    hb = heartbeat.configure(hb_path)
    try:
        fused_pbt(
            wl,
            population=4,
            generations=2,
            steps_per_gen=4,
            seed=0,
            step_chunk=2,  # 2 sub-launches per generation
        )
    finally:
        heartbeat.deconfigure()
    # per generation: 2 sub-launch beats, the shared engine's
    # wave-dispatched beat (resident mode is the one-wave case of
    # train/engine.py's interval loop), and the exploit boundary_span
    # beat — so --stall-timeout can still be sized to one step_chunk
    assert hb.beats == 2 * (2 + 1 + 1)
    rec = heartbeat.read_beat(hb_path)
    assert rec is not None and rec["beats"] == hb.beats
