"""Bench record schema: the BENCH_r0*.json drift gate (ISSUE 10).

The trajectory comparison (`trace --diff` on embedded attributions,
bench_all's --gate-base verdict) depends on bench records keeping a
declared shape. This gate: version-2 records must carry
``schema_version``/``trace``/``device_memory``; the committed
BENCH_r01-r05 + BENCH_ALL.json history must stay valid as the legacy
shape; and the whole-trajectory ``bench_gate`` honors each metric's
better-direction.
"""

from __future__ import annotations

import glob
import json
import os

import pytest

from mpi_opt_tpu.obs.diff import (
    BENCH_SCHEMA_VERSION,
    bench_gate,
    validate_bench_record,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _v2(**over):
    rec = {
        "schema_version": BENCH_SCHEMA_VERSION,
        "metric": "pbt_cifar10_cnn_member_generations_per_sec_per_chip",
        "value": 8.8,
        "unit": "trials/sec/chip",
        "trace": None,
        "device_memory": None,
    }
    rec.update(over)
    return rec


def _phases(train_p50, n=4):
    return {
        "train": {
            "count": n,
            "total_s": train_p50 * n,
            "self_s": train_p50 * n,
            "p50_s": train_p50,
            "p95_s": train_p50 * 1.01,
            "mean_self_s": train_p50,
            "sd_self_s": train_p50 * 0.01,
            "p50_self_s": train_p50,
            "p95_self_s": train_p50 * 1.01,
        }
    }


def _attribution(train_p50):
    return {
        "wall_s": train_p50 * 5,
        "phases": _phases(train_p50),
        "compile": {
            "cold": {"count": 1, "total_s": 2.0},
            "persistent": {"count": 0, "total_s": 0.0},
        },
        "train": {"tflops_per_sec": 33.0},
        "time_to_first_trial_s": 3.0,
        "memory": {"peak_bytes": 1 << 30},
    }


# -- the record validator -------------------------------------------------


def test_v2_record_validates_and_requires_new_keys():
    assert validate_bench_record(_v2()) == []
    # trace/device_memory may be null but must be PRESENT
    rec = _v2()
    del rec["trace"]
    assert any("trace" in p for p in validate_bench_record(rec))
    rec = _v2()
    del rec["device_memory"]
    assert any("device_memory" in p for p in validate_bench_record(rec))
    # populated shapes are checked too
    assert validate_bench_record(
        _v2(trace=_attribution(1.0), device_memory={"bytes_in_use": 1, "source": "live_arrays"})
    ) == []
    assert any(
        "phases" in p or "trace" in p
        for p in validate_bench_record(_v2(trace={"not": "an attribution"}))
    )
    assert any(
        "device_memory" in p
        for p in validate_bench_record(_v2(device_memory={"bogus": 1}))
    )
    # drift in the core keys is always caught
    rec = _v2()
    del rec["unit"]
    assert validate_bench_record(rec)
    assert any(
        "newer" in p
        for p in validate_bench_record(_v2(schema_version=BENCH_SCHEMA_VERSION + 1))
    )


def test_v2_trace_intra_phase_sections_are_optional():
    """ISSUE 11: bubbles/staging/roofline ride in round-8+ embedded
    attributions, but they are OPTIONAL — a round-7 embed (or --no-trace
    record) without them must keep validating forever, and a present
    section must be an object."""
    # absent: valid (the round-7 shape)
    assert validate_bench_record(_v2(trace=_attribution(1.0))) == []
    # present and well-shaped: valid
    tr = _attribution(1.0)
    tr["bubbles"] = {"idle_frac": 0.1, "idle_s": 0.5, "by_cause": {"compile": 0.5}}
    tr["staging"] = {"overlap_frac": 0.76, "overlap_s": 3.0, "wait_s": 1.0}
    tr["roofline"] = {"bound": "compute-bound", "mxu_frac": 0.21, "peak_tflops": 157.0}
    assert validate_bench_record(_v2(trace=tr)) == []
    # explicit null: valid (an untraced-memory environment)
    tr2 = _attribution(1.0)
    tr2["bubbles"] = tr2["staging"] = tr2["roofline"] = None
    assert validate_bench_record(_v2(trace=tr2)) == []
    # present but mis-typed: flagged
    for key in ("bubbles", "staging", "roofline"):
        bad = _attribution(1.0)
        bad[key] = "not an object"
        assert any(
            key in p for p in validate_bench_record(_v2(trace=bad))
        ), key


def test_optional_scores_field_absent_valid_mistyped_flagged():
    """ISSUE 17: bench config 8's multi-objective summary rides an
    OPTIONAL ``scores`` object ({objective: number}) beside the scalar
    metric. Absent is valid forever (the whole scalar history); present
    it must keep the declared shape."""
    # absent: valid (every pre-17 record)
    assert validate_bench_record(_v2()) == []
    # explicit null and a well-typed object: valid
    assert validate_bench_record(_v2(scores=None)) == []
    assert validate_bench_record(
        _v2(scores={"accuracy": 0.93, "hypervolume_at_budget": 12.5})
    ) == []
    # mis-typed shapes are each flagged
    for bad in (
        [0.93, 12.5],  # a bare vector loses the objective names
        {},  # present-but-empty says nothing
        {"accuracy": "high"},
        {"accuracy": True},  # JSON true is drift, not a score
        "0.93",
    ):
        assert any(
            "scores" in p for p in validate_bench_record(_v2(scores=bad))
        ), bad
    # legacy records (no schema_version) never grew the field; the gate
    # only applies to v2 shapes, so history cannot be flagged
    legacy = {"metric": "m", "value": 1.0, "unit": "trials/sec"}
    assert validate_bench_record(legacy) == []


def test_wave_sha_config_record_shape_validates():
    """ISSUE 18: bench config 9 (wave-scheduled fused SHA) rides the
    v2 shape with the engine's staging counters as plain extra keys —
    the validator must accept them (extras are informational, never
    drift) and the gate must judge the headline like any throughput
    metric."""
    rec = _v2(
        config=9,
        metric="wave_sha64_fashion_mlp_trials_per_sec_per_chip",
        value=12.0,
        wave_size=16,
        n_waves=4,
        staged_bytes=1 << 26,
        stage_transfer_s=1.25,
        stage_wait_s=0.2,
        stage_overlap_s=1.0,
    )
    assert validate_bench_record(rec) == []
    # throughput direction: a big drop in trials/s gates
    worse = dict(rec, value=6.0)
    rep = bench_gate([rec], [worse], {})
    assert not rep["ok"]
    rep = bench_gate([rec], [rec], {})
    assert rep["ok"], rep["violations"]


def test_committed_bench_history_stays_valid():
    """BENCH_r01-r05 predate the schema_version field: they must
    validate as the legacy shape forever (the trajectory's early rounds
    are history, not drift)."""
    wrappers = sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r0*.json")))
    assert wrappers, "committed BENCH rounds missing?"
    for path in wrappers:
        with open(path) as f:
            doc = json.load(f)
        problems = validate_bench_record(doc.get("parsed"))
        assert problems == [], (path, problems)
    with open(os.path.join(REPO_ROOT, "BENCH_ALL.json")) as f:
        records = json.load(f)
    for rec in records:
        if "error" in rec:  # a failed config records the error, not a metric
            continue
        problems = validate_bench_record(rec)
        assert problems == [], (rec.get("config"), problems)


def test_bench_all_finish_record_stamps_schema_and_watermark():
    import bench_all

    rec = bench_all._finish_record({"config": 1, "metric": "m", "value": 1.0, "unit": "trials/sec"})
    assert rec["schema_version"] == BENCH_SCHEMA_VERSION
    assert "trace" in rec and "device_memory" in rec
    # on this CPU container the watermark comes from live-array
    # accounting; either way the validator passes the stamped record
    assert validate_bench_record(rec) == []


# -- the whole-trajectory gate -------------------------------------------


def test_bench_gate_value_direction_per_unit():
    base = [
        {"config": 2, "metric": "asha", "value": 50.0, "unit": "trials/sec/chip"},
        {"config": 3, "metric": "wtt", "value": 100.0, "unit": "seconds_to_target_val_acc"},
    ]
    # throughput down 40% + wall-to-target up 60%: both regress
    worse = [
        {"config": 2, "metric": "asha", "value": 30.0, "unit": "trials/sec/chip"},
        {"config": 3, "metric": "wtt", "value": 160.0, "unit": "seconds_to_target_val_acc"},
    ]
    rep = bench_gate(base, worse, {})
    assert not rep["ok"] and len(rep["violations"]) == 2
    # throughput UP and wall-to-target DOWN are improvements, not gated
    better = [
        {"config": 2, "metric": "asha", "value": 80.0, "unit": "trials/sec/chip"},
        {"config": 3, "metric": "wtt", "value": 60.0, "unit": "seconds_to_target_val_acc"},
    ]
    rep = bench_gate(base, better, {})
    assert rep["ok"], rep["violations"]
    assert rep["configs"]["config2"]["value"]["ok"]


def test_bench_gate_diffs_embedded_traces():
    base = [_v2(config=3, trace=_attribution(1.0))]
    new = [_v2(config=3, trace=_attribution(2.0))]
    rep = bench_gate(base, new, {"phases": {"train": 0.25}})
    assert not rep["ok"]
    assert any("train" in v for v in rep["violations"])
    assert rep["configs"]["config3"]["trace_gate"]["ok"] is False
    # same trace both sides: clean
    rep = bench_gate(base, base, {"phases": {"train": 0.25}})
    assert rep["ok"], rep["violations"]
    assert rep["configs"]["config3"]["trace_gate"]["ok"] is True


def test_bench_gate_flags_config_that_lost_its_value():
    """A config whose new-round bench crashed (error record, no value)
    or whose target was never reached is the WORST regression shape —
    it must gate 1, not shrug as unjudgeable."""
    base = [{"config": 5, "metric": "resnet", "value": 2.5, "unit": "trials/sec/chip"}]
    crashed = [{"config": 5, "error": "RESOURCE_EXHAUSTED: oom"}]
    rep = bench_gate(base, crashed, {})
    assert not rep["ok"]
    assert any("RESOURCE_EXHAUSTED" in v for v in rep["violations"])
    assert rep["configs"]["config5"]["value"]["ok"] is False
    # the reverse (base never measured it) stays unjudgeable, not a fail
    rep = bench_gate(crashed, base, {})
    assert rep["ok"]
    assert rep["configs"]["config5"]["value"]["ok"] is None


def test_bench_gate_empty_or_garbage_base_is_a_failure():
    """An empty list or non-record JSON as --gate-base must fail, not
    vacuously pass with nothing gated."""
    new = [{"config": 1, "metric": "a", "value": 1.0, "unit": "trials/sec"}]
    for bad_base in ([], ["oops"], [{"no": "keys"}]):
        rep = bench_gate(bad_base, new, {})
        assert not rep["ok"], bad_base
        assert any("no bench records" in v for v in rep["violations"]), bad_base


def test_bench_gate_zero_overlap_is_a_failure_not_a_pass():
    """A --gate-base file sharing NO keys with this run's records gates
    nothing — that must be rc 1 (wrong file, wrong configs), never a
    vacuous clean verdict."""
    base = [{"config": 1, "metric": "a", "value": 1.0, "unit": "trials/sec"}]
    new = [{"config": 2, "metric": "b", "value": 1.0, "unit": "trials/sec"}]
    rep = bench_gate(base, new, {})
    assert rep["unmatched_base"] == ["config1"]
    assert rep["unmatched_new"] == ["config2"]
    assert not rep["ok"]
    assert any("no comparable records" in v for v in rep["violations"])
    # partial overlap still judges the matched pair and stays ok when
    # that pair is clean (the unmatched rest is reported, not failed)
    base.append({"config": 2, "metric": "b", "value": 1.0, "unit": "trials/sec"})
    rep = bench_gate(base, new, {})
    assert rep["ok"] and rep["unmatched_base"] == ["config1"]


def test_bench_gate_accepts_bench_r0_wrapper_shape():
    """A BENCH_r0*.json driver wrapper (record under 'parsed') gates
    directly against a flat record set — the trajectory files are the
    gate's native input."""
    base = [{"n": 5, "rc": 0, "parsed": {"metric": "m", "value": 8.81, "unit": "trials/sec/chip"}}]
    new = [{"metric": "m", "value": 4.0, "unit": "trials/sec/chip"}]
    rep = bench_gate(base, new, {})
    assert not rep["ok"]
    with pytest.raises(ValueError, match="unknown tolerance keys"):
        bench_gate(base, new, {"bogus": 1})
