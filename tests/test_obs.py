"""Observability layer (obs/): span tracing, attribution, registry lint.

Covers the ISSUE-8 test satellites: disabled-mode overhead (a span
with no sink does zero JSON work), thread safety under StagingEngine's
background transfer thread, multi-rank merge ordering, TF/s arithmetic
against known FLOP counts, the trace-CLI JSON schema gate, and the
event-name registry lint that stops silent stream-schema drift.
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from mpi_opt_tpu.obs import events, trace
from mpi_opt_tpu.obs.report import attribute, discover_streams, load_stream, trace_main
from mpi_opt_tpu.utils.metrics import MetricsLogger

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_trace_state():
    """Every test starts untraced and restores whatever was configured
    before it (the same nesting contract cli.main honors)."""
    saved = trace.save()
    trace.deconfigure()
    yield
    trace.deconfigure(saved)


def _spans(path):
    return [r for r in load_stream(path) if r.get("event") == "span"]


# -- the tracer ----------------------------------------------------------


def test_disabled_span_does_zero_json_work(monkeypatch):
    """The null contract: with no sink, a span never touches json — it
    only maintains the thread-local stack the heartbeat phase needs."""

    def boom(*a, **k):  # any serialization attempt fails the test
        raise AssertionError("json.dumps called with tracing disabled")

    monkeypatch.setattr(json, "dumps", boom)
    assert not trace.enabled()
    with trace.span("train", launch=1):
        assert trace.current_phase() == "train"
    assert trace.current_phase() is None


def test_span_record_fields_and_self_time(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(path=path)
    prior = trace.configure(m, rank=2, tenant="alice")
    try:
        with trace.span("train", launch=3) as sp:
            with trace.span("journal", n=1):
                time.sleep(0.02)
            sp["flops"] = 1e9
    finally:
        trace.deconfigure(prior)
        m.close()
    spans = _spans(path)
    by_name = {r["span"]: r for r in spans}
    assert set(by_name) == {"train", "journal"}
    tr, jn = by_name["train"], by_name["journal"]
    for r in (tr, jn):
        assert r["rank"] == 2 and r["tenant"] == "alice"
        assert isinstance(r["ts"], float) and r["dur_s"] > 0
    assert tr["flops"] == 1e9 and tr["launch"] == 3
    # self time excludes the nested journal span's duration
    assert tr["self_s"] <= tr["dur_s"] - jn["dur_s"] + 1e-3
    # child emitted before parent (exit order), both ts-stamped at exit
    assert jn["ts"] <= tr["ts"]


def test_traced_decorator_and_exception_emission(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(path=path)
    prior = trace.configure(m)
    try:

        @trace.traced("save")
        def do_save():
            return 7

        assert do_save() == 7
        with pytest.raises(ValueError, match="boom"):
            with trace.span("restore"):
                raise ValueError("boom")
    finally:
        trace.deconfigure(prior)
        m.close()
    names = [r["span"] for r in _spans(path)]
    # the crashed phase is visible in the attribution, not vanished
    assert names == ["save", "restore"]
    assert trace.current_phase() is None  # stack unwound past the raise


def test_suppressed_spans_do_not_emit(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(path=path)
    prior = trace.configure(m)
    try:
        with trace.suppressed():
            with trace.span("compile"):
                pass
        with trace.span("train"):
            pass
    finally:
        trace.deconfigure(prior)
        m.close()
    assert [r["span"] for r in _spans(path)] == ["train"]


def test_thread_safety_concurrent_spans(tmp_path):
    """N threads spanning through one sink concurrently: every line
    parses whole (MetricsLogger serializes sink writes) and per-thread
    nesting stays separate (distinct tids)."""
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(path=path)
    prior = trace.configure(m)
    n_threads, per_thread = 4, 50

    def work(i):
        for k in range(per_thread):
            with trace.span("train", launch=k, worker=i):
                pass

    try:
        threads = [threading.Thread(target=work, args=(i,)) for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        trace.deconfigure(prior)
        m.close()
    spans = _spans(path)  # load_stream skips any malformed line: count proves none
    assert len(spans) == n_threads * per_thread
    assert len({r["tid"] for r in spans}) == n_threads


def test_staging_engine_spans_and_heartbeat_phase(tmp_path):
    """The background transfer thread traces its fetches (stage_out with
    bytes), drain traces the un-hidden wait, and the worker's heartbeat
    carries phase=stage_out — the 'stalled during stage_out' signal."""
    import numpy as np

    from mpi_opt_tpu.health import heartbeat
    from mpi_opt_tpu.train.staging import StagingEngine

    path = str(tmp_path / "m.jsonl")
    hb_path = str(tmp_path / "hb.json")
    m = MetricsLogger(path=path)
    prior = trace.configure(m)
    heartbeat.configure(hb_path)
    got = []
    try:
        import jax.numpy as jnp

        with StagingEngine() as engine:
            engine.stage_out({"x": jnp.arange(64.0)}, lambda h: got.append(h))
            engine.drain()
    finally:
        heartbeat.deconfigure()
        trace.deconfigure(prior)
        m.close()
    assert len(got) == 1 and np.asarray(got[0]["x"]).shape == (64,)
    by_name = {}
    for r in _spans(path):
        by_name.setdefault(r["span"], []).append(r)
    assert by_name["stage_out"][0]["bytes"] > 0
    assert "stage_wait" in by_name
    # worker thread != main thread in the records
    assert by_name["stage_out"][0]["tid"] != by_name["stage_wait"][0]["tid"]
    beat = heartbeat.read_beat(hb_path)
    assert beat is not None and beat["phase"] == "stage_out"


def test_heartbeat_phase_from_active_span(tmp_path):
    from mpi_opt_tpu.health import heartbeat

    hb = str(tmp_path / "hb.json")
    heartbeat.configure(hb)
    try:
        with trace.span("stage_in"):
            heartbeat.beat(stage="wave 1")
        in_span = heartbeat.read_beat(hb)
        heartbeat.beat(stage="boundary")
        outside = heartbeat.read_beat(hb)
    finally:
        heartbeat.deconfigure()
    assert in_span["phase"] == "stage_in"
    assert in_span["progress"]["stage"] == "wave 1"
    assert outside["phase"] is None  # no active span anywhere


def test_heartbeat_phase_carries_boundary_op(tmp_path):
    """Boundary spans fold their ``op`` attribute into the heartbeat
    phase (ISSUE 18 satellite): a stall during SHA's rung cut reads
    "stalled during boundary:rung_cut" in the launch event, not just
    "boundary" — the engine's boundary_span helper beats on entry so
    the phase is fresh even if the boundary op itself wedges."""
    from mpi_opt_tpu.health import heartbeat
    from mpi_opt_tpu.train.engine import boundary_span

    hb = str(tmp_path / "hb.json")
    heartbeat.configure(hb)
    try:
        with boundary_span("rung_cut", rung=2):
            cut = heartbeat.read_beat(hb)  # beat happens on span entry
        with trace.span("boundary", op="exploit"):
            heartbeat.beat(stage="gen 3")
        exploit = heartbeat.read_beat(hb)
    finally:
        heartbeat.deconfigure()
    assert cut["phase"] == "boundary:rung_cut"
    assert cut["progress"]["stage"] == "boundary rung_cut"
    assert exploit["phase"] == "boundary:exploit"


def test_launch_stall_phases_from_beat_files(tmp_path):
    """launch.py's stall event includes each wedged rank's last-beat
    phase (active-span field, progress-stage fallback)."""
    from mpi_opt_tpu.health.heartbeat import Heartbeat
    from mpi_opt_tpu.launch import _hb_path, _stall_phases

    d = str(tmp_path)
    with trace.span("stage_in"):
        Heartbeat(_hb_path(d, 0)).beat(stage="wave 2")
    Heartbeat(_hb_path(d, 1)).beat(stage="driver")  # no span: stage fallback
    phases = _stall_phases(d, [0, 1, 2])  # rank 2 never beat
    assert phases == {"0": "stage_in", "1": "driver", "2": None}


# -- attribution ---------------------------------------------------------


def _rec(span, ts, dur, self_s=None, **attrs):
    return {
        "event": "span",
        "span": span,
        "ts": ts,
        "dur_s": dur,
        "self_s": dur if self_s is None else self_s,
        "tid": 0,
        **attrs,
    }


def test_multi_rank_merge_ordering_and_wall():
    """Two rank streams with interleaved timestamps merge by absolute
    ``ts``; the merged wall spans the earliest begin to the latest end."""
    a = [_rec("train", 103.0, 2.0, rank=0), _rec("save", 104.5, 0.5, rank=0)]
    b = [_rec("train", 102.0, 1.0, rank=1), _rec("train", 106.0, 1.5, rank=1)]
    rep = attribute({"rank0.out": a, "rank1.out": b})
    assert [s["label"] for s in rep["streams"]] == ["rank0.out", "rank1.out"]
    # earliest begin = 102-1 = 101; latest end = 106
    assert rep["wall_s"] == pytest.approx(5.0)
    assert rep["streams"][0]["rank"] == 0 and rep["streams"][1]["rank"] == 1
    assert rep["phases"]["train"]["count"] == 3
    # per-stream walls are local: rank0 spans 101.0->104.5? no: begin
    # 103-2=101, end 104.5 -> 3.5
    assert rep["streams"][0]["wall_s"] == pytest.approx(3.5)


def test_tflops_arithmetic_against_known_flops():
    recs = [
        _rec("train", 10.0, 1.0, flops=2e12, launch=1),
        _rec("train", 13.0, 2.0, flops=4e12, launch=2),
    ]
    rep = attribute({"s": recs})
    t = rep["train"]
    assert t["flops"] == pytest.approx(6e12)
    assert t["train_s"] == pytest.approx(3.0)
    assert t["tflops_per_sec"] == pytest.approx(2.0)
    per = {e["launch"]: e["tflops_per_sec"] for e in t["per_launch"]}
    assert per == {1: pytest.approx(2.0), 2: pytest.approx(2.0)}


def test_attribution_self_time_and_compile_breakdown():
    recs = [
        _rec("compile", 100.8, 0.8, cache="cold"),
        _rec("compile", 101.0, 0.1, cache="persistent"),
        # train span enclosing both compiles: self excludes them
        _rec("train", 103.0, 3.0, self_s=2.1, launch=1),
    ]
    rep = attribute({"s": recs})
    assert rep["compile"]["cold"] == {"count": 1, "total_s": 0.8}
    assert rep["compile"]["persistent"] == {"count": 1, "total_s": 0.1}
    ph = rep["phases"]
    assert ph["train"]["self_s"] == pytest.approx(2.1)
    assert ph["train"]["total_s"] == pytest.approx(3.0)
    # attributed = sum of self times, never double-counting nesting
    assert rep["attributed_s"] == pytest.approx(0.8 + 0.1 + 2.1)
    # wall = begin(compile cold)=100.0 .. end(train)=103.0
    assert rep["wall_s"] == pytest.approx(3.0)
    assert rep["coverage"] == pytest.approx(1.0)


def test_time_to_first_trial_from_batch_event_and_train_span():
    recs = [
        {"event": "resume", "ts": 100.0},
        _rec("setup", 103.0, 3.0),
        {"event": "batch", "ts": 104.0},
        _rec("train", 106.0, 1.0),
    ]
    rep = attribute({"s": recs})
    # first trial evidence: the batch event at 104, stream start 100
    assert rep["time_to_first_trial_s"] == pytest.approx(4.0)


def test_per_tenant_breakdown():
    recs_a = [_rec("train", 101.0, 1.0, tenant="alice")]
    recs_b = [_rec("train", 102.0, 0.5, tenant="bob"), _rec("save", 102.5, 0.2, tenant="bob")]
    rep = attribute({"a": recs_a, "b": recs_b})
    assert set(rep["tenants"]) == {"alice", "bob"}
    assert rep["tenants"]["bob"]["save"]["count"] == 1
    assert rep["tenants"]["alice"]["train"]["self_s"] == pytest.approx(1.0)


# -- the trace CLI -------------------------------------------------------


def test_trace_cli_json_schema(tmp_path, capsys):
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as f:
        for r in (
            {"event": "resume", "ts": 100.0},
            _rec("train", 105.0, 5.0, flops=1e12, launch=1, rank=0),
            _rec("save", 105.5, 0.5, rank=0),
        ):
            f.write(json.dumps(r) + "\n")
    assert trace_main([path, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    # the stable --json surface benches/CI consume
    for key in (
        "streams",
        "records",
        "span_records",
        "wall_s",
        "attributed_s",
        "coverage",
        "phases",
        "compile",
        "train",
        "time_to_first_trial_s",
        "bubbles",
        "staging",
        "roofline",
        "tenants",
    ):
        assert key in rep, key
    assert rep["phases"]["train"]["count"] == 1
    for stat in ("count", "total_s", "self_s", "wall_pct", "p50_s", "p95_s"):
        assert stat in rep["phases"]["train"], stat
    assert rep["train"]["tflops_per_sec"] == pytest.approx(0.2)


def test_trace_cli_dir_discovery_skips_ledgers(tmp_path, capsys):
    d = str(tmp_path)
    for name in ("rank0.out", "rank1.out"):
        with open(os.path.join(d, name), "w") as f:
            f.write(json.dumps(_rec("train", 100.0, 1.0)) + "\n")
    # a ledger sniffs as kind=header, not an event stream: excluded
    with open(os.path.join(d, "sweep.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "header", "version": 1}) + "\n")
    assert sorted(os.path.basename(p) for p in discover_streams(d)) == [
        "rank0.out",
        "rank1.out",
    ]
    assert trace_main([d, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert len(rep["streams"]) == 2


def test_trace_cli_empty_dir_is_an_error(tmp_path, capsys):
    assert trace_main([str(tmp_path), "--json"]) == 1
    out = capsys.readouterr()
    assert "no metrics streams" in out.err
    json.loads(out.out)  # --json stdout stays machine-parseable


# -- registry lint (the schema-drift gate) -------------------------------


def test_event_and_span_registry_lint():
    """Every literal event/span name at every call site in the codebase
    must be registered in obs/events.py — adding an event means adding
    one reviewed line there (the `ts` field was once added ad hoc; the
    NAME space is now gated)."""
    problems = events.lint(REPO_ROOT)
    assert problems == [], "\n".join(problems)


def test_registry_scan_sees_known_sites():
    """The AST scanner actually finds the emitters the lint gates on
    (an empty scan would make the lint vacuously green)."""
    sites = list(events.scan_call_sites(REPO_ROOT))
    kinds = {(k, n) for _p, _l, k, n in sites}
    assert ("event", "summary") in kinds  # metrics.log in utils/metrics.py
    assert ("event", "stall") in kinds  # launch.py _event
    assert ("event", "snapshot_corrupt") in kinds  # integrity notify
    assert ("span", "train") in kinds  # fused drivers
    assert ("span", "stage_out") in kinds  # staging worker


# -- flops hint gating ---------------------------------------------------


def test_segment_flops_hint_gated_on_tracing(tmp_path):
    from mpi_opt_tpu.train.common import segment_flops_hint

    class Dummy:
        pass

    wl = Dummy()
    # tracing off: no probe, no cache, None
    assert segment_flops_hint(wl, 4, 10) is None
    assert not hasattr(wl, "_flops_hint_cache")
    # tracing on with a non-population workload: the probe fails soft
    # (population_sweep_flops returns None) and the failure is cached
    m = MetricsLogger(path=str(tmp_path / "m.jsonl"))
    prior = trace.configure(m)
    try:
        assert segment_flops_hint(wl, 4, 10) is None
        assert wl._flops_hint_cache == {(4, 10): None}
    finally:
        trace.deconfigure(prior)
        m.close()


# -- launch-window profiling ---------------------------------------------


def test_parse_launch_window():
    from mpi_opt_tpu.utils.profiling import parse_launch_window

    assert parse_launch_window("3") == (3, 3)
    assert parse_launch_window("2:5") == (2, 5)
    for bad in ("0", "3:2", "a", "1:2:3"):
        with pytest.raises(ValueError):
            parse_launch_window(bad)


def test_profile_window_launch_ticks(tmp_path, monkeypatch):
    import jax

    from mpi_opt_tpu.utils import profiling

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: calls.append(("stop",)))
    d = str(tmp_path / "prof")
    with profiling.profile_window(d, launches=(2, 2)):
        profiling.launch_tick()  # launch 1: before the window
        assert not profiling.active() and calls == []
        profiling.launch_tick()  # launch 2: window opens
        assert profiling.active() and calls == [("start", d)]
        profiling.launch_tick()  # launch 3: window closed
        assert not profiling.active()
    assert calls == [("start", d), ("stop",)]
    # a window never closed by ticks is closed by the context exit
    calls.clear()
    with profiling.profile_window(d, launches=(1, 99)):
        profiling.launch_tick()
    assert calls == [("start", d), ("stop",)] and not profiling.active()


def test_cli_validates_profile_launches(capsys):
    from mpi_opt_tpu.cli import main

    with pytest.raises(SystemExit) as e:
        main(["--workload", "quadratic", "--profile-launches", "2:3"])
    assert e.value.code == 2
    assert "requires --profile-dir" in capsys.readouterr().err


# -- service live phase --------------------------------------------------


def test_service_live_phase_surface(tmp_path):
    from mpi_opt_tpu.health.heartbeat import Heartbeat
    from mpi_opt_tpu.service.spool import live_phase

    d = str(tmp_path)
    with trace.span("train"):
        Heartbeat(os.path.join(d, "heartbeat.json")).beat(stage="gen 2")
    status = {"state": "running", "slice_started_ts": time.time() - 2.0}
    live = live_phase(d, status)
    assert live["phase"] == "train"
    assert 1.0 <= live["slice_elapsed_s"] <= 60.0
    assert live_phase(d, {"state": "parked"}) is None
    # beat-less running tenant: fields degrade to None, never an error
    empty = live_phase(str(tmp_path / "nope"), {"state": "running"})
    assert empty == {"phase": None, "slice_elapsed_s": None}


# -- end to end: the schema gate on a real traced sweep ------------------


def test_traced_fused_sweep_end_to_end(tmp_path, capsys):
    """Tier-1 twin of probes/tier1.sh's TRACE_DRILL: a tiny fused PBT
    sweep traced into a metrics file, rendered by the trace CLI —
    compile/train/save spans present, wall sums sane, achieved TF/s and
    time-to-first-trial reported."""
    from mpi_opt_tpu.cli import main

    mf = str(tmp_path / "m.jsonl")
    rc = main(
        [
            "--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
            "--no-mesh", "--population", "2", "--generations", "2",
            "--steps-per-generation", "1", "--seed", "0",
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--metrics-file", mf, "--trace",
        ]
    )
    capsys.readouterr()  # drop the sweep's own stdout
    assert rc == 0
    assert not trace.enabled()  # cli.main restored the entry state
    assert trace_main([mf, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    ph = rep["phases"]
    for need in ("compile", "train", "save", "digest", "setup"):
        assert need in ph and ph[need]["count"] > 0, (need, sorted(ph))
    assert rep["compile"]["cold"]["count"] > 0
    # wall sums within tolerance: attributed self-seconds cannot exceed
    # the single-threaded stream's wall (plus rounding epsilon)
    assert 0 < rep["attributed_s"] <= rep["wall_s"] * 1.05 + 0.5
    assert rep["coverage"] > 0.3
    assert rep["time_to_first_trial_s"] is not None
    # XLA:CPU cost analysis is available in this container, so the
    # train spans carry FLOPs and achieved TF/s is a number
    assert rep["train"] is not None and rep["train"]["tflops_per_sec"] > 0
    # the intra-phase sections (ISSUE 11) ride in every attribution:
    # bubble totals obey busy + idle == wall, and the roofline verdict
    # is one of the three bound classes
    bub = rep["bubbles"]
    assert bub is not None and bub["idle_frac"] is not None
    assert bub["busy_s"] + bub["idle_s"] == pytest.approx(bub["wall_s"], abs=0.01)
    assert rep["roofline"]["bound"] in (
        "compute-bound", "transfer-bound", "bubble-bound",
    )
