"""Metrics utilities: the wall-clock-to-target metric of record."""

import numpy as np

from mpi_opt_tpu.utils.metrics import MetricsLogger, wall_to_target


def test_wall_to_target_prorates_by_generation():
    # target reached at generation index 1 of 4 -> 2/4 of the wall
    assert wall_to_target([0.5, 0.8, 0.9, 0.95], 100.0, 0.75) == 50.0
    # reached immediately -> one generation's share
    assert wall_to_target([0.9, 0.95], 60.0, 0.75) == 30.0
    # never reached -> None
    assert wall_to_target([0.1, 0.2], 60.0, 0.75) is None
    # exact-equality counts as reached (>=, not >)
    assert wall_to_target([0.75], 10.0, 0.75) == 10.0
    # accepts numpy inputs (the benches pass device-derived arrays)
    assert wall_to_target(np.asarray([0.2, 0.8]), 10.0, 0.5) == 10.0


def test_metrics_logger_per_chip_normalization(tmp_path):
    import json

    path = tmp_path / "m.jsonl"
    m = MetricsLogger(path=str(path), n_chips=4)
    m.count_trials(8)
    m.log("batch", size=8)
    # trials/sec/chip divides by the chip count; pin the clock far from
    # zero so the two live wall reads agree to high precision
    import math
    import time

    m.t_start = time.perf_counter() - 100.0
    per_chip = m.trials_per_sec_per_chip()
    total = m.trials_done / max(m.wall, 1e-9)
    assert math.isclose(per_chip * 4, total, rel_tol=1e-4)
    m.close()  # release the file handle (ResourceWarning-clean)
    rec = json.loads(path.read_text().splitlines()[0])
    assert rec["event"] == "batch" and rec["size"] == 8
