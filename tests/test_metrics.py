"""Metrics utilities: the wall-clock-to-target metric of record."""

import numpy as np

import pytest

from mpi_opt_tpu.utils.metrics import (
    MetricsLogger,
    wall_to_target,
    wall_to_target_launchwise,
)


def test_wall_to_target_prorates_by_generation():
    # target reached at generation index 1 of 4 -> 2/4 of the wall
    assert wall_to_target([0.5, 0.8, 0.9, 0.95], 100.0, 0.75) == 50.0
    # reached immediately -> one generation's share
    assert wall_to_target([0.9, 0.95], 60.0, 0.75) == 30.0
    # never reached -> None
    assert wall_to_target([0.1, 0.2], 60.0, 0.75) is None
    # exact-equality counts as reached (>=, not >)
    assert wall_to_target([0.75], 10.0, 0.75) == 10.0
    # accepts numpy inputs (the benches pass device-derived arrays)
    assert wall_to_target(np.asarray([0.2, 0.8]), 10.0, 0.5) == 10.0


def test_wall_to_target_launchwise_uses_measured_boundaries():
    # two launches of 2 gens: 10s then 30s (the second launch is slower —
    # exactly what whole-sweep prorating gets wrong). Target reached at
    # gen index 2 = first gen of launch 2 -> 10 + 30 * 1/2 = 25.
    curve = [0.2, 0.4, 0.8, 0.9]
    assert wall_to_target_launchwise(curve, [2, 2], [10.0, 30.0], 0.75) == 25.0
    # whole-sweep prorating would have said 40 * 3/4 = 30
    assert wall_to_target(curve, 40.0, 0.75) == 30.0
    # reached in the first launch's first gen
    assert wall_to_target_launchwise([0.9, 0.9], [2], [10.0], 0.5) == 5.0
    # never reached
    assert wall_to_target_launchwise([0.1, 0.2], [1, 1], [5.0, 5.0], 0.75) is None
    # identical launch costs == the uniform assumption: both agree
    assert wall_to_target_launchwise(curve, [2, 2], [20.0, 20.0], 0.75) == 30.0
    # misaligned inputs are errors, not silent misattribution
    with pytest.raises(ValueError, match="align"):
        wall_to_target_launchwise(curve, [2, 2], [10.0], 0.75)
    with pytest.raises(ValueError, match="curve"):
        wall_to_target_launchwise(curve, [2, 3], [10.0, 30.0], 0.75)


def test_fused_pbt_reports_launch_walls():
    """The fused sweep returns measured per-launch durations aligned with
    its launch split, and a resumed sweep restores pre-crash durations."""
    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("fashion_mlp", n_train=512, n_val=256)
    res = fused_pbt(wl, population=4, generations=3, steps_per_gen=2, seed=0, gen_chunk=2)
    assert res["launch_gens"] == [2, 1]
    assert len(res["launch_walls"]) == 2
    assert all(w > 0 for w in res["launch_walls"])


def test_metrics_logger_per_chip_normalization(tmp_path):
    import json

    path = tmp_path / "m.jsonl"
    m = MetricsLogger(path=str(path), n_chips=4)
    m.count_trials(8)
    m.log("batch", size=8)
    # trials/sec/chip divides by the chip count; pin the clock far from
    # zero so the two live wall reads agree to high precision
    import math
    import time

    m.t_start = time.perf_counter() - 100.0
    per_chip = m.trials_per_sec_per_chip()
    total = m.trials_done / max(m.wall, 1e-9)
    assert math.isclose(per_chip * 4, total, rel_tol=1e-4)
    m.close()  # release the file handle (ResourceWarning-clean)
    rec = json.loads(path.read_text().splitlines()[0])
    assert rec["event"] == "batch" and rec["size"] == 8


def test_summary_includes_failure_counters():
    m = MetricsLogger()
    m.count_trials(10)
    m.count_failure("failed")
    m.count_failure("failed")
    m.count_failure("timeout")
    m.count_retries(3)
    s = m.summary()
    assert s["trials"] == 10
    assert s["trials_failed"] == 2
    assert s["trials_timeout"] == 1
    assert s["trials_retried"] == 3
    # fresh loggers report explicit zeros (operators diff summaries)
    z = MetricsLogger().summary()
    assert (z["trials_failed"], z["trials_retried"], z["trials_timeout"]) == (0, 0, 0)


def test_summary_includes_health_counters():
    """preempted / stalls_detected (health layer) reach the summary
    record operators alarm on — explicit zeros when nothing happened."""
    m = MetricsLogger()
    m.count_preempted()
    m.count_stalls(2)
    s = m.summary()
    assert s["preempted"] == 1
    assert s["stalls_detected"] == 2
    z = MetricsLogger().summary()
    assert (z["preempted"], z["stalls_detected"]) == (0, 0)


def test_null_logger_log_path_is_sink_free(monkeypatch):
    """null_logger() must stay zero-cost on the hot path: with no file
    and no stream, log() must not serialize (the driver logs per-batch
    and per-failure events unconditionally)."""
    from mpi_opt_tpu.utils import metrics as metrics_mod
    from mpi_opt_tpu.utils.metrics import null_logger

    def boom(*a, **k):
        raise AssertionError("json.dumps called on the null-logger path")

    monkeypatch.setattr(metrics_mod.json, "dumps", boom)
    m = null_logger()
    rec = m.log("batch", size=4)
    assert rec["event"] == "batch" and rec["size"] == 4
    m.count_failure("timeout")
    s = m.summary()
    assert s["trials_timeout"] == 1


def test_summary_includes_staging_counters():
    """staged_bytes / stage_overlap_s (wave-scheduled fused sweeps)
    reach the metrics summary; zero-valued when no staging ran."""
    from mpi_opt_tpu.utils.metrics import MetricsLogger

    m = MetricsLogger()
    m.count_staging(1024, 0.5)
    m.count_staging(1024, 0.25)
    s = m.summary()
    assert s["staged_bytes"] == 2048
    assert s["stage_overlap_s"] == 0.75
    z = MetricsLogger().summary()
    assert z["staged_bytes"] == 0 and z["stage_overlap_s"] == 0.0


def test_summary_includes_snapshot_quarantine_counter():
    """snapshots_quarantined (integrity layer) reaches the summary
    record operators alarm on — explicit zero when nothing happened."""
    from mpi_opt_tpu.utils.metrics import MetricsLogger

    m = MetricsLogger()
    m.count_quarantined()
    m.count_quarantined(2)
    assert m.summary()["snapshots_quarantined"] == 3
    assert MetricsLogger().summary()["snapshots_quarantined"] == 0


def test_summary_includes_members_journaled():
    """members_journaled (fused-ledger member records appended) reaches
    the metrics summary; zero-valued when no fused journaling ran."""
    from mpi_opt_tpu.utils.metrics import MetricsLogger

    m = MetricsLogger()
    m.count_journaled(8)
    m.count_journaled(4)
    assert m.summary()["members_journaled"] == 12
    assert MetricsLogger().summary()["members_journaled"] == 0
